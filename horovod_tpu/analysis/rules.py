"""The distributed-correctness rules `hvt-lint` ships.

Each rule encodes an invariant this repo has actually been bitten by (or
designed around, loudly, in CHANGES.md/docstrings) — not generic style:

* HVT001 — collective symmetry: a collective/barrier reached only under
  rank-conditional control flow is the classic Horovod hang class
  (arXiv:1802.05799): the gated ranks never enter, the rest block
  forever (or the coordination service SIGABRTs them).
* HVT002 — teardown discipline: `jax.distributed.shutdown` is a BARRIER
  on this stack; one-sided teardown kills survivors (PR 2). Only the
  sanctioned runtime/elastic boundary modules may touch it directly.
* HVT003 — tracing hazards: host side effects inside jit/scan/shard_map
  functions execute once at trace time (or diverge per-rank) — the
  silent-divergence class.
* HVT004 — env-knob registry: every ``HVT_*`` knob must be declared in
  `analysis/registry.py`, and inline ``os.environ`` reads must go
  through the typed accessors.
* HVT005 — checkpoint-write atomicity: artifact writes go through
  `checkpoint._atomic_write` (atomic rename + ``.sha256`` sidecar); a
  bare truncating ``open`` can tear under crash/preemption (PR 3).
* HVT006 — data-layer determinism: unseeded host RNG inside
  ``horovod_tpu/data/`` breaks the durable-stream-cursor contract
  (every feeding path's order must be a pure function of
  ``(seed, epoch, pass)`` — `data.stream`); a global-RNG draw or a
  seedless generator makes resumed byte streams irreproducible.
* HVT007 — collective-order symmetry: sibling branches that issue
  DIFFERENT collective sequences deadlock the fleet when the branch
  condition varies by rank (mismatched submission order — the class
  Horovod's coordinator exists to prevent).
* HVT008 — reduction-composition discipline: gradient reductions in the
  accumulation/ZeRO surface must route through the bucketed boundary
  entry point (`collectives.reduce_gradients`), never a raw per-leaf
  psum — the guardrail ROADMAP item 3's reduce-scatter refactor builds
  on.
* HVT009 — metric-registry discipline: every ``obs.counter/gauge/
  histogram`` emission site must name a series declared in
  `obs/core.py` (the HVT004 pattern for the /metrics surface), and no
  ``obs.*`` call may sit inside a jit/shard_map-traced body (a host
  effect — the HVT003 class).
* HVT010 — whole-program schedule agreement (`analysis/schedule.py`,
  the `hvt-sched check` rule): every rank-feasible path through a unit
  must submit the SAME collective sequence — the cross-function,
  cross-module generalization of HVT007 (rank-gated early returns that
  skip later collectives, rank-varying loop trip counts, gates passed
  into helpers as arguments).
* HVT011 — expert-parallel all-to-all discipline: payload all-to-alls
  in EP-surface modules must route through `collectives.all_to_all`
  (flight-recorded, `hvt-audit alltoalls=N`-auditable), never raw
  ``lax.all_to_all`` at the model layer — the HVT008 pattern for the
  MoE dispatch/combine wire (ROADMAP item 4).
* HVT012 — tunable-knob resolver discipline: a raw ``os.environ``/
  ``os.getenv`` read of a knob carrying registry ``tunable=`` domain
  metadata, anywhere outside the registry resolver itself, is a silent
  autotuning blind spot — `hvt-tune` selects configs by writing the
  resolver's env surface, so a bypassing read sees stale values the
  tuner can neither observe nor override (ROADMAP item 5).
* HVT013 — data-layer retried-read discipline: a raw read-mode
  ``open()`` / ``np.load`` / ``np.memmap`` of corpus files inside
  ``horovod_tpu/data/`` outside the `stream.read_with_retries` wrapper
  turns one transient NFS/FUSE blip into a dead rank — the bounded
  retry-with-backoff contract (``HVT_DATA_RETRIES``) the hvt-data
  failover arc is built on must be checked, not convention.

Rules are interprocedural where the bug class demands it (HVT001 taints
rank-gated CALLS whose callee transitively issues a collective; HVT007
inlines callee sequences — both via `analysis.callgraph`), lexical
everywhere else: a collective gated by an early ``return`` under a rank
check, or a rank value laundered through a local variable, is NOT
caught. The rules catch the shapes that actually appear; the
suppressions (``# hvt: noqa[RULE]``, baseline) keep the false-positive
cost at zero.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from horovod_tpu.analysis import callgraph, registry
from horovod_tpu.analysis.core import (
    Finding,
    ModuleSource,
    Project,
    Rule,
    dotted_name,
    register_rule,
    resolved_dotted,
    terminal_name,
)

# The shared vocabulary (rank gates, collective tables) lives in
# `callgraph` so the graph and the rules cannot drift.

# --- HVT001 -----------------------------------------------------------------


@register_rule
class CollectiveSymmetry(Rule):
    rule_id = "HVT001"
    title = "collective reachable only under rank-conditional control flow"
    project_wide = True
    rationale = (
        "A collective/barrier that only some ranks issue is the classic "
        "Horovod hang class (arXiv:1802.05799): the gated ranks never "
        "enter, the rest block forever — or the coordination service "
        "SIGABRTs them. Since PR 9 the check is INTERPROCEDURAL: a call "
        "under a rank gate is tainted when its callee transitively "
        "issues a collective, any number of helper hops deep, resolved "
        "through the module-set call graph."
    )
    provenance = (
        "PR 2's one-sided `runtime.shutdown` SIGABRT and PR 3's "
        "rank-gated-checkpoint tear; the helper-hop upgrade is PR 9."
    )
    example = (
        "if runtime.process_rank() == 0:\n"
        "    helper(x)        # helper() -> inner() -> psum(...)\n"
    )

    def check(self, module: ModuleSource) -> Iterator[Finding]:
        # Single-module convenience (fixtures, editor integrations):
        # the same analysis over a one-module project — helper hops
        # within the module still resolve.
        return self.check_project(Project([module]))

    def check_project(self, project: Project) -> Iterator[Finding]:
        graph = project.callgraph()
        effects = graph.effects()
        for unit in graph.units.values():
            for site in unit.collectives:
                if site.gate is None:
                    continue
                line, cond = site.gate
                yield unit.module.finding(
                    self.rule_id, site.node,
                    f"collective/barrier `{site.name}` is reached only "
                    f"under rank-conditional control flow (gated at "
                    f"line {line}: `{cond}`) — ranks outside the "
                    "branch never issue it, and the others hang in "
                    "it (the Horovod one-sided-collective class); "
                    "hoist the collective out of the rank gate",
                )
            for edge in unit.calls:
                if edge.gate is None:
                    continue
                if effects.get(edge.callee) != callgraph.ISSUES:
                    continue
                line, cond = edge.gate
                chain = " -> ".join(
                    [edge.display] + graph.witness(edge.callee)
                )
                yield unit.module.finding(
                    self.rule_id, edge.node,
                    f"`{edge.display}(...)` transitively issues a "
                    f"collective ({chain}) and is reached only under "
                    f"rank-conditional control flow (gated at line "
                    f"{line}: `{cond}`) — ranks outside the branch "
                    "never issue it, and the others hang in it (the "
                    "Horovod one-sided-collective class, through one "
                    "or more helper hops); hoist the call out of the "
                    "rank gate or make the callee's collective "
                    "unconditional",
                )


# --- HVT002 -----------------------------------------------------------------

# The only modules allowed to touch the raw teardown primitives: the
# runtime owns the shutdown barrier, compat implements it, and the two
# elastic modules run the sanctioned `_teardown_and_interrupt` /
# `ensure_world` boundaries where lockstep is guaranteed by the
# membership agreement.
_SANCTIONED_TEARDOWN_MODULES = (
    "horovod_tpu/runtime.py",
    "horovod_tpu/compat.py",
    "horovod_tpu/elastic/rescale.py",
    "horovod_tpu/elastic/state.py",
)


@register_rule
class TeardownDiscipline(Rule):
    rule_id = "HVT002"
    title = "raw distributed teardown outside the sanctioned boundary"
    rationale = (
        "`jax.distributed.shutdown` is a BARRIER on this stack: one-"
        "sided teardown propagates a coordination-service error that "
        "kills the surviving ranks with SIGABRT. Only the runtime/"
        "compat/elastic boundary modules — where lockstep is guaranteed "
        "by the membership agreement — may touch the raw primitives."
    )
    provenance = "PR 2 (elastic teardown discipline; the SIGABRT class)."
    example = (
        "def cleanup():\n"
        "    jax.distributed.shutdown()   # outside runtime/elastic\n"
    )

    def check(self, module: ModuleSource) -> Iterator[Finding]:
        if module.relpath in _SANCTIONED_TEARDOWN_MODULES:
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = resolved_dotted(module, node.func)
            if resolved is None:
                continue
            if resolved.endswith("jax.distributed.shutdown"):
                target = "jax.distributed.shutdown"
            elif resolved.split(".")[-1] == "clear_backends":
                target = resolved
            else:
                continue
            yield module.finding(
                self.rule_id, node,
                f"direct `{target}` call — the distributed teardown is a "
                "BARRIER (one-sided teardown SIGABRTs the survivors); "
                "call `runtime.shutdown()`/`runtime.reinit()` or go "
                "through the elastic membership boundary "
                "(`_teardown_and_interrupt`), which guarantee lockstep",
            )


# --- HVT003 -----------------------------------------------------------------

_TRACE_WRAPPERS = {"jit", "pjit", "shard_map"}


def _decorator_traces(dec: ast.AST) -> bool:
    for node in ast.walk(dec):
        if isinstance(node, (ast.Name, ast.Attribute)):
            if terminal_name(node) in _TRACE_WRAPPERS:
                return True
    return False


def _collect_traced_roots(module: ModuleSource) -> list[ast.AST]:
    """Function bodies that run under a jax trace: defs decorated with
    jit/pjit/shard_map (incl. through `partial`), and functions/lambdas
    handed to `jax.jit(f)` / `shard_map(f, ...)` / `lax.scan(f, ...)`."""
    defs_by_name: dict[str, ast.AST] = {}
    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name[node.name] = node

    roots: list[ast.AST] = []
    seen: set[int] = set()

    def add(node: ast.AST):
        if id(node) not in seen:
            seen.add(id(node))
            roots.append(node)

    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_decorator_traces(d) for d in node.decorator_list):
                add(node)
        elif isinstance(node, ast.Call):
            name = terminal_name(node.func)
            is_wrapper = name in _TRACE_WRAPPERS
            if not is_wrapper and name == "scan":
                resolved = resolved_dotted(module, node.func) or ""
                is_wrapper = resolved.endswith("lax.scan")
            if not is_wrapper or not node.args:
                continue
            fn = node.args[0]
            if isinstance(fn, ast.Lambda):
                add(fn)
            elif isinstance(fn, ast.Name) and fn.id in defs_by_name:
                add(defs_by_name[fn.id])
    return roots


@register_rule
class TracingHazards(Rule):
    rule_id = "HVT003"
    title = "host side effect inside a traced (jit/scan/shard_map) function"
    rationale = (
        "Host side effects inside jit/pjit/shard_map/scan bodies execute "
        "ONCE at trace time (clocks/env become burned-in constants) — and "
        "any rank-varying value silently diverges the compiled program "
        "across the fleet, the silent-divergence class."
    )
    provenance = "PR 6 (designed-around invariant; trainer.py discipline)."
    example = (
        "@jax.jit\n"
        "def step(x):\n"
        "    return x + time.time()   # traced once, constant forever\n"
    )

    def check(self, module: ModuleSource) -> Iterator[Finding]:
        reported: set[tuple[int, int]] = set()
        for root in _collect_traced_roots(module):
            body = root.body if isinstance(root.body, list) else [root.body]
            for stmt in body:
                for node in ast.walk(stmt):
                    finding = self._hazard(module, node)
                    if finding and (finding.line, finding.col) not in reported:
                        reported.add((finding.line, finding.col))
                        yield finding

    def _hazard(self, module: ModuleSource, node: ast.AST) -> Finding | None:
        if isinstance(node, ast.Call):
            resolved = resolved_dotted(module, node.func)
            if resolved is not None:
                if resolved.startswith("time."):
                    return module.finding(
                        self.rule_id, node,
                        f"`{resolved}` inside a traced function reads the "
                        "host clock ONCE at trace time (a constant "
                        "thereafter) — and any rank-varying value "
                        "silently diverges the compiled program; compute "
                        "timestamps outside the traced region",
                    )
                if resolved.startswith(("random.", "numpy.random.")):
                    return module.finding(
                        self.rule_id, node,
                        f"seed-free `{resolved}` inside a traced function "
                        "draws per-rank host randomness at trace time — "
                        "the silent-divergence class; thread a "
                        "`jax.random` key through the function instead",
                    )
                if resolved == "os.getenv":
                    return module.finding(
                        self.rule_id, node,
                        "`os.getenv` inside a traced function is read "
                        "once at trace time and may differ across ranks; "
                        "resolve knobs outside the traced region",
                    )
            if isinstance(node.func, ast.Name) and node.func.id in (
                "print", "open", "input"
            ):
                return module.finding(
                    self.rule_id, node,
                    f"host side effect `{node.func.id}(...)` inside a "
                    "traced function runs at TRACE time, not per step — "
                    "use `jax.debug.print`/`io_callback`, or hoist it "
                    "out of the traced region",
                )
        elif isinstance(node, ast.Attribute) and isinstance(
            node.ctx, ast.Load
        ):
            if (
                node.attr == "environ"
                and resolved_dotted(module, node) == "os.environ"
            ):
                return module.finding(
                    self.rule_id, node,
                    "`os.environ` read inside a traced function is "
                    "evaluated once at trace time and may differ across "
                    "ranks; resolve knobs outside the traced region",
                )
        return None


# --- HVT004 -----------------------------------------------------------------

_KNOB_RE = re.compile(r"^HVT_[A-Z0-9_]+$")


@register_rule
class EnvKnobRegistry(Rule):
    rule_id = "HVT004"
    title = "HVT_* env knob not declared in analysis/registry.py"
    rationale = (
        "Every `HVT_*` knob must carry a registry row (type, default, "
        "subsystem, description) and be read through the typed accessors "
        "— the single source of truth `docs/ENVVARS.md` is generated "
        "from; undeclared literals and inline `os.environ` reads are how "
        "the knob surface drifted before PR 6."
    )
    provenance = "PR 6 (central knob registry; 19 inline reads migrated)."
    example = (
        "flag = os.environ.get(\"HVT_NEW_KNOB\")   # undeclared, inline\n"
    )

    def check(self, module: ModuleSource) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                if _KNOB_RE.match(node.value) and not registry.is_registered(
                    node.value
                ):
                    yield module.finding(
                        self.rule_id, node,
                        f"`{node.value}` is not declared in "
                        "horovod_tpu/analysis/registry.py — add a Knob "
                        "row (type, default, subsystem, description) and "
                        "regenerate docs/ENVVARS.md, so the knob surface "
                        "can't drift",
                    )
            elif isinstance(node, ast.Call):
                key = self._env_read_key(module, node)
                if key is not None:
                    yield module.finding(
                        self.rule_id, node,
                        f"inline `os.environ` read of `{key}` — go "
                        "through the typed registry accessors "
                        "(`horovod_tpu.analysis.registry.get_*`), which "
                        "carry the declared default and the "
                        "empty-string-is-unset contract",
                    )
            elif isinstance(node, ast.Subscript) and isinstance(
                node.ctx, ast.Load
            ):
                if (
                    resolved_dotted(module, node.value) == "os.environ"
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)
                    and _KNOB_RE.match(node.slice.value)
                ):
                    yield module.finding(
                        self.rule_id, node,
                        f"inline `os.environ[{node.slice.value!r}]` read "
                        "— go through the typed registry accessors "
                        "(`horovod_tpu.analysis.registry.get_*`)",
                    )

    @staticmethod
    def _env_read_key(module: ModuleSource, call: ast.Call) -> str | None:
        resolved = resolved_dotted(module, call.func)
        if resolved not in ("os.environ.get", "os.getenv"):
            return None
        if not call.args:
            return None
        key = call.args[0]
        if isinstance(key, ast.Constant) and isinstance(key.value, str):
            if _KNOB_RE.match(key.value):
                return key.value
        return None


# --- HVT005 -----------------------------------------------------------------

# The one function allowed to open artifact files for writing: it owns the
# tmp-name + os.replace + .sha256-sidecar discipline every checkpoint
# consumer (discovery, restore, elastic reassembly) verifies against.
_SANCTIONED_WRITERS = {"_atomic_write"}

_WRITE_MODES = ("w", "x", "+")


@register_rule
class CheckpointWriteAtomicity(Rule):
    rule_id = "HVT005"
    title = "truncating file write outside the atomic-write helper"
    rationale = (
        "A crash/preemption mid-write tears a truncating `open(..., "
        "'w')`; checkpoint artifacts additionally need the `.sha256` "
        "sidecar that discovery and restore verify. Artifact writes "
        "route through `checkpoint._atomic_write` (tmp name + "
        "os.replace + sidecar); deliberate non-artifact writers carry a "
        "noqa with the reason."
    )
    provenance = "PR 3 (checkpoint integrity; torn-bundle export fix PR 6)."
    example = (
        "with open(manifest_path, \"w\") as f:   # tears under SIGKILL\n"
        "    json.dump(manifest, f)\n"
    )

    def check(self, module: ModuleSource) -> Iterator[Finding]:
        for writer, node in self._truncating_opens(module.tree):
            if writer in _SANCTIONED_WRITERS:
                continue
            yield module.finding(
                self.rule_id, node,
                "truncating `open(..., 'w')` outside "
                "`checkpoint._atomic_write` — a crash/preemption "
                "mid-write tears the file, and checkpoint artifacts "
                "additionally need the `.sha256` sidecar that discovery "
                "and restore verify; route artifact writes through "
                "`checkpoint._atomic_write`/`save*` (non-artifact "
                "writes: suppress with `# hvt: noqa[HVT005]` and say "
                "why)",
            )

    @staticmethod
    def _truncating_opens(tree: ast.AST):
        """(enclosing function name, call node) for each truncating open."""

        def walk(node: ast.AST, fn_name: str | None):
            for child in ast.iter_child_nodes(node):
                child_fn = fn_name
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    child_fn = child.name
                if isinstance(child, ast.Call) and isinstance(
                    child.func, ast.Name
                ) and child.func.id == "open":
                    mode = None
                    if len(child.args) >= 2:
                        mode = child.args[1]
                    for kw in child.keywords:
                        if kw.arg == "mode":
                            mode = kw.value
                    if (
                        isinstance(mode, ast.Constant)
                        and isinstance(mode.value, str)
                        and any(c in mode.value for c in _WRITE_MODES)
                    ):
                        yield (fn_name, child)
                yield from walk(child, child_fn)

        yield from walk(tree, None)


# --- HVT006 -----------------------------------------------------------------

# The data layer the durable-stream-cursor contract covers: every feeding
# path here must derive its order purely from (seed, epoch, pass).
_DATA_LAYER_PREFIX = "horovod_tpu/data/"

# Draw/mutate functions on the GLOBAL numpy/stdlib RNGs — process-state-
# dependent, hence irreproducible across a resume.
_GLOBAL_RNG_FNS = {
    "rand", "randn", "randint", "random", "random_sample", "ranf",
    "sample", "choice", "shuffle", "permutation", "uniform", "normal",
    "standard_normal", "randrange", "getrandbits", "bytes", "seed",
}

# Generator constructors that MUST carry an explicit seed argument.
_SEEDED_CTORS = {"RandomState", "default_rng", "Generator", "Random",
                 "SeedSequence", "PCG64", "Philox"}


@register_rule
class DataLayerSeededRng(Rule):
    rule_id = "HVT006"
    title = "unseeded RNG in the data layer (durable-cursor determinism)"
    rationale = (
        "The durable-stream-cursor contract (data/stream.py) requires "
        "every feeding path's order to be a PURE function of (seed, "
        "epoch, pass); a global-RNG draw or a seedless generator inside "
        "`horovod_tpu/data/` makes a resumed byte stream irreproducible."
    )
    provenance = "PR 8 (byte-exact cross-epoch resume; StreamCursor)."
    example = (
        "def order(n):\n"
        "    return np.random.permutation(n)   # process-history RNG\n"
    )

    def check(self, module: ModuleSource) -> Iterator[Finding]:
        if not module.relpath.startswith(_DATA_LAYER_PREFIX):
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = resolved_dotted(module, node.func)
            if resolved is None:
                continue
            tail = resolved.split(".")[-1]
            on_np_random = resolved.startswith(
                ("numpy.random.", "np.random.")
            )
            on_stdlib_random = (
                resolved.startswith("random.")
                and resolved.count(".") == 1
            )
            if tail in _GLOBAL_RNG_FNS and (
                on_np_random or on_stdlib_random
            ):
                yield module.finding(
                    self.rule_id, node,
                    f"`{resolved}` draws from the GLOBAL RNG: the order "
                    "it produces depends on process history, so a "
                    "resumed stream cannot reproduce it — the durable-"
                    "cursor byte-identity contract (data/stream.py) "
                    "requires every data-layer draw to come from a "
                    "generator seeded purely by (seed, epoch, pass); "
                    "use np.random.RandomState(stream.epoch_seed(...))",
                )
            elif tail in _SEEDED_CTORS and (
                on_np_random or resolved == "random.Random"
            ):
                has_seed = bool(node.args) or any(
                    kw.arg in ("seed", "entropy") for kw in node.keywords
                )
                if not has_seed:
                    yield module.finding(
                        self.rule_id, node,
                        f"`{resolved}()` without an explicit seed draws "
                        "OS entropy — the stream it feeds is "
                        "irreproducible on resume; pass a seed derived "
                        "from (seed, epoch, pass) (`stream.epoch_seed`)",
                    )


# --- HVT007 -----------------------------------------------------------------


@register_rule
class CollectiveOrderDivergence(Rule):
    rule_id = "HVT007"
    title = "sibling branches issue different collective sequences"
    project_wide = True
    rationale = (
        "Collectives match up across ranks by SUBMISSION ORDER: when an "
        "`if`/`else` pair issues different collective sequences "
        "(directly or through helpers — callee sequences are inlined "
        "via the call graph) and the condition varies by rank, rank A's "
        "first collective pairs with rank B's different one — wrong "
        "results at best, a fleet-wide deadlock at worst (the "
        "mismatched-order class Horovod's coordinator exists to "
        "prevent). A branch whose condition is provably uniform across "
        "ranks (a config knob) is safe — suppress with a noqa stating "
        "the uniformity argument."
    )
    provenance = (
        "PR 9, pinning the Horovod timeline/stall-check class "
        "(arXiv:1802.05799 §4) before the ZeRO-1 composition refactor."
    )
    example = (
        "if phase == 0:           # rank-varying in practice\n"
        "    psum(x); allgather(y)\n"
        "else:\n"
        "    allgather(y); psum(x)   # same ops, different order\n"
    )

    def check(self, module: ModuleSource) -> Iterator[Finding]:
        return self.check_project(Project([module]))

    def check_project(self, project: Project) -> Iterator[Finding]:
        graph = project.callgraph()
        for module in project.modules:
            yield from self._check_module(module, graph)

    def _check_module(self, module, graph) -> Iterator[Finding]:
        def visit(node: ast.AST, class_path: tuple):
            for child in ast.iter_child_nodes(node):
                child_path = class_path
                if isinstance(child, ast.ClassDef):
                    child_path = class_path + (child.name,)
                if isinstance(child, ast.If) and child.orelse:
                    enclosing = ".".join(class_path) or None
                    seq_body = graph.sequence_of(
                        module, child.body, enclosing
                    )
                    seq_else = graph.sequence_of(
                        module, child.orelse, enclosing
                    )
                    if seq_body and seq_else and seq_body != seq_else:
                        yield module.finding(
                            self.rule_id, child,
                            "sibling branches issue different collective "
                            f"sequences — if: {list(seq_body)}, else: "
                            f"{list(seq_else)} (helper calls inlined) — "
                            "a rank-varying condition here submits "
                            "collectives in different orders across the "
                            "fleet and deadlocks it; issue the same "
                            "collectives in the same order on both "
                            "paths, or suppress with a noqa stating why "
                            "the condition is uniform across ranks",
                        )
                yield from visit(child, child_path)

        yield from visit(module.tree, ())


# --- HVT008 -----------------------------------------------------------------

# The accumulation/ZeRO composition surface: modules touching these names
# participate in the gradient-reduction contract ROADMAP item 3 composes
# (backward_passes_per_step x shard_update x hierarchy x elastic).
_COMPOSITION_SURFACE = re.compile(
    r"backward_passes_per_step|shard_update|accumulation_spec"
)
# The raw per-leaf wire operations a composition-surface module must not
# issue directly — `collectives.reduce_gradients` owns bucketing, the
# ICI/DCN two-hop, wire compression and (future) reduce-scatter layout.
_PER_LEAF_REDUCTIONS = {
    "psum", "psum_scatter", "hierarchical_psum", "quantized_group_sum",
}
# The one module allowed to spell the raw operations: the entry point.
_REDUCTION_ENTRY_MODULE = "horovod_tpu/parallel/collectives.py"

_TREE_MAP_TAILS = (".tree.map", ".tree_map", ".tree_multimap")


def _is_tree_map(module: ModuleSource, call: ast.Call) -> bool:
    resolved = resolved_dotted(module, call.func)
    if resolved is None:
        return False
    return resolved.endswith(_TREE_MAP_TAILS)


@register_rule
class ReductionComposition(Rule):
    rule_id = "HVT008"
    title = "per-leaf gradient reduction outside the bucketed entry point"
    rationale = (
        "In the accumulation/ZeRO surface (anything touching "
        "`backward_passes_per_step`, `shard_update` or "
        "`accumulation_spec`), gradient reductions must route through "
        "`collectives.reduce_gradients`: a raw per-leaf psum "
        "(`tree.map(lambda g: psum(g), grads)`) forfeits the "
        "dtype-homogeneous bucket fusion (÷K communication), skips the "
        "ICI/DCN two-hop and wire compression, and cannot become the "
        "ZeRO-1 reduce-scatter the composition refactor (ROADMAP item "
        "3, arXiv:2004.13336) lowers the boundary into. `psum_scatter` "
        "likewise belongs inside the entry point, where the sharded "
        "update layout is derived from the bucket spec."
    )
    provenance = (
        "PR 9, pinning PR 4's one-bucketed-reduction-per-step invariant "
        "as the guardrail for the ZeRO x accumulation composition."
    )
    example = (
        "grads = jax.tree.map(lambda g: lax.psum(g, 'data'), grads)\n"
        "# in a module that also wires backward_passes_per_step\n"
    )

    def check(self, module: ModuleSource) -> Iterator[Finding]:
        if module.relpath == _REDUCTION_ENTRY_MODULE:
            return
        if not _COMPOSITION_SURFACE.search(module.text):
            return
        defs_by_name = {
            n.name: n
            for n in ast.walk(module.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = terminal_name(node.func)
            if name == "psum_scatter":
                yield module.finding(
                    self.rule_id, node,
                    "raw `psum_scatter` in an accumulation/ZeRO-surface "
                    "module — the sharded-update reduction must go "
                    "through `collectives.reduce_gradients`, which owns "
                    "the bucket spec the reduce-scatter layout is "
                    "derived from (ROADMAP item 3)",
                )
                continue
            if not _is_tree_map(module, node) or not node.args:
                continue
            fn = node.args[0]
            body = None
            if isinstance(fn, ast.Lambda):
                body = fn
            elif isinstance(fn, ast.Name) and fn.id in defs_by_name:
                body = defs_by_name[fn.id]
            if body is None:
                continue
            for inner in ast.walk(body):
                if isinstance(inner, ast.Call) and terminal_name(
                    inner.func
                ) in _PER_LEAF_REDUCTIONS:
                    yield module.finding(
                        self.rule_id, node,
                        f"per-leaf `{terminal_name(inner.func)}` inside "
                        "`tree.map` in an accumulation/ZeRO-surface "
                        "module — route the gradient tree through "
                        "`collectives.reduce_gradients` (dtype-"
                        "homogeneous buckets, ICI/DCN two-hop, wire "
                        "compression); per-leaf collectives forfeit the "
                        "÷K bucket fusion and break the ZeRO-1 "
                        "reduce-scatter composition (ROADMAP item 3)",
                    )
                    break


# --- HVT009 -----------------------------------------------------------------

# The obs emission verbs (module-level functions AND Registry methods).
_OBS_EMITTERS = {"counter", "counter_set", "gauge", "histogram"}
# A call resolving into the obs package's emission surface:
# `obs.counter(...)`, `horovod_tpu.obs.gauge(...)`, `obs.core.histogram`.
_OBS_CALL_RE = re.compile(
    r"(^|\.)obs(\.[a-z_]+)*\.(counter|counter_set|gauge|histogram)$"
)
# Any call into the obs package at all (the traced-body check casts the
# wider net: render/collect/server calls are host effects too).
_OBS_ANY_RE = re.compile(r"(^|\.)obs(\.[a-z_]+)*\.[a-z_]+$")
# The span surface (horovod_tpu.trace): entering a span inside a traced
# body records the TRACE's wall time once, then replays as a constant —
# a timeline that looks live and is frozen (same HVT003 class).
_TRACE_SPAN_RE = re.compile(r"(^|\.)trace\.(span|emit_span|maybe_trace)$")


def _obs_metric_literal(module: ModuleSource, call: ast.Call):
    """The metric-name string literal of an obs emission call, or None
    when this call is not an emission site / the name is dynamic.

    Two shapes count as emission sites: calls resolving into the obs
    package's module-level verbs (import-alias-resolved), and
    ``<anything>.counter/gauge/...("hvt_*", ...)`` method calls — a
    `Registry` instance can't be typed statically, so the ``hvt_``
    naming convention is the discriminator (every declared metric
    carries it; no other API in this repo spells that shape)."""
    resolved = resolved_dotted(module, call.func)
    is_obs = resolved is not None and _OBS_CALL_RE.search(resolved)
    lit = None
    if call.args and isinstance(call.args[0], ast.Constant) and isinstance(
        call.args[0].value, str
    ):
        lit = call.args[0].value
    if not is_obs:
        if (
            isinstance(call.func, ast.Attribute)
            and call.func.attr in _OBS_EMITTERS
            and lit is not None
            and lit.startswith("hvt_")
        ):
            is_obs = True
    return lit if is_obs else None


@register_rule
class MetricRegistryDiscipline(Rule):
    rule_id = "HVT009"
    title = (
        "undeclared metric name, or obs/trace emission inside a traced "
        "body"
    )
    rationale = (
        "`horovod_tpu/obs/core.py` is the single declaration point for "
        "every exported metric series (the HVT004 pattern for the "
        "/metrics surface): an emission site naming an undeclared "
        "series either typos an existing one (a gauge that silently "
        "never lands where the dashboard looks) or ships a series "
        "missing from the catalog/HELP text — the instruments refuse it "
        "at runtime, this rule refuses it at lint time. And any "
        "`obs.*` call inside a jit/pjit/shard_map/scan body is a host "
        "effect executed ONCE at trace time (the HVT003 class): the "
        "gauge would freeze at its trace-time value while looking live. "
        "`trace.span`/`trace.emit_span` (the HVT_TRACE_DIR span stream "
        "hvt-trace merges into the fleet timeline) are the same hazard "
        "in span form: entered inside a traced body they clock the "
        "TRACE, write one record at compile time, and never fire again "
        "— a frozen span that poisons the merged timeline's clock "
        "anchors. Spans wrap the host-side call of the compiled step, "
        "never code inside it."
    )
    provenance = (
        "ISSUE 13 (one-pane-of-glass telemetry registry), extending the "
        "PR 6 registry discipline to the metric export surface; ISSUE "
        "15 (hvt-trace) added the traced-span check."
    )
    example = (
        "obs.gauge(\"hvt_stpe_ms\", v)   # typo'd, undeclared\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    obs.counter(\"hvt_optimizer_steps_total\")  # traced host "
        "effect\n"
        "    with trace.span(\"step\"):  # clocks the TRACE, fires once\n"
        "        x = x + 1\n"
        "    return x\n"
    )

    def check(self, module: ModuleSource) -> Iterator[Finding]:
        from horovod_tpu.obs import core as obs_core

        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            metric = _obs_metric_literal(module, node)
            if metric is not None and not obs_core.is_declared(metric):
                yield module.finding(
                    self.rule_id, node,
                    f"metric `{metric}` is not declared in "
                    "horovod_tpu/obs/core.py — add a MetricSpec row "
                    "(kind, help, subsystem, labels, buckets) so the "
                    "/metrics catalog stays the single source of truth "
                    "(the instruments refuse undeclared names at "
                    "runtime too)",
                )
        reported: set[tuple[int, int]] = set()
        for root in _collect_traced_roots(module):
            body = root.body if isinstance(root.body, list) else [root.body]
            for stmt in body:
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    resolved = resolved_dotted(module, node.func)
                    if resolved is None:
                        continue
                    is_obs = bool(_OBS_ANY_RE.search(resolved))
                    is_span = bool(_TRACE_SPAN_RE.search(resolved))
                    if not is_obs and not is_span:
                        continue
                    key = (node.lineno, node.col_offset)
                    if key in reported:
                        continue
                    reported.add(key)
                    if is_span:
                        yield module.finding(
                            self.rule_id, node,
                            f"`{resolved}(...)` entered inside a traced "
                            "(jit/scan/shard_map) function — the span "
                            "clocks the TRACE and writes exactly one "
                            "record at compile time (the HVT003 class), "
                            "poisoning the merged timeline's clock "
                            "anchors; wrap the host-side call of the "
                            "compiled step instead",
                        )
                        continue
                    yield module.finding(
                        self.rule_id, node,
                        f"`{resolved}(...)` inside a traced "
                        "(jit/scan/shard_map) function — metric "
                        "emission is a host effect that runs ONCE at "
                        "trace time (the HVT003 class), so the series "
                        "would freeze at its trace-time value while "
                        "looking live; emit from the host-side loop "
                        "around the step instead",
                    )


# --- HVT010 -----------------------------------------------------------------


@register_rule
class ScheduleDivergence(Rule):
    rule_id = "HVT010"
    title = "rank-feasible paths submit divergent collective schedules"
    project_wide = True
    rationale = (
        "Collectives pair up across ranks by SUBMISSION ORDER, and this "
        "framework deliberately dropped Horovod's runtime coordinator — "
        "so schedule agreement must hold STATICALLY along every path a "
        "rank can take. HVT001 sees a collective under a gate and HVT007 "
        "sees one if/else pair; neither sees the composed shapes: a "
        "rank-gated early RETURN that skips every later collective, a "
        "loop whose trip count reads the rank, or a gate passed into a "
        "helper as an argument (the cross-function case). "
        "`analysis/schedule.py` lifts the call graph's sequences and "
        "rank-taint facts into a schedule automaton per unit and "
        "enumerates the rank-feasible paths (rank-predicate-aware, "
        "loop/cycle-bounded, callee sequences inlined); any two paths of "
        "the same uniform configuration with different sequences "
        "deadlock a fleet whose ranks take different arms. Branches on "
        "provably-uniform values (an allgathered vote, a config knob) "
        "group paths into separate configurations and are never "
        "compared across — suppress genuinely uniform rank-syntax "
        "branches with a noqa stating the uniformity argument."
    )
    provenance = (
        "ISSUE 14 (hvt-sched), closing the verification gap between "
        "HVT007's sibling branches (PR 9) and `hvt-audit`'s single "
        "compiled program before the pipeline/MPMD and MoE all-to-all "
        "schedules land (ROADMAP items 2 and 4)."
    )
    example = (
        "def step(x):\n"
        "    if rank() == 0:\n"
        "        return x          # rank 0 skips the psum below\n"
        "    return psum(x)        # everyone else blocks in it forever\n"
    )

    def check(self, module: ModuleSource) -> Iterator[Finding]:
        return self.check_project(Project([module]))

    def check_project(self, project: Project) -> Iterator[Finding]:
        from horovod_tpu.analysis import schedule as schedule_mod

        graph = project.callgraph()
        checker = schedule_mod.checker_for(graph)
        for key, div in checker.check_all():
            unit = graph.units[key]
            a, b = div.path_a, div.path_b
            op_a, op_b = div.mismatch_ops()
            chain_a = "; ".join(d.describe() for d in a.rank_dec) or (
                "(no rank fork taken)"
            )
            chain_b = "; ".join(d.describe() for d in b.rank_dec) or (
                "(no rank fork taken)"
            )
            anchor = _line_anchor(unit, div.anchor_line)
            yield unit.module.finding(
                self.rule_id, anchor,
                f"rank-feasible paths through `{unit.name}` submit "
                f"DIVERGENT collective schedules — path A "
                f"[{chain_a}]: {list(a.seq)}; path B [{chain_b}]: "
                f"{list(b.seq)}; first mismatched submission at op "
                f"{div.mismatch_index}: `{op_a}` vs `{op_b}`. Ranks "
                "taking different arms submit mismatched collective "
                "orders and deadlock the fleet (the class Horovod's "
                "coordinator exists to prevent, arXiv:1802.05799); make "
                "every rank-feasible path submit the identical "
                "sequence, or suppress with a noqa stating why the "
                "condition is uniform across ranks",
            )


def _line_anchor(unit, line: int | None):
    """An AST-node-shaped anchor for a finding: the distinguishing fork's
    line when it lives in the unit's own module, else the unit's
    definition line (cross-module forks — the noqa then goes on the
    def)."""
    import types

    if line is not None:
        return types.SimpleNamespace(lineno=line, col_offset=0)
    node = unit.node
    return types.SimpleNamespace(
        lineno=getattr(node, "lineno", 1),
        col_offset=getattr(node, "col_offset", 0),
    )


# --- HVT011 -----------------------------------------------------------------

# The expert-parallel surface: modules touching the expert mesh axis /
# MoE routing vocabulary participate in the EP dispatch/combine contract
# (ROADMAP item 4) — their payload all-to-alls must route through the
# entry point where flight recording and the `alltoalls=N` audit grammar
# live.
_EP_SURFACE = re.compile(
    r"EXPERT_AXIS|n_experts|expert_choice|moe_|'expert'|\"expert\""
)


@register_rule
class ExpertAllToAllDiscipline(Rule):
    rule_id = "HVT011"
    title = "raw all-to-all outside the collectives entry point (EP surface)"
    rationale = (
        "MoE dispatch/combine all-to-alls are the EP axis's payload "
        "wire, and they must carry the same discipline as the gradient "
        "wire: routed through `collectives.all_to_all`, every submission "
        "is flight-recorded (the hvt-sched evidence trail) and the "
        "compiled program's payload all-to-all count is auditable "
        "(`hvt-audit --expect alltoalls=N`). A raw `lax.all_to_all` at "
        "the model layer is invisible to both — the HVT008 "
        "entry-point pattern applied to the expert-parallel surface."
    )
    provenance = (
        "ISSUE 14 satellite of ROADMAP item 4 (EP as a first-class "
        "axis), pinning the entry point before the MoE trainer path "
        "composes it."
    )
    example = (
        "dispatched = lax.all_to_all(x, 'expert', 0, 0)\n"
        "# in a module that also wires n_experts / EXPERT_AXIS\n"
    )

    def check(self, module: ModuleSource) -> Iterator[Finding]:
        if module.relpath == _REDUCTION_ENTRY_MODULE:
            return  # the entry point spells the raw op by definition
        if not _EP_SURFACE.search(module.text):
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            if terminal_name(node.func) != "all_to_all":
                continue
            resolved = resolved_dotted(module, node.func) or ""
            if resolved.startswith("horovod_tpu.parallel.collectives."):
                continue  # the sanctioned entry point itself
            yield module.finding(
                self.rule_id, node,
                "raw `all_to_all` in an expert-parallel-surface module — "
                "route the dispatch/combine payload through "
                "`collectives.all_to_all`, the EP entry point that "
                "flight-records every submission and keeps the compiled "
                "program auditable (`hvt-audit --expect alltoalls=N`); a "
                "model-layer `lax.all_to_all` is invisible to both "
                "(ROADMAP item 4's wire discipline)",
            )


# --- HVT012 -----------------------------------------------------------------

# The one module allowed to touch the raw environment for tunable knobs:
# the typed resolver every other read (and the autotuner's overrides)
# funnel through.
_REGISTRY_MODULE = "horovod_tpu/analysis/registry.py"


@register_rule
class TunableKnobResolverOnly(Rule):
    rule_id = "HVT012"
    title = "raw environ read of a tunable HVT_* knob outside the resolver"
    rationale = (
        "Knobs carrying registry `tunable=` domain metadata are the "
        "autotuner's search space: `hvt-tune` selects a config by "
        "writing the resolver-visible env surface (job env, probe "
        "legs), so a raw `os.environ`/`os.getenv` read that bypasses "
        "the typed accessors is a silent tuning blind spot — the site "
        "keeps its own notion of the knob's value, which the tuner can "
        "neither observe nor override. Sharper than HVT004's generic "
        "inline-read finding: a tunable-knob bypass is never "
        "baseline-able, because it breaks `hvt-tune` semantics, not "
        "just doc hygiene."
    )
    provenance = (
        "PR 19 (hvt-tune; the registry `tunable=` domains the search "
        "enumerates from — ROADMAP item 5)."
    )
    example = (
        "b = int(os.environ.get(\"HVT_BUCKET_BYTES\", \"0\"))   # tuner-blind\n"
    )

    def check(self, module: ModuleSource) -> Iterator[Finding]:
        if module.relpath == _REGISTRY_MODULE:
            return  # the resolver owns the raw read by definition
        for node in ast.walk(module.tree):
            key = None
            if isinstance(node, ast.Call):
                key = EnvKnobRegistry._env_read_key(module, node)
            elif isinstance(node, ast.Subscript) and isinstance(
                node.ctx, ast.Load
            ):
                if (
                    resolved_dotted(module, node.value) == "os.environ"
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)
                    and _KNOB_RE.match(node.slice.value)
                ):
                    key = node.slice.value
            if key is None or not registry.is_registered(key):
                continue
            if registry.knob(key).tunable is None:
                continue
            yield module.finding(
                self.rule_id, node,
                f"raw environ read of tunable knob `{key}` outside the "
                "registry resolver — `hvt-tune` selects this knob's "
                "value by writing the resolver-visible env surface, so "
                "a bypassing read is a silent tuning blind spot; go "
                "through `horovod_tpu.analysis.registry.get_*`",
            )


# --- HVT013 -----------------------------------------------------------------

# Dotted read entry points into corpus bytes (import-alias-resolved;
# `np.*` kept alongside `numpy.*` because resolved_dotted preserves the
# module alias the call site used — the HVT006 precedent).
_RAW_READ_DOTTED = {
    "numpy.load", "np.load", "numpy.memmap", "np.memmap",
    "numpy.lib.format.open_memmap", "mmap.mmap",
}

# Mode characters that make an `open()` a WRITER — HVT005's atomicity
# domain, not this rule's: the retried-read discipline covers reads.
_NON_READ_MODES = "wxa+"


@register_rule
class DataLayerRetriedReads(Rule):
    rule_id = "HVT013"
    title = "raw corpus read in the data layer outside read_with_retries"
    rationale = (
        "Dataset reads ride shared filesystems that blip (NFS/FUSE "
        "EIO/ESTALE, a shard vanishing mid-replace): an unwrapped read "
        "turns one transient fault into a dead rank, while "
        "`data.stream.read_with_retries` absorbs it under the bounded "
        "HVT_DATA_RETRIES x HVT_DATA_BACKOFF_S budget and escalates "
        "actionably when the budget is spent — the exact discipline the "
        "hvt-data service client's degrade-to-local failover is built "
        "on. Inside `horovod_tpu/data/`, every read-mode `open()` / "
        "`np.load` / `np.memmap` must run inside the wrapper (a lambda "
        "or a named function passed to it); write/append opens are "
        "HVT005's domain."
    )
    provenance = (
        "PR 20 (hvt-data distributed data service; the transient-I/O "
        "convention from PR 8 became checked)."
    )
    example = (
        "with open(index_path) as f:   # one NFS blip kills the rank\n"
        "    index = json.load(f)\n"
    )

    def check(self, module: ModuleSource) -> Iterator[Finding]:
        if not module.relpath.startswith(_DATA_LAYER_PREFIX):
            return
        wrapped = self._wrapped_nodes(module)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call) or id(node) in wrapped:
                continue
            what = self._raw_read(module, node)
            if what is None:
                continue
            yield module.finding(
                self.rule_id, node,
                f"raw {what} outside `stream.read_with_retries` — a "
                "transient filesystem fault here kills the rank instead "
                "of being absorbed by the bounded retry budget "
                "(HVT_DATA_RETRIES); wrap the read in a callable passed "
                "to `read_with_retries` (deliberate exceptions: "
                "suppress with `# hvt: noqa[HVT013]` and say why)",
            )

    @staticmethod
    def _wrapped_nodes(module: ModuleSource) -> set[int]:
        """ids of AST nodes lexically covered by the wrapper: every
        argument subtree of a `read_with_retries(...)` call (the lambda
        idiom), plus the bodies of functions whose NAME is passed as an
        argument to one (the named-closure idiom — filedataset's
        `read_index`)."""
        wrapped: set[int] = set()
        named_fns: set[str] = set()
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = resolved_dotted(module, node.func)
            name = (
                resolved.split(".")[-1] if resolved is not None
                else (node.func.id if isinstance(node.func, ast.Name)
                      else None)
            )
            if name != "read_with_retries":
                continue
            args = list(node.args) + [kw.value for kw in node.keywords]
            for arg in args:
                if isinstance(arg, ast.Name):
                    named_fns.add(arg.id)
                for sub in ast.walk(arg):
                    wrapped.add(id(sub))
        if named_fns:
            for node in ast.walk(module.tree):
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ) and node.name in named_fns:
                    for sub in ast.walk(node):
                        wrapped.add(id(sub))
        return wrapped

    @staticmethod
    def _raw_read(module: ModuleSource, call: ast.Call) -> str | None:
        """A human-readable description of the raw read this call
        performs, or None when it is not one."""
        if isinstance(call.func, ast.Name) and call.func.id == "open":
            mode = call.args[1] if len(call.args) >= 2 else None
            for kw in call.keywords:
                if kw.arg == "mode":
                    mode = kw.value
            if mode is None:
                return "read-mode `open()`"
            if (
                isinstance(mode, ast.Constant)
                and isinstance(mode.value, str)
                and not any(c in mode.value for c in _NON_READ_MODES)
            ):
                return f"read-mode `open(..., {mode.value!r})`"
            return None  # a writer (HVT005's domain) or a dynamic mode
        resolved = resolved_dotted(module, call.func)
        if resolved in _RAW_READ_DOTTED:
            return f"`{resolved}(...)`"
        return None


if __name__ == "__main__":
    # Regenerate docs/LINT_RULES.md (the ENVVARS.md pattern):
    #   python -m horovod_tpu.analysis.rules > docs/LINT_RULES.md
    import sys

    # Under `-m` this file IS `__main__`; alias it so iter_rules'
    # `import horovod_tpu.analysis.rules` finds the already-registered
    # rule set instead of executing the module a second time (which
    # would trip the duplicate-rule-id guard).
    sys.modules.setdefault(
        "horovod_tpu.analysis.rules", sys.modules[__name__]
    )
    from horovod_tpu.analysis.core import generate_rules_doc

    print(generate_rules_doc(), end="")
