"""Static analysis (`hvt-lint`) + the central env-knob registry.

The reliability spine's correctness invariants (collective symmetry,
lockstep teardown, trace purity, knob discipline, atomic artifact writes)
previously lived only in prose — this subsystem enforces them at lint
time. See `core` (framework), `rules` (HVT001-HVT005), `registry` (the
``HVT_*`` knob table ``docs/ENVVARS.md`` is generated from) and `cli`
(the ``hvt-lint`` entry point).

Import discipline: `registry` is stdlib-only and importable from the
earliest bootstrap (`runtime.init` reads knobs through it); nothing here
imports jax.
"""

from horovod_tpu.analysis import registry
from horovod_tpu.analysis.core import (
    Finding,
    LintResult,
    Rule,
    iter_rules,
    lint_paths,
    register_rule,
)

__all__ = [
    "registry",
    "Finding",
    "LintResult",
    "Rule",
    "iter_rules",
    "lint_paths",
    "register_rule",
]
