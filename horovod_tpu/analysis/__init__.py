"""Static analysis (`hvt-lint`/`hvt-audit`/`hvt-sched`) + the central
knob registry.

The reliability spine's correctness invariants (collective symmetry,
lockstep teardown, trace purity, knob discipline, atomic artifact writes)
previously lived only in prose — this subsystem enforces them at lint
time, since PR 9 at COMPILE time, and since ISSUE 14 across the WHOLE
PROGRAM. Three layers:

* Source analysis — `core` (framework: per-module + project-wide rules),
  `callgraph` (module-set call graph, collectives-effect summaries,
  rank-taint propagation), `rules` (HVT001-HVT011; ``docs/LINT_RULES.md``
  is generated from their metadata), `registry` (the ``HVT_*`` knob
  table ``docs/ENVVARS.md`` is generated from), `cli` (``hvt-lint``).
* Compiled-program audit — `hlo_audit` (structured StableHLO/HLO
  inspector: `collective_ops`, `gradient_reductions`,
  `payload_alltoalls`, `donated_args`, `assert_program`), `step_probe`
  (the canonical lowered trainer step + the EP dispatch/combine probe),
  `audit_cli` (``hvt-audit step/moe/file``).
* Schedule verification — `schedule` (rank-feasible path model checking
  over the call graph: rule HVT010, the entry-path automata report),
  `sched_cli` (``hvt-sched check/replay`` — the replay side cross-checks
  the per-rank flight records `horovod_tpu.flight` captures at runtime).

Import discipline: `registry`, `core`, `callgraph`, `rules`, `schedule`
and `hlo_audit` are stdlib-only and importable from the earliest
bootstrap (`runtime.init` reads knobs through the registry); only
`step_probe` (and `hvt-audit step/moe`) imports jax, lazily.
"""

from horovod_tpu.analysis import registry
from horovod_tpu.analysis.core import (
    Finding,
    LintResult,
    Project,
    Rule,
    generate_rules_doc,
    iter_rules,
    lint_paths,
    register_rule,
)

__all__ = [
    "registry",
    "Finding",
    "LintResult",
    "Project",
    "Rule",
    "generate_rules_doc",
    "iter_rules",
    "lint_paths",
    "register_rule",
]
