"""Static analysis (`hvt-lint`/`hvt-audit`) + the central knob registry.

The reliability spine's correctness invariants (collective symmetry,
lockstep teardown, trace purity, knob discipline, atomic artifact writes)
previously lived only in prose — this subsystem enforces them at lint
time, and since PR 9 at COMPILE time too. Two layers:

* Source analysis — `core` (framework: per-module + project-wide rules),
  `callgraph` (module-set call graph, collectives-effect summaries,
  rank-taint propagation), `rules` (HVT001-HVT008; ``docs/LINT_RULES.md``
  is generated from their metadata), `registry` (the ``HVT_*`` knob
  table ``docs/ENVVARS.md`` is generated from), `cli` (``hvt-lint``).
* Compiled-program audit — `hlo_audit` (structured StableHLO/HLO
  inspector: `collective_ops`, `gradient_reductions`, `donated_args`,
  `assert_program`), `step_probe` (the canonical lowered trainer step),
  `audit_cli` (``hvt-audit step/file``).

Import discipline: `registry`, `core`, `callgraph`, `rules` and
`hlo_audit` are stdlib-only and importable from the earliest bootstrap
(`runtime.init` reads knobs through the registry); only `step_probe`
(and `hvt-audit step`) imports jax, lazily.
"""

from horovod_tpu.analysis import registry
from horovod_tpu.analysis.core import (
    Finding,
    LintResult,
    Project,
    Rule,
    generate_rules_doc,
    iter_rules,
    lint_paths,
    register_rule,
)

__all__ = [
    "registry",
    "Finding",
    "LintResult",
    "Project",
    "Rule",
    "generate_rules_doc",
    "iter_rules",
    "lint_paths",
    "register_rule",
]
