"""`hvt-lint` — the distributed-correctness static analyzer CLI.

Usage::

    hvt-lint horovod_tpu/                 # human output, committed baseline
    hvt-lint --format json horovod_tpu/   # machine output (CI annotations)
    hvt-lint --select HVT001,HVT003 ...   # subset of rules
    hvt-lint --write-baseline ...         # grandfather current findings
    hvt-lint --list-rules
    hvt-lint --explain HVT007             # rationale/provenance/example

Exit codes (pre-commit-hook friendly):

* ``0`` — clean: zero findings, or every finding matches the committed
  baseline;
* ``1`` — at least one non-baselined finding (printed);
* ``2`` — usage error / unreadable input.

Also reachable as ``python -m horovod_tpu.launch lint ...`` (the
launcher's tooling surface) and ``python -m horovod_tpu.analysis``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from horovod_tpu.analysis import core


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="hvt-lint",
        description="AST-based distributed-correctness checks "
        "(collective symmetry, teardown discipline, tracing hazards, "
        "env-knob registry, checkpoint-write atomicity)",
    )
    parser.add_argument(
        "paths", nargs="*", default=["horovod_tpu"],
        help="files or directories to lint (default: horovod_tpu)")
    parser.add_argument(
        "--format", choices=("human", "json"), default="human")
    parser.add_argument(
        "--select", default=None, metavar="HVT001,HVT002,...",
        help="run only these rules")
    parser.add_argument(
        "--baseline", default=core.DEFAULT_BASELINE, metavar="PATH",
        help="baseline file of grandfathered findings "
        "(default: the committed horovod_tpu/analysis/baseline.json)")
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline: report every finding")
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="write the current findings to --baseline (justifications "
        "left as TODO for hand-editing) and exit 0")
    parser.add_argument(
        "--root", default=None, metavar="DIR",
        help="directory findings/baseline paths are relative to "
        "(default: cwd)")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument(
        "--explain", default=None, metavar="RULE",
        help="print one rule's rationale/provenance/example and exit "
        "(the docs/LINT_RULES.md entry, at the terminal)")
    args = parser.parse_args(argv)

    if args.list_rules:
        for cls in core.iter_rules():
            print(f"{cls.rule_id}  {cls.title}")
        return 0

    if args.explain:
        wanted = args.explain.strip().upper()
        for cls in core.iter_rules():
            if cls.rule_id == wanted:
                print(f"{cls.rule_id} — {cls.title}")
                if cls.rationale:
                    print(f"\nWhy: {cls.rationale}")
                if cls.provenance:
                    print(f"\nProvenance: {cls.provenance}")
                if cls.example:
                    print("\nFlags:\n" + "\n".join(
                        "    " + ln
                        for ln in cls.example.strip("\n").splitlines()
                    ))
                return 0
        print(f"hvt-lint: unknown rule {args.explain!r} — see "
              "--list-rules", file=sys.stderr)
        return 2

    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        print(
            f"hvt-lint: no such file or directory: {', '.join(missing)}",
            file=sys.stderr,
        )
        return 2

    select = args.select.split(",") if args.select else None
    baseline_path = None if args.no_baseline else args.baseline
    try:
        result = core.lint_paths(
            args.paths, root=args.root, select=select,
            baseline_path=None if args.write_baseline else baseline_path,
        )
    except (OSError, ValueError) as e:
        print(f"hvt-lint: {e}", file=sys.stderr)
        return 2
    if result.files == 0:
        # A gate that lints nothing must not report "clean" — a typo'd
        # path or a CI step run from the wrong directory stays loud.
        print(
            "hvt-lint: no python files under "
            f"{', '.join(args.paths)} — nothing was linted",
            file=sys.stderr,
        )
        return 2

    if args.write_baseline:
        try:
            existing = core.load_baseline(args.baseline)
        except ValueError as e:
            print(f"hvt-lint: {e}", file=sys.stderr)
            return 2
        core.write_baseline(
            args.baseline, result.findings,
            existing=existing, selected=select,
        )
        print(
            f"hvt-lint: wrote {len(result.findings)} finding(s) to "
            f"{args.baseline} — edit the TODO justifications before "
            "committing"
        )
        return 0

    if args.format == "json":
        print(json.dumps({
            "files": result.files,
            "findings": [f.to_json() for f in result.findings],
            "baselined": [f.to_json() for f in result.baselined],
        }, indent=2))
    else:
        for f in result.findings:
            print(f.format())
        summary = (
            f"hvt-lint: {len(result.findings)} finding(s) in "
            f"{result.files} file(s)"
        )
        if result.baselined:
            summary += f" ({len(result.baselined)} baselined)"
        print(summary)
    return 0 if result.clean else 1


def cli() -> None:
    """Console entry point (`hvt-lint`, pyproject.toml)."""
    raise SystemExit(main())


if __name__ == "__main__":
    raise SystemExit(main())
