"""Module-set call graph + collectives-effect summaries (hvt-lint v2).

Layer 1 of the interprocedural analyzer. The lexical rules (PR 6) see
one function at a time, so a collective hidden behind one helper hop
under a rank gate — exactly the PR 2 one-sided-teardown shape — sailed
through. This module gives the rules whole-program context:

* `CallGraph` — every function/method of the analyzed module set, keyed
  ``module.dotted:Class.method``, with call edges resolved through each
  module's import-alias map (``from .state import sync``,
  ``collectives.reduce_gradients``, ``self.helper`` within a class).
* Effect summaries — each unit is classified `ISSUES` (reaches a
  collective on an un-rank-gated path, directly or transitively),
  `RANK_GATED` (touches collectives only under rank gates — those sites
  are HVT001 findings in their own right), or `CLEAN`. Computed as a
  fixed point over the call edges, so taint propagates any number of
  hops; `witness(key)` returns one concrete chain to a collective for
  the finding message.
* Collective sequences — the ordered collective names a unit issues
  (callees inlined, cycle-guarded, capped), the input to HVT007's
  sibling-branch order-divergence check: two branches that issue
  collectives in different orders deadlock the fleet when the branch
  condition varies by rank (Horovod's mismatched-submission-order
  class, arXiv:1802.05799).

Resolution is deliberately conservative: a call that cannot be resolved
inside the analyzed module set (stdlib, jax, dynamic dispatch) simply
contributes no edge — taint never propagates through guesses, so the
interprocedural layer adds no false-positive surface beyond the lexical
rules'. Nested ``def``s are separate scopes (a def under a rank gate is
conditionally DEFINED, not executed) and are not call-graph-addressable;
lambda bodies, by contrast, are folded into their enclosing unit's
EFFECTS (the codebase uses lambdas as immediately-consumed callbacks —
``tree.map(lambda g: psum(g), ...)`` really issues the psum) while
staying a fresh scope for gate tracking, matching the lexical rule.
"""

from __future__ import annotations

import ast
import dataclasses

from horovod_tpu.analysis.core import (
    ModuleSource,
    dotted_name,
    resolved_dotted,
    terminal_name,
)

# --- classifications --------------------------------------------------------

CLEAN = "clean"
RANK_GATED = "rank-gated"
ISSUES = "issues-collective"

# --- shared collective / rank-gate vocabulary (HVT001 and the graph) --------

# Topology queries whose result gates single-writer code paths. Both the
# call forms (`runtime.rank()`, `jax.process_index()`, `hvt.is_primary()`)
# and the attribute forms (`world.process_rank`) count.
RANK_CALLS = {"rank", "process_rank", "process_index", "local_rank",
              "is_primary"}
RANK_ATTRS = {"process_rank", "process_index", "local_rank", "is_primary"}

# Collective/barrier operations that every rank of the world must issue
# together, matched by terminal callee name regardless of qualification.
COLLECTIVES_ANY = {
    "psum", "psum_scatter", "pmean", "hierarchical_psum",
    "allreduce", "allgather", "all_gather", "broadcast",
    "broadcast_object", "allgather_object", "broadcast_pytree",
    "pmean_pytree", "reduce_gradients", "barrier", "wait_at_barrier",
    "sync_global_devices", "quantized_group_sum", "all_to_all",
}
# Operations matched only when qualified, to dodge same-name methods on
# unrelated objects (`httpd.shutdown()`, `os.sync()`):
#   runtime.shutdown / runtime.reinit (also bare, via the import map) are
#   world-teardown barriers; `<...>.state.sync` / `ElasticState.sync` is
#   the elastic state collective.
QUALIFIED_COLLECTIVES = {
    "shutdown": {"runtime", "hvt", "horovod_tpu"},
    "reinit": {"runtime", "hvt", "horovod_tpu"},
    "sync": {"state", "elastic_state", "ElasticState"},
}


def is_rank_gated(test: ast.AST) -> bool:
    """True when a branch condition reads the process's rank/primacy."""
    for node in ast.walk(test):
        if isinstance(node, ast.Call):
            if terminal_name(node.func) in RANK_CALLS:
                return True
        elif isinstance(node, ast.Attribute) and isinstance(
            node.ctx, ast.Load
        ):
            if node.attr in RANK_ATTRS:
                return True
    return False


def collective_name(module: ModuleSource, call: ast.Call) -> str | None:
    """The display name of the collective `call` issues, or None."""
    name = terminal_name(call.func)
    if name is None:
        return None
    if name in COLLECTIVES_ANY:
        return dotted_name(call.func) or name
    if name in QUALIFIED_COLLECTIVES:
        resolved = resolved_dotted(module, call.func) or name
        segments = resolved.split(".")
        if len(segments) == 1 or segments[-2] in QUALIFIED_COLLECTIVES[name]:
            return dotted_name(call.func) or name
    return None


# --- scan results -----------------------------------------------------------


@dataclasses.dataclass
class CollectiveSite:
    """One collective issued inside a unit."""

    name: str            # display name (dotted where written so)
    node: ast.Call
    gate: tuple | None   # rank gate in force at the site, if any


@dataclasses.dataclass
class CallEdge:
    """One resolved call from a unit to another unit in the module set."""

    callee: str          # target unit key
    display: str         # the call as written (`helper`, `mod.helper`)
    node: ast.Call
    gate: tuple | None


@dataclasses.dataclass
class Unit:
    """One execution scope: a function/method, or a module's top level."""

    key: str                     # "pkg.mod:Class.fn" / "pkg.mod:<module>"
    name: str                    # bare display name
    module: ModuleSource
    node: ast.AST                # FunctionDef or Module
    body: list                   # the statements this unit executes
    enclosing_class: str | None  # dotted class path for self./cls. calls
    collectives: list = dataclasses.field(default_factory=list)
    calls: list = dataclasses.field(default_factory=list)


MODULE_UNIT = "<module>"
_SEQUENCE_CAP = 32


class CallGraph:
    """The module set's units, call edges, effects and sequences."""

    def __init__(self, modules: list[ModuleSource]):
        self.modules = list(modules)
        self.units: dict[str, Unit] = {}
        # modname -> set of local unit paths ("fn", "Class.fn") — the
        # dotted-name resolution table.
        self._locals: dict[str, set[str]] = {}
        for module in self.modules:
            self._collect_units(module)
        for unit in self.units.values():
            self._scan_unit(unit)
        self._effects: dict[str, str] | None = None
        self._witness: dict[str, list] = {}

    # --- unit collection ----------------------------------------------------

    def _collect_units(self, module: ModuleSource) -> None:
        modname = module.modname
        local = self._locals.setdefault(modname, set())

        def visit(node: ast.AST, class_path: tuple, addressable: bool):
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    path = ".".join(class_path + (child.name,))
                    key = f"{modname}:{path}"
                    if key in self.units:
                        # Redefinition (fallback def after a try-import,
                        # same-name overload): the FIRST def keeps the
                        # addressable key — call edges resolve to it —
                        # but the clash must still be SCANNED, like a
                        # nested def, or its collectives go dark.
                        n = 2
                        while f"{key}#{n}" in self.units:
                            n += 1
                        key = f"{key}#{n}"
                    else:
                        if addressable:
                            local.add(path)
                    self.units[key] = Unit(
                        key=key, name=child.name, module=module,
                        node=child, body=child.body,
                        enclosing_class=(
                            ".".join(class_path) if class_path else None
                        ),
                    )
                    # Nested defs are separate scopes and must still be
                    # SCANNED (a rank-gated collective inside one is a
                    # finding) but are not addressable by callers.
                    visit(child, class_path + (child.name,), False)
                elif isinstance(child, ast.ClassDef):
                    visit(child, class_path + (child.name,), addressable)

        visit(module.tree, (), True)
        mkey = f"{modname}:{MODULE_UNIT}"
        self.units[mkey] = Unit(
            key=mkey, name=MODULE_UNIT, module=module, node=module.tree,
            body=list(module.tree.body), enclosing_class=None,
        )

    # --- call resolution ----------------------------------------------------

    def _lookup_dotted(self, dotted: str) -> str | None:
        """``a.b.c.fn`` / ``a.b.C.m`` -> unit key, longest module prefix
        first."""
        parts = dotted.split(".")
        for i in range(len(parts) - 1, 0, -1):
            modname = ".".join(parts[:i])
            local = self._locals.get(modname)
            if local is None:
                continue
            path = ".".join(parts[i:])
            if path in local:
                return f"{modname}:{path}"
            return None  # module known, symbol not a def we saw
        return None

    def resolve_call(self, module: ModuleSource, call: ast.Call,
                     enclosing_class: str | None) -> str | None:
        """The unit key `call` dispatches to, or None when the target is
        outside the analyzed module set (no edge — taint never guesses)."""
        f = call.func
        modname = module.modname
        if isinstance(f, ast.Name):
            if f.id in self._locals.get(modname, ()):
                return f"{modname}:{f.id}"
            origin = module.import_map().get(f.id)
            if origin and "." in origin:
                return self._lookup_dotted(origin)
            return None
        if isinstance(f, ast.Attribute):
            dotted = dotted_name(f)
            if dotted is None:
                return None
            head, _, rest = dotted.partition(".")
            if head in ("self", "cls") and enclosing_class and rest:
                path = f"{enclosing_class}.{rest}"
                if path in self._locals.get(modname, ()):
                    return f"{modname}:{path}"
                return None
            resolved = resolved_dotted(module, f)
            if resolved:
                return self._lookup_dotted(resolved)
        return None

    # --- per-unit scan (gate-tracked, lexically faithful to HVT001) ---------

    def _scan_unit(self, unit: Unit) -> None:
        module = unit.module

        def record_call(node: ast.Call, gate):
            name = collective_name(module, node)
            if name is not None:
                unit.collectives.append(CollectiveSite(name, node, gate))
                return
            callee = self.resolve_call(module, node, unit.enclosing_class)
            if callee is not None and callee != unit.key:
                display = dotted_name(node.func) or terminal_name(
                    node.func
                ) or "?"
                unit.calls.append(CallEdge(callee, display, node, gate))

        def visit(node: ast.AST, gate):
            if isinstance(node, ast.Call):
                record_call(node, gate)
                for child in ast.iter_child_nodes(node):
                    visit(child, gate)
                return
            if isinstance(node, (ast.If, ast.While)):
                branch_gate = gate
                if is_rank_gated(node.test):
                    branch_gate = (node.lineno, module.line_at(node.lineno))
                visit(node.test, gate)
                for child in node.body:
                    visit(child, branch_gate)
                for child in node.orelse:
                    visit(child, branch_gate)
                return
            if isinstance(node, ast.IfExp):
                branch_gate = gate
                if is_rank_gated(node.test):
                    branch_gate = (node.lineno, module.line_at(node.lineno))
                visit(node.test, gate)
                visit(node.body, branch_gate)
                visit(node.orelse, branch_gate)
                return
            if isinstance(node, ast.BoolOp):
                # `rank() == 0 and collective()`: operands after a
                # rank-gated one are short-circuit-conditional on it.
                seen_gate = gate
                for value in node.values:
                    visit(value, seen_gate)
                    if seen_gate is None and is_rank_gated(value):
                        seen_gate = (
                            node.lineno, module.line_at(node.lineno)
                        )
                return
            if isinstance(node, ast.Lambda):
                # Fresh gate scope (a lambda under a gate is defined, not
                # executed there) but SAME unit: its collectives count
                # toward this unit's effects — lambdas here are
                # immediately-consumed callbacks (tree.map, scan).
                visit(node.body, None)
                return
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                return  # separate unit (or unaddressable nested scope)
            if isinstance(node, ast.ClassDef):
                # Methods are separate units; class-level statements run
                # at import in a fresh gate scope (lexical-rule parity).
                for child in node.body:
                    if not isinstance(
                        child,
                        (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef),
                    ):
                        visit(child, None)
                return
            for child in ast.iter_child_nodes(node):
                visit(child, gate)

        for stmt in unit.body:
            visit(stmt, None)

    # --- effect summaries (fixed point over call edges) ---------------------

    def effects(self) -> dict[str, str]:
        """key -> CLEAN | RANK_GATED | ISSUES. ISSUES means an un-gated
        path through the unit reaches a collective (possibly via callees);
        RANK_GATED means collectives are reachable only under rank gates
        (each such site is an HVT001 finding at its own location)."""
        if self._effects is not None:
            return self._effects
        effects: dict[str, str] = {}
        for key, unit in self.units.items():
            direct = [s for s in unit.collectives if s.gate is None]
            if direct:
                effects[key] = ISSUES
                self._witness[key] = [direct[0].name]
            elif unit.collectives:
                effects[key] = RANK_GATED
            else:
                effects[key] = CLEAN
        changed = True
        while changed:
            changed = False
            for key, unit in self.units.items():
                if effects[key] == ISSUES:
                    continue
                for edge in unit.calls:
                    if edge.gate is None and effects.get(
                        edge.callee
                    ) == ISSUES:
                        effects[key] = ISSUES
                        self._witness[key] = [edge.display] + self._witness[
                            edge.callee
                        ]
                        changed = True
                        break
                else:
                    if effects[key] == CLEAN and any(
                        effects.get(e.callee) == ISSUES for e in unit.calls
                    ):
                        effects[key] = RANK_GATED
        self._effects = effects
        return effects

    def effect(self, key: str) -> str:
        return self.effects().get(key, CLEAN)

    def witness(self, key: str) -> list:
        """One concrete chain of names from `key` to a collective —
        ``['helper_b', 'psum']`` — for finding messages. Empty unless
        the unit's effect is ISSUES."""
        self.effects()
        return list(self._witness.get(key, ()))

    # --- collective sequences (HVT007's input) ------------------------------

    def sequence_of(self, module: ModuleSource, nodes,
                    enclosing_class: str | None, _stack=None) -> tuple:
        """Ordered collective names issued by `nodes` (statement list or
        single AST node), with resolved callees' sequences inlined
        (recursion cycle-guarded, capped at _SEQUENCE_CAP). Both arms of
        internal branches contribute in source order — a deliberate
        flattening: the sequence is an order WITNESS, not an exact
        trace."""
        stack = _stack or set()
        out: list = []

        def visit(node: ast.AST):
            if len(out) >= _SEQUENCE_CAP:
                return
            if isinstance(node, ast.Call):
                name = collective_name(module, node)
                if name is not None:
                    # Key sequences on the terminal op name: `lax.psum`
                    # and `psum` are the same wire operation.
                    out.append(terminal_name(node.func) or name)
                else:
                    callee = self.resolve_call(module, node,
                                               enclosing_class)
                    if callee is not None and callee not in stack:
                        unit = self.units.get(callee)
                        if unit is not None:
                            # Guard RECURSION only: pop after inlining,
                            # so a helper called twice as siblings
                            # contributes its sequence twice (the whole
                            # point of an order witness).
                            stack.add(callee)
                            for stmt in unit.body:
                                sub = self.sequence_of(
                                    unit.module, stmt,
                                    unit.enclosing_class, _stack=stack,
                                )
                                out.extend(sub)
                                if len(out) >= _SEQUENCE_CAP:
                                    break
                            stack.discard(callee)
                for child in ast.iter_child_nodes(node):
                    visit(child)
                return
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                return
            for child in ast.iter_child_nodes(node):
                visit(child)

        if isinstance(nodes, (list, tuple)):
            for n in nodes:
                visit(n)
        else:
            visit(nodes)
        return tuple(out[:_SEQUENCE_CAP])

    # --- classification export ---------------------------------------------

    def summary(self) -> dict:
        """key -> classification, for tooling/tests."""
        return dict(self.effects())
