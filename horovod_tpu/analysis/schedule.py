"""Whole-program collective-schedule verification (hvt-sched, analysis
layer 3 — rule HVT010).

Horovod's coordinator forces every rank to submit collectives in an
agreed order because one disagreement deadlocks the fleet
(arXiv:1802.05799 §4). This framework dropped the coordinator: schedule
agreement is a STATIC property of the SPMD program — which the first two
analysis layers only check locally. HVT001 flags a collective *under* a
rank gate; HVT007 compares the two arms of *one* ``if``; ``hvt-audit``
checks *one compiled program* is well-formed. None of them can see the
composed, cross-function failure shapes:

* a rank-gated **early return** that skips every LATER collective
  (``if rank() == 0: return`` ... ``psum(x)``) — no collective under the
  gate, no sibling arm, one compiled program per rank that is locally
  fine;
* **loop-count divergence** — a loop whose trip count reads the rank
  (``for _ in range(rank()): psum(x)``) submits a different NUMBER of
  collectives per rank;
* the **cross-function gate**: ``step`` passes ``rank() == 0`` into a
  helper whose branch on that parameter issues different sequences —
  the gate and the divergence live in different functions (or modules),
  invisible to both the lexical gate detector and HVT007's
  sibling-branch comparison.

This module lifts the call graph's per-unit collective sequences and
rank-taint facts into a *schedule automaton* per unit: every statement
list is enumerated into the set of **rank-feasible paths** — at each
branch whose condition is rank-varying (a syntactic rank read, a local
tainted by one, a parameter bound to a rank-varying argument at an
inlined call site, or a call to a helper that *returns* a rank-varying
value), the enumeration forks, because two ranks of one fleet can take
different arms. Branches on anything else are UNIFORM — every rank
agrees on the arm — so they key a *configuration*, not a fork: paths are
grouped by their uniform-decision assignment and only same-configuration
path pairs are compared (this is what keeps `elastic/state.py`'s
uniform transport pick — both ranks provably branch on the same
allgathered votes — out of the findings). Any same-configuration pair
whose collective sequences differ is an HVT010 finding carrying both
witness chains and the first mismatched op.

Callee sequences are inlined through the module-set call graph
(cycle-guarded, depth- and path-capped); loops are bounded to the
{0 iterations, 1 iteration} pair when rank-varying — the smallest
witness of a count divergence — and one pass otherwise. The analysis is
deliberately lexical about rank-ness, like every rule here: a
rank-varying value laundered through a container or attribute is not
tracked, and `IfExp`/`BoolOp` collectives are flattened (their gated
forms are HVT001's, not this rule's). Soundness direction: uniform
misclassification can only SUPPRESS findings, never invent them.

The real entry paths the ISSUE names — the `Trainer` step/fit loops,
`ElasticState.commit/sync`, the `elastic.run` rescale boundary, and
checkpoint save/broadcast — are declared in `ENTRY_PATHS` and
summarized by `entry_report` (the ``hvt-sched check`` banner); the rule
itself verifies EVERY unit, entries included, so a divergence is
reported at the unit that owns the rank fork.
"""

from __future__ import annotations

import ast
import dataclasses

from horovod_tpu.analysis.callgraph import (
    MODULE_UNIT,
    RANK_ATTRS,
    RANK_CALLS,
    CallGraph,
    collective_name,
)
from horovod_tpu.analysis.core import terminal_name

#: Bounds. Exceeding a cap truncates deterministically (first paths kept,
#: sequences clipped): completeness degrades, false positives do not.
PATH_CAP = 64
SEQ_CAP = 32
DEPTH_CAP = 8

#: The real whole-program entry paths (module dotted name, unit path) —
#: where a schedule disagreement actually deadlocks a fleet: the trainer
#: loops, the elastic commit/sync boundary, the rescale driver, and the
#: checkpoint save/broadcast surface. `entry_report` summarizes their
#: automata; the project-wide rule checks every unit regardless.
ENTRY_PATHS = (
    ("horovod_tpu.training.trainer", "Trainer.fit"),
    ("horovod_tpu.training.trainer", "Trainer.evaluate"),
    ("horovod_tpu.elastic.state", "ElasticState.commit"),
    ("horovod_tpu.elastic.state", "ElasticState.sync"),
    ("horovod_tpu.elastic.state", "ElasticState.gather_committed"),
    ("horovod_tpu.elastic.rescale", "run"),
    ("horovod_tpu.checkpoint", "save_checkpoint"),
    ("horovod_tpu.checkpoint", "restore_latest_and_broadcast"),
)


@dataclasses.dataclass(frozen=True)
class Decision:
    """One branch choice along a path."""

    relpath: str   # module of the branch
    line: int
    cond: str      # the branch condition's source line, stripped
    arm: str       # "if-arm" | "else-arm" | "0-iterations" | ...
    rank: bool     # rank-feasible fork (True) vs uniform configuration

    def describe(self) -> str:
        return f"{self.relpath}:{self.line} `{self.cond}` -> {self.arm}"


@dataclasses.dataclass
class Path:
    """One rank-feasible path through a unit's schedule automaton."""

    seq: tuple = ()        # collective names, submission order
    rank_dec: tuple = ()   # Decision(rank=True) choices along the way
    uni_key: tuple = ()    # hashable uniform-configuration assignment
    returned: bool = False

    def child(self, **kw) -> "Path":
        return dataclasses.replace(self, **kw)


@dataclasses.dataclass
class Divergence:
    """Two same-configuration paths with different collective sequences."""

    unit_key: str
    path_a: Path
    path_b: Path
    anchor_line: int | None  # line in the unit's module (None = def line)

    @property
    def mismatch_index(self) -> int:
        a, b = self.path_a.seq, self.path_b.seq
        for i in range(max(len(a), len(b))):
            if i >= len(a) or i >= len(b) or a[i] != b[i]:
                return i
        return 0

    def mismatch_ops(self) -> tuple:
        i = self.mismatch_index
        a = self.path_a.seq[i] if i < len(self.path_a.seq) else "(nothing)"
        b = self.path_b.seq[i] if i < len(self.path_b.seq) else "(nothing)"
        return a, b


def _first_differing_rank_decision(a: Path, b: Path):
    """The fork where the two witness paths part ways — the natural
    anchor (and noqa site) for the finding."""
    for da, db in zip(a.rank_dec, b.rank_dec):
        if da != db:
            return da
    short = min(len(a.rank_dec), len(b.rank_dec))
    longer = a.rank_dec if len(a.rank_dec) > len(b.rank_dec) else b.rank_dec
    return longer[short] if len(longer) > short else None


def checker_for(graph: CallGraph) -> "ScheduleChecker":
    """The graph's memoized `ScheduleChecker` — the HVT010 rule and the
    entry-path report share one instance per call graph, so `hvt-sched
    check` enumerates each unit's paths exactly once."""
    checker = getattr(graph, "_schedule_checker", None)
    if checker is None:
        checker = ScheduleChecker(graph)
        graph._schedule_checker = checker
    return checker


class ScheduleChecker:
    """Path model checking over one `CallGraph`'s units."""

    def __init__(self, graph: CallGraph):
        self.graph = graph
        self._paths: dict = {}       # (key, tainted) -> list[Path]
        self._verdict: dict = {}     # key -> Divergence | None (taint-free)
        self._rank_returners: set | None = None

    # --- rank-taint of return values (the cross-function gate's fuel) ----

    def _returns_rank(self, key: str) -> bool:
        """Whether the unit returns a rank-varying value (``return
        rank() == 0`` — directly, or through a callee that does).
        Fixed point over the call graph, lexical about rank reads."""
        if self._rank_returners is None:
            members: set = set()

            def direct(unit) -> bool:
                for node in ast.walk(unit.node):
                    if isinstance(node, ast.Return) and node.value is not None:
                        if self._expr_reads_rank(node.value):
                            return True
                return False

            for k, unit in self.graph.units.items():
                if unit.name != MODULE_UNIT and direct(unit):
                    members.add(k)
            changed = True
            while changed:
                changed = False
                for k, unit in self.graph.units.items():
                    if k in members or unit.name == MODULE_UNIT:
                        continue
                    for node in ast.walk(unit.node):
                        if not (
                            isinstance(node, ast.Return)
                            and node.value is not None
                        ):
                            continue
                        for call in ast.walk(node.value):
                            if not isinstance(call, ast.Call):
                                continue
                            callee = self.graph.resolve_call(
                                unit.module, call, unit.enclosing_class
                            )
                            if callee in members:
                                members.add(k)
                                changed = True
                                break
                        if k in members:
                            break
            self._rank_returners = members
        return key in self._rank_returners

    @staticmethod
    def _expr_reads_rank(expr: ast.AST) -> bool:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                if terminal_name(node.func) in RANK_CALLS:
                    return True
            elif isinstance(node, ast.Attribute) and isinstance(
                node.ctx, ast.Load
            ):
                if node.attr in RANK_ATTRS:
                    return True
        return False

    def _rank_varying(self, unit, expr: ast.AST, tainted: set) -> bool:
        """Whether ``expr``'s value can differ across ranks: a syntactic
        rank read, a tainted local/parameter, or a call into a unit that
        returns a rank-varying value."""
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                if terminal_name(node.func) in RANK_CALLS:
                    return True
                callee = self.graph.resolve_call(
                    unit.module, node, unit.enclosing_class
                )
                if callee is not None and self._returns_rank(callee):
                    return True
            elif isinstance(node, ast.Attribute) and isinstance(
                node.ctx, ast.Load
            ):
                if node.attr in RANK_ATTRS:
                    return True
            elif isinstance(node, ast.Name) and isinstance(
                node.ctx, ast.Load
            ):
                if node.id in tainted:
                    return True
        return False

    # --- path enumeration -------------------------------------------------

    def unit_paths(self, key: str, tainted: frozenset = frozenset(),
                   _depth: int = 0, _stack=None) -> list:
        """The unit's rank-feasible paths (capped, cached). ``tainted``
        names parameters bound to rank-varying arguments at the inlining
        call site."""
        stack = _stack if _stack is not None else set()
        cache_key = (key, tainted)
        cached = self._paths.get(cache_key)
        if cached is not None:
            return cached
        unit = self.graph.units.get(key)
        if unit is None or _depth > DEPTH_CAP or key in stack:
            return [Path()]
        stack.add(key)
        env = set(tainted)
        paths = self._eval_block(
            unit, unit.body, env, [Path()], _depth, stack
        )
        stack.discard(key)
        self._paths[cache_key] = paths
        return paths

    def _cap(self, paths: list) -> list:
        return paths[:PATH_CAP]

    def _eval_block(self, unit, stmts, env, paths, depth, stack) -> list:
        for stmt in stmts:
            done = [p for p in paths if p.returned]
            alive = [p for p in paths if not p.returned]
            if not alive:
                return self._cap(done)
            alive = self._eval_stmt(unit, stmt, env, alive, depth, stack)
            paths = self._cap(done + alive)
        return paths

    def _decision(self, unit, node, arm: str, rank: bool) -> Decision:
        return Decision(
            relpath=unit.module.relpath, line=node.lineno,
            cond=unit.module.line_at(node.lineno), arm=arm, rank=rank,
        )

    def _fork(self, unit, node, env, paths, depth, stack, arms) -> list:
        """Fork ``paths`` over ``arms`` = [(arm_name, stmt_list), ...].
        ``rank=True`` forks append to rank_dec; uniform forks key the
        configuration (uni_key)."""
        rank = arms[0][2]
        out = []
        for arm_name, body, _rank in arms:
            dec = self._decision(unit, node, arm_name, rank)
            branch = [
                p.child(
                    rank_dec=p.rank_dec + (dec,) if rank else p.rank_dec,
                    uni_key=p.uni_key if rank else p.uni_key + (
                        (dec.relpath, dec.line, arm_name),
                    ),
                )
                for p in paths
            ]
            out.extend(
                self._eval_block(unit, body, env, branch, depth, stack)
            )
        return self._cap(out)

    def _contains_fork_material(self, unit, nodes, env) -> bool:
        """Whether a statement list can change path STRUCTURE: returns,
        raises, or (possibly nested) rank-varying branch points. Uniform
        branches free of these are flattened instead of forked — the
        HVT007 order-witness treatment — which keeps path counts small
        in branch-heavy real code."""
        for root in nodes:
            for node in ast.walk(root):
                if isinstance(node, (ast.Return, ast.Raise)):
                    return True
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue
                if isinstance(node, (ast.If, ast.While)):
                    if self._rank_varying(unit, node.test, env):
                        return True
                if isinstance(node, ast.For):
                    if self._rank_varying(unit, node.iter, env):
                        return True
        return False

    def _eval_stmt(self, unit, stmt, env, paths, depth, stack) -> list:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return paths  # separate units / import-time class bodies
        if isinstance(stmt, (ast.Return, ast.Raise)):
            if getattr(stmt, "value", None) is not None:
                paths = self._eval_expr(
                    unit, stmt.value, env, paths, depth, stack
                )
            if isinstance(stmt, ast.Raise) and stmt.exc is not None:
                paths = self._eval_expr(
                    unit, stmt.exc, env, paths, depth, stack
                )
            return [p.child(returned=True) for p in paths]
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = stmt.value
            if value is not None:
                paths = self._eval_expr(unit, value, env, paths, depth,
                                        stack)
                tainted_value = self._rank_varying(unit, value, env)
                targets = (
                    stmt.targets if isinstance(stmt, ast.Assign)
                    else [stmt.target]
                )
                for t in targets:
                    if not isinstance(t, ast.Name):
                        continue
                    if tainted_value:
                        env.add(t.id)
                    elif not isinstance(stmt, ast.AugAssign):
                        # A plain rebind to a uniform value CLEARS the
                        # taint (soundness direction: stale taint would
                        # INVENT divergences on provably-uniform
                        # branches); += keeps it — the old rank-varying
                        # value still feeds the result.
                        env.discard(t.id)
            return paths
        if isinstance(stmt, ast.If):
            if self._rank_varying(unit, stmt.test, env):
                paths = self._eval_expr(unit, stmt.test, env, paths,
                                        depth, stack)
                return self._fork(unit, stmt, env, paths, depth, stack, [
                    ("if-arm", stmt.body, True),
                    ("else-arm", stmt.orelse, True),
                ])
            paths = self._eval_expr(unit, stmt.test, env, paths, depth,
                                    stack)
            if self._contains_fork_material(
                unit, stmt.body, env
            ) or self._contains_fork_material(unit, stmt.orelse, env):
                return self._fork(unit, stmt, env, paths, depth, stack, [
                    ("if-arm", stmt.body, False),
                    ("else-arm", stmt.orelse, False),
                ])
            # Straight-line arms: flatten in source order (HVT007's
            # order-witness treatment) — identical on every path, so
            # uniform content can never read as divergence.
            paths = self._eval_block(unit, stmt.body, env, paths, depth,
                                     stack)
            return self._eval_block(unit, stmt.orelse, env, paths, depth,
                                    stack)
        if isinstance(stmt, ast.While):
            paths = self._eval_expr(unit, stmt.test, env, paths, depth,
                                    stack)
            if self._rank_varying(unit, stmt.test, env):
                # Loop/cycle bound: {0, 1} iterations is the smallest
                # witness of a rank-varying trip count.
                return self._fork(unit, stmt, env, paths, depth, stack, [
                    ("0-iterations", [], True),
                    (">=1-iteration", stmt.body, True),
                ])
            return self._eval_block(
                unit, stmt.body + stmt.orelse, env, paths, depth, stack
            )
        if isinstance(stmt, ast.For):
            paths = self._eval_expr(unit, stmt.iter, env, paths, depth,
                                    stack)
            if self._rank_varying(unit, stmt.iter, env):
                return self._fork(unit, stmt, env, paths, depth, stack, [
                    ("0-iterations", [], True),
                    (">=1-iteration", stmt.body, True),
                ])
            return self._eval_block(
                unit, stmt.body + stmt.orelse, env, paths, depth, stack
            )
        if isinstance(stmt, ast.Try):
            # The no-exception path is the schedule under verification;
            # handlers fork a uniform "configuration" each (an exception
            # either hits every rank of an SPMD step or is a crash, not
            # a schedule question — and `except: return` must not kill
            # the straight-line path).
            arms = [("no-exception", stmt.body + stmt.orelse, False)]
            for i, handler in enumerate(stmt.handlers):
                arms.append((f"handler-{i}", list(handler.body), False))
            paths = self._fork(unit, stmt, env, paths, depth, stack, arms)
            return self._eval_block(unit, stmt.finalbody, env, paths,
                                    depth, stack)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                paths = self._eval_expr(unit, item.context_expr, env,
                                        paths, depth, stack)
            return self._eval_block(unit, stmt.body, env, paths, depth,
                                    stack)
        # Everything else: evaluate contained expressions generically.
        return self._eval_expr(unit, stmt, env, paths, depth, stack)

    def _eval_expr(self, unit, node, env, paths, depth, stack) -> list:
        """Collect collective submissions (and inline resolved callees)
        from an expression tree, in the callgraph scanner's order."""
        if node is None:
            return paths
        if isinstance(node, ast.Call):
            name = collective_name(unit.module, node)
            # Arguments evaluate before the call.
            for child in ast.iter_child_nodes(node):
                paths = self._eval_expr(unit, child, env, paths, depth,
                                        stack)
            if name is not None:
                op = terminal_name(node.func) or name
                return [
                    p if len(p.seq) >= SEQ_CAP
                    else p.child(seq=p.seq + (op,))
                    for p in paths
                ]
            callee = self.graph.resolve_call(
                unit.module, node, unit.enclosing_class
            )
            if callee is not None:
                return self._inline_call(unit, node, callee, env, paths,
                                         depth, stack)
            return paths
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return paths
        if isinstance(node, ast.Lambda):
            # Callgraph parity: lambdas are immediately-consumed
            # callbacks; their collectives count for this unit.
            return self._eval_expr(unit, node.body, env, paths, depth,
                                   stack)
        for child in ast.iter_child_nodes(node):
            paths = self._eval_expr(unit, child, env, paths, depth, stack)
        return paths

    def _inline_call(self, unit, call, callee_key, env, paths, depth,
                     stack) -> list:
        """Cartesian-extend ``paths`` with the callee's path set,
        propagating rank taint into parameters bound to rank-varying
        arguments. A taint-free callee that is DIVERGENT on its own
        contributes one representative path — its divergence is its own
        finding, not every caller's."""
        callee = self.graph.units.get(callee_key)
        if callee is None:
            return paths
        tainted = self._tainted_params(unit, call, callee, env)
        sub = self.unit_paths(callee_key, tainted, depth + 1, stack)
        if not tainted and len(sub) > 1 and callee_key not in stack:
            if self._divergence_of(callee_key, _stack=stack) is not None:
                sub = sub[:1]
        out = []
        for p in paths:
            for s in sub:
                seq = (p.seq + s.seq)[:SEQ_CAP]
                out.append(p.child(
                    seq=seq,
                    rank_dec=p.rank_dec + s.rank_dec,
                    uni_key=p.uni_key + s.uni_key,
                ))
        return self._cap(out)

    def _tainted_params(self, unit, call, callee, env) -> frozenset:
        """Parameter names of ``callee`` bound to rank-varying argument
        expressions at this call site."""
        fn = callee.node
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return frozenset()
        params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
        if params and params[0] in ("self", "cls") and callee.enclosing_class:
            params = params[1:]
        tainted = set()
        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                break
            if i < len(params) and self._rank_varying(unit, arg, env):
                tainted.add(params[i])
        all_params = set(params) | {
            a.arg for a in fn.args.kwonlyargs
        }
        for kw in call.keywords:
            if kw.arg and kw.arg in all_params and self._rank_varying(
                unit, kw.value, env
            ):
                tainted.add(kw.arg)
        return frozenset(tainted)

    # --- verdicts ---------------------------------------------------------

    def _divergence_of(self, key: str, _stack=None) -> Divergence | None:
        if key in self._verdict:
            return self._verdict[key]
        paths = self.unit_paths(key, frozenset(),
                                _stack=_stack if _stack is not None
                                else set())
        div = self._compare(key, paths)
        self._verdict[key] = div
        return div

    def _compare(self, key: str, paths: list) -> Divergence | None:
        groups: dict = {}
        for p in paths:
            groups.setdefault(p.uni_key, {}).setdefault(p.seq, p)
        unit = self.graph.units[key]
        for by_seq in groups.values():
            if len(by_seq) < 2:
                continue
            reps = list(by_seq.values())[:2]
            a, b = reps[0], reps[1]
            dec = _first_differing_rank_decision(a, b)
            anchor = (
                dec.line
                if dec is not None and dec.relpath == unit.module.relpath
                else None
            )
            return Divergence(
                unit_key=key, path_a=a, path_b=b, anchor_line=anchor
            )
        return None

    def check_unit(self, key: str) -> Divergence | None:
        """The unit's verdict: None (all rank-feasible paths of every
        uniform configuration submit the same collective sequence) or
        the first Divergence."""
        return self._divergence_of(key)

    def check_all(self):
        """(key, Divergence) for every divergent unit, key-sorted."""
        for key in sorted(self.graph.units):
            div = self.check_unit(key)
            if div is not None:
                yield key, div


# --- entry-path report (the hvt-sched check banner) -------------------------


def entry_units(graph: CallGraph) -> list:
    """Unit keys matching `ENTRY_PATHS` that exist in this module set."""
    out = []
    for modname, path in ENTRY_PATHS:
        key = f"{modname}:{path}"
        if key in graph.units:
            out.append(key)
    return out


def entry_report(graph: CallGraph,
                 checker: ScheduleChecker | None = None) -> list:
    """Per-entry automaton summary: rank-feasible path count, distinct
    sequence count per uniform configuration (1 everywhere = the entry
    verifies), and a representative sequence."""
    checker = checker or checker_for(graph)
    rows = []
    for key in entry_units(graph):
        paths = checker.unit_paths(key)
        groups: dict = {}
        for p in paths:
            groups.setdefault(p.uni_key, set()).add(p.seq)
        agree = all(len(seqs) <= 1 for seqs in groups.values())
        rep = max((p.seq for p in paths), key=len, default=())
        rows.append({
            "unit": key,
            "paths": len(paths),
            "configurations": len(groups),
            "agree": agree,
            "sequence": list(rep),
        })
    return rows
