"""Structured auditor for compiled/lowered XLA programs (hvt-lint v2,
layer 2).

Every compiled-program invariant the framework actually relies on —
exactly one gradient reduction per optimizer step (PR 4), wire dtype on
the DCN hop (PR 7), donation aliasing, the overlap peel — used to live
as copy-pasted HLO-text greps in three test files and ``bench.py``. This
module is the single implementation: a small parser over the two text
dialects jax emits (lowered StableHLO from ``.lower().as_text()``,
post-optimization HLO from ``.compile().as_text()``) exposing the ops as
data, plus an `assert_program` API whose failures print a structured
diff instead of a regex mismatch.

The load-bearing discrimination, shared verbatim with the bench
(previously private as ``bench._reduction_calls``): cross-worker
GRADIENT traffic is

* any non-scalar all-reduce — scalar all-reduces are the loss/accuracy
  metric means, which exist on every path; and
* any rank >= 2 all-gather — the quantized (int8/fp8) wire reduces as a
  gather-sum, one PAYLOAD gather per bucket (a 1-D bucket stacked over
  shards), while the per-bucket f32 scale rides a separate rank-1
  gather (one scalar per shard, noise bytes) that must not inflate the
  count.

The ZeRO-1 composed step (PR 10) adds the SCATTER-form discrimination
(`scatter_reductions`): non-scalar reduce-scatters plus rank >= 2
all-to-alls — the quantized wire's reduce-scatter hop is an all-to-all
with receiver-side f32 summation — with the `scatter-reduction` /
`scatters=N` expectation asserting no full-payload all-reduce survives
anywhere in the program. Since the per-bucket overlapped schedule
(PR 12) the scatter buckets are leaf-aligned and issue bucket-by-bucket
inside the peeled backward, with the tail-family (non-divisible) leaves
merged onto the same buckets — `scatters=N` therefore counts exactly
the bucket count (N == 1 for the canonical probe at the default fusion
threshold), and the small rank-1 all-gather returning the tail columns
is deliberately outside every count (it is the second shot of the
tail's two-shot all-reduce, not a reduction).

Deliberately stdlib-only (`re`/`dataclasses`): the lint/audit CLIs and
the earliest CI hooks import this without jax. Only `step_probe` (which
produces the text) touches jax.
"""

from __future__ import annotations

import dataclasses
import re

__all__ = [
    "CollectiveOp",
    "ProgramAuditError",
    "ProgramExpectation",
    "assert_program",
    "audit",
    "collective_ops",
    "donated_args",
    "gradient_reductions",
    "op_bytes",
    "op_bytes_by_kind",
    "payload_alltoalls",
    "scatter_reductions",
    "while_count",
    "wire_dtype",
]


# --- the parsed op ----------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class CollectiveOp:
    """One cross-device collective in a program's text.

    ``index`` is the op's position among the program's collectives in
    TEXT order — the submission (channel) order every rank must agree
    on; ``dtype`` is the canonical element type of the result payload
    (``i8``, ``f8e4m3``, ``bf16``, ``f32``, ...), identical for both
    dialects (HLO spells int8 ``s8``, StableHLO ``i8``)."""

    kind: str             # "all-reduce" | "all-gather" | "reduce-scatter" | ...
    dtype: str
    shape: tuple
    line: int             # 1-based line in the source text
    index: int

    @property
    def rank(self) -> int:
        return len(self.shape)

    @property
    def scalar(self) -> bool:
        return not self.shape

    def describe(self) -> str:
        dims = "x".join(str(d) for d in self.shape)
        return (
            f"[{self.index}] {self.kind} {self.dtype}"
            f"[{dims}] (line {self.line})"
        )


# --- dtype canonicalization -------------------------------------------------

_DTYPE_CANON = {
    "s8": "i8", "u8": "u8", "si8": "i8",
    "f8e4m3fn": "f8e4m3", "f8e4m3": "f8e4m3",
    "f8e5m2": "f8e5m2", "f8e5m2fn": "f8e5m2",
}

# What a wire/compression NAME (DistributedOptimizer(compression=...),
# HVT_COMPRESSION) means as a payload element type.
WIRE_DTYPES = {
    "int8": "i8", "i8": "i8",
    "fp8": "f8e4m3", "f8": "f8e4m3", "f8e4m3": "f8e4m3",
    "bf16": "bf16",
    "fp16": "f16", "f16": "f16",
    "none": "f32", "f32": "f32", "float32": "f32",
}


def _canon_dtype(raw: str) -> str:
    return _DTYPE_CANON.get(raw.lower(), raw.lower())


def wire_dtype(name: str) -> str:
    """Canonical payload element type for a compression/wire name."""
    try:
        return WIRE_DTYPES[name.strip().lower()]
    except KeyError:
        raise ValueError(
            f"unknown wire {name!r} — one of {sorted(WIRE_DTYPES)}"
        ) from None


# --- parsers ----------------------------------------------------------------

_KINDS = "all_reduce|all_gather|reduce_scatter|all_to_all|collective_permute"

# StableHLO prints the op's attrs (and a reduction region) first and the
# type signature LAST, possibly many lines later:
#   %177 = "stablehlo.all_reduce"(%112) <{...}> ({ region }) :
#       (tensor<2410xf32>) -> tensor<2410xf32>
# so the result type is the first `-> tensor<...>` after the op token
# (tuple results open with `-> (tensor<...>`).
_STABLEHLO_RE = re.compile(
    rf"stablehlo\.({_KINDS})\b.*?->\s*\(?\s*tensor<([^>]*)>", re.S
)

# Post-optimization HLO puts the result type BEFORE the op name on the
# defining line:
#   %all-reduce.6 = f32[2410]{0} all-reduce(f32[2410]{0} %x), channel_id=1
#   %ag = (s8[...], s8[...]) all-gather-start(...)
# `-done` is the same op's completion and must not double-count.
_HLO_RE = re.compile(
    r"=\s*(\([^=]*?\)|\S+)\s+"
    r"(all-reduce|all-gather|reduce-scatter|all-to-all|collective-permute)"
    r"(-start)?\("
)
_HLO_TYPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")


def _parse_tensor_spec(spec: str) -> tuple[str, tuple]:
    """``'8x301xi8'`` -> ('i8', (8, 301)); ``'f32'`` -> ('f32', ())."""
    parts = spec.strip().split("x")
    dims = []
    for p in parts:
        if p.isdigit():
            dims.append(int(p))
        else:
            return _canon_dtype(p), tuple(dims)
    return _canon_dtype(parts[-1]), tuple(dims[:-1])


def _parse_stablehlo(text: str) -> list[CollectiveOp]:
    ops = []
    for m in _STABLEHLO_RE.finditer(text):
        dtype, shape = _parse_tensor_spec(m.group(2))
        ops.append(CollectiveOp(
            kind=m.group(1).replace("_", "-"), dtype=dtype, shape=shape,
            line=text.count("\n", 0, m.start()) + 1, index=len(ops),
        ))
    return ops


def _parse_hlo(text: str) -> list[CollectiveOp]:
    ops = []
    for i, line in enumerate(text.splitlines(), start=1):
        if "-done" in line:
            continue
        m = _HLO_RE.search(line)
        if not m:
            continue
        tm = _HLO_TYPE_RE.search(m.group(1))
        if not tm:
            continue
        dims = tuple(
            int(d) for d in tm.group(2).split(",") if d.strip().isdigit()
        )
        ops.append(CollectiveOp(
            kind=m.group(2), dtype=_canon_dtype(tm.group(1)), shape=dims,
            line=i, index=len(ops),
        ))
    return ops


def collective_ops(text: str) -> list[CollectiveOp]:
    """Every cross-device collective in the program text, in submission
    (channel) order. Dialect auto-detected."""
    if "stablehlo." in text:
        return _parse_stablehlo(text)
    return _parse_hlo(text)


def gradient_reductions(text) -> list[CollectiveOp]:
    """The GRADIENT-traffic collectives (see module docstring): non-
    scalar all-reduces plus rank >= 2 all-gathers (quantized-wire payload
    gathers; rank-1 scale gathers excluded). Accepts program text or a
    pre-parsed op list."""
    ops = collective_ops(text) if isinstance(text, str) else text
    out = []
    for op in ops:
        if op.kind == "all-reduce" and not op.scalar:
            out.append(op)
        elif op.kind == "all-gather" and op.rank >= 2:
            out.append(op)
        elif op.kind == "reduce-scatter" and not op.scalar:
            out.append(op)
    return out


def scatter_reductions(text) -> list[CollectiveOp]:
    """The SCATTER-form gradient reductions: non-scalar reduce-scatters
    plus rank >= 2 all-to-alls (the quantized wire expresses its
    reduce-scatter hop as an all-to-all with receiver-side f32
    summation — sub-16-bit partial sums must never exist on the wire).
    The ZeRO-1 composed step (``Trainer(shard_update=True)`` with
    accumulation/compression) must reduce THIS way: one bucketed group
    of these per optimizer step, and no full-payload all-reduce
    anywhere. Accepts program text or a pre-parsed op list.

    NOTE: check the LOWERED StableHLO — it carries only the explicit
    (shard_map-placed) collectives, so the sharded update's implicit
    parameter all-gather (a GSPMD artifact of the compiled program)
    cannot pollute the count."""
    ops = collective_ops(text) if isinstance(text, str) else text
    return [
        op for op in ops
        if (op.kind == "reduce-scatter" and not op.scalar)
        or (op.kind == "all-to-all" and op.rank >= 2)
    ]


def payload_alltoalls(text) -> list[CollectiveOp]:
    """The PAYLOAD all-to-alls: rank >= 2 — the EP dispatch/combine wire
    (`collectives.all_to_all`) and the quantized wire's reduce-scatter
    shot alike. Rank-1 all-to-alls are scale/column movement (the
    quantized wire's per-bucket f32 scales, a tail-span column shuffle)
    and are excluded, the same discrimination every other count here
    applies to all-gathers. Both dialects. Accepts program text or a
    pre-parsed op list."""
    ops = collective_ops(text) if isinstance(text, str) else text
    return [op for op in ops if op.kind == "all-to-all" and op.rank >= 2]


def _wire_payload_ops(ops) -> list[CollectiveOp]:
    """Every op whose payload must carry the wire dtype: the gradient
    reductions plus the quantized wire's rank >= 2 all-to-alls (rank-1
    scale gathers stay excluded, as everywhere)."""
    grads = gradient_reductions(ops)
    a2a = [
        op for op in ops
        if op.kind == "all-to-all" and op.rank >= 2 and op not in grads
    ]
    return sorted(grads + a2a, key=lambda op: op.index)


#: Payload element sizes for `op_bytes` (canonical dtype -> bytes).
_DTYPE_BYTES = {
    "f64": 8, "s64": 8, "u64": 8,
    "f32": 4, "s32": 4, "u32": 4, "i32": 4,
    "bf16": 2, "f16": 2, "s16": 2, "u16": 2,
    "i8": 1, "u8": 1, "s8": 1, "f8e4m3": 1, "f8e5m2": 1,
    "pred": 1, "i1": 1,
}


def op_bytes(op: CollectiveOp) -> int:
    """Payload bytes of one collective's RESULT (elements x element
    size) — the structural bytes-on-wire accounting the bench reports.
    Unknown element types count 4 bytes (the f32 default)."""
    n = 1
    for d in op.shape:
        n *= d
    return n * _DTYPE_BYTES.get(op.dtype, 4)


def op_bytes_by_kind(ops) -> dict:
    """Per-kind payload-byte totals over the program's PAYLOAD
    collectives (non-scalar reductions, rank >= 2 gathers/all-to-alls —
    the same discrimination as the counts; scale noise excluded). The
    expectation-diff context: when a count expectation fails, WHERE the
    wire bytes actually went is the first question."""
    if isinstance(ops, str):
        ops = collective_ops(ops)
    out: dict = {}
    for op in ops:
        payload = (
            (op.kind in ("all-reduce", "reduce-scatter") and not op.scalar)
            or (op.kind in ("all-gather", "all-to-all") and op.rank >= 2)
        )
        if payload:
            out[op.kind] = out.get(op.kind, 0) + op_bytes(op)
    return out


def while_count(text: str) -> int:
    """Loop (scan) ops in the program — the overlap peel's structural
    witness (PR 7: the peeled K=2 step has strictly fewer)."""
    if "stablehlo." in text:
        return text.count("stablehlo.while")
    return sum(
        1 for line in text.splitlines()
        if re.search(r"=\s*[^=]*\bwhile\(", line)
    )


# Donation: lowered StableHLO marks donated args with `tf.aliasing_output`
# / `jax.buffer_donor` arg attributes; compiled HLO records the aliasing
# map in the module header.
_STABLEHLO_DONOR_RE = re.compile(
    r"tf\.aliasing_output\s*=\s*(\d+)|jax\.buffer_donor\s*=\s*true"
)
_HLO_ALIAS_RE = re.compile(r"\{[0-9, ]*\}:\s*\((\d+)\s*,")


def donated_args(text: str) -> list[int]:
    """Argument numbers the program donates (aliases to outputs).

    From compiled HLO the numbers are the header's ``input_output_alias``
    parameter indices; from lowered StableHLO, the positions of
    arg-attribute donation markers in declaration order (an approximation
    — compile for the exact map)."""
    if "input_output_alias=" in text:
        header = text.split("input_output_alias={", 1)[1]
        # the alias map is brace-balanced; entries look like
        # `{0}: (0, {}, may-alias)` — harvest the arg numbers.
        depth, end = 1, 0
        for i, ch in enumerate(header):
            if ch == "{":
                depth += 1
            elif ch == "}":
                depth -= 1
                if depth == 0:
                    end = i
                    break
        return sorted({
            int(g) for g in _HLO_ALIAS_RE.findall(header[:end])
        })
    hits = []
    for i, m in enumerate(_STABLEHLO_DONOR_RE.finditer(text)):
        hits.append(int(m.group(1)) if m.group(1) is not None else i)
    return sorted(set(hits))


# --- expectations -----------------------------------------------------------


class ProgramAuditError(AssertionError):
    """A compiled program violated its expectations (structured diff in
    the message)."""


@dataclasses.dataclass
class ProgramExpectation:
    """What a compiled step must look like. Unset fields are unchecked.

    ``wire`` implies at least one gradient reduction exists (an empty
    program trivially satisfying 'every reduction is int8' is itself a
    violation — the invariant is about traffic that must be present)."""

    gradient_reductions: int | None = None   # exact count
    max_gradient_reductions: int | None = None
    # Compression name or dtype. Check the LOWERED StableHLO: post-
    # optimization HLO may legalize wire dtypes per backend (CPU upcasts
    # the bf16 all-reduce to f32) — counts survive optimization, element
    # types do not.
    wire: str | None = None
    no_explicit_collectives: bool = False
    min_donated: int | None = None
    # Scatter mode (the ZeRO-1 composed step): the gradient traffic must
    # be ONE bucketed reduce-scatter group — only scatter-form reductions
    # (`scatter_reductions`), with NO full-payload (non-scalar)
    # all-reduce anywhere in the program. ``scatter_reductions`` pins the
    # exact op count (== the bucket count); the bare flag only asserts
    # the shape. Like ``wire``, check the LOWERED StableHLO — it carries
    # the explicit collectives only, so the sharded update's implicit
    # parameter all-gather cannot leak into the counts.
    scatter_mode: bool = False
    scatter_reductions: int | None = None
    # The EP dispatch/combine shape: exactly N PAYLOAD (rank >= 2)
    # all-to-alls — `collectives.all_to_all` submissions; rank-1
    # scale/column all-to-alls never count (`payload_alltoalls`).
    alltoalls: int | None = None

    @classmethod
    def parse(cls, spec: str) -> "ProgramExpectation":
        """CLI grammar: comma-separated tokens —
        ``one-reduction`` | ``reductions=N`` | ``max-reductions=N`` |
        ``wire=int8`` | ``no-collectives`` | ``donates=N`` |
        ``scatter-reduction`` | ``scatters=N`` | ``alltoalls=N``.
        (``overlap`` is a CLI-level expectation: it needs two compiles.)
        """
        exp = cls()
        for token in spec.split(","):
            token = token.strip().lower()
            if not token:
                continue
            key, _, value = token.partition("=")
            if token == "one-reduction":
                exp.gradient_reductions = 1
            elif key == "reductions" and value:
                exp.gradient_reductions = int(value)
            elif key == "max-reductions" and value:
                exp.max_gradient_reductions = int(value)
            elif key == "wire" and value:
                wire_dtype(value)  # validate now -> usage error, not audit
                exp.wire = value
            elif token == "no-collectives":
                exp.no_explicit_collectives = True
            elif key == "donates" and value:
                exp.min_donated = int(value)
            elif token == "scatter-reduction":
                exp.scatter_mode = True
            elif key == "scatters" and value:
                exp.scatter_mode = True
                exp.scatter_reductions = int(value)
            elif key == "alltoalls" and value:
                exp.alltoalls = int(value)
            else:
                raise ValueError(
                    f"unknown expectation {token!r} — grammar: "
                    "one-reduction | reductions=N | max-reductions=N | "
                    "wire=<int8|fp8|bf16|fp16|f32> | no-collectives | "
                    "donates=N | scatter-reduction | scatters=N | "
                    "alltoalls=N | overlap"
                )
        return exp


def audit(text: str, expects: ProgramExpectation, *,
          ops: list | None = None) -> list[str]:
    """Check `text` against `expects`; returns human-readable violation
    lines (empty = clean). ``ops`` lets a caller that already parsed
    the program (`collective_ops`) skip the re-parse; the text is still
    needed for the donation-alias header."""
    if ops is None:
        ops = collective_ops(text)
    grads = gradient_reductions(ops)
    violations = []
    if expects.no_explicit_collectives and ops:
        violations.append(
            f"expected NO explicit collectives, found {len(ops)}:\n"
            + _op_table(ops)
        )
    if expects.gradient_reductions is not None and len(grads) != (
        expects.gradient_reductions
    ):
        violations.append(
            f"expected exactly {expects.gradient_reductions} gradient "
            f"reduction(s) per step, found {len(grads)}:\n"
            + _op_table(grads)
        )
    if expects.max_gradient_reductions is not None and len(grads) > (
        expects.max_gradient_reductions
    ):
        violations.append(
            f"expected at most {expects.max_gradient_reductions} gradient "
            f"reduction(s), found {len(grads)}:\n" + _op_table(grads)
        )
    if expects.scatter_mode:
        scatters = scatter_reductions(ops)
        full_ar = [
            op for op in ops if op.kind == "all-reduce" and not op.scalar
        ]
        if full_ar:
            violations.append(
                "scatter mode forbids full-payload all-reduces (the "
                "reduction must lower into the sharded update's layout), "
                f"found {len(full_ar)}:\n" + _op_table(full_ar)
            )
        if not scatters:
            violations.append(
                "expected scatter-form gradient reductions (reduce-"
                "scatter / payload all-to-all), found none"
            )
        if expects.scatter_reductions is not None and len(scatters) != (
            expects.scatter_reductions
        ):
            violations.append(
                f"expected exactly {expects.scatter_reductions} scatter-"
                f"form reduction(s) — one bucketed group — found "
                f"{len(scatters)}:\n" + _op_table(scatters)
            )
    if expects.wire is not None:
        want = wire_dtype(expects.wire)
        payload = _wire_payload_ops(ops)
        if not payload:
            violations.append(
                f"expected {expects.wire} ({want}) gradient traffic, "
                "found NO gradient reductions at all"
            )
        off_wire = [op for op in payload if op.dtype != want]
        if off_wire:
            violations.append(
                f"expected every gradient payload (reductions and "
                f"scatter all-to-alls) in {expects.wire} ({want}), found "
                "off-wire traffic:\n" + _op_table(off_wire)
            )
    if expects.alltoalls is not None:
        a2a = payload_alltoalls(ops)
        if len(a2a) != expects.alltoalls:
            excluded = [
                op for op in ops
                if op.kind == "all-to-all" and op.rank < 2
            ]
            violations.append(
                f"expected exactly {expects.alltoalls} payload "
                f"all-to-all(s) (the dispatch/combine shape), found "
                f"{len(a2a)}:\n" + _op_table(a2a)
                + (
                    f"\n      ({len(excluded)} rank-1 scale/column "
                    "all-to-all(s) excluded from the count)"
                    if excluded else ""
                )
            )
    if expects.min_donated is not None:
        donated = donated_args(text)
        if len(donated) < expects.min_donated:
            violations.append(
                f"expected >= {expects.min_donated} donated (aliased) "
                f"inputs, found {len(donated)}: {donated}"
            )
    if violations:
        totals = op_bytes_by_kind(ops)
        if totals:
            # Expectation-diff context: where the wire bytes actually
            # went, per kind — the first question a failed count raises.
            violations.append(
                "payload op_bytes by kind: " + ", ".join(
                    f"{k}={v}" for k, v in sorted(totals.items())
                )
            )
    return violations


def _op_table(ops) -> str:
    if not ops:
        return "      (none)"
    return "\n".join("      " + op.describe() for op in ops)


def assert_program(text: str, expects: ProgramExpectation | str) -> None:
    """Raise `ProgramAuditError` (an AssertionError) with a structured
    diff when `text` violates `expects` (a `ProgramExpectation` or the
    CLI expectation string)."""
    if isinstance(expects, str):
        expects = ProgramExpectation.parse(expects)
    violations = audit(text, expects)
    if violations:
        grads = gradient_reductions(text)
        raise ProgramAuditError(
            "compiled program violates expectations:\n"
            + "\n".join(f"  - {v}" for v in violations)
            + f"\n  gradient reductions observed: {len(grads)}"
            + (("\n" + _op_table(grads)) if grads else "")
        )
