"""`hvt-lint` checker framework: the pieces every rule shares.

The analyzer is a plain AST pass — no imports of the analyzed code, so it
runs in milliseconds over the whole package and can't be wedged by
import-time side effects (the same reason it can lint a file whose
dependencies aren't installed). Structure:

* `ModuleSource` — one parsed file: source text, AST, per-line ``noqa``
  suppressions, and an import-alias map (so rules can resolve
  ``from jax import random`` vs stdlib ``random``).
* `Project` — the whole module set of one lint run, plus the lazily
  built interprocedural call graph (`analysis.callgraph`) the
  project-wide rules share.
* `Rule` + `register_rule` — the visitor registry. A per-module rule
  yields `Finding`s from `check(module)`; a rule with
  ``project_wide = True`` instead implements `check_project(project)`
  and sees every module at once (HVT001's rank-taint propagation,
  HVT007's transitive collective sequences need the call graph).
* Baseline — a committed JSON file of grandfathered findings, each with a
  one-line justification. Matching is by (rule, path, source-line
  snippet), NOT line number, so unrelated edits above a baselined site
  don't invalidate it — while any edit to the flagged line itself does.
* `lint_paths` — the runner: walk files, parse, run rules, partition
  into fresh findings vs baselined.

Suppressions, narrowest first:

1. ``# hvt: noqa[HVT001]`` (or a comma list) on the flagged line —
   site-local, visible in review;
2. a baseline entry with a justification — for grandfathered findings;
3. nothing rule-wide: a rule that needs blanket exceptions should encode
   them (see HVT002's sanctioned-module set).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Iterable, Iterator

# Parse failures surface as findings under this pseudo-rule (a file the
# analyzer cannot read is a lint failure, not a silent skip).
PARSE_ERROR_RULE = "HVT000"

_NOQA_RE = re.compile(
    r"#\s*hvt:\s*noqa(?:\[(?P<rules>[A-Z0-9,\s]+)\])?", re.IGNORECASE
)

_ALL = "ALL"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    rule: str
    path: str      # forward-slash path relative to the lint root
    line: int      # 1-based
    col: int       # 0-based
    message: str
    snippet: str   # the stripped source line (the baseline match key)

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col + 1}: {self.rule} {self.message}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def _parse_noqa(text: str) -> dict[int, set[str] | str]:
    """Per-line suppressions: line number -> set of rule ids, or _ALL."""
    out: dict[int, set[str] | str] = {}
    for i, line in enumerate(text.splitlines(), start=1):
        if "noqa" not in line:
            continue
        m = _NOQA_RE.search(line)
        if not m:
            continue
        rules = m.group("rules")
        if rules is None:
            out[i] = _ALL
        else:
            out[i] = {r.strip().upper() for r in rules.split(",") if r.strip()}
    return out


class ModuleSource:
    """One file under analysis."""

    def __init__(self, path: str, relpath: str, text: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)  # may raise SyntaxError
        self.noqa = _parse_noqa(text)

    @property
    def modname(self) -> str:
        """Dotted module name derived from the relative path —
        ``horovod_tpu/parallel/collectives.py`` ->
        ``horovod_tpu.parallel.collectives`` (``__init__.py`` names the
        package itself). The call graph keys cross-module resolution on
        this."""
        parts = self.relpath.split("/")
        if parts[-1].endswith(".py"):
            parts[-1] = parts[-1][:-3]
        if parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join(p for p in parts if p)

    @property
    def is_package(self) -> bool:
        return self.relpath.endswith("__init__.py")

    def line_at(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def suppressed(self, rule: str, lineno: int) -> bool:
        entry = self.noqa.get(lineno)
        if entry is None:
            return False
        return entry == _ALL or rule in entry

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        return Finding(
            rule=rule,
            path=self.relpath,
            line=node.lineno,
            col=node.col_offset,
            message=message,
            snippet=self.line_at(node.lineno),
        )

    # --- shared AST helpers used by several rules ---------------------------

    def import_map(self) -> dict[str, str]:
        """Local name -> dotted origin for module-level imports, e.g.
        ``{'np': 'numpy', 'random': 'jax.random'}`` after
        ``import numpy as np; from jax import random``. Relative imports
        (``from .state import x``) resolve against this module's package
        so cross-module call-graph edges work inside the package. Cached."""
        cached = getattr(self, "_import_map", None)
        if cached is not None:
            return cached
        mapping: dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    mapping[alias.asname or alias.name.split(".")[0]] = (
                        alias.name if alias.asname else alias.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    parts = self.modname.split(".")
                    drop = node.level - (1 if self.is_package else 0)
                    anchor = parts[: max(0, len(parts) - drop)]
                    base = ".".join(anchor + ([node.module] if node.module
                                              else []))
                if not base:
                    continue
                for alias in node.names:
                    mapping[alias.asname or alias.name] = (
                        f"{base}.{alias.name}"
                    )
        self._import_map = mapping
        return mapping


def dotted_name(node: ast.AST) -> str | None:
    """``jax.lax.psum`` for the matching Attribute/Name chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def terminal_name(node: ast.AST) -> str | None:
    """The rightmost identifier of a call target: ``psum`` for both
    ``psum(...)`` and ``jax.lax.psum(...)``."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def resolved_dotted(module: ModuleSource, node: ast.AST) -> str | None:
    """`dotted_name` with the leading segment resolved through the module's
    imports: ``np.random.rand`` -> ``numpy.random.rand``."""
    dotted = dotted_name(node)
    if dotted is None:
        return None
    head, _, rest = dotted.partition(".")
    origin = module.import_map().get(head, head)
    return f"{origin}.{rest}" if rest else origin


# --- rule registry ----------------------------------------------------------


class Rule:
    """Base class: subclass, set `rule_id`/`title`, implement `check` —
    or set ``project_wide = True`` and implement `check_project`, which
    sees the whole module set (and its shared call graph) at once.

    `rationale`/`provenance`/`example` feed the generated
    ``docs/LINT_RULES.md`` (`generate_rules_doc`): the one-paragraph
    reason the rule exists, the PR/bug it is grounded in, and a minimal
    flagged snippet."""

    rule_id: str = "HVT000"
    title: str = ""
    project_wide: bool = False
    rationale: str = ""
    provenance: str = ""
    example: str = ""

    def check(self, module: ModuleSource) -> Iterator[Finding]:
        raise NotImplementedError

    def check_project(self, project: "Project") -> Iterator[Finding]:
        raise NotImplementedError


class Project:
    """Every module of one lint run plus the shared call graph."""

    def __init__(self, modules: list[ModuleSource]):
        self.modules = modules
        self._by_path = {m.relpath: m for m in modules}
        self._graph = None

    def module(self, relpath: str) -> ModuleSource | None:
        return self._by_path.get(relpath)

    def callgraph(self):
        """The interprocedural `analysis.callgraph.CallGraph`, built once
        and shared by every project-wide rule (lazy import keeps `core`
        cycle-free)."""
        if self._graph is None:
            from horovod_tpu.analysis import callgraph as _callgraph

            self._graph = _callgraph.CallGraph(self.modules)
        return self._graph


_RULES: dict[str, type[Rule]] = {}


def register_rule(cls: type[Rule]) -> type[Rule]:
    if cls.rule_id in _RULES:
        raise ValueError(f"duplicate rule id {cls.rule_id}")
    _RULES[cls.rule_id] = cls
    return cls


def iter_rules() -> list[type[Rule]]:
    """Registered rules, id-sorted. Importing `rules` populates the
    registry; done lazily here so `core` stays import-cycle-free."""
    from horovod_tpu.analysis import rules as _rules  # noqa: F401

    return [_RULES[k] for k in sorted(_RULES)]


_RULES_DOC_HEADER = """\
# `hvt-lint` rules

<!-- GENERATED FILE — do not edit by hand.
     Source of truth: the Rule classes in horovod_tpu/analysis/rules.py
     (rationale/provenance/example metadata).
     Regenerate: python -m horovod_tpu.analysis.rules > docs/LINT_RULES.md
     (tests/test_lint_clean.py fails when this file drifts). -->

Every rule the distributed-correctness analyzer ships, generated from the
rule registry the same way `docs/ENVVARS.md` is generated from the knob
registry. Each rule encodes an invariant this repo was actually bitten
by — the provenance row names the PR that fixed (or designed around) the
bug class. Suppress a deliberate site with ``# hvt: noqa[RULE]`` plus a
reason, or grandfather it in ``horovod_tpu/analysis/baseline.json`` with
a one-line justification; `hvt-lint --explain RULE` prints a rule's
entry at the terminal.

`HVT000` (not listed below) is the parse-failure pseudo-rule: a file the
analyzer cannot read is a lint failure, not a silent skip.
"""


def generate_rules_doc() -> str:
    """Render docs/LINT_RULES.md from the registry. Deterministic:
    id-sorted, one section per rule."""
    parts = [_RULES_DOC_HEADER]
    for cls in iter_rules():
        parts.append(f"\n## {cls.rule_id} — {cls.title}\n")
        if cls.rationale:
            parts.append(f"**Why:** {cls.rationale}\n")
        if cls.provenance:
            parts.append(f"**Provenance:** {cls.provenance}\n")
        if cls.example:
            parts.append("**Flags:**\n")
            parts.append("```python")
            parts.append(cls.example.strip("\n"))
            parts.append("```")
    return "\n".join(parts) + "\n"


# --- baseline ---------------------------------------------------------------

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


def load_baseline(path: str | None) -> list[dict]:
    """Baseline entries: ``{rule, path, snippet, justification}``. A
    missing file is an empty baseline."""
    if not path or not os.path.exists(path):
        return []
    with open(path) as f:
        data = json.load(f)
    entries = data["findings"] if isinstance(data, dict) else data
    for e in entries:
        for key in ("rule", "path", "snippet", "justification"):
            if key not in e:
                raise ValueError(
                    f"baseline entry {e!r} is missing {key!r} — every "
                    "grandfathered finding needs a one-line justification"
                )
    return entries


def write_baseline(
    path: str,
    findings: Iterable[Finding],
    *,
    existing: Iterable[dict] = (),
    selected: Iterable[str] | None = None,
) -> None:
    """Emit a baseline covering `findings`. A rewrite must not destroy
    hand-written grandfather clauses: entries in `existing` that still
    match a finding keep their justification (TODO only for NEW
    findings), and when `selected` restricts the run to a rule subset,
    existing entries for the other rules are carried over untouched —
    otherwise ``--select HVT001 --write-baseline`` would silently drop
    every other rule's entries from the committed file."""
    by_key: dict[tuple, dict] = {
        (e["rule"], e["path"], e["snippet"]): e for e in existing
    }
    entries = []
    seen: set[tuple] = set()
    for f in findings:
        key = _baseline_key(f.rule, f.path, f.snippet)
        if key in seen:
            continue
        seen.add(key)
        prev = by_key.get(key)
        entries.append({
            "rule": f.rule, "path": f.path, "snippet": f.snippet,
            "justification": (
                prev["justification"] if prev else "TODO: justify or fix"
            ),
        })
    if selected is not None:
        wanted = {s.upper() for s in selected}
        entries.extend(
            e for k, e in by_key.items()
            if e["rule"] not in wanted and k not in seen
        )
    entries.sort(key=lambda e: (e["path"], e["rule"], e["snippet"]))
    # Dev-tool output, hand-edited before commit — not a crash-consistency
    # artifact (no reader verifies it mid-write).
    with open(path, "w") as f:  # hvt: noqa[HVT005]
        json.dump({"findings": entries}, f, indent=2, sort_keys=True)
        f.write("\n")


def _baseline_key(rule: str, path: str, snippet: str) -> tuple:
    return (rule, path, snippet)


@dataclasses.dataclass
class LintResult:
    findings: list[Finding]       # fresh — these fail the lint
    baselined: list[Finding]      # matched a committed baseline entry
    files: int = 0
    # The parsed module set + shared call graph of this run, so callers
    # needing more than findings (hvt-sched's entry-path report) reuse
    # the parse instead of re-reading the tree.
    project: "Project | None" = None

    @property
    def clean(self) -> bool:
        return not self.findings


def iter_python_files(paths: Iterable[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(
                d for d in dirnames
                if d not in ("__pycache__", ".git", ".hypothesis")
            )
            for name in sorted(filenames):
                if name.endswith(".py"):
                    yield os.path.join(dirpath, name)


def lint_paths(
    paths: Iterable[str],
    *,
    root: str | None = None,
    select: Iterable[str] | None = None,
    baseline_path: str | None = DEFAULT_BASELINE,
) -> LintResult:
    """Run every (selected) rule over every ``.py`` under `paths`.

    `root` anchors the relative paths findings/baselines are keyed by
    (default: the current directory) — run from the repo root, or pass
    the repo root, for baseline paths like ``horovod_tpu/tbevents.py``
    to match."""
    root = os.path.abspath(root or os.getcwd())

    def anchor_relpath(abspath: str) -> str:
        if abspath.startswith(root + os.sep):
            return os.path.relpath(abspath, root)
        # Input outside `root` (an absolute path from another cwd, an
        # editor integration): anchor at the LAST `horovod_tpu` path
        # segment — the package directory — so the paths that key the
        # HVT002 sanctioned-module set and the committed baseline are
        # invocation-directory-independent.
        parts = abspath.split(os.sep)
        if "horovod_tpu" in parts:
            i = len(parts) - 1 - parts[::-1].index("horovod_tpu")
            return os.sep.join(parts[i:])
        return abspath

    wanted = {s.upper() for s in select} if select else None
    rules = [
        cls() for cls in iter_rules()
        if wanted is None or cls.rule_id in wanted
    ]
    baseline = {
        _baseline_key(e["rule"], e["path"], e["snippet"])
        for e in load_baseline(baseline_path)
    }
    result = LintResult(findings=[], baselined=[])

    # Phase 1: parse everything. Project-wide rules (rank-taint through
    # helpers, collective-order sequences) need the full module set
    # before any rule can run.
    modules: list[ModuleSource] = []
    for filepath in iter_python_files(paths):
        result.files += 1
        abspath = os.path.abspath(filepath)
        relpath = anchor_relpath(abspath)
        with open(filepath, encoding="utf-8") as f:
            text = f.read()
        try:
            modules.append(ModuleSource(abspath, relpath, text))
        except SyntaxError as e:
            result.findings.append(Finding(
                rule=PARSE_ERROR_RULE, path=relpath.replace(os.sep, "/"),
                line=e.lineno or 1, col=(e.offset or 1) - 1,
                message=f"file does not parse: {e.msg}", snippet="",
            ))
    project = Project(modules)

    def deliver(finding: Finding, module: ModuleSource | None):
        if module is not None and module.suppressed(
            finding.rule, finding.line
        ):
            return
        key = _baseline_key(finding.rule, finding.path, finding.snippet)
        if key in baseline:
            result.baselined.append(finding)
        else:
            result.findings.append(finding)

    # Phase 2: per-module rules, then project-wide rules.
    for module in modules:
        for rule in rules:
            if rule.project_wide:
                continue
            for finding in rule.check(module):
                deliver(finding, module)
    for rule in rules:
        if not rule.project_wide:
            continue
        for finding in rule.check_project(project):
            deliver(finding, project.module(finding.path))
    result.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    result.baselined.sort(key=lambda f: (f.path, f.line, f.rule))
    result.project = project
    return result
