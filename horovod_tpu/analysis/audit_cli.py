"""`hvt-audit` — the compiled-program auditor CLI (hvt-lint v2 layer 2).

Usage::

    # Audit a freshly compiled canonical trainer step (the CI gate):
    hvt-audit step --k 4 --compression int8 \\
        --expect one-reduction,wire=int8,overlap

    # The composed ZeRO-1 gate: accumulation x sharded update x
    # quantized wire — exactly one bucketed scatter-form reduction per
    # optimizer step, no full-payload all-reduce, wire dtype checked:
    hvt-audit step --k 4 --zero1 --compression int8 \\
        --expect scatters=1,wire=int8,overlap

    # Audit a saved program text (lowered StableHLO or compiled HLO):
    hvt-audit file step.hlo --expect reductions=3,wire=bf16

``step`` builds the canonical probe trainer (`analysis.step_probe`) at
the requested accumulation factor / wire compression, lowers ONE
optimizer step and checks it against the expectations — so the
one-reduction-per-step, wire-dtype and overlap invariants can gate CI
against the real compiled program, not a prose promise. ``--expect``
defaults to what the requested config promises (K>1 or any compression
=> exactly one bucketed boundary reduction; a quantized/16-bit wire =>
every gradient payload in that dtype; plain K=1 => no explicit
collective at all).

The ``overlap`` expectation needs two programs: the K=2 peel probe is
compiled with the overlap knob forced on and off and must show strictly
fewer loop ops when on (the PR 7 structural witness) — AND the audited
configuration itself must have overlap enabled, so a fleet running with
``HVT_OVERLAP_REDUCTION=0`` fails the gate loudly.

Exit codes (the `hvt-lint` contract): 0 clean, 1 violations (printed),
2 usage/build error.
"""

from __future__ import annotations

import argparse
import os
import sys

from horovod_tpu.analysis import hlo_audit


def _default_expect(k: int, compression: str, bucket_bytes,
                    zero1: bool = False, compression_ici: str = "none",
                    dcn=None) -> str:
    compressed = compression.lower() not in ("", "none")
    quantized = compression.lower() in ("int8", "fp8")
    ici_set = (compression_ici or "none").lower() not in ("", "none")
    # Under a real dcn factoring (--dcn > 1) exact counts and wire
    # dtypes are not derivable: the hierarchical reduction legitimately
    # adds per-hop ops — the dense layout's ICI hop is a FULL-PRECISION
    # all-reduce (off-wire by design, and full-payload, which the
    # scatter-mode shape forbids), a quantized ICI hop adds a payload
    # all-to-all per bucket — so the derivation degrades: shape-only
    # for the scatter layout, nothing for the rest (pass --expect).
    two_hop = dcn is not None and dcn > 1
    if zero1 and (k > 1 or compressed or ici_set):
        if quantized and two_hop:
            # Dense (quantized) layout over the factoring: the ICI hop's
            # full-precision all-reduce makes every scatter-mode token
            # unsatisfiable by design.
            return ""
        # The composed ZeRO-1 step: scatter-form reductions only, no
        # full-payload all-reduce. At the default fusion threshold the
        # probe's single-dtype gradient tree packs into exactly ONE
        # bucket on both layouts — the scatter layout merges the
        # tail-family (non-divisible) leaves onto the same bucket and
        # all-gathers just their columns back, and the quantized dense
        # layout runs one two-shot group — so the derived count is
        # scatters=1; a custom bucket_bytes (or a quantized ICI hop)
        # changes the count, so only the shape is pinned then.
        # String-compared, not imported: this runs before the jax env
        # shaping.
        tokens = []
        if bucket_bytes is None and not two_hop:
            # Any two-hop factoring changes the per-bucket op count
            # (ICI-hop reduce-scatter or payload all-to-all next to the
            # DCN hop) — shape-only there.
            tokens.append("scatters=1")
        else:
            tokens.append("scatter-reduction")
        if compressed and not two_hop:
            tokens.append(f"wire={compression}")
        return ",".join(tokens)
    tokens = []
    if compressed:
        if two_hop:
            return ""  # per-hop ops; the ICI hop is off-wire by design
        if bucket_bytes is None:
            tokens.append("one-reduction")
        tokens.append(f"wire={compression}")
    elif k > 1 or ici_set:
        # An ICI wire alone forces the explicit-collective step too
        # (Trainer._explicit_step), so no-collectives would be wrong.
        if bucket_bytes is None and not two_hop:
            tokens.append("one-reduction")
    else:
        tokens.append("no-collectives")
    return ",".join(tokens)


def _run_step(args) -> int:
    overlap = {"auto": None, "on": True, "off": False}[args.overlap]
    expect_spec = args.expect
    if expect_spec is None:
        expect_spec = _default_expect(
            args.k, args.compression, args.bucket_bytes, args.zero1,
            args.compression_ici, args.dcn,
        )
        if expect_spec:
            print(f"hvt-audit: derived --expect {expect_spec}")
        else:
            print(
                "hvt-audit: no expectation derivable for this config "
                "(hierarchical per-hop ops are factoring-dependent) — "
                "pass --expect to pin invariants"
            )
    want_overlap = False
    tokens = []
    for token in expect_spec.split(","):
        if token.strip().lower() == "overlap":
            want_overlap = True
        elif token.strip():
            tokens.append(token)
    # Usage errors surface before the (expensive) backend init.
    expects = hlo_audit.ProgramExpectation.parse(",".join(tokens))

    # Environment shaping must precede the first jax import.
    if args.platform:
        os.environ["HVT_PLATFORM"] = args.platform
        if args.platform == "cpu" and args.devices:
            os.environ["HVT_NUM_CPU_DEVICES"] = str(args.devices)
    if args.dcn:
        # Fake the multi-slice factoring so the two-hop reduction (and
        # the --compression-ici wire that rides its ICI hop) is what
        # lowers — the HVT_DCN_FACTOR contract.
        os.environ["HVT_DCN_FACTOR"] = str(args.dcn)

    import horovod_tpu as hvt
    from horovod_tpu.analysis import step_probe

    hvt.init()

    x, y = step_probe.probe_data()
    trainer = step_probe.build_trainer(
        args.k, args.compression, compression_ici=args.compression_ici,
        overlap=overlap, bucket_bytes=args.bucket_bytes, zero1=args.zero1,
    )
    text = step_probe.lowered_step_text(trainer, x, y, args.k)
    if args.dump:
        with open(args.dump, "w") as f:  # hvt: noqa[HVT005] debug dump
            f.write(text)
        print(f"hvt-audit: wrote lowered step to {args.dump}")

    violations = hlo_audit.audit(text, expects)

    if want_overlap:
        if not trainer._overlap:
            violations.append(
                "overlap expected but the audited configuration resolves "
                "overlap_reduction=OFF (HVT_OVERLAP_REDUCTION/--overlap) "
                "— the boundary reduction serializes after the "
                "accumulation scan"
            )
        else:
            # The K=2 structural witness: peel empties the scan. With
            # --zero1 the SAME two programs must also carry an unchanged
            # scatter-form reduction count — the peel moves the
            # scatter-family buckets INTO the schedulable region, it
            # must not change how many there are (a drifted count would
            # mean the peel re-bucketed the reduction rather than
            # re-scheduling it).
            on_text = step_probe.lowered_step_text(
                step_probe.build_trainer(
                    2, args.compression,
                    compression_ici=args.compression_ici, overlap=True,
                    bucket_bytes=args.bucket_bytes, zero1=args.zero1,
                ), x, y, 2,
            )
            off_text = step_probe.lowered_step_text(
                step_probe.build_trainer(
                    2, args.compression,
                    compression_ici=args.compression_ici, overlap=False,
                    bucket_bytes=args.bucket_bytes, zero1=args.zero1,
                ), x, y, 2,
            )
            on = hlo_audit.while_count(on_text)
            off = hlo_audit.while_count(off_text)
            if not on < off:
                violations.append(
                    "overlap peel is structurally ABSENT: the K=2 "
                    f"overlapped step carries {on} loop op(s) vs "
                    f"{off} serialized — the last microbatch is not "
                    "peeled out of the accumulation scan, so bucket "
                    "reductions cannot overlap its backward"
                )
            if args.zero1:
                s_on = len(hlo_audit.scatter_reductions(on_text))
                s_off = len(hlo_audit.scatter_reductions(off_text))
                if s_on != s_off:
                    violations.append(
                        "overlap peel changed the scatter-form reduction "
                        f"count ({s_on} overlapped vs {s_off} serialized) "
                        "— the peel must move the buckets into the "
                        "schedulable region, not re-bucket the reduction"
                    )

    grads = hlo_audit.gradient_reductions(text)
    config = (
        f"k={args.k} compression={args.compression} "
        f"overlap={'on' if trainer._overlap else 'off'}"
        + (
            f" ici={args.compression_ici}"
            if args.compression_ici.lower() not in ("", "none") else ""
        )
        + (f" dcn={args.dcn}" if args.dcn else "")
        + (" zero1" if args.zero1 else "")
    )
    if violations:
        print(f"hvt-audit: step ({config}) FAILED:")
        for v in violations:
            print(f"  - {v}")
        return 1
    print(
        f"hvt-audit: step ({config}) ok — "
        f"{len(grads)} gradient reduction(s)"
        + (f" [{', '.join(op.dtype for op in grads)}]" if grads else "")
        + (", overlap peel verified" if want_overlap else "")
    )
    return 0


def _run_moe(args) -> int:
    """Audit the EP dispatch/combine probe (`step_probe.
    lowered_moe_dispatch_text`): the MoE wire must be exactly two
    payload all-to-alls through `collectives.all_to_all` — the
    `alltoalls=N` grammar's canonical gate (ROADMAP item 4)."""
    expect_spec = args.expect
    if expect_spec is None:
        expect_spec = "alltoalls=2"
        print(f"hvt-audit: derived --expect {expect_spec}")
    expects = hlo_audit.ProgramExpectation.parse(expect_spec)

    if args.platform:
        os.environ["HVT_PLATFORM"] = args.platform
        if args.platform == "cpu" and args.devices:
            os.environ["HVT_NUM_CPU_DEVICES"] = str(args.devices)

    import horovod_tpu as hvt
    from horovod_tpu.analysis import step_probe

    hvt.init()
    text = step_probe.lowered_moe_dispatch_text()
    if args.dump:
        with open(args.dump, "w") as f:  # hvt: noqa[HVT005] debug dump
            f.write(text)
        print(f"hvt-audit: wrote lowered MoE dispatch to {args.dump}")
    ops = hlo_audit.collective_ops(text)
    violations = hlo_audit.audit(text, expects, ops=ops)
    a2a = hlo_audit.payload_alltoalls(ops)
    if violations:
        print("hvt-audit: moe dispatch/combine FAILED:")
        for v in violations:
            print(f"  - {v}")
        return 1
    print(
        f"hvt-audit: moe dispatch/combine ok — {len(a2a)} payload "
        f"all-to-all(s)"
        + (f" [{', '.join(op.dtype for op in a2a)}]" if a2a else "")
    )
    return 0


def _run_file(args) -> int:
    try:
        with open(args.path, encoding="utf-8") as f:
            text = f.read()
    except OSError as e:
        print(f"hvt-audit: {e}", file=sys.stderr)
        return 2
    expects = hlo_audit.ProgramExpectation.parse(args.expect)
    violations = hlo_audit.audit(text, expects)
    ops = hlo_audit.collective_ops(text)
    if violations:
        print(f"hvt-audit: {args.path} FAILED:")
        for v in violations:
            print(f"  - {v}")
        return 1
    print(
        f"hvt-audit: {args.path} ok — {len(ops)} collective(s), "
        f"{len(hlo_audit.gradient_reductions(ops))} gradient reduction(s)"
    )
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="hvt-audit",
        description="Structured compiled-program audits: gradient-"
        "reduction count, wire dtype, donation aliasing, overlap "
        "structure — against a live trainer step or a saved program "
        "text.",
    )
    sub = parser.add_subparsers(dest="cmd")

    step = sub.add_parser(
        "step", help="compile the canonical trainer step and audit it")
    step.add_argument("--k", type=int, default=4,
                      help="backward_passes_per_step (default 4)")
    step.add_argument("--compression", default=None,
                      help="gradient wire: none/bf16/fp16/int8/fp8 "
                      "(default: HVT_COMPRESSION, else none)")
    step.add_argument("--compression-ici", default=None,
                      help="ICI-hop wire for the two-hop reduction "
                      "(default: HVT_COMPRESSION_ICI, else none); "
                      "audit-visible only with --dcn > 1")
    step.add_argument("--dcn", type=int, default=None,
                      help="fake multi-slice factor (sets HVT_DCN_FACTOR "
                      "before init) so the hierarchical two-hop reduction "
                      "is what lowers")
    step.add_argument("--bucket-bytes", type=int, default=None)
    step.add_argument("--zero1", action="store_true",
                      help="audit the composed ZeRO-1 step "
                      "(Trainer(shard_update=True)): the boundary "
                      "reduction must lower into the sharded update's "
                      "layout — scatter-form reductions only, no "
                      "full-payload all-reduce")
    step.add_argument("--overlap", choices=("auto", "on", "off"),
                      default="auto",
                      help="force the overlap knob (auto = env default)")
    step.add_argument("--expect", default=None,
                      metavar="one-reduction,wire=int8,overlap,...",
                      help="expectation list (default: derived from the "
                      "requested config)")
    step.add_argument("--platform", default=None,
                      help="force the jax platform before init (sets "
                      "HVT_PLATFORM; e.g. cpu)")
    step.add_argument("--devices", type=int, default=8,
                      help="virtual device count with --platform cpu "
                      "(sets HVT_NUM_CPU_DEVICES; default 8)")
    step.add_argument("--dump", default=None, metavar="PATH",
                      help="also write the lowered step text to PATH")

    moe = sub.add_parser(
        "moe", help="audit the EP dispatch/combine probe (the MoE "
        "all-to-all wire shape)")
    moe.add_argument("--expect", default=None,
                     metavar="alltoalls=N,...",
                     help="expectation list (default: alltoalls=2 — "
                     "one dispatch + one combine)")
    moe.add_argument("--platform", default=None,
                     help="force the jax platform before init (sets "
                     "HVT_PLATFORM; e.g. cpu)")
    moe.add_argument("--devices", type=int, default=8,
                     help="virtual device count with --platform cpu "
                     "(the expert axis spans them; default 8)")
    moe.add_argument("--dump", default=None, metavar="PATH",
                     help="also write the lowered probe text to PATH")

    filecmd = sub.add_parser(
        "file", help="audit a saved StableHLO/HLO program text")
    filecmd.add_argument("path")
    filecmd.add_argument("--expect", required=True,
                         metavar="reductions=N,wire=bf16,...")

    args = parser.parse_args(argv)
    if args.cmd is None:
        parser.print_help()
        return 2
    try:
        if args.cmd == "step":
            # Registry-declared defaults for the wires.
            if args.compression is None or args.compression_ici is None:
                from horovod_tpu.analysis import registry

                if args.compression is None:
                    args.compression = registry.get_str("HVT_COMPRESSION")
                if args.compression_ici is None:
                    args.compression_ici = registry.get_str(
                        "HVT_COMPRESSION_ICI"
                    )
            return _run_step(args)
        if args.cmd == "moe":
            return _run_moe(args)
        return _run_file(args)
    except ValueError as e:
        print(f"hvt-audit: {e}", file=sys.stderr)
        return 2


def cli() -> None:
    """Console entry point (`hvt-audit`, pyproject.toml)."""
    raise SystemExit(main())


if __name__ == "__main__":
    raise SystemExit(main())
