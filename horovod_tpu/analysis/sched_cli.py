"""`hvt-sched` — whole-program collective-schedule verification CLI
(analysis layer 3: the static path model checker + the flight-record
replayer).

Usage::

    # Static side: verify every unit's schedule automaton (rule HVT010)
    # and print the entry-path report (Trainer loops, elastic
    # commit/sync, rescale boundary, checkpoint save/broadcast):
    hvt-sched check horovod_tpu/
    hvt-sched check --format json horovod_tpu/

    # Runtime side: cross-check N ranks' flight records (the JSONL the
    # supervisor auto-collects on a hang classification) and name the
    # first divergent submission:
    hvt-sched replay /path/to/flight-dir
    hvt-sched replay --window 5 models/flight/hang-2

Exit codes (the `hvt-lint`/`hvt-audit` contract):

* ``0`` — schedules agree (check: zero non-baselined HVT010 findings;
  replay: every member's record matches op-for-op);
* ``1`` — divergence (printed: witness chains + first mismatched op for
  check; member/seq/op + per-rank context windows for replay);
* ``2`` — usage error / nothing to analyze.
"""

from __future__ import annotations

import argparse
import json
import sys

from horovod_tpu.analysis import core


def _run_check(args) -> int:
    baseline_path = None if args.no_baseline else args.baseline
    try:
        result = core.lint_paths(
            args.paths, root=args.root, select=["HVT010"],
            baseline_path=baseline_path,
        )
    except (OSError, ValueError) as e:
        print(f"hvt-sched: {e}", file=sys.stderr)
        return 2
    if result.files == 0:
        print(
            f"hvt-sched: no python files under {', '.join(args.paths)} — "
            "nothing was verified",
            file=sys.stderr,
        )
        return 2

    entries = _entry_rows(result)
    if args.format == "json":
        print(json.dumps({
            "files": result.files,
            "entries": entries,
            "findings": [f.to_json() for f in result.findings],
            "baselined": [f.to_json() for f in result.baselined],
        }, indent=2))
        return 0 if result.clean else 1

    for row in entries:
        seq = ", ".join(row["sequence"]) or "(no collectives)"
        status = "agree" if row["agree"] else "DIVERGE"
        print(
            f"entry {row['unit']}: {row['paths']} path(s) / "
            f"{row['configurations']} configuration(s) — {status} "
            f"[{seq}]"
        )
    for f in result.findings:
        print(f.format())
    summary = (
        f"hvt-sched: {len(result.findings)} schedule finding(s) in "
        f"{result.files} file(s)"
    )
    if result.baselined:
        summary += f" ({len(result.baselined)} baselined)"
    print(summary)
    return 0 if result.clean else 1


def _entry_rows(result: core.LintResult) -> list:
    """The entry-path automaton report — the banner that makes 'the
    real entry paths verify' a printed fact, not a prose claim. Reuses
    the lint pass's parsed module set AND its memoized schedule checker
    (`schedule.checker_for`): the whole check parses and enumerates
    each unit exactly once."""
    from horovod_tpu.analysis import schedule as schedule_mod

    if result.project is None:
        return []
    return schedule_mod.entry_report(result.project.callgraph())


def _run_replay(args) -> int:
    from horovod_tpu import flight

    by_member = flight.load_members(args.dir)
    if not by_member:
        print(
            f"hvt-sched: no flight-*.jsonl records under {args.dir} — "
            "was HVT_FLIGHT_RECORD set on the run, and did the "
            "supervisor's hang path collect?",
            file=sys.stderr,
        )
        return 2
    counts = ", ".join(
        f"{lb}={len(rs)}" for lb, rs in sorted(by_member.items())
    )
    # The verdict itself is shared with the supervisor policy engine's
    # hang auto-triage (`launch.policy.PolicyEngine.on_hang` journals
    # the same shape) — this CLI only adds the human rendering.
    verdict = flight.replay_verdict(by_member)
    if verdict is None:
        print(
            f"hvt-sched: only one member's record under {args.dir} "
            f"({counts}) — replay needs at least two ranks to "
            "cross-check",
            file=sys.stderr,
        )
        return 2
    if verdict["status"] == "agree":
        print(
            f"hvt-sched: replay ok — {len(by_member)} member(s) agree "
            f"op-for-op ({counts})"
        )
        return 0
    a, b = verdict["member_a"], verdict["member_b"]
    print(
        f"hvt-sched: replay FAILED — first divergent submission at "
        f"seq {verdict['seq']} ({verdict['kind']}):"
    )
    print(f"  member {a}: {verdict['op_a']}")
    print(f"  member {b}: {verdict['op_b']}")
    for label in (a, b):
        print(f"  --- {label} context (seq ±{args.window}) ---")
        for rec in flight.context_window(
            by_member[label], verdict["seq"], args.window
        ):
            marker = ">>" if rec["seq"] == verdict["seq"] else "  "
            print(f"  {marker} [{rec['seq']}] {flight.format_op(rec)}")
    return 1


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="hvt-sched",
        description="Whole-program collective-schedule verification: "
        "static rank-feasible path model checking (HVT010) and "
        "flight-record replay cross-checking.",
    )
    sub = parser.add_subparsers(dest="cmd")

    check = sub.add_parser(
        "check", help="verify schedule automata over a source tree")
    check.add_argument(
        "paths", nargs="*", default=["horovod_tpu"],
        help="files or directories to verify (default: horovod_tpu)")
    check.add_argument(
        "--format", choices=("human", "json"), default="human")
    check.add_argument(
        "--baseline", default=core.DEFAULT_BASELINE, metavar="PATH",
        help="baseline file of grandfathered findings (shared with "
        "hvt-lint)")
    check.add_argument("--no-baseline", action="store_true")
    check.add_argument(
        "--root", default=None, metavar="DIR",
        help="directory findings/baseline paths are relative to")

    replay = sub.add_parser(
        "replay", help="cross-check N ranks' flight records")
    replay.add_argument(
        "dir", help="directory of flight-<member>.jsonl records (the "
        "HVT_FLIGHT_RECORD dir, or a supervisor hang-collection dir)")
    replay.add_argument(
        "--window", type=int, default=3,
        help="context records to print around the divergence "
        "(default 3)")

    args = parser.parse_args(argv)
    if args.cmd is None:
        parser.print_help()
        return 2
    if args.cmd == "check":
        return _run_check(args)
    return _run_replay(args)


def cli() -> None:
    """Console entry point (`hvt-sched`, pyproject.toml)."""
    raise SystemExit(main())


if __name__ == "__main__":
    raise SystemExit(main())
