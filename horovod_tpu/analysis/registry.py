"""Central registry of every ``HVT_*`` environment knob.

The reliability spine (PRs 1-5) grew ~30 env knobs whose names, types and
defaults lived only at their scattered read sites — drift in BOTH
directions (a knob read but documented nowhere; a knob documented but no
longer read) was unobservable. This module is the single source of truth:

* every knob is declared here with type, default, owning subsystem and a
  one-line description;
* code reads knobs through the typed accessors (`get_raw`/`get_str`/
  `get_int`/`get_float`/`get_flag`), which refuse undeclared names — so a
  new knob cannot ship without a registry row;
* the `hvt-lint` rule HVT004 (`analysis/rules.py`) statically rejects any
  ``HVT_*`` string literal in the package that is not declared here, and
  any inline ``os.environ`` read that bypasses the accessors;
* ``docs/ENVVARS.md`` is GENERATED from this table (`generate_doc`;
  ``python -m horovod_tpu.analysis.registry`` prints it) and a tier-1
  test asserts regeneration produces no diff.

Value contract, uniform across every accessor: an UNSET variable and a
variable set to the EMPTY STRING are both "unset" (the registered default
applies). Boolean knobs follow `runtime.env_flag`'s spelling contract:
unset/''/'0'/'false'/'no' (case-insensitive) are off, anything else is on
— that contract is implemented here (`flag_like`) and `runtime.env_flag`
delegates to it, so the accepted spellings cannot drift.

Deliberately dependency-free (stdlib only): the ``hvt-lint`` CLI and the
earliest bootstrap code (`runtime.init`, before any backend exists) both
import this module.
"""

from __future__ import annotations

import dataclasses
import os

__all__ = [
    "Knob", "Tunable", "KNOBS", "UnknownKnobError", "knob", "is_registered",
    "tunable_knobs", "get_raw", "get_str", "get_int", "get_float",
    "get_flag", "flag_like", "generate_doc",
]


@dataclasses.dataclass(frozen=True)
class Tunable:
    """Machine-readable search domain for a knob the autotuner may set.

    `hvt-tune` enumerates its candidate space from these rows — a knob
    without a `Tunable` is invisible to the tuner by construction, and
    rule HVT012 rejects raw env reads of any knob that carries one (a
    read the registry resolver doesn't mediate is a value the tuner
    cannot override).

    kind:
      * ``int``    — integer range [lo, hi]; ``scale`` says how to walk
        it: ``log`` enumerates powers of two, ``linear`` every value.
      * ``choice`` — explicit value set (``choices``).
      * ``flag``   — boolean; candidates are off/on.
    """

    kind: str                      # "int" | "choice" | "flag"
    lo: int | None = None          # int kind: inclusive bounds
    hi: int | None = None
    scale: str = "linear"          # int kind: "log" | "linear"
    choices: tuple = ()            # choice kind: the value set

    def __post_init__(self):
        if self.kind not in ("int", "choice", "flag"):
            raise ValueError(f"unknown tunable kind {self.kind!r}")
        if self.kind == "int":
            if self.lo is None or self.hi is None or self.lo > self.hi:
                raise ValueError(f"int tunable needs lo <= hi, got "
                                 f"[{self.lo}, {self.hi}]")
            if self.scale not in ("log", "linear"):
                raise ValueError(f"unknown tunable scale {self.scale!r}")
        if self.kind == "choice" and not self.choices:
            raise ValueError("choice tunable needs a non-empty choice set")

    def values(self) -> tuple:
        """The concrete candidate values the tuner enumerates."""
        if self.kind == "flag":
            return (False, True)
        if self.kind == "choice":
            return tuple(self.choices)
        if self.scale == "log":
            out, v = [], 1
            while v < self.lo:
                v *= 2
            while v <= self.hi:
                out.append(v)
                v *= 2
            if not out:
                out = [self.lo]
            return tuple(out)
        return tuple(range(self.lo, self.hi + 1))

    def domain_str(self) -> str:
        """Human-readable domain for generated docs and reports."""
        if self.kind == "flag":
            return "off/on"
        if self.kind == "choice":
            return "/".join(str(c) for c in self.choices)
        return f"[{self.lo}, {self.hi}] ({self.scale})"


@dataclasses.dataclass(frozen=True)
class Knob:
    """One declared environment knob."""

    name: str
    type: str          # "str" | "int" | "float" | "flag" | "path" | "spec"
    default: object    # the value accessors return when unset ('' == unset)
    subsystem: str     # owning layer (the ENVVARS.md grouping)
    description: str
    tunable: Tunable | None = None   # autotuner search domain (hvt-tune)


_SUBSYSTEM_ORDER = (
    "runtime", "parallel", "training", "checkpoint", "elastic",
    "launch", "serving", "data", "observability", "testing", "examples",
)


def _decl(knobs: list[Knob]) -> dict[str, Knob]:
    table: dict[str, Knob] = {}
    for k in knobs:
        if k.name in table:
            raise ValueError(f"duplicate knob declaration {k.name}")
        if k.subsystem not in _SUBSYSTEM_ORDER:
            raise ValueError(
                f"{k.name}: unknown subsystem {k.subsystem!r} — add it to "
                "_SUBSYSTEM_ORDER so ENVVARS.md ordering stays deterministic"
            )
        table[k.name] = k
    return table


KNOBS: dict[str, Knob] = _decl([
    # --- runtime bootstrap (runtime.init) ----------------------------------
    Knob("HVT_COORDINATOR_ADDRESS", "str", None, "runtime",
         "jax.distributed coordinator `host:port`; unset = single-process "
         "(every collective degrades to a local op)."),
    Knob("HVT_NUM_PROCESSES", "int", None, "runtime",
         "Process count of the static (non-elastic) world."),
    Knob("HVT_PROCESS_ID", "int", None, "runtime",
         "This process's rank in the static world."),
    Knob("HVT_LOCAL_RANK", "int", 0, "runtime",
         "Ordinal among co-located processes on one host (launcher-set)."),
    Knob("HVT_PLATFORM", "str", None, "runtime",
         "Force the jax platform (e.g. `cpu`) before backend init — "
         "overrides a site hook's forced accelerator registration."),
    Knob("HVT_NUM_CPU_DEVICES", "int", None, "runtime",
         "Virtual CPU device count for launched children (authoritative: "
         "replaces an inherited XLA_FLAGS device count)."),
    Knob("HVT_FAST_RNG", "flag", False, "runtime",
         "Use the TPU hardware RNG (`rbg`) instead of threefry: faster "
         "dropout, not bit-reproducible across topologies."),
    # --- parallel / mesh ---------------------------------------------------
    Knob("HVT_MESH", "spec", None, "parallel",
         "Mesh axis sizes, `axis=size` pairs (`data=2,seq=4`); "
         "unset/empty = pure data parallelism (`MeshSpec.from_string`)."),
    Knob("HVT_MESH_ORDER", "str", "auto", "parallel",
         "Physical device layout: `auto` (ICI-torus-aware mesh_utils) or "
         "`flat` (enumeration-order reshape)."),
    Knob("HVT_DCN_FACTOR", "int", None, "parallel",
         "Override the derived multi-slice factor of the data axis — the "
         "fake-topology knob for the ICI/DCN two-hop reduction; must "
         "divide the axis size."),
    Knob("HVT_BUCKET_BYTES", "int", None, "parallel",
         "Gradient-fusion bucket cap in bytes for the explicit-collective "
         "boundary reduction (default: collectives.DEFAULT_BUCKET_BYTES, "
         "64 MB — Horovod's fusion threshold).",
         tunable=Tunable("int", lo=1 << 18, hi=1 << 28, scale="log")),
    Knob("HVT_OVERLAP_REDUCTION", "flag", True, "parallel",
         "Overlap the boundary reduction with the backward: peel the last "
         "microbatch out of the accumulation scan so bucket-wise "
         "reductions issue inside the same schedulable region as its "
         "backward (async start/done overlap on TPU). Off = serialize "
         "the reduction after the scan (identical arithmetic).",
         tunable=Tunable("flag")),
    Knob("HVT_BUCKET_ORDER", "str", "reverse", "parallel",
         "Boundary-reduction bucket issue order: `reverse` (last-produced "
         "gradients reduce first — Horovod's fusion order, overlappable "
         "with the backward) or `forward` (pytree order)."),
    # --- training ----------------------------------------------------------
    Knob("HVT_SAVE_EVERY_STEPS", "int", 0, "training",
         "ModelCheckpoint mid-epoch save cadence in optimizer steps "
         "(0 = epoch cadence only). Single-file checkpoints only."),
    Knob("HVT_EPOCH_CHUNK_STEPS", "int", 0, "training",
         "fit(cache='device'): split each on-device epoch into compiled "
         "chunks of this many optimizer steps (0 = whole-epoch program), "
         "so on_batch_end fires per chunk and sub-epoch commit/rescale "
         "cadences work on the device-cached path too."),
    # --- elastic -----------------------------------------------------------
    Knob("HVT_ELASTIC_COORDINATOR", "str", None, "elastic",
         "Rendezvous coordinator `host:port` (supervisor-set); presence "
         "switches faults and entry scripts into elastic mode."),
    Knob("HVT_ELASTIC_MEMBER", "str", None, "elastic",
         "This process's stable elastic member identity (supervisor-set)."),
    Knob("HVT_COMMIT_EVERY", "int", 1, "elastic",
         "Elastic commit cadence in epochs (ElasticStateCallback default; "
         "job-spec `elastic: {commit_every}` travels as this)."),
    Knob("HVT_COMMIT_EVERY_STEPS", "int", 0, "elastic",
         "Additional sub-epoch commit cadence in optimizer steps "
         "(0 = epoch cadence only)."),
    Knob("HVT_RESCALE_EVERY_STEPS", "int", 0, "elastic",
         "Sub-epoch membership-agreement cadence in optimizer steps "
         "(0 = epoch boundaries only)."),
    Knob("HVT_ELASTIC_SPARE", "flag", False, "elastic",
         "Member-side warm-standby parking (supervisor-set when spares "
         "are configured): a 'world is full' rendezvous rejection makes "
         "the client wait and re-knock instead of failing, so spare "
         "processes stay parked until an eviction frees a slot."),
    # --- launch / supervision ----------------------------------------------
    Knob("HVT_HEARTBEAT_DIR", "path", None, "launch",
         "Per-rank liveness dir (supervisor-set); fit() auto-installs "
         "HeartbeatCallback when present."),
    Knob("HVT_RESTART_LOG_MAX_LINES", "int", 100000, "launch",
         "Restart-journal rotation bound in lines (0 disables)."),
    Knob("HVT_RESTART_LOG_MAX_MB", "float", 64.0, "launch",
         "Restart-journal rotation bound in MB (0 disables)."),
    Knob("HVT_STATUS_HOST", "str", "127.0.0.1", "launch",
         "Bind host for the supervisor status endpoint (`--status-port`); "
         "loopback by default — set 0.0.0.0 to expose off-host."),
    Knob("HVT_POLICY", "str", "off", "launch",
         "Supervisor policy engine mode: off | dry-run | on. dry-run "
         "journals every decision (policy_* events) without acting; on "
         "closes the observe->act loop (straggler evict-and-shrink, "
         "hot-spare promotion, hang auto-triage)."),
    Knob("HVT_POLICY_STRAGGLER_WINDOWS", "int", 3, "launch",
         "Consecutive fresh metric windows a majority-named straggler "
         "must persist before the policy engine evicts it."),
    Knob("HVT_POLICY_STRAGGLER_WAIT_MS", "float", 100.0, "launch",
         "Minimum peak hvt_barrier_wait_ms across the fleet for a "
         "straggler window to count toward eviction."),
    Knob("HVT_POLICY_EVICT_BUDGET", "int", 1, "launch",
         "Policy-initiated evictions allowed per supervised run "
         "(separate from the restart budget)."),
    Knob("HVT_POLICY_COOLDOWN_S", "float", 60.0, "launch",
         "Minimum seconds between policy actions (eviction cooldown)."),
    Knob("HVT_POLICY_SPARES", "int", 0, "launch",
         "Warm standby processes the elastic supervisor keeps parked at "
         "rendezvous; an eviction frees a slot and a spare joins the "
         "next generation so world size is preserved."),
    Knob("HVT_FLEET_TICK_S", "float", 0.5, "launch",
         "hvt-launch fleet scheduler cadence in seconds (reap exits, "
         "scrape job controller ledgers, place/preempt/regrow)."),
    Knob("HVT_FLEET_QUARANTINE_S", "float", 60.0, "launch",
         "Cooldown before a host declared lost (all co-resident ranks "
         "died together) returns to the fleet scheduler's pool."),
    Knob("HVT_FLEET_HOST", "str", None, "launch",
         "The pool host this rank was placed on (fleetd-set via the "
         "member env) — host identity for host-loss classification and "
         "the hostdown fault's blast radius."),
    Knob("HVT_TUNE_EVIDENCE", "path", None, "launch",
         "Evidence directory for the `hvt-tune` offline model (BENCH_* "
         "rows, trace spans); unset = the working directory. The job "
         "spec `tune: {evidence}` key travels as this."),
    Knob("HVT_TUNE_STEPS", "int", 3, "launch",
         "In-situ probe: real optimizer steps per timed leg when "
         "`hvt-tune probe` A/B-races candidate configs at job start."),
    Knob("HVT_TUNE_CANDIDATES", "int", 3, "launch",
         "In-situ probe shortlist size: the offline model ranks the "
         "candidate space and only the top N race real steps."),
    # --- serving (continuous batching engine + replica fleet) ---------------
    Knob("HVT_SERVE_MAX_SEQS", "int", 0, "serving",
         "Continuous batching: max concurrently scheduled sequences per "
         "replica (decode slots). 0 = the bundle's compiled batch size; "
         "values above it clamp to the compiled shape."),
    Knob("HVT_SERVE_BLOCK_TOKENS", "int", 16, "serving",
         "Paged-KV block granularity in tokens: admission reserves "
         "ceil((prompt+max_new)/block) blocks for a sequence's whole "
         "lifetime, so a running sequence can never hit OOM mid-decode."),
    Knob("HVT_SERVE_KV_BLOCKS", "int", 0, "serving",
         "Total paged-KV blocks in the admission budget. 0 = auto-size "
         "to max_seqs full-length sequences (admission then gates purely "
         "on slots); smaller budgets make the allocator the gate — "
         "exhaustion queues new sequences and 429s past the queue."),
    Knob("HVT_SERVE_QUEUE_DEPTH", "int", 64, "serving",
         "Admission wait-queue depth per replica: sequences past the "
         "block/slot budget wait here FIFO; a full queue answers 429 "
         "(AdmissionError) instead of stacking unbounded memory."),
    Knob("HVT_SERVE_REPLICAS", "int", 2, "serving",
         "`hvt-launch serve` fleet width: replica server processes "
         "behind the router (each with its own engine + KV budget)."),
    Knob("HVT_SERVE_DRAIN_TIMEOUT_S", "float", 30.0, "serving",
         "Drain budget in seconds: how long a replica waits for in-flight "
         "requests to finish on SIGTERM, and how long a weight reload "
         "waits for the engine to empty before refusing the swap."),
    Knob("HVT_SERVE_SWAP_TIMEOUT_S", "float", 120.0, "serving",
         "Zero-downtime weight swap budget per replica: router drain + "
         "reload + health check must fit here or the swap aborts and the "
         "replica is readmitted on its OLD weights (journaled)."),
    Knob("HVT_SERVE_AUTOSCALE", "str", "off", "serving",
         "Fleet autoscale hook: off / dry-run (journal "
         "policy_scale_up/down without acting) / on (spawn or drain a "
         "replica). Decisions come from the policy engine's "
         "ServeAutoscaler over the router's TTFT histogram."),
    Knob("HVT_SERVE_TTFT_P95_MS", "float", 250.0, "serving",
         "Autoscale SLO: windowed p95 TTFT (ms) above this for "
         "consecutive windows scales up; far below (x0.3) scales down."),
    # --- data --------------------------------------------------------------
    Knob("HVT_NO_NATIVE", "flag", False, "data",
         "Disable the native C++ loader; fall back to the pure-python "
         "feeding path."),
    Knob("HVT_PREFETCH_DEPTH", "int", 2, "data",
         "Device-prefetch queue depth for the streamed fit path (staged "
         "batches ahead of the consuming step; 2 = classic double "
         "buffering — the step donates each consumed batch's buffer)."),
    Knob("HVT_DATA_DIR", "path", "~/.cache/horovod_tpu", "data",
         "Dataset cache directory (the keras-layout npz archives)."),
    Knob("HVT_DATA_RETRIES", "int", 3, "data",
         "Bounded retries for TRANSIENT dataset I/O failures (shard mmap "
         "opens, index reads — the flaky-NFS OSError class) before "
         "failing fast with the checkpoint-fallback escalation "
         "(0 = no retry)."),
    Knob("HVT_DATA_BACKOFF_S", "float", 0.05, "data",
         "Base backoff in seconds between dataset-read retries; doubles "
         "per attempt (exponential)."),
    Knob("HVT_DATA_SERVICE", "str", None, "data",
         "hvt-data dispatcher address (`host:port`): a service client "
         "(data/client.py) with this set fetches batches from the "
         "shared dispatcher under the HVT_DATA_RETRIES budget, "
         "degrading to rank-local feeding FROM THE SAME CURSOR "
         "(byte-identical) when the budget is exhausted and "
         "re-attaching at the next epoch boundary. Unset = pure local "
         "feeding. fleetd injects it into every job when the fleet "
         "spec carries a `data_service:` block."),
    Knob("HVT_DATA_JOB", "str", "default", "data",
         "Job name a service client admits its stream under on the "
         "hvt-data dispatcher — the per-job isolation and "
         "hvt_data_*{job=} metrics key (give each fleet job a distinct "
         "name)."),
    Knob("HVT_DATA_TIMEOUT_S", "float", 5.0, "data",
         "Per-socket-operation timeout (seconds) for hvt-data client "
         "fetches: a hung dispatcher surfaces as a retriable timeout "
         "inside the HVT_DATA_RETRIES budget instead of wedging the "
         "fed rank."),
    # --- observability ------------------------------------------------------
    Knob("HVT_PROFILE", "path", None, "observability",
         "Capture a jax.profiler trace of fit()/bench into this dir — the "
         "HOROVOD_TIMELINE contract, primary-process-gated."),
    Knob("HVT_PEAK_FLOPS", "float", None, "observability",
         "Per-chip peak FLOP/s override for the MFU denominator — set it "
         "when the device kind is missing from the built-in peak table "
         "(CPU CI topologies, new TPU generations) so every BENCH_* row "
         "carries a real MFU trend number instead of null; bench.py "
         "calibrates a matmul-peak fallback when unset on an unknown "
         "device, and exits 2 on an unparseable override."),
    Knob("HVT_METRICS_DIR", "path", None, "observability",
         "Metrics-stream directory (default: $PS_MODEL_PATH, else "
         "./models)."),
    Knob("HVT_METRICS_PORT", "int", None, "observability",
         "Opt-in trainer-side Prometheus exporter: every training "
         "process serves GET /metrics (live step-phase/MFU gauges) and "
         "POST /profile?seconds=N (on-demand jax.profiler capture) on "
         "port N + local_rank; 0 binds an ephemeral port; unset = off."),
    Knob("HVT_METRICS_EVERY", "int", 32, "observability",
         "Step-phase sampling cadence in optimizer steps for the "
         "trainer exporter: every N steps the fit loop drains the "
         "pipeline once and refreshes the step_ms{total,compute,comm,"
         "input} / examples-per-sec / MFU gauges (bench A/B-gates the "
         "overhead at <= 2% of step time)."),
    Knob("HVT_FLIGHT_RECORD", "path", None, "observability",
         "Collective flight recorder: set to a DIRECTORY and every "
         "collectives.py submission site appends a bounded per-process "
         "JSONL record (seq, kind, dtype, shape, bytes, bucket id, "
         "caller tag) to <dir>/flight-<member>.jsonl — write-through "
         "before the collective blocks, dumped on SIGTERM and "
         "POST /flightrecord, auto-collected by the supervisor's hang "
         "path, cross-checked by `hvt-sched replay`. Unset = recorder "
         "off (zero instrumentation cost)."),
    Knob("HVT_FLIGHT_RECORD_SIZE", "int", 512, "observability",
         "Flight-recorder ring bound in records per process (explicit "
         "dumps rewrite the file to at most this many)."),
    Knob("HVT_TRACE_DIR", "path", None, "observability",
         "Structured trace-span directory: nestable JSONL span records "
         "(step, reduction, commit, rescale, checkpoint-save, serving "
         "request/queue-wait/decode), one rank-tagged file per process "
         "(trace.span); also the landing dir for POST /profile "
         "captures, and the input of `hvt-trace timeline/report/skew` "
         "(cross-rank merge, obs/timeline.py). Unset = spans off."),
    Knob("HVT_SKEW_PROBE", "flag", True, "observability",
         "Live cross-rank straggler detection (trainer.SkewProbe): at "
         "each step-phase sample window a tiny host allgather of drain "
         "waits publishes hvt_step_skew_ms / hvt_straggler_rank / "
         "hvt_barrier_wait_ms. Only active when the trainer exporter "
         "(HVT_METRICS_PORT) is on and the run is multi-process; set 0 "
         "to kill the probe while keeping the exporter."),
    Knob("HVT_FLEET_POLL_S", "float", 10.0, "observability",
         "Supervisor fleet-rollup poll cadence in seconds: how often "
         "the status server re-scrapes each member's trainer exporter "
         "into the GET /fleet cache (also what the final metrics.prom "
         "dump merges, so per-rank series survive the fleet). 0 "
         "disables background polling — /fleet then scrapes only on "
         "request."),
    # --- testing / chaos ----------------------------------------------------
    Knob("HVT_FAULT", "spec", None, "testing",
         "Deterministic fault injection, `rank:epoch[.step]:kind` (kinds "
         "kill/exitN/hang/leave/reorder/corrupt[@target]/slow:MS/"
         "netdrop:MS/dataslow:MS/hostdown; `hostdown` SIGKILLs every "
         "rank sharing the firing "
         "rank's host via the HVT_FAULT_HOST_PIDS registry — the "
         "host-loss ground truth for hvt-launch fleet; "
         "`reorder` swaps the rank's last two flight-recorded "
         "submissions, then wedges like `hang` — the hvt-sched replay "
         "acceptance fault; `slow:MS` makes the rank sleep MS ms per "
         "step from the target epoch on, recurring — the hvt-trace "
         "straggler-detection ground truth; the data-plane kinds "
         "`netdrop:MS` (hvt-data client drops its dispatcher "
         "connection + delays reconnect MS ms before every fetch "
         "DURING the target epoch) and `dataslow:MS` (dispatcher "
         "delays every batch response MS ms from the target epoch on) "
         "fire in data/client.py and data/service.py via "
         "faults.data_fault_ms, not in the trainer callback)."),
    Knob("HVT_FAULT_STAMP", "path", None, "testing",
         "One-shot stamp file: the fault fires once, never while the "
         "stamp exists — across relaunches."),
    Knob("HVT_FAULT_HOST_PIDS", "path", None, "testing",
         "Per-host pid registry directory for the `hostdown` fault kind "
         "(fleetd points every rank placed on host H at `<dir>/H`); each "
         "rank's fault callback registers its pid there at epoch begin, "
         "and a firing `hostdown` SIGKILLs every registered live pid — "
         "peers first, self last. Unset degrades hostdown to a "
         "self-SIGKILL."),
    Knob("HVT_DATA_FAULT_READS", "int", 0, "testing",
         "Inject N deterministic TRANSIENT read faults (OSError) into "
         "the dataset-read retry path (data.stream.read_with_retries) — "
         "the chaos hook for exercising HVT_DATA_RETRIES."),
    # --- examples / bench (read by entry scripts, not the package) ----------
    Knob("HVT_BACKWARD_PASSES", "int", 1, "examples",
         "Gradient-accumulation factor K for the example entry scripts "
         "(DistributedOptimizer backward_passes_per_step).",
         tunable=Tunable("int", lo=1, hi=8, scale="log")),
    Knob("HVT_COMPRESSION", "str", "none", "examples",
         "Gradient wire compression for the example/bench entry scripts "
         "(none/bf16/fp16/int8/fp8 — DistributedOptimizer(compression=); "
         "int8/fp8 carry error-feedback residuals by default).",
         tunable=Tunable("choice",
                         choices=("none", "bf16", "fp16", "int8", "fp8"))),
    Knob("HVT_COMPRESSION_ICI", "str", "none", "examples",
         "ICI-hop gradient wire for the example/bench entry scripts "
         "(none/bf16/fp16/int8/fp8 — DistributedOptimizer("
         "compression_ici=): the hierarchical two-hop reduction's "
         "intra-slice hop, error-feedback-charged per hop for int8/fp8; "
         "inert on single-slice meshes where dcn == 1).",
         tunable=Tunable("choice",
                         choices=("none", "bf16", "fp16", "int8", "fp8"))),
    Knob("HVT_DEVICE_CACHE", "flag", False, "examples",
         "Examples: stage the dataset into HBM once (`cache='device'`)."),
    Knob("HVT_EXPORT_FORMAT", "str", "stablehlo", "examples",
         "Examples: serving-bundle export format (stablehlo/savedmodel)."),
])


class UnknownKnobError(KeyError):
    """An env knob was read that is not declared in this registry."""

    def __init__(self, name: str):
        super().__init__(
            f"{name} is not a declared HVT_* knob — add a Knob row to "
            "horovod_tpu/analysis/registry.py (type, default, subsystem, "
            "description) and regenerate docs/ENVVARS.md"
        )


def knob(name: str) -> Knob:
    try:
        return KNOBS[name]
    except KeyError:
        raise UnknownKnobError(name) from None


def is_registered(name: str) -> bool:
    return name in KNOBS


def tunable_knobs() -> dict[str, Knob]:
    """The knobs carrying autotuner domain metadata, name-sorted — the
    whole candidate space `hvt-tune` is allowed to search."""
    return {name: k for name, k in sorted(KNOBS.items()) if k.tunable}


def flag_like(value: str | None) -> bool:
    """The shared boolean env contract (see module docstring)."""
    return (value or "").lower() not in ("", "0", "false", "no")


def get_raw(name: str, *, environ=None) -> str | None:
    """The raw string value, or None when unset/empty. The name must be
    registered — this is the choke point HVT004 pushes every read through."""
    k = knob(name)
    env = os.environ if environ is None else environ
    raw = env.get(k.name, "")
    return raw if raw != "" else None


def get_str(name: str, *, environ=None) -> str | None:
    raw = get_raw(name, environ=environ)
    return raw if raw is not None else knob(name).default


def get_int(name: str, *, environ=None) -> int | None:
    raw = get_raw(name, environ=environ)
    if raw is None:
        d = knob(name).default
        return None if d is None else int(d)
    return int(raw)


def get_float(name: str, *, environ=None) -> float | None:
    raw = get_raw(name, environ=environ)
    if raw is None:
        d = knob(name).default
        return None if d is None else float(d)
    return float(raw)


def get_flag(name: str, *, environ=None) -> bool:
    k = knob(name)
    raw = get_raw(name, environ=environ)
    return bool(k.default) if raw is None else flag_like(raw)


# --- generated reference doc (docs/ENVVARS.md) ------------------------------

_DOC_HEADER = """\
# `HVT_*` environment variables

<!-- GENERATED FILE — do not edit by hand.
     Source of truth: horovod_tpu/analysis/registry.py.
     Regenerate: python -m horovod_tpu.analysis.registry > docs/ENVVARS.md
     (tests/test_lint_clean.py fails when this file drifts). -->

Every knob the framework reads, from the central registry
(`horovod_tpu/analysis/registry.py`). Contract, uniform across all knobs:
**unset and empty-string are equivalent** (the default applies); `flag`
knobs treat `''`/`0`/`false`/`no` (case-insensitive) as off and anything
else as on. The static analyzer (`hvt-lint`, rule HVT004) rejects any
`HVT_*` read in the package that is not declared in the registry.

`PS_MODEL_PATH` (not `HVT_`-prefixed — inherited from the reference
stack) is the checkpoint/metrics root many defaults hang off; it is
documented where used rather than registered here.
"""


def _fmt_default(k: Knob) -> str:
    if k.default is None:
        return "—"
    if k.type == "flag":
        return "on" if k.default else "off"
    return f"`{k.default}`"


def generate_doc() -> str:
    """Render the ENVVARS.md content. Deterministic: grouped by subsystem
    in `_SUBSYSTEM_ORDER`, name-sorted within a group."""
    parts = [_DOC_HEADER]
    for sub in _SUBSYSTEM_ORDER:
        group = sorted(
            (k for k in KNOBS.values() if k.subsystem == sub),
            key=lambda k: k.name,
        )
        if not group:
            continue
        parts.append(f"\n## {sub}\n")
        parts.append("| name | type | default | description |")
        parts.append("|---|---|---|---|")
        for k in group:
            parts.append(
                f"| `{k.name}` | {k.type} | {_fmt_default(k)} "
                f"| {k.description} |"
            )
    tunables = tunable_knobs()
    if tunables:
        parts.append("\n## autotuner domains\n")
        parts.append(
            "Knobs carrying machine-readable `tunable=` domain metadata — "
            "the candidate space `hvt-tune` enumerates (offline model "
            "search and in-situ probe shortlist). A knob not listed here "
            "is invisible to the tuner by construction."
        )
        parts.append("")
        parts.append("| name | kind | domain |")
        parts.append("|---|---|---|")
        for name, k in tunables.items():
            parts.append(
                f"| `{name}` | {k.tunable.kind} | {k.tunable.domain_str()} |"
            )
    return "\n".join(parts) + "\n"


if __name__ == "__main__":
    print(generate_doc(), end="")
