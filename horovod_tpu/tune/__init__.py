"""hvt-tune — the trace-replay autotuner (ISSUE 19).

The repo records everything a tuner needs (per-bucket comm timings,
audited FLOPs and wire bytes, per-phase trace attributions, serialized
vs overlapped step pairs); this package closes the loop so the config
searches itself — the `HOROVOD_AUTOTUNE` counterpart (arxiv
1802.05799), characterization-driven (arxiv 1810.11112) instead of
black-box:

* `space`    — candidate configs enumerated from registry ``tunable=``
               domain metadata (the tuner's reach is a registry edit);
* `evidence` — loaders funneling BENCH_* rows, audit counts and trace
               spans into model inputs;
* `model`    — the analytic alpha-beta comm/compute model, fitted from
               evidence with per-term provenance;
* `offline`  — rank the space on predictions alone; report + --check;
* `probe`    — the paired-leg A/B discipline (extracted from bench.py)
               with an injectable clock;
* `insitu`   — job-start resolution: offline shortlist, real-step
               probe race in a subprocess, journaled + persisted so a
               restart reuses the winner;
* `cli`      — the `hvt-tune` console script (exit contract 0/1/2).

Import-light by design: everything except `insitu.build_probe_step`
(the probe subprocess's leg builder) stays off jax.
"""

from horovod_tpu.tune.probe import PairedResult, paired_compare

__all__ = ["PairedResult", "paired_compare"]
