"""Paired-leg A/B measurement: the discipline every timed comparison
in this repo uses, extracted from bench.py (PR 13) so the autotuner can
point it at itself.

A naive A/B on a shared noisy host crowns fake winners two ways:
monotone machine drift (thermal, cache warming) systematically favors
whichever leg runs second, and a single outlier sample swings a mean.
The discipline here kills both:

* legs run in TEMPORALLY ADJACENT PAIRS with alternating order
  (pair 0: A then B, pair 1: B then A, ...), so drift cancels across
  pairs instead of accumulating into one leg;
* the gate statistic is the MEDIAN of per-pair relative differences
  (outlier pairs cannot move it);
* pairs keep accumulating until the median is STABLE — median absolute
  deviation of the pair diffs <= ``mad_stop_pct`` — or the cap is hit
  (adaptive stop: quiet hosts converge in ``pairs_min`` pairs, noisy
  hosts buy resolution with wall clock).

The clock is injectable (``clock=``) so the discipline itself is
testable against a fake clock with no real legs at all.
"""

from __future__ import annotations

import dataclasses
import time

__all__ = ["PairedResult", "paired_compare", "median"]


def median(xs) -> float:
    """Upper median — matches the bench gate's sorted()[n // 2]."""
    xs = sorted(xs)
    if not xs:
        raise ValueError("median of empty sequence")
    return xs[len(xs) // 2]


@dataclasses.dataclass(frozen=True)
class PairedResult:
    """Outcome of one paired A/B race.

    ``median_pct`` is the median over pairs of ``(t_b - t_a) / t_a``
    in percent: POSITIVE means leg B is slower than leg A.
    """

    median_pct: float
    mad_pct: float          # median absolute deviation of the pair diffs
    pairs: int
    a_times: tuple          # per-pair leg-A seconds, chronological
    b_times: tuple
    converged: bool         # stopped on MAD stability, not the pair cap

    @property
    def b_wins(self) -> bool:
        return self.median_pct < 0.0


def paired_compare(leg_a, leg_b, *, pairs_min: int = 3, pairs_cap: int = 9,
                   mad_stop_pct: float = 0.75,
                   clock=time.perf_counter) -> PairedResult:
    """Race two zero-arg legs and return the paired-median verdict.

    Each leg callable runs one full measurement leg (including any
    device sync at its boundaries) and is timed here with ``clock``.
    Legs should be pre-warmed: the first invocation is already scored.
    """
    pairs_min = max(1, int(pairs_min))
    pairs_cap = max(pairs_min, int(pairs_cap))
    diffs: list[float] = []
    a_times: list[float] = []
    b_times: list[float] = []
    converged = False
    while True:
        p = len(diffs)
        order = ("a", "b") if p % 2 == 0 else ("b", "a")
        t = {}
        for which in order:
            fn = leg_a if which == "a" else leg_b
            t0 = clock()
            fn()
            t[which] = clock() - t0
        diffs.append((t["b"] - t["a"]) / t["a"] * 100.0)
        a_times.append(t["a"])
        b_times.append(t["b"])
        if len(diffs) >= pairs_min:
            med = median(diffs)
            spread = median([abs(d - med) for d in diffs])
            if spread <= mad_stop_pct:
                converged = True
                break
            if len(diffs) >= pairs_cap:
                break
    med = median(diffs)
    mad = median([abs(d - med) for d in diffs])
    return PairedResult(
        median_pct=med, mad_pct=mad, pairs=len(diffs),
        a_times=tuple(a_times), b_times=tuple(b_times),
        converged=converged,
    )
