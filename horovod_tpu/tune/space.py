"""Candidate-space enumeration for the autotuner.

The space is METADATA-DRIVEN: every knob `hvt-tune` may set is a
registry row carrying ``tunable=`` domain metadata
(`analysis/registry.py`), and the candidate values come from
``Tunable.values()``. Growing the tuner's reach is a registry edit, not
a tuner edit — and a knob without domain metadata cannot be touched by
the tuner at all (the same property rule HVT012 polices from the other
side: no raw env read of a tunable knob outside the resolver).

A "config" throughout the tune package is a plain dict mapping the
tunable knob NAMES to concrete resolved values, e.g.::

    {"HVT_BUCKET_BYTES": 4194304, "HVT_BACKWARD_PASSES": 4,
     "HVT_COMPRESSION": "none", "HVT_COMPRESSION_ICI": "none",
     "HVT_OVERLAP_REDUCTION": True}
"""

from __future__ import annotations

import itertools

from horovod_tpu.analysis import registry

__all__ = [
    "DEFAULT_BUCKET_BYTES", "domains", "default_config", "resolved_config",
    "enumerate_configs", "env_of", "deviations",
]

# Mirrors collectives.DEFAULT_BUCKET_BYTES (Horovod's 64 MB fusion
# threshold) without importing the jax-heavy collectives module into the
# CLI path; a tier-1 test asserts the two never drift.
DEFAULT_BUCKET_BYTES = 64 * 1024 * 1024


def domains() -> dict[str, tuple]:
    """name -> candidate values, for every tunable knob (name-sorted)."""
    return {name: k.tunable.values()
            for name, k in registry.tunable_knobs().items()}


def _resolve_one(name: str, environ=None):
    k = registry.knob(name)
    if k.type == "int":
        v = registry.get_int(name, environ=environ)
    elif k.type == "flag":
        v = registry.get_flag(name, environ=environ)
    else:
        v = registry.get_str(name, environ=environ)
    if v is None and name == "HVT_BUCKET_BYTES":
        v = DEFAULT_BUCKET_BYTES
    return v


def default_config() -> dict:
    """The registry-default values of every tunable knob — the config a
    job runs under when nobody sets anything (the tuner's baseline)."""
    return resolved_config(environ={})


def resolved_config(environ=None) -> dict:
    """The fully-resolved tunable-knob values under ``environ`` (the
    process env by default) — what BENCH rows stamp as ``config:``."""
    return {name: _resolve_one(name, environ=environ)
            for name in registry.tunable_knobs()}


def enumerate_configs(*, knobs=None, pin=None, environ=None) -> list[dict]:
    """The candidate configs, as the cross product of tunable domains.

    ``knobs`` restricts which knobs VARY (the rest hold their resolved
    value under ``environ``); ``pin`` forces specific values outright.
    Unknown or non-tunable names in either are an error — the caller
    asked the tuner to touch a knob it cannot see.
    """
    base = resolved_config(environ=environ)
    doms = domains()
    pin = dict(pin or {})
    vary = list(doms) if knobs is None else list(knobs)
    for name in list(pin) + vary:
        if name not in doms:
            raise ValueError(
                f"{name} is not a tunable knob — give it `tunable=` domain "
                "metadata in analysis/registry.py to put it in the "
                "tuner's reach"
            )
    vary = [n for n in vary if n not in pin]
    out = []
    for combo in itertools.product(*(doms[n] for n in vary)):
        cfg = dict(base)
        cfg.update(pin)
        cfg.update(zip(vary, combo))
        out.append(cfg)
    return out


def env_of(config: dict) -> dict[str, str]:
    """Render a config as env-var strings (what the launcher exports)."""
    out = {}
    for name, v in config.items():
        if isinstance(v, bool):
            out[name] = "1" if v else "0"
        else:
            out[name] = str(v)
    return out


def deviations(config: dict) -> int:
    """How many knobs differ from the registry default — the tiebreak
    (prefer the config that changes the least) for equal predictions."""
    base = default_config()
    return sum(1 for n, v in config.items() if base.get(n) != v)
