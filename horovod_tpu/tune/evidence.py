"""Evidence loaders: recorded measurements -> tuner inputs.

The repo already records everything an analytic tuner needs, it just
records it in four places. This module is the funnel:

* **BENCH_* rows** (``{"n", "cmd", "rc", "tail"}`` with a JSON tail
  printed by bench.py) — per-bucket ``step_ms.comm_buckets`` timings,
  the step decomposition, the serialized-vs-overlapped pair, the
  structural ``wire_bytes_per_opt_step``. Rows stamped with a
  ``config:`` block (PR 19) are self-describing; LEGACY rows without
  one get their tunable values inferred from the row keys bench has
  always emitted (``bucket_bytes``, ``k``, ``compression`` ...).
* **hvt-trace spans** (``HVT_TRACE_DIR`` JSONL) — per-phase wall-time
  attribution via `obs.timeline.phase_attribution`, used to
  cross-check the input/compute split.
* **hvt-audit structural counts** ride inside the rows
  (``wire_bytes_per_opt_step``, ``flops_per_opt_step`` are audited
  from the lowered program, not timed), so loading rows loads them.

Every loader degrades to "no evidence" (empty/None) rather than
raising: the offline CLI turns missing evidence into exit 2, not a
traceback.
"""

from __future__ import annotations

import glob
import json
import os

from horovod_tpu.tune import space

__all__ = [
    "load_rows", "config_of", "anchor_row", "comm_points",
    "load_trace", "wire_ratio",
]

# Bytes-on-wire ratio per compression wire, relative to f32. Structural
# (dtype width), not timed — int8/fp8 quantized wires are byte-equal to
# their dtype width by construction (hvt-audit's wire gate checks this).
_WIRE_RATIO = {"none": 1.0, "bf16": 0.5, "fp16": 0.5,
               "int8": 0.25, "fp8": 0.25}


def wire_ratio(name: str) -> float:
    return _WIRE_RATIO.get(str(name or "none"), 1.0)


def load_rows(evidence_dir: str) -> list[dict]:
    """Parse every BENCH_*.json under ``evidence_dir`` into tail dicts.

    Each returned dict is the bench tail with bookkeeping keys added:
    ``_source`` (filename) and ``_cmd`` (the recorded command line).
    Unparseable files are skipped — stale evidence must not brick the
    tuner. Sorted by filename, so the NEWEST row (highest r-number)
    is last.
    """
    rows = []
    for path in sorted(glob.glob(os.path.join(evidence_dir, "BENCH_*.json"))):
        try:
            with open(path, encoding="utf-8") as f:
                rec = json.load(f)
            tail = rec.get("tail") if isinstance(rec, dict) else None
            row = json.loads(tail) if isinstance(tail, str) else (
                tail if isinstance(tail, dict) else rec)
            if not isinstance(row, dict):
                continue
            row = dict(row)
            row["_source"] = os.path.basename(path)
            row["_cmd"] = rec.get("cmd", "") if isinstance(rec, dict) else ""
            rows.append(row)
        except (OSError, ValueError):
            continue
    return rows


def config_of(row: dict) -> dict:
    """The tunable-knob values a row ran under.

    Rows since PR 19 carry an explicit ``config:`` block; legacy rows
    are inferred from the measurement keys bench always emitted, with
    registry defaults filling the gaps.
    """
    cfg = dict(space.default_config())
    legacy = {
        "HVT_BUCKET_BYTES": row.get("bucket_bytes"),
        "HVT_BACKWARD_PASSES": row.get("k"),
        "HVT_COMPRESSION": row.get("compression"),
        "HVT_COMPRESSION_ICI": row.get("compression_ici"),
        # bench's zero1 headline leg has always been the overlapped one
        # (serialized is the B leg) — a row reporting overlap_fraction
        # measured with the overlap on.
        "HVT_OVERLAP_REDUCTION": (True if "overlap_fraction" in row
                                  else None),
    }
    for name, v in legacy.items():
        if v is not None:
            cfg[name] = v
    stamped = row.get("config")
    if isinstance(stamped, dict):
        for name, v in stamped.items():
            if name in cfg and v is not None:
                cfg[name] = v
    return cfg


def anchor_row(rows: list[dict]) -> dict | None:
    """The newest row rich enough to anchor the model: needs the
    per-bucket comm attribution and the step decomposition."""
    for row in reversed(rows):
        sm = row.get("step_ms")
        if (isinstance(sm, dict) and sm.get("comm_buckets")
                and sm.get("total")):
            return row
    return None


def comm_points(rows: list[dict]) -> list[tuple[float, float]]:
    """Pooled per-bucket ``(bytes, ms)`` samples across every row that
    recorded them — the alpha/beta fit's input. Only f32-wire rows
    contribute (quantized wires would need their own fit line)."""
    pts = []
    for row in rows:
        cfg = config_of(row)
        if cfg.get("HVT_COMPRESSION") != "none":
            continue
        sm = row.get("step_ms")
        if not isinstance(sm, dict):
            continue
        for b in sm.get("comm_buckets") or []:
            try:
                pts.append((float(b["bytes"]), float(b["ms"])))
            except (KeyError, TypeError, ValueError):
                continue
    return pts


def load_trace(trace_dir: str | None) -> dict:
    """Per-phase wall-time attribution from hvt-trace spans, or {}.

    Imported lazily: the obs layer is optional evidence, and the tuner
    must work from bench rows alone."""
    if not trace_dir or not os.path.isdir(trace_dir):
        return {}
    try:
        from horovod_tpu.obs import timeline
        return timeline.phase_attribution(trace_dir)
    except Exception:
        return {}
