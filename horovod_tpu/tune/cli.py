"""`hvt-tune` — the trace-replay autotuner CLI.

Subcommands:

* ``offline`` — fit the analytic comm/compute model from recorded
  evidence (BENCH_* rows) and rank the registry-enumerated candidate
  space without running anything. ``--check`` is the tier-1 self-test.
* ``probe`` — execute a probe plan (race candidate configs with real
  steps, paired-leg discipline). Normally invoked as a subprocess by
  `insitu.resolve`; jax-heavy.

Exit contract, shared with hvt-lint / hvt-audit / hvt-sched /
hvt-trace: 0 = clean/winner found, 1 = a finding (check failed, no
evidenced winner, probe crowned nobody), 2 = usage error (no usable
evidence, bad plan).
"""

from __future__ import annotations

import argparse
import json
import sys

from horovod_tpu.analysis import registry
from horovod_tpu.tune import evidence as evidence_lib
from horovod_tpu.tune import model as model_lib
from horovod_tpu.tune import offline as offline_lib
from horovod_tpu.tune import space as space_lib

__all__ = ["main", "cli"]


def _cmd_offline(a) -> int:
    evidence_dir = (a.evidence
                    or registry.get_str("HVT_TUNE_EVIDENCE") or ".")
    if a.check:
        code, msg = offline_lib.check(evidence_dir)
        print(msg)
        return code
    rows = evidence_lib.load_rows(evidence_dir)
    try:
        cost = model_lib.fit(rows, trace=evidence_lib.load_trace(a.trace))
    except model_lib.FitError as e:
        print(f"hvt-tune: {e}", file=sys.stderr)
        return 2
    knobs = a.knobs.split(",") if a.knobs else [
        n for n in space_lib.domains() if n != "HVT_BACKWARD_PASSES"
    ]
    try:
        configs = space_lib.enumerate_configs(knobs=knobs)
    except ValueError as e:
        print(f"hvt-tune: {e}", file=sys.stderr)
        return 2
    scored = offline_lib.rank(cost, configs)
    win = offline_lib.best(scored)
    if a.json:
        out = {
            "winner": win.config if win else None,
            "predicted": (dataclasses_dict(win.prediction)
                          if win else None),
            "provenance": cost.provenance,
            "candidates": len(scored),
        }
        print(json.dumps(out))
    else:
        print(offline_lib.render_report(cost, scored, top=a.top))
    return 0 if win else 1


def dataclasses_dict(pred) -> dict:
    import dataclasses

    d = dataclasses.asdict(pred)
    d["exposed_ms"] = pred.exposed_ms
    return d


def _cmd_probe(a) -> int:
    from horovod_tpu.tune import insitu

    try:
        with open(a.plan, encoding="utf-8") as f:
            plan = json.load(f)
        if "default" not in plan:
            raise ValueError("plan needs a 'default' config")
    except (OSError, ValueError) as e:
        print(f"hvt-tune probe: unreadable plan: {e}", file=sys.stderr)
        return 2
    if a.steps:
        plan["steps"] = a.steps
    out = insitu.run_probe_plan(plan)
    text = json.dumps(out)
    if a.out:
        # Probe-result handoff, re-printed on stdout anyway; a torn
        # write just fails the caller's JSON parse.
        with open(a.out, "w", encoding="utf-8") as f:  # hvt: noqa[HVT005]
            f.write(text)
    print(text)
    return 0


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="hvt-tune",
        description="trace-replay autotuner: offline analytic search "
                    "over recorded evidence, in-situ probe racing",
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    off = sub.add_parser(
        "offline", help="fit the model from BENCH_* rows and rank the "
                        "candidate space without running the fleet")
    off.add_argument("--evidence", default=None,
                     help="evidence dir (default: HVT_TUNE_EVIDENCE or .)")
    off.add_argument("--trace", default=None,
                     help="hvt-trace span dir for phase attribution")
    off.add_argument("--knobs", default=None,
                     help="comma-separated knobs to vary (default: every "
                          "tunable knob except HVT_BACKWARD_PASSES)")
    off.add_argument("--top", type=int, default=10,
                     help="report rows (default 10)")
    off.add_argument("--check", action="store_true",
                     help="tier-1 self-test: evidence loads, the model "
                          "reproduces the anchor, the search beats it")
    off.add_argument("--json", action="store_true",
                     help="machine-readable winner instead of the report")
    pr = sub.add_parser(
        "probe", help="race candidate configs with real steps "
                      "(paired-leg discipline); used by the launcher")
    pr.add_argument("--plan", required=True,
                    help="JSON plan: {default, candidates, steps}")
    pr.add_argument("--out", default=None, help="write result JSON here")
    pr.add_argument("--steps", type=int, default=None,
                    help="override steps per timed leg")
    a = p.parse_args(argv)
    return _cmd_offline(a) if a.cmd == "offline" else _cmd_probe(a)


def cli() -> None:
    """Console entry point (`hvt-tune`, pyproject.toml)."""
    raise SystemExit(main())


if __name__ == "__main__":
    raise SystemExit(main())
