from horovod_tpu.tune.cli import main

raise SystemExit(main())
