"""The analytic comm/compute model the offline tuner searches.

Shape of the model (classic alpha-beta cost model, arxiv 1802.05799's
fusion tradeoff made explicit):

* each reduction bucket costs ``alpha + beta * bytes`` — alpha is the
  per-bucket launch/latency overhead, beta the per-byte wire cost. Both
  are LEAST-SQUARES FIT over the pooled per-bucket ``(bytes, ms)``
  samples the bench rows recorded (``step_ms.comm_buckets``), then
  SCALED so the model reproduces the anchor row's measured whole-step
  comm exactly (isolated per-bucket timings carry per-program overhead
  a fused step does not; the scale calibrates it away).
* total payload ``S`` is the structural sum of bucket bytes (audited,
  not timed), so bucket count at cap ``b`` is ``ceil(S / b)``.
* true compute is ``serialized_total - comm`` from the anchor's own
  serialized (overlap-off) leg; it scales linearly in K.
* the overlap hides up to ``hide_rate * (n-1)/n`` ms of comm: with n
  buckets, the last-produced bucket's reduction cannot overlap its own
  backward (Horovod's fusion-order argument), so hiding capacity grows
  with bucket count while per-bucket alpha cost grows against it —
  THE tradeoff the tuner searches. ``hide_rate`` is calibrated from
  the anchor's measured (serialized - overlapped) gap.

Every term's provenance (which row, which field) is carried into the
prediction so the report can say where each number came from.
"""

from __future__ import annotations

import dataclasses
import math

from horovod_tpu.tune import evidence as evidence_lib

__all__ = ["CostModel", "Prediction", "fit", "FitError"]


class FitError(ValueError):
    """The evidence is too thin to fit a model (no usable anchor row)."""


@dataclasses.dataclass(frozen=True)
class Prediction:
    """One config's predicted step decomposition (ms, per opt step)."""

    total_ms: float
    compute_ms: float
    comm_ms: float          # isolated (un-overlapped) comm cost
    hidden_ms: float        # comm the overlap is predicted to hide
    input_ms: float
    n_buckets: int
    per_example: float      # total_ms / K — the ranking objective
    unevidenced: tuple      # knob names whose effect no evidence covers

    @property
    def exposed_ms(self) -> float:
        return self.comm_ms - self.hidden_ms

    @property
    def evidenced(self) -> bool:
        return not self.unevidenced


@dataclasses.dataclass(frozen=True)
class CostModel:
    """Fitted analytic model + the evidence each term came from."""

    alpha_ms: float          # per-bucket overhead (calibrated)
    beta_ms_per_byte: float  # per-byte wire cost (calibrated)
    payload_bytes: float     # S: structural sum of gradient bucket bytes
    compute_ms: float        # true compute at anchor K (serialized - comm)
    hide_rate_ms: float      # overlap hiding capacity at n -> inf
    input_ms: float
    anchor_k: int
    anchor_config: dict
    anchor_total_ms: float   # the measured total the fit must reproduce
    n_points: int            # pooled comm samples behind alpha/beta
    provenance: dict         # term -> human-readable evidence source

    def buckets(self, bucket_bytes: float) -> int:
        return max(1, math.ceil(self.payload_bytes / max(1.0, bucket_bytes)))

    def comm(self, bucket_bytes: float, wire: str) -> float:
        n = self.buckets(bucket_bytes)
        wire_bytes = self.payload_bytes * evidence_lib.wire_ratio(wire)
        return n * self.alpha_ms + wire_bytes * self.beta_ms_per_byte

    def predict(self, config: dict) -> Prediction:
        b = float(config.get("HVT_BUCKET_BYTES")
                  or self.anchor_config["HVT_BUCKET_BYTES"])
        k = int(config.get("HVT_BACKWARD_PASSES") or self.anchor_k)
        wire = str(config.get("HVT_COMPRESSION", "none"))
        wire_ici = str(config.get("HVT_COMPRESSION_ICI", "none"))
        overlap = bool(config.get("HVT_OVERLAP_REDUCTION", True))
        n = self.buckets(b)
        comm = self.comm(b, wire)
        compute = self.compute_ms * k / max(1, self.anchor_k)
        inp = self.input_ms * k / max(1, self.anchor_k)
        hidden = 0.0
        if overlap and n > 1:
            # The last-produced bucket can't hide behind its own
            # backward: capacity scales as (n-1)/n, and can never
            # exceed the comm there is, nor the compute to hide it in.
            hidden = min(self.hide_rate_ms * (n - 1) / n, comm, compute)
        unevidenced = []
        anchor_wire = str(self.anchor_config.get("HVT_COMPRESSION", "none"))
        if wire != anchor_wire:
            # The byte ratio is structural, but quantize/dequantize
            # compute and convergence cost are not in any recorded row.
            unevidenced.append("HVT_COMPRESSION")
        if wire_ici != str(self.anchor_config.get("HVT_COMPRESSION_ICI",
                                                  "none")):
            # Inert on single-slice meshes (dcn == 1) and no multi-slice
            # row exists to calibrate the ICI hop.
            unevidenced.append("HVT_COMPRESSION_ICI")
        total = compute + comm - hidden + inp
        return Prediction(
            total_ms=total, compute_ms=compute, comm_ms=comm,
            hidden_ms=hidden, input_ms=inp, n_buckets=n,
            per_example=total / max(1, k),
            unevidenced=tuple(unevidenced),
        )


def _fit_alpha_beta(points: list[tuple[float, float]]) -> tuple[float, float]:
    """Least-squares line ms = alpha + beta * bytes, clamped physical
    (alpha >= 0, beta > 0)."""
    n = len(points)
    mx = sum(p[0] for p in points) / n
    my = sum(p[1] for p in points) / n
    sxx = sum((p[0] - mx) ** 2 for p in points)
    if sxx <= 0.0:
        # One distinct bucket size: no slope information — attribute
        # everything to the wire (pessimistic for small buckets, which
        # only makes the tuner conservative about fragmenting).
        return 0.0, my / max(1.0, mx)
    sxy = sum((p[0] - mx) * (p[1] - my) for p in points)
    beta = sxy / sxx
    alpha = my - beta * mx
    if beta <= 0.0:
        return 0.0, my / max(1.0, mx)
    return max(0.0, alpha), beta


def fit(rows: list[dict], trace: dict | None = None) -> CostModel:
    """Fit the model from loaded evidence rows (see `evidence.load_rows`).

    ``trace``, when given (`evidence.load_trace`), cross-checks the
    input attribution: if the traced input phase is slower than the
    bench row's input column, trust the trace (bench hides staged input
    behind the prefetch queue; the trace sees the drain)."""
    anchor = evidence_lib.anchor_row(rows)
    if anchor is None:
        raise FitError(
            "no usable evidence: need at least one BENCH_* row with "
            "step_ms.comm_buckets (run BENCH_MODEL=zero1 python bench.py)"
        )
    points = evidence_lib.comm_points(rows)
    if not points:
        raise FitError("no per-bucket comm samples in any evidence row")
    cfg0 = evidence_lib.config_of(anchor)
    sm = anchor["step_ms"]
    total0 = float(sm["total"])
    comm0 = float(sm.get("comm") or 0.0)
    input0 = float(sm.get("input") or 0.0)
    src = anchor["_source"]
    payload = float(sum(b["bytes"] for b in sm["comm_buckets"]))
    alpha_fit, beta_fit = _fit_alpha_beta(points)
    # Calibrate: isolated per-bucket timings include per-program launch
    # overhead the fused step doesn't pay; scale the fit so the model's
    # comm at the anchor's own bucket cap equals the measured comm.
    b0 = float(cfg0["HVT_BUCKET_BYTES"])
    n0 = max(1, math.ceil(payload / b0))
    raw = n0 * alpha_fit + payload * beta_fit * evidence_lib.wire_ratio(
        cfg0.get("HVT_COMPRESSION", "none"))
    scale = (comm0 / raw) if (raw > 0 and comm0 > 0) else 1.0
    alpha = alpha_fit * scale
    beta = beta_fit * scale
    serialized0 = anchor.get("serialized_step_ms_total")
    if serialized0 is not None:
        compute0 = max(0.0, float(serialized0) - comm0 - input0)
        hidden0 = max(0.0, float(serialized0) - total0)
    else:
        # No overlap-off leg recorded: treat the measured total as fully
        # serialized (no hiding evidence -> the model won't credit any).
        compute0 = max(0.0, total0 - comm0 - input0)
        hidden0 = 0.0
    g0 = (n0 - 1) / n0 if n0 > 1 else 1.0
    hide_rate = hidden0 / g0 if hidden0 > 0 else 0.0
    if trace:
        step_in = trace.get("input") or trace.get("step_input")
        if step_in and step_in.get("mean_ms", 0.0) > input0:
            input0 = float(step_in["mean_ms"])
    prov = {
        "alpha/beta": (f"least-squares over {len(points)} per-bucket "
                       f"comm samples (step_ms.comm_buckets), "
                       f"calibrated to {src} step_ms.comm"),
        "payload": f"{src} comm_buckets structural bytes "
                   f"({int(payload)} B)",
        "compute": (f"{src} serialized_step_ms_total - comm - input"
                    if serialized0 is not None
                    else f"{src} step_ms.total - comm - input"),
        "hide_rate": (f"{src} serialized_step_ms_total - step_ms.total "
                      f"over (n-1)/n at n={n0}"
                      if hidden0 > 0 else "no overlap evidence"),
        "input": ("trace phase attribution"
                  if trace and trace.get("input") else f"{src} step_ms.input"),
        "anchor": f"{src} (k={cfg0['HVT_BACKWARD_PASSES']}, "
                  f"bucket_bytes={int(b0)})",
    }
    return CostModel(
        alpha_ms=alpha, beta_ms_per_byte=beta, payload_bytes=payload,
        compute_ms=compute0, hide_rate_ms=hide_rate, input_ms=input0,
        anchor_k=int(cfg0["HVT_BACKWARD_PASSES"]), anchor_config=cfg0,
        anchor_total_ms=total0, n_points=len(points), provenance=prov,
    )
