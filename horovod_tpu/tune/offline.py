"""Offline mode: search the knob space without running the fleet.

``hvt-tune offline`` fits the analytic model from recorded evidence
(`model.fit`), enumerates the candidate space from registry domain
metadata (`space.enumerate_configs`), ranks every config by predicted
per-example step cost, and reports the winner with its predicted
``step_ms.total`` decomposition and the evidence each term came from.

Configs whose effect NO recorded evidence covers (e.g. a quantized wire
when every row ran f32) are still ranked — the report shows them — but
are excluded from winner selection unless ``require_evidence=False``:
an autotuner must not crown a config on a term it invented.
"""

from __future__ import annotations

import dataclasses

from horovod_tpu.tune import evidence as evidence_lib
from horovod_tpu.tune import model as model_lib
from horovod_tpu.tune import space as space_lib

__all__ = ["Scored", "rank", "best", "render_report", "check"]


@dataclasses.dataclass(frozen=True)
class Scored:
    config: dict
    prediction: model_lib.Prediction
    deviations: int

    @property
    def score(self) -> float:
        return self.prediction.per_example


def rank(model: model_lib.CostModel, configs: list[dict]) -> list[Scored]:
    """Predict every config, best (lowest per-example ms) first; ties
    break toward the config deviating least from registry defaults."""
    scored = [
        Scored(config=c, prediction=model.predict(c),
               deviations=space_lib.deviations(c))
        for c in configs
    ]
    scored.sort(key=lambda s: (s.score, s.deviations))
    return scored


def best(scored: list[Scored], *, require_evidence: bool = True
         ) -> Scored | None:
    for s in scored:
        if s.prediction.evidenced or not require_evidence:
            return s
    return None


def _cfg_str(config: dict) -> str:
    short = {
        "HVT_BUCKET_BYTES": "bucket",
        "HVT_BACKWARD_PASSES": "k",
        "HVT_COMPRESSION": "wire",
        "HVT_COMPRESSION_ICI": "wire_ici",
        "HVT_OVERLAP_REDUCTION": "overlap",
    }
    parts = []
    for name, label in short.items():
        v = config.get(name)
        if name == "HVT_BUCKET_BYTES" and v:
            v = f"{int(v) >> 20}MB" if int(v) >= (1 << 20) else f"{int(v)}B"
        if isinstance(v, bool):
            v = "on" if v else "off"
        parts.append(f"{label}={v}")
    return " ".join(parts)


def render_report(model: model_lib.CostModel, scored: list[Scored],
                  *, top: int = 10) -> str:
    """The human report: winner, decomposition, provenance, top table."""
    lines = []
    win = best(scored)
    lines.append("hvt-tune offline — analytic search over "
                 f"{len(scored)} candidate configs")
    lines.append(f"model: alpha={model.alpha_ms:.3f} ms/bucket, "
                 f"beta={model.beta_ms_per_byte * 1e6:.3f} ms/MB, "
                 f"payload={int(model.payload_bytes)} B, "
                 f"{model.n_points} comm samples")
    lines.append("")
    if win is None:
        lines.append("winner: NONE — no evidenced candidate "
                     "(record more BENCH rows)")
    else:
        p = win.prediction
        lines.append(f"winner: {_cfg_str(win.config)}")
        lines.append(f"  predicted step_ms.total = {p.total_ms:.1f}")
        lines.append(f"    compute  {p.compute_ms:9.1f} ms   "
                     f"[{model.provenance['compute']}]")
        lines.append(f"    comm     {p.comm_ms:9.1f} ms over "
                     f"{p.n_buckets} bucket(s)   "
                     f"[{model.provenance['alpha/beta']}]")
        lines.append(f"    hidden  -{p.hidden_ms:9.1f} ms by overlap   "
                     f"[{model.provenance['hide_rate']}]")
        lines.append(f"    input    {p.input_ms:9.1f} ms   "
                     f"[{model.provenance['input']}]")
        lines.append(f"  per-example objective = {p.per_example:.2f} "
                     "ms/opt-step/K")
        if win.config.get("HVT_BACKWARD_PASSES") != model.anchor_k:
            lines.append("  note: K differs from the anchor — changes the "
                         "effective batch (numerics), not just speed")
    lines.append("")
    lines.append(f"top {min(top, len(scored))} of {len(scored)} "
                 "(pred ms/step | per-example | evidence):")
    for s in scored[:top]:
        p = s.prediction
        tag = "ok" if p.evidenced else (
            "UNEVIDENCED:" + ",".join(p.unevidenced))
        lines.append(f"  {p.total_ms:8.1f} | {p.per_example:8.2f} | "
                     f"{tag:14s} | {_cfg_str(s.config)}")
    anchor = model.predict(model.anchor_config)
    lines.append("")
    lines.append(f"anchor [{model.provenance['anchor']}]: measured "
                 f"{model.anchor_total_ms:.1f} ms, model reproduces "
                 f"{anchor.total_ms:.1f} ms")
    return "\n".join(lines)


def check(evidence_dir: str, *, tolerance_pct: float = 5.0) -> tuple[int, str]:
    """The ``--check`` self-test: (exit_code, message).

    2 = no usable evidence (can't even fit); 1 = the model or domain
    metadata is broken (fit doesn't reproduce the measured anchor, the
    search can't beat its own anchor, or a tuned knob lost its domain);
    0 = the tuner is trustworthy on the recorded evidence.
    """
    rows = evidence_lib.load_rows(evidence_dir)
    try:
        model = model_lib.fit(rows)
    except model_lib.FitError as e:
        return 2, f"hvt-tune check: {e}"
    msgs = []
    doms = space_lib.domains()
    for name in ("HVT_BUCKET_BYTES", "HVT_BACKWARD_PASSES",
                 "HVT_COMPRESSION", "HVT_COMPRESSION_ICI",
                 "HVT_OVERLAP_REDUCTION"):
        if name not in doms:
            msgs.append(f"{name} lost its tunable domain metadata")
    anchor_pred = model.predict(model.anchor_config)
    err = abs(anchor_pred.total_ms - model.anchor_total_ms) \
        / model.anchor_total_ms * 100.0
    if err > tolerance_pct:
        msgs.append(
            f"model does not reproduce the anchor row: predicted "
            f"{anchor_pred.total_ms:.1f} ms vs measured "
            f"{model.anchor_total_ms:.1f} ms ({err:.1f}% > "
            f"{tolerance_pct}%)"
        )
    scored = rank(model, space_lib.enumerate_configs(
        pin={"HVT_BACKWARD_PASSES": model.anchor_k}))
    win = best(scored)
    if win is None:
        msgs.append("no evidenced candidate in the search space")
    elif win.score > anchor_pred.per_example * (1 + tolerance_pct / 100.0):
        msgs.append(
            f"search lost to its own anchor: winner "
            f"{win.score:.2f} vs anchor {anchor_pred.per_example:.2f} "
            "per-example ms"
        )
    if msgs:
        return 1, "hvt-tune check: FAIL\n  " + "\n  ".join(msgs)
    return 0, (
        f"hvt-tune check: ok — {len(rows)} evidence rows, "
        f"{model.n_points} comm samples, anchor reproduced within "
        f"{err:.2f}%, winner {_cfg_str(win.config)}"
    )
