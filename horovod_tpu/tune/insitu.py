"""In-situ mode: tune at job start, remember across restarts.

`launch.job.run_job` calls `resolve()` when a spec carries a ``tune:``
block (the launcher's ``--tune`` flag builds the same block):

    tune:
      mode: probe          # offline | probe | off
      # knobs: [HVT_BUCKET_BYTES, HVT_OVERLAP_REDUCTION]
      # evidence: .        # BENCH_* row dir (default HVT_TUNE_EVIDENCE)
      # steps: 3           # probe: real opt steps per timed leg
      # candidates: 3      # probe: shortlist size from the offline rank
      # store: path        # default <PS_MODEL_PATH>/tune.json

``offline`` trusts the analytic model outright; ``probe`` takes the
model's shortlist and races each candidate against the config the job
would otherwise run — a few REAL steps apiece in a subprocess (the
launcher process must never initialize jax), decided by the same
paired-leg discipline as every bench gate (`tune.probe`).

The winner is written into the resolved env (spec-pinned env still
wins: an operator's explicit knob is a decision, not a suggestion) and
journaled. The selection is also persisted to ``store`` keyed by a
fingerprint of the block + the registry's tunable domains, so a
RESTART of the same job reuses the stored winner instead of re-probing
— `launch.job._reset_journal` deliberately leaves ``tune.json`` alone.

``HVT_BACKWARD_PASSES`` (K) is only tuned when ``knobs:`` names it
explicitly: K changes the effective batch (numerics), and a tuner must
not silently trade convergence for wall clock.
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys
import tempfile

from horovod_tpu.analysis import registry
from horovod_tpu.tune import evidence as evidence_lib
from horovod_tpu.tune import model as model_lib
from horovod_tpu.tune import offline as offline_lib
from horovod_tpu.tune import space as space_lib

__all__ = ["TuneError", "validate_block", "resolve", "build_probe_step",
           "run_probe_plan"]

_BLOCK_KEYS = {"mode", "knobs", "evidence", "steps", "candidates", "store"}
_MODES = ("off", "offline", "probe")


class TuneError(ValueError):
    """A tune: block that cannot be resolved (bad keys, no evidence)."""


def validate_block(block) -> None:
    """Raise TuneError on a malformed block — `validate_spec`'s dry-build
    hook, so a typo fails before any side effect."""
    if not isinstance(block, dict):
        raise TuneError(f"must be a mapping, got {block!r}")
    unknown = set(block) - _BLOCK_KEYS
    if unknown:
        raise TuneError(
            f"unknown keys {sorted(unknown)} (valid: {sorted(_BLOCK_KEYS)})"
        )
    mode = block.get("mode", "probe")
    if mode not in _MODES:
        raise TuneError(f"mode must be one of {_MODES}, got {mode!r}")
    knobs = block.get("knobs")
    if knobs is not None:
        doms = space_lib.domains()
        if not isinstance(knobs, list) or not knobs:
            raise TuneError(f"knobs must be a non-empty list, got {knobs!r}")
        for name in knobs:
            if name not in doms:
                raise TuneError(
                    f"{name!r} is not a tunable knob — registry rows with "
                    f"tunable= metadata: {sorted(doms)}"
                )
    for key in ("steps", "candidates"):
        if key in block and (not isinstance(block[key], int)
                             or block[key] < 1):
            raise TuneError(f"{key} must be a positive int, "
                            f"got {block[key]!r}")


def _fingerprint(block: dict) -> str:
    basis = {
        "block": {k: block.get(k) for k in sorted(_BLOCK_KEYS)},
        "domains": {n: list(v) for n, v in space_lib.domains().items()},
    }
    return hashlib.sha256(
        json.dumps(basis, sort_keys=True, default=str).encode()
    ).hexdigest()[:16]


def _subprocess_prober(plan: dict, env: dict) -> dict:
    """Run the probe plan in a fresh interpreter: the caller is the
    LAUNCHER, which must never initialize jax itself."""
    with tempfile.TemporaryDirectory(prefix="hvt-tune-") as td:
        plan_path = os.path.join(td, "plan.json")
        out_path = os.path.join(td, "out.json")
        # Plan handoff in a private tempdir, consumed once by the
        # child; nothing restart-durable can tear here.
        with open(plan_path, "w", encoding="utf-8") as f:  # hvt: noqa[HVT005]
            json.dump(plan, f)
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.tune", "probe",
             "--plan", plan_path, "--out", out_path],
            env=env, capture_output=True, text=True,
        )
        if proc.returncode != 0 or not os.path.exists(out_path):
            raise TuneError(
                f"probe subprocess failed (rc {proc.returncode}): "
                f"{(proc.stderr or proc.stdout).strip()[-500:]}"
            )
        with open(out_path, encoding="utf-8") as f:
            return json.load(f)


def resolve(block: dict, env: dict, *, workdir: str | None = None,
            prober=None) -> tuple[dict, dict]:
    """Resolve a ``tune:`` block into ``(tuned_env, event)``.

    ``tuned_env`` maps env-var names to string values (empty for mode
    off); ``event`` describes what happened for the journal:
    ``{"event": "tune_selected" | "tune_reused" | "tune_off", ...}``.
    ``prober`` overrides the probe runner (tests inject a fake).
    """
    validate_block(block)
    mode = block.get("mode", "probe")
    if mode == "off":
        return {}, {"event": "tune_off"}
    merged = dict(os.environ)
    merged.update({str(k): str(v) for k, v in (env or {}).items()})
    model_dir = merged.get("PS_MODEL_PATH") or "./models"
    store = block.get("store") or os.path.join(model_dir, "tune.json")
    fp = _fingerprint(block)
    if os.path.exists(store):
        try:
            with open(store, encoding="utf-8") as f:
                rec = json.load(f)
        except (OSError, ValueError):
            rec = None
        if rec and rec.get("fingerprint") == fp:
            return dict(rec.get("env") or {}), {
                "event": "tune_reused", "mode": rec.get("mode", mode),
                "store": store, "config": rec.get("config"),
            }
    evidence_dir = (block.get("evidence")
                    or registry.get_str("HVT_TUNE_EVIDENCE", environ=merged)
                    or workdir or ".")
    rows = evidence_lib.load_rows(evidence_dir)
    try:
        cost = model_lib.fit(rows)
    except model_lib.FitError as e:
        raise TuneError(f"{e} (evidence dir: {evidence_dir})") from None
    knobs = block.get("knobs")
    if knobs is None:
        knobs = [n for n in space_lib.domains()
                 if n != "HVT_BACKWARD_PASSES"]
    scored = offline_lib.rank(
        cost, space_lib.enumerate_configs(knobs=knobs, environ=merged))
    win = offline_lib.best(scored)
    if win is None:
        raise TuneError("no evidenced candidate config — record more "
                        "BENCH rows into the evidence dir")
    detail: dict = {"predicted_total_ms": round(win.prediction.total_ms, 3)}
    config = win.config
    if mode == "probe":
        shortlist, seen = [], set()
        want = int(block.get("candidates")
                   or registry.get_int("HVT_TUNE_CANDIDATES",
                                       environ=merged))
        for s in scored:
            key = json.dumps(s.config, sort_keys=True, default=str)
            if s.prediction.evidenced and key not in seen:
                seen.add(key)
                shortlist.append(s.config)
            if len(shortlist) >= want:
                break
        plan = {
            "default": space_lib.resolved_config(environ=merged),
            "candidates": shortlist,
            "steps": int(block.get("steps")
                         or registry.get_int("HVT_TUNE_STEPS",
                                             environ=merged)),
        }
        probe_out = (prober or _subprocess_prober)(plan, merged)
        config = probe_out.get("winner") or plan["default"]
        detail["probe"] = probe_out.get("results")
    tuned_env = space_lib.env_of(config)
    rec = {
        "fingerprint": fp, "mode": mode, "config": config,
        "env": tuned_env, "detail": detail,
    }
    os.makedirs(os.path.dirname(store) or ".", exist_ok=True)
    # The store is a cache, not an artifact: the reader above treats a
    # torn/corrupt file as a miss and refits, so no sidecar is needed.
    with open(store, "w", encoding="utf-8") as f:  # hvt: noqa[HVT005]
        json.dump(rec, f, indent=1, sort_keys=True)
    event = {"event": "tune_selected", "mode": mode, "store": store,
             "config": config}
    event.update(detail)
    return tuned_env, event


# --- the probe side (runs inside `python -m horovod_tpu.tune probe`) --------


def build_probe_step(config: dict, *, hidden: int = 1024,
                     per_chip_batch: int = 16, steps: int = 3):
    """Compile one candidate config into a zero-arg timed leg: ``steps``
    real ZeRO-1 optimizer steps at the bench MLP shape, fused into one
    program with an honest data-dependent fetch (see bench._timed).

    jax-heavy — only the probe subprocess calls this."""
    import jax
    import numpy as np
    import optax
    from flax import linen as nn

    import horovod_tpu as hvt

    hvt.init()
    n_chips = jax.device_count()
    k = int(config.get("HVT_BACKWARD_PASSES", 1))
    global_batch = per_chip_batch * n_chips

    class Mlp(nn.Module):
        @nn.compact
        def __call__(self, x, *, train: bool = False):
            import jax.numpy as jnp

            x = x.astype(jnp.float32)
            x = nn.relu(nn.Dense(hidden)(x))
            x = nn.relu(nn.Dense(hidden)(x))
            return nn.Dense(16)(x)

    trainer = hvt.Trainer(
        Mlp(),
        hvt.DistributedOptimizer(
            optax.adam(hvt.scale_lr(1e-3)),
            backward_passes_per_step=k,
            average_aggregated_gradients=True,
            compression=str(config.get("HVT_COMPRESSION", "none")),
            compression_ici=str(config.get("HVT_COMPRESSION_ICI", "none")),
        ),
        loss="sparse_categorical_crossentropy",
        shard_update=True,
        overlap_reduction=bool(config.get("HVT_OVERLAP_REDUCTION", True)),
        bucket_bytes=int(config.get("HVT_BUCKET_BYTES")
                         or space_lib.DEFAULT_BUCKET_BYTES),
    )
    rng = np.random.RandomState(0)
    x = rng.rand(2048, 512).astype(np.float32)
    y = rng.randint(0, 16, 2048).astype(np.int32)

    def draw():
        idx = rng.randint(0, len(x), size=global_batch)
        return x[idx], y[idx]

    def step_batch():
        # One optimizer step's feed: [G, F] for k=1, a [k, G, F]
        # microbatch stack for the accumulating step (bench.measure's
        # shape contract for _train_chunk).
        if k == 1:
            return draw()
        micro = [draw() for _ in range(k)]
        return tuple(np.stack([m[i] for m in micro]) for i in range(2))

    state = trainer.build(draw()[0])
    scale = np.float32(1.0)
    zero_acc = {m: np.float32(0) for m in trainer.metric_names}
    chunks = [step_batch() for _ in range(steps)]
    mega = tuple(np.stack([c[i] for c in chunks]) for i in range(2))
    dev = trainer._shard_chunk(mega, 2 if k > 1 else 1)
    compiled = trainer._train_chunk.lower(
        state, dev, scale, zero_acc).compile()
    w_state, _, w_acc = compiled(state, dev, scale, zero_acc)
    float(jax.device_get(w_acc["loss"]))  # settle: compile + first run
    holder = {"state": w_state}

    def leg():
        holder["state"], _, acc = compiled(
            holder["state"], dev, scale, zero_acc)
        return float(jax.device_get(acc["loss"]))

    return leg


def run_probe_plan(plan: dict, *, builder=build_probe_step,
                   clock=None) -> dict:
    """Race every candidate against the default config with the
    paired-leg discipline; pick the winner. ``builder``/``clock`` are
    injectable so the race logic tests over a fake clock."""
    import time

    from horovod_tpu.tune import probe as probe_lib

    clock = clock or time.perf_counter
    steps = int(plan.get("steps", 3))
    default_cfg = plan["default"]
    base_leg = builder(default_cfg, steps=steps)
    base_leg()  # settle
    results = []
    best_cfg, best_pct = None, 0.0
    for cand in plan.get("candidates", []):
        if cand == default_cfg:
            results.append({"config": cand, "median_pct": 0.0,
                            "mad_pct": 0.0, "pairs": 0,
                            "note": "is the default"})
            continue
        leg = builder(cand, steps=steps)
        leg()  # settle
        # a = default, b = candidate: negative median means the
        # candidate is FASTER than what the job would otherwise run.
        res = probe_lib.paired_compare(base_leg, leg, clock=clock)
        results.append({"config": cand,
                        "median_pct": round(res.median_pct, 3),
                        "mad_pct": round(res.mad_pct, 3),
                        "pairs": res.pairs,
                        "converged": res.converged})
        if res.median_pct < best_pct:
            best_cfg, best_pct = cand, res.median_pct
    return {
        "winner": best_cfg or default_cfg,
        "improvement_pct": round(-best_pct, 3),
        "results": results,
    }
