"""Profiling — the Horovod-Timeline / NCCL_DEBUG role, TPU-native (§5.1).

`jax.profiler` traces capture XLA op timing *and* ICI collective phases —
strictly more than Horovod's Chrome-trace Timeline — viewable in
TensorBoard/perfetto. Primary-process-gated like every writer in the
framework.
"""

from __future__ import annotations

import contextlib
import time

import jax

from horovod_tpu import runtime


@contextlib.contextmanager
def trace(log_dir: str, primary_only: bool = True):
    """``with trace('/tmp/trace'): step(...)`` — emits a profiler dump."""
    active = runtime.is_primary() or not primary_only
    if active:
        jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        if active:
            jax.profiler.stop_trace()


class StepTimer:
    """Wall-clock step/throughput accounting feeding the bench harness."""

    def __init__(self):
        self.times: list[float] = []
        self._t0 = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.times.append(time.perf_counter() - self._t0)

    @property
    def mean_s(self) -> float:
        return sum(self.times) / max(1, len(self.times))

    def throughput(self, items_per_step: int) -> float:
        return items_per_step / self.mean_s if self.times else 0.0
