"""Profiling + FLOPs/MFU accounting — the Horovod-Timeline / NCCL_DEBUG role,
TPU-native (§5.1).

`jax.profiler` traces capture XLA op timing *and* ICI collective phases —
strictly more than Horovod's Chrome-trace Timeline — viewable in
TensorBoard/perfetto. Primary-process-gated like every writer in the
framework. `HVT_PROFILE=<dir>` turns tracing on in `Trainer.fit` and
`bench.py` without code changes (the `HOROVOD_TIMELINE=<file>` env-var
contract, SURVEY.md §2.3 Timeline row).

FLOPs come from XLA's own cost model on the *compiled* step
(`Compiled.cost_analysis()`), so the count covers exactly what runs —
forward, backward, optimizer, collectives — for any model, with no
per-architecture analytic bookkeeping to drift out of date. MFU is that
count against the chip's peak; "match or beat" needs this denominator
(VERDICT round 1)."""

from __future__ import annotations

import contextlib
import os
import time

import jax
import numpy as np

from horovod_tpu import runtime
from horovod_tpu.analysis import registry

# Peak dense-matmul throughput per chip, FLOP/s. bf16 peaks from the public
# TPU spec sheets; fp32 on TPU runs through the same MXU passes (bf16x3) so
# bf16 peak is the standard MFU denominator. Keyed by substrings of
# `device.device_kind`.
_PEAK_FLOPS = {
    "tpu v7": 4614e12,   # Ironwood
    "tpu v6 lite": 918e12,   # Trillium / v6e
    "tpu v5p": 459e12,
    "tpu v5 lite": 197e12,   # v5e
    "tpu v5": 459e12,        # plain "TPU v5" kinds are v5p pods
    "tpu v4 lite": 138e12,
    "tpu v4": 275e12,
    "tpu v3": 123e12,
    "tpu v2": 46e12,
}


def device_peak_flops(device=None) -> float | None:
    """Peak FLOP/s of one chip, or None when unknown (e.g. CPU).

    ``HVT_PEAK_FLOPS`` overrides the table — the explicit per-chip peak
    for device kinds the table doesn't know (CPU CI topologies, new TPU
    generations), so MFU can be a real trend number everywhere. An
    unparseable override raises ``ValueError`` (bench.py exits 2 on
    it)."""
    override = registry.get_float("HVT_PEAK_FLOPS")
    if override:
        return float(override)
    device = device or jax.devices()[0]
    kind = device.device_kind.lower()
    for key, peak in sorted(_PEAK_FLOPS.items(), key=lambda kv: -len(kv[0])):
        if key in kind:
            return peak
    return None


def compiled_flops(jitted_fn, *args, **kwargs) -> float | None:
    """Total FLOPs of one invocation, from XLA's cost model on the lowered
    + compiled computation. None when the backend doesn't report them."""
    try:
        return compiled_cost_flops(jitted_fn.lower(*args, **kwargs).compile())
    except Exception:
        return None


def compiled_cost_flops(compiled) -> float | None:
    """FLOPs from an already-`Compiled` computation's cost analysis."""
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):  # some backends wrap per-module
            cost = cost[0]
        flops = cost.get("flops")
        return float(flops) if flops and flops > 0 else None
    except Exception:
        return None


def flash_attention_flops(batch: int, seq_q: int, seq_k: int, heads: int,
                          head_dim: int, *, causal: bool = True,
                          backward: bool = True,
                          window: int | None = None) -> float:
    """Matmul FLOPs one flash-attention call actually executes — the part
    XLA's cost model cannot see (a Mosaic custom call is opaque to it;
    BASELINE.md footnote 1).

    Counted from the kernel's own structure (ops/flash_attention.py): the
    forward runs 2 block dots per (q, k) tile pair (scores, P·V); the
    backward runs 7 (dq pass: recomputed scores, dP, dQ; dkv pass:
    recomputed scores, dV, dP, dK). Each full-sequence dot is
    ``2·B·H·Tq·Tk·D`` FLOPs; causal block-skipping halves the executed
    tiles, and a sliding ``window`` shrinks them to the band area
    W·T − W(W−1)/2 (self-attention; element-granularity approximation of
    the tile-granular skip). Training callers add this per flash call (per
    layer, per step) to the XLA cost-model count."""
    per_dot = 2.0 * batch * heads * seq_q * seq_k * head_dim
    dots = 9 if backward else 2
    if causal and window is not None:
        # Executed score entries: query row i sees min(w, i + Tk − Tq + 1)
        # keys (end-aligned causal band, clamped at 0 for rows before the
        # first key when Tk < Tq) — summed over rows, never negative.
        w = min(window, seq_k)
        rows = np.arange(seq_q, dtype=np.float64)
        visible = np.clip(rows + (seq_k - seq_q) + 1, 0.0, float(w))
        frac = float(visible.sum()) / (seq_q * seq_k)
        return dots * per_dot * frac
    return dots * per_dot * (0.5 if causal else 1.0)


def fused_ce_flops(n_tokens: int, d_model: int, vocab: int,
                   n_chunks: int) -> float:
    """Matmul FLOPs the fused chunked-CE head (ops/fused_ce.py) executes
    beyond what XLA's cost model counts. The head's forward and backward
    each run inside a ``lax.scan`` whose body the cost model counts ONCE
    but which executes ``n_chunks`` times. Executed per step over all
    N = B·T tokens: forward logits 2·N·D·V, backward recompute 2·N·D·V +
    dh 2·N·D·V + dW 2·N·D·V = 8·N·D·V total; counted = that / n_chunks —
    so the uncounted remainder is 8·N·D·V·(1 − 1/n_chunks)."""
    return 8.0 * n_tokens * d_model * vocab * (1.0 - 1.0 / max(1, n_chunks))


def mfu(flops_per_step: float | None, step_time_s: float, n_chips: int = 1,
        device=None) -> float | None:
    """Model FLOPs utilization: achieved FLOP/s ÷ fleet peak FLOP/s."""
    peak = device_peak_flops(device)
    if not peak or not flops_per_step or step_time_s <= 0:
        return None
    return flops_per_step / step_time_s / (peak * n_chips)


def profile_dir() -> str | None:
    """The `HVT_PROFILE` target directory, or None when profiling is off."""
    return registry.get_str("HVT_PROFILE")


@contextlib.contextmanager
def maybe_trace(log_dir: str | None):
    """`trace(...)` when a directory is given, no-op otherwise — callers can
    wrap hot loops unconditionally with `maybe_trace(profile_dir())`."""
    if log_dir:
        with trace(log_dir):
            yield
    else:
        yield


@contextlib.contextmanager
def trace(log_dir: str, primary_only: bool = True):
    """``with trace('/tmp/trace'): step(...)`` — emits a profiler dump."""
    active = runtime.is_primary() or not primary_only
    if active:
        jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        if active:
            jax.profiler.stop_trace()


class StepTimer:
    """Wall-clock step/throughput accounting feeding the bench harness."""

    def __init__(self):
        self.times: list[float] = []
        self._t0 = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.times.append(time.perf_counter() - self._t0)

    @property
    def mean_s(self) -> float:
        return sum(self.times) / max(1, len(self.times))

    def throughput(self, items_per_step: int) -> float:
        return items_per_step / self.mean_s if self.times else 0.0
