"""Profiling + FLOPs/MFU accounting — the Horovod-Timeline / NCCL_DEBUG role,
TPU-native (§5.1).

`jax.profiler` traces capture XLA op timing *and* ICI collective phases —
strictly more than Horovod's Chrome-trace Timeline — viewable in
TensorBoard/perfetto. Primary-process-gated like every writer in the
framework. `HVT_PROFILE=<dir>` turns tracing on in `Trainer.fit` and
`bench.py` without code changes (the `HOROVOD_TIMELINE=<file>` env-var
contract, SURVEY.md §2.3 Timeline row).

FLOPs come from XLA's own cost model on the *compiled* step
(`Compiled.cost_analysis()`), so the count covers exactly what runs —
forward, backward, optimizer, collectives — for any model, with no
per-architecture analytic bookkeeping to drift out of date. MFU is that
count against the chip's peak; "match or beat" needs this denominator
(VERDICT round 1)."""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time

import jax
import numpy as np

from horovod_tpu import runtime
from horovod_tpu.analysis import registry

# Peak dense-matmul throughput per chip, FLOP/s. bf16 peaks from the public
# TPU spec sheets; fp32 on TPU runs through the same MXU passes (bf16x3) so
# bf16 peak is the standard MFU denominator. Keyed by substrings of
# `device.device_kind`.
_PEAK_FLOPS = {
    "tpu v7": 4614e12,   # Ironwood
    "tpu v6 lite": 918e12,   # Trillium / v6e
    "tpu v5p": 459e12,
    "tpu v5 lite": 197e12,   # v5e
    "tpu v5": 459e12,        # plain "TPU v5" kinds are v5p pods
    "tpu v4 lite": 138e12,
    "tpu v4": 275e12,
    "tpu v3": 123e12,
    "tpu v2": 46e12,
}


def device_peak_flops(device=None) -> float | None:
    """Peak FLOP/s of one chip, or None when unknown (e.g. CPU).

    ``HVT_PEAK_FLOPS`` overrides the table — the explicit per-chip peak
    for device kinds the table doesn't know (CPU CI topologies, new TPU
    generations), so MFU can be a real trend number everywhere. An
    unparseable override raises ``ValueError`` (bench.py exits 2 on
    it)."""
    override = registry.get_float("HVT_PEAK_FLOPS")
    if override:
        return float(override)
    device = device or jax.devices()[0]
    kind = device.device_kind.lower()
    for key, peak in sorted(_PEAK_FLOPS.items(), key=lambda kv: -len(kv[0])):
        if key in kind:
            return peak
    return None


def compiled_flops(jitted_fn, *args, **kwargs) -> float | None:
    """Total FLOPs of one invocation, from XLA's cost model on the lowered
    + compiled computation. None when the backend doesn't report them."""
    try:
        return compiled_cost_flops(jitted_fn.lower(*args, **kwargs).compile())
    except Exception:
        return None


def compiled_cost_flops(compiled) -> float | None:
    """FLOPs from an already-`Compiled` computation's cost analysis."""
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):  # some backends wrap per-module
            cost = cost[0]
        flops = cost.get("flops")
        return float(flops) if flops and flops > 0 else None
    except Exception:
        return None


def flash_attention_flops(batch: int, seq_q: int, seq_k: int, heads: int,
                          head_dim: int, *, causal: bool = True,
                          backward: bool = True,
                          window: int | None = None) -> float:
    """Matmul FLOPs one flash-attention call actually executes — the part
    XLA's cost model cannot see (a Mosaic custom call is opaque to it;
    BASELINE.md footnote 1).

    Counted from the kernel's own structure (ops/flash_attention.py): the
    forward runs 2 block dots per (q, k) tile pair (scores, P·V); the
    backward runs 7 (dq pass: recomputed scores, dP, dQ; dkv pass:
    recomputed scores, dV, dP, dK). Each full-sequence dot is
    ``2·B·H·Tq·Tk·D`` FLOPs; causal block-skipping halves the executed
    tiles, and a sliding ``window`` shrinks them to the band area
    W·T − W(W−1)/2 (self-attention; element-granularity approximation of
    the tile-granular skip). Training callers add this per flash call (per
    layer, per step) to the XLA cost-model count."""
    per_dot = 2.0 * batch * heads * seq_q * seq_k * head_dim
    dots = 9 if backward else 2
    if causal and window is not None:
        # Executed score entries: query row i sees min(w, i + Tk − Tq + 1)
        # keys (end-aligned causal band, clamped at 0 for rows before the
        # first key when Tk < Tq) — summed over rows, never negative.
        w = min(window, seq_k)
        rows = np.arange(seq_q, dtype=np.float64)
        visible = np.clip(rows + (seq_k - seq_q) + 1, 0.0, float(w))
        frac = float(visible.sum()) / (seq_q * seq_k)
        return dots * per_dot * frac
    return dots * per_dot * (0.5 if causal else 1.0)


def fused_ce_flops(n_tokens: int, d_model: int, vocab: int,
                   n_chunks: int) -> float:
    """Matmul FLOPs the fused chunked-CE head (ops/fused_ce.py) executes
    beyond what XLA's cost model counts. The head's forward and backward
    each run inside a ``lax.scan`` whose body the cost model counts ONCE
    but which executes ``n_chunks`` times. Executed per step over all
    N = B·T tokens: forward logits 2·N·D·V, backward recompute 2·N·D·V +
    dh 2·N·D·V + dW 2·N·D·V = 8·N·D·V total; counted = that / n_chunks —
    so the uncounted remainder is 8·N·D·V·(1 − 1/n_chunks)."""
    return 8.0 * n_tokens * d_model * vocab * (1.0 - 1.0 / max(1, n_chunks))


def resolve_peak_flops(calibrate: bool = True) -> tuple:
    """(per-chip peak FLOP/s, source) for any MFU denominator — shared by
    bench.py (`_resolve_peak_flops` delegates here) and the live trainer
    MFU gauge, so no surface reports ``mfu: null``.

    Resolution order: the explicit ``HVT_PEAK_FLOPS`` override, the
    built-in TPU peak table (`device_peak_flops`), and — with
    ``calibrate=True`` — a measured matmul calibration on THIS host
    (best-of-3 chained f32 matmuls), the honest trend denominator for
    device kinds with no published peak (the CPU CI topology). The
    calibrated value is exported back into ``HVT_PEAK_FLOPS`` so every
    later resolution in the process divides by the same number.
    ``calibrate=False`` returns ``(None, "unknown")`` instead of paying
    the ~second of matmuls."""
    import jax.numpy as jnp

    if registry.get_raw("HVT_PEAK_FLOPS") is not None:
        return float(registry.get_float("HVT_PEAK_FLOPS")), "override"
    peak = device_peak_flops()
    if peak:
        return peak, "table"
    if not calibrate:
        return None, "unknown"
    n = int(os.environ.get("BENCH_PEAK_CALIB_N", 1024))
    a = jnp.ones((n, n), jnp.float32)
    b = jnp.ones((n, n), jnp.float32)
    f = jax.jit(lambda a, b: (a @ b).sum())
    float(jax.device_get(f(a, b)))  # compile + settle
    reps = 8

    def chain():
        t = jnp.float32(0)
        for _ in range(reps):
            t = t + f(a, b)
        return float(jax.device_get(t))

    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        chain()
        dt = (time.perf_counter() - t0) / reps
        best = dt if best is None else min(best, dt)
    peak = 2.0 * n ** 3 / best
    os.environ["HVT_PEAK_FLOPS"] = f"{peak:.6g}"
    return peak, "calibrated"


def mfu(flops_per_step: float | None, step_time_s: float, n_chips: int = 1,
        device=None) -> float | None:
    """Model FLOPs utilization: achieved FLOP/s ÷ fleet peak FLOP/s."""
    peak = device_peak_flops(device)
    if not peak or not flops_per_step or step_time_s <= 0:
        return None
    return flops_per_step / step_time_s / (peak * n_chips)


def profile_dir() -> str | None:
    """The `HVT_PROFILE` target directory, or None when profiling is off."""
    return registry.get_str("HVT_PROFILE")


@contextlib.contextmanager
def maybe_trace(log_dir: str | None):
    """`trace(...)` when a directory is given, no-op otherwise — callers can
    wrap hot loops unconditionally with `maybe_trace(profile_dir())`."""
    if log_dir:
        with trace(log_dir):
            yield
    else:
        yield


@contextlib.contextmanager
def trace(log_dir: str, primary_only: bool = True):
    """``with trace('/tmp/trace'): step(...)`` — emits a profiler dump."""
    active = runtime.is_primary() or not primary_only
    if active:
        jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        if active:
            jax.profiler.stop_trace()


# --- structured trace spans (HVT_TRACE_DIR) ---------------------------------
#
# Nestable JSONL span records around the framework's operational
# boundaries — step, reduction, commit, rescale, checkpoint-save — one
# rank-tagged file per process, so a fleet's spans can be merged by
# (rank, ts) into a timeline without a collector. Each record:
#
#   {"name", "ts" (epoch seconds, span START), "dur_s", "rank", "pid",
#    "id", "parent" (enclosing span id or null), "depth", ...attrs}
#
# Off (zero overhead beyond one registry read) unless HVT_TRACE_DIR is
# set. Writes are per-record appends with a flush — span cadence is the
# optimizer step at its finest, never per-microbatch. Span emission must
# never take training down: write failures are swallowed after the
# first (the writer disables itself) — but never SILENTLY: every span a
# dead writer loses is counted and exported as
# `hvt_trace_spans_dropped_total` through the obs registry, so a torn
# trace dir reads as a climbing counter on /metrics instead of a
# mysteriously empty timeline. Records carry the writing HOST so
# `hvt-trace` (obs/timeline.py) knows which ranks share a clock.


def span_dir() -> str | None:
    """The ``HVT_TRACE_DIR`` target, or None when spans are off."""
    return registry.get_str("HVT_TRACE_DIR")


def _dropped_spans_collector(reg) -> None:
    """Mirror the span writer's drop count at scrape time (the
    `obs.register_collector` idiom — a NAMED module-level function so
    re-registration dedupes by identity). Reads the module attribute, so
    tests that swap `_span_writer` stay covered."""
    reg.counter_set("hvt_trace_spans_dropped_total", _span_writer.drops)


class _SpanWriter:
    """This process's span file (lazy; thread-safe; fail-once-silent —
    but drop-counted: see the section comment above)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._fh = None
        self._dead = False
        self._seq = 0
        self._tls = threading.local()
        self.drops = 0  # spans lost to a dead/torn writer

    def _stack(self) -> list:
        if not hasattr(self._tls, "stack"):
            self._tls.stack = []
        return self._tls.stack

    @staticmethod
    def _register_drop_mirror() -> None:
        # Idempotent (collector registration dedupes by identity); NOT
        # on the healthy write path — asserted once at writer open and
        # again on every drop, which also re-covers an obs.reset()
        # between fits (any post-reset drop re-registers).
        from horovod_tpu import obs

        obs.register_collector(_dropped_spans_collector)

    def write(self, record: dict) -> None:
        if self._dead:
            with self._lock:
                self.drops += 1
            self._register_drop_mirror()
            return
        try:
            with self._lock:
                if self._fh is None:
                    d = span_dir()
                    os.makedirs(d, exist_ok=True)
                    rank = runtime.process_rank()
                    self._fh = open(
                        os.path.join(
                            d, f"spans-rank{rank}-pid{os.getpid()}.jsonl"
                        ),
                        "a",
                    )
                    register = True
                else:
                    register = False
                self._fh.write(json.dumps(record) + "\n")
                self._fh.flush()
            if register:
                self._register_drop_mirror()
        except OSError:
            with self._lock:
                self._dead = True  # observability must never kill training
                self.drops += 1
            self._register_drop_mirror()

    def next_id(self) -> int:
        with self._lock:
            self._seq += 1
            return self._seq


_span_writer = _SpanWriter()
_HOST = None


def _host() -> str:
    """The span-stamping hostname (cached): ranks sharing it share a
    clock, which is what lets `hvt-trace` skip cross-host clock
    alignment for them (obs/timeline.py)."""
    global _HOST
    if _HOST is None:
        import socket

        try:
            _HOST = socket.gethostname() or "unknown"
        except OSError:
            _HOST = "unknown"
    return _HOST


def emit_span(name: str, ts: float, dur_s: float, **attrs) -> None:
    """Write one span record with CALLER-supplied timings — an interval
    measured somewhere the ``with`` form can't sit (another thread's
    queue wait, a retroactive split of a blocking call). Parent/depth
    come from the calling thread's open-span stack, exactly like
    `span`; no-op when ``HVT_TRACE_DIR`` is unset."""
    if not span_dir():
        return
    stack = _span_writer._stack()
    # Core fields LAST so a caller attr can never clobber the span
    # schema (an `id=` attr silently breaking parent linkage was a real
    # bug — timeline merge keys on these).
    _span_writer.write({
        **attrs,
        "name": name,
        "ts": ts,
        "dur_s": dur_s,
        "rank": runtime.process_rank(),
        "pid": os.getpid(),
        "host": _host(),
        "id": _span_writer.next_id(),
        "parent": stack[-1] if stack else None,
        "depth": len(stack),
    })


@contextlib.contextmanager
def span(name: str, **attrs):
    """``with trace.span('commit', epoch=3): ...`` — one JSONL span
    record on exit, nesting tracked per thread. No-op (and attr kwargs
    unevaluated only if the caller guards — they're cheap scalars at
    every call site) when ``HVT_TRACE_DIR`` is unset."""
    if not span_dir():
        yield
        return
    stack = _span_writer._stack()
    sid = _span_writer.next_id()
    parent = stack[-1] if stack else None
    stack.append(sid)
    t0 = time.time()
    p0 = time.perf_counter()
    try:
        yield
    finally:
        stack.pop()
        # Core fields LAST — see emit_span.
        _span_writer.write({
            **attrs,
            "name": name,
            "ts": t0,
            "dur_s": time.perf_counter() - p0,
            "rank": runtime.process_rank(),
            "pid": os.getpid(),
            "host": _host(),
            "id": sid,
            "parent": parent,
            "depth": len(stack),
        })


class StepTimer:
    """Wall-clock step/throughput accounting feeding the bench harness."""

    def __init__(self):
        self.times: list[float] = []
        self._t0 = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.times.append(time.perf_counter() - self._t0)

    @property
    def mean_s(self) -> float:
        return sum(self.times) / max(1, len(self.times))

    def throughput(self, items_per_step: int) -> float:
        return items_per_step / self.mean_s if self.times else 0.0
