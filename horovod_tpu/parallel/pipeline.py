"""Pipeline parallelism: a GPipe microbatch schedule as one SPMD program.

The reference has no pipeline parallelism (SURVEY.md §2.2: single-stage
model); this fills the framework's ``pipe`` mesh axis the TPU-native way —
no runtime stage processes, no send/recv threads, no schedule executor.
Instead the whole pipeline is ONE differentiable jitted function:

* **Stages are a sharding.** Per-layer parameter stacks ``[n_layers, ...]``
  are sharded over ``pipe`` on dim 0, so each pipe device holds a contiguous
  block of layers (its stage). There is no separate stage assignment
  machinery — the partitioner IS the assignment.
* **The schedule is a `lax.scan`.** Inside a `shard_map` over the mesh,
  every device runs ``n_micro + n_stages - 1`` identical ticks; at tick t,
  stage s processes microbatch ``t - s`` (a rotating activation register),
  then hands its output to stage ``s+1`` via `lax.ppermute`. Bubbles are
  ticks whose result is masked out — uniform control flow, exactly what XLA
  wants.
* **Backward is derived, not written.** The schedule is built from
  differentiable primitives (`scan`, `ppermute`, `psum`), so `jax.grad`
  mechanically produces the reverse pipeline (activations rematerialized per
  the standard AD rules) — where a runtime-scheduler design (GPipe/
  PipeDream's C++ executors) needs hand-written backward scheduling, here it
  falls out of the autodiff transform.

Cost notes: the GPipe bubble fraction is ``(S-1)/(T+S-1)`` for S stages and
T microbatches — pick ``n_micro >= 4*n_stages`` to keep it under ~20%. The
final broadcast of outputs off the last stage is a masked `psum` over
``pipe`` (one activation-sized allreduce per step; simple and fully
differentiable).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.parallel.mesh import PIPE_AXIS


def spmd_pipeline(
    stage_fn: Callable,
    x_micro,
    *,
    axis_name: str = PIPE_AXIS,
    extras=None,
    with_aux: bool = False,
):
    """Run ``stage_fn`` as a GPipe pipeline over the ``axis_name`` mesh axis.

    Must be called INSIDE a manual region (`shard_map`) where ``axis_name``
    is a collective axis and ``stage_fn`` closes over this device's stage
    parameters (its slice of the layer stack).

    Args:
      stage_fn: ``activation [mb, ...] -> activation [mb, ...]`` — this
        stage's chunk of the network, same signature on every stage. With
        ``extras`` given, called as ``stage_fn(activation, extra)``.
      x_micro: ``[n_micro, mb, ...]`` microbatched stage-0 input.
      extras: optional pytree of ``[n_micro, ...]`` per-microbatch
        CONSTANTS (segment ids, positions, loss masks). Unlike activations
        they are not transformed between stages, so they never ride the
        ppermute ring — every stage indexes the microbatch it is currently
        processing directly (replicated over pipe). Gradients do not flow
        into extras.
      with_aux: when True, ``stage_fn`` returns ``(activation, aux)`` where
        ``aux`` is a pytree of per-invocation scalars (e.g. a MoE router's
        load-balance loss); the schedule sums it over this device's VALID
        ticks (bubble ticks masked out) and the call returns ``(out,
        aux_sums)``. The per-device sums cover this stage's layers on every
        microbatch — callers psum over ``axis_name`` to total the stages.
        Differentiable: gradients flow back into the stage on the same
        ticks the values came from.

    Returns:
      ``[n_micro, mb, ...]`` outputs of the LAST stage, identical on every
      pipe device (masked psum broadcast); with ``with_aux``, a ``(out,
      aux_sums)`` pair.
    """
    out, _, aux = _run_schedule(
        stage_fn, x_micro, axis_name, record_inputs=False, extras=extras,
        with_aux=with_aux,
    )
    return (out, aux) if with_aux else out


def _micro_extra(extras, mc):
    """This tick's slice of the per-microbatch constants."""
    return jax.tree.map(
        lambda e: lax.dynamic_index_in_dim(e, mc, 0, keepdims=False), extras
    )


def _aux_zeros(apply, state, extras, with_aux):
    """Zeros matching the aux pytree ``apply`` returns (None when unused)."""
    if not with_aux:
        return None
    args = (state,) if extras is None else (state, _micro_extra(extras, 0))
    _, aux_sd = jax.eval_shape(apply, *args)
    return jax.tree.map(lambda sd: jnp.zeros(sd.shape, sd.dtype), aux_sd)


def _run_schedule(apply, x_micro, axis_name, *, record_inputs: bool,
                  extras=None, with_aux: bool = False):
    """The GPipe tick loop shared by `spmd_pipeline` (mechanical-AD backward)
    and `spmd_pipeline_1f1b`'s forward (which additionally records each
    microbatch's stage input — its activation stash). Returns
    ``(last-stage outputs broadcast over pipe, saved-inputs-or-None,
    aux-sums-or-None)``."""
    s = lax.axis_index(axis_name)
    n_stages = lax.psum(1, axis_name)
    n_micro = x_micro.shape[0]
    ticks = n_micro + n_stages - 1

    state = jnp.zeros(x_micro.shape[1:], x_micro.dtype)  # incoming activation
    out_buf = jnp.zeros_like(x_micro)
    saved = jnp.zeros_like(x_micro) if record_inputs else None
    aux_acc = _aux_zeros(apply, state, extras, with_aux)
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        state, out_buf, saved, aux_acc = carry
        # Stage 0 feeds itself from the microbatch queue; later stages from
        # the activation handed over the ring. Clipped reads/writes keep
        # shapes static; bubble results are masked, never stored.
        x_t = lax.dynamic_index_in_dim(
            x_micro, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False
        )
        inp = jnp.where(s == 0, x_t, state)
        m = t - s  # the microbatch this stage processes at tick t
        mc = jnp.clip(m, 0, n_micro - 1)
        valid = (m >= 0) & (m < n_micro)
        if saved is not None:
            cur_saved = lax.dynamic_index_in_dim(saved, mc, 0, keepdims=False)
            saved = lax.dynamic_update_index_in_dim(
                saved, jnp.where(valid, inp, cur_saved), mc, 0
            )
        if extras is None:
            out = apply(inp)
        else:
            out = apply(inp, _micro_extra(extras, mc))
        if with_aux:
            out, aux = out
            # Bubble ticks run on garbage registers; their aux never lands.
            aux_acc = jax.tree.map(
                lambda acc, a: acc + jnp.where(valid, a, 0.0), aux_acc, aux
            )

        widx = t - (n_stages - 1)  # microbatch finishing at the last stage
        cidx = jnp.clip(widx, 0, n_micro - 1)
        cur = lax.dynamic_index_in_dim(out_buf, cidx, 0, keepdims=False)
        out_buf = lax.dynamic_update_index_in_dim(
            out_buf, jnp.where(widx >= 0, out, cur), cidx, 0
        )
        state = lax.ppermute(out, axis_name, perm)
        return (state, out_buf, saved, aux_acc), None

    (_, out_buf, saved, aux_acc), _ = lax.scan(
        tick, (state, out_buf, saved, aux_acc), jnp.arange(ticks)
    )

    # Only the last stage holds real outputs; broadcast them to every pipe
    # device so downstream (loss head) runs replicated over `pipe`.
    out = lax.psum(jnp.where(s == n_stages - 1, out_buf, 0.0), axis_name)
    return out, saved, aux_acc


def spmd_pipeline_1f1b(
    stage_fn: Callable,
    stage_params,
    x_micro,
    *,
    axis_name: str = PIPE_AXIS,
    extras=None,
    with_aux: bool = False,
):
    """GPipe-tick forward + hand-scheduled staggered backward (the 1F1B
    memory discipline) as a `jax.custom_vjp`.

    `spmd_pipeline` derives its backward mechanically from AD of the forward
    scan — correct, but the scan's saved state makes the backward hold
    every tick's stage internals. This variant instead saves ONLY each
    microbatch's stage INPUT ([n_micro, mb, ...] per device — the 1F1B
    activation stash) and runs a reverse pipeline scan that recomputes each
    stage's VJP on the fly (per-microbatch rematerialization): at backward
    tick τ, stage s processes the cotangent of microbatch ``τ-(S-1-s)`` —
    the last stage drains first, exactly 1F1B's staggered order — and hands
    ``d(input)`` to stage s-1 over the reversed ring. True fwd/bwd tick
    interleaving is impossible under jit-level AD (the output cotangent
    exists only after the whole forward), but the memory high-water mark —
    what 1F1B exists for — matches: stage inputs + one in-flight VJP.

    Unlike `spmd_pipeline`, parameters are EXPLICIT (``stage_fn(params,
    act)`` — or ``stage_fn(params, act, extra)`` with per-microbatch
    ``extras``, which take no gradient) — a closure's captures are
    constants to custom_vjp, so the closed-over form would silently drop
    parameter gradients.

    Cotangent conventions (why no psum appears in the backward): the
    enclosing `shard_map`'s transpose already reduces per-device
    contributions per in_spec — returning this device's raw ``d(params)``
    (its stage slice / its data shard) and a ``d(x_micro)`` that is nonzero
    only on stage 0 composes with that reduction; any manual psum here
    would double-count.
    """
    s_axis = axis_name

    @jax.custom_vjp
    def pipe(params, xm, ex):
        out, _, aux = _fwd_impl(params, xm, ex)
        return (out, aux) if with_aux else out

    def _stage(params, a, extra):
        if extras is None:
            return stage_fn(params, a)
        return stage_fn(params, a, extra)

    def _fwd_impl(params, xm, ex):
        if ex is None:
            return _run_schedule(
                lambda a: stage_fn(params, a), xm, s_axis,
                record_inputs=True, with_aux=with_aux,
            )
        return _run_schedule(
            lambda a, e: stage_fn(params, a, e), xm, s_axis,
            record_inputs=True, extras=ex, with_aux=with_aux,
        )

    def fwd(params, xm, ex):
        out, saved, aux = _fwd_impl(params, xm, ex)
        return ((out, aux) if with_aux else out), (params, saved, ex)

    def bwd(res, g):
        params, saved, ex = res
        s = lax.axis_index(s_axis)
        n_stages = lax.psum(1, s_axis)
        # Aux sums are per-device (callers psum over pipe OUTSIDE this vjp,
        # so that psum's own transpose already replicated g_aux here); each
        # valid tick's stage re-vjp receives it alongside the activation
        # cotangent.
        g_aux = None
        if with_aux:
            g, g_aux = g
        # The forward tail is `psum(masked)`; its VJP is a psum of the
        # incoming cotangent over pipe (every device's output depended on
        # the last stage's buffer). The mechanical-AD GPipe path gets this
        # from the psum's own transpose rule; a hand-written backward must
        # reproduce it or every gradient is 1/n_stages too small.
        g = lax.psum(g, s_axis)
        n_micro = saved.shape[0]
        ticks = n_micro + n_stages - 1
        # Reverse ring: stage s+1 hands d(input) back to stage s.
        perm_bwd = [(i, (i - 1) % n_stages) for i in range(n_stages)]

        cot0 = jnp.zeros(saved.shape[1:], jnp.float32)
        dparams0 = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params
        )
        dx0 = jnp.zeros(saved.shape, jnp.float32)

        def tick(carry, tau):
            cot_in, dparams, dx_buf = carry
            m = tau - (n_stages - 1 - s)  # staggered: last stage drains first
            mc = jnp.clip(m, 0, n_micro - 1)
            valid = (m >= 0) & (m < n_micro)
            x_in = lax.dynamic_index_in_dim(saved, mc, 0, keepdims=False)
            g_m = lax.dynamic_index_in_dim(g, mc, 0, keepdims=False)
            cot = jnp.where(s == n_stages - 1, g_m.astype(jnp.float32), cot_in)
            extra = None if ex is None else _micro_extra(ex, mc)
            _, vjp_fn = jax.vjp(
                lambda p, a: _stage(p, a, extra), params, x_in
            )
            if with_aux:
                gaux_t = jax.tree.map(
                    lambda v: jnp.where(valid, v, 0.0).astype(v.dtype), g_aux
                )
                dp, dx = vjp_fn((cot.astype(x_in.dtype), gaux_t))
            else:
                dp, dx = vjp_fn(cot.astype(x_in.dtype))
            dparams = jax.tree.map(
                lambda acc, d: acc + jnp.where(valid, d.astype(jnp.float32), 0.0),
                dparams, dp,
            )
            cur = lax.dynamic_index_in_dim(dx_buf, mc, 0, keepdims=False)
            dx_buf = lax.dynamic_update_index_in_dim(
                dx_buf, jnp.where(valid, dx.astype(jnp.float32), cur), mc, 0
            )
            cot_out = lax.ppermute(dx.astype(jnp.float32), s_axis, perm_bwd)
            return (cot_out, dparams, dx_buf), None

        (_, dparams, dx_buf), _ = lax.scan(
            tick, (cot0, dparams0, dx0), jnp.arange(ticks)
        )
        # x_micro is consumed by stage 0 only; other stages contribute zero
        # (the shard_map transpose psums these per-device values over pipe).
        dx = jnp.where(s == 0, dx_buf, 0.0).astype(saved.dtype)
        dparams = jax.tree.map(
            lambda p, d: d.astype(p.dtype), params, dparams
        )
        # extras are integer/constant side inputs: no cotangent.
        return dparams, dx, None

    pipe.defvjp(fwd, bwd)
    return pipe(stage_params, x_micro, extras)


def spmd_pipeline_interleaved(
    chunk_fn: Callable,
    chunk_params,
    x_micro,
    *,
    n_virtual: int,
    axis_name: str = PIPE_AXIS,
    extras=None,
    with_aux: bool = False,
):
    """Interleaved (virtual-stage) schedule: each device hosts ``n_virtual``
    non-adjacent model chunks, so the pipeline fill costs S-1 *chunk* times
    instead of S-1 *stage* times (Megatron's interleaved 1F1B insight,
    arXiv:2104.04473 — here as the forward schedule with AD-derived
    backward, matching `spmd_pipeline`'s design).

    Logical chunks ``c = 0 .. S·v - 1`` map to device ``c mod S``; microbatch
    ``m`` on round ``r`` (its ``r``-th lap around the ring) runs on device
    ``d`` at tick ``r·T + m + d`` — for fixed ``d`` the (r, m) decomposition
    of ``t - d`` is unique, so every device does exactly one chunk per tick
    (uniform SPMD control flow) and the whole schedule is
    ``v·T + S - 1`` ticks of 1/v-sized stage work:
    relative overhead (v·T + S - 1)/(v·T) vs GPipe's (T + S - 1)/T.

    The ring handoff (d → d+1) delivers the next round's input directly on
    devices 1..S-1; the wrap S-1 → 0 arrives T - S ticks early and waits in
    a per-microbatch register file (``buf``) until round r+1 reaches that
    microbatch — the memory cost of interleaving is that [T, mb, ...]
    waiting room (plus the extra in-flight activations AD saves).

    Args:
      chunk_fn: ``(one chunk's params, activation [mb, ...]) ->
        activation`` (with ``extras``: ``(params, act, extra)``) — applies
        ``n_layers / (S·v)`` layers.
      chunk_params: this device's ``[v, layers_per_chunk, ...]`` stacks —
        chunk ``r`` at index r, holding LOGICAL chunk ``r·S + d``.
      x_micro: ``[n_micro, mb, ...]`` stage-0 inputs. Requires
        ``n_micro >= S`` (the wrap must not outrun the schedule).

    Must be called inside `shard_map` (like `spmd_pipeline`). Returns the
    last logical chunk's outputs ``[n_micro, mb, ...]``, broadcast over pipe.
    """
    s = lax.axis_index(axis_name)
    n_stages = lax.psum(1, axis_name)  # static: psum of a literal
    n_micro = x_micro.shape[0]
    v = n_virtual
    if n_virtual > 1 and n_micro < int(n_stages):
        # The wrap register-file entry for (m, r+1) is written at tick
        # r·T + m + S but read at (r+1)·T + m — with T < S the read
        # happens FIRST and consumes stale zeros. (v == 1 has no wrap
        # reads at all, so any n_micro is safe there — the degenerate
        # GPipe-style tick loop the init probe uses.)
        raise ValueError(
            f"interleaved schedule needs n_micro ({n_micro}) >= n_stages "
            f"({int(n_stages)}) — the ring wrap would outrun the schedule"
        )
    ticks = v * n_micro + n_stages - 1
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    state = jnp.zeros(x_micro.shape[1:], x_micro.dtype)
    buf = jnp.zeros_like(x_micro)      # wrap waiting room, keyed by microbatch
    out_buf = jnp.zeros_like(x_micro)
    chunk0 = jax.tree.map(lambda p: p[0], chunk_params)
    aux_acc = _aux_zeros(
        lambda *a: chunk_fn(chunk0, *a), state, extras, with_aux
    )

    def tick(carry, t):
        state, buf, out_buf, aux_acc = carry
        # Stash the arriving activation under its sender's microbatch id:
        # sender (s-1 mod S) processed u' = (t-1) - sender at tick t-1.
        sender = (s - 1) % n_stages
        u_arr = (t - 1) - sender
        m_arr = jnp.clip(u_arr % n_micro, 0, n_micro - 1)
        arr_valid = (u_arr >= 0) & (u_arr < v * n_micro)
        cur = lax.dynamic_index_in_dim(buf, m_arr, 0, keepdims=False)
        buf = lax.dynamic_update_index_in_dim(
            buf, jnp.where(arr_valid, state, cur), m_arr, 0
        )

        # This device's work item: u = t - s decomposes uniquely as
        # r·T + m.
        u = t - s
        m = jnp.clip(u % n_micro, 0, n_micro - 1)
        r = jnp.clip(u // n_micro, 0, v - 1)
        valid = (u >= 0) & (u < v * n_micro)
        x_t = lax.dynamic_index_in_dim(x_micro, m, 0, keepdims=False)
        held = lax.dynamic_index_in_dim(buf, m, 0, keepdims=False)
        first_round = (u // n_micro) == 0
        inp = jnp.where((s == 0) & first_round, x_t, held)

        chunk = jax.tree.map(
            lambda p: lax.dynamic_index_in_dim(p, r, 0, keepdims=False),
            chunk_params,
        )
        if extras is None:
            out = chunk_fn(chunk, inp)
        else:
            out = chunk_fn(chunk, inp, _micro_extra(extras, m))
        if with_aux:
            out, aux = out
            aux_acc = jax.tree.map(
                lambda acc, a: acc + jnp.where(valid, a, 0.0), aux_acc, aux
            )

        # The last logical chunk (c = S·v - 1 lives on device S-1, round
        # v-1) finishes microbatch m here.
        is_final = valid & (s == n_stages - 1) & (r == v - 1)
        cur_out = lax.dynamic_index_in_dim(out_buf, m, 0, keepdims=False)
        out_buf = lax.dynamic_update_index_in_dim(
            out_buf, jnp.where(is_final, out, cur_out), m, 0
        )
        state = lax.ppermute(out, axis_name, perm)
        return (state, buf, out_buf, aux_acc), None

    (_, _, out_buf, aux_acc), _ = lax.scan(
        tick, (state, buf, out_buf, aux_acc), jnp.arange(ticks)
    )
    out = lax.psum(
        jnp.where(s == n_stages - 1, out_buf, 0.0), axis_name
    )
    return (out, aux_acc) if with_aux else out


def interleaved_layer_order(n_layers: int, n_stages: int,
                            n_virtual: int) -> list[int]:
    """Physical row ``p`` → logical layer index, for the interleaved layout.

    The pipe axis shards layer stacks contiguously (device d = rows
    [d·L/S, (d+1)·L/S)), but interleaving needs device d to hold logical
    chunks ``d, d+S, ..., d+(v-1)·S``. The model therefore stores stacks in
    *placement order* — device-major, round-minor — and this mapping
    converts: a stack built from logical layers ``[order[p] for p in
    range(L)]`` places the right chunks on the right devices. Checkpoints of
    an interleaved config carry this order; `pipelined_lm.to_logical_order`
    / `to_interleaved_order` convert.
    """
    if n_layers % (n_stages * n_virtual) != 0:
        raise ValueError(
            f"n_layers ({n_layers}) must divide into n_stages ({n_stages}) "
            f"x n_virtual ({n_virtual}) chunks"
        )
    lpc = n_layers // (n_stages * n_virtual)
    order = []
    for d in range(n_stages):
        for r in range(n_virtual):
            c = r * n_stages + d
            order.extend(range(c * lpc, (c + 1) * lpc))
    return order


def stage_slice_size(n_layers: int, n_stages: int) -> int:
    """Layers per stage; n_layers must divide evenly."""
    if n_layers % n_stages != 0:
        raise ValueError(
            f"n_layers ({n_layers}) must be divisible by pipe ({n_stages})"
        )
    return n_layers // n_stages
