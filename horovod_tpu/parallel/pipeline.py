"""Pipeline parallelism: a GPipe microbatch schedule as one SPMD program.

The reference has no pipeline parallelism (SURVEY.md §2.2: single-stage
model); this fills the framework's ``pipe`` mesh axis the TPU-native way —
no runtime stage processes, no send/recv threads, no schedule executor.
Instead the whole pipeline is ONE differentiable jitted function:

* **Stages are a sharding.** Per-layer parameter stacks ``[n_layers, ...]``
  are sharded over ``pipe`` on dim 0, so each pipe device holds a contiguous
  block of layers (its stage). There is no separate stage assignment
  machinery — the partitioner IS the assignment.
* **The schedule is a `lax.scan`.** Inside a `shard_map` over the mesh,
  every device runs ``n_micro + n_stages - 1`` identical ticks; at tick t,
  stage s processes microbatch ``t - s`` (a rotating activation register),
  then hands its output to stage ``s+1`` via `lax.ppermute`. Bubbles are
  ticks whose result is masked out — uniform control flow, exactly what XLA
  wants.
* **Backward is derived, not written.** The schedule is built from
  differentiable primitives (`scan`, `ppermute`, `psum`), so `jax.grad`
  mechanically produces the reverse pipeline (activations rematerialized per
  the standard AD rules) — where a runtime-scheduler design (GPipe/
  PipeDream's C++ executors) needs hand-written backward scheduling, here it
  falls out of the autodiff transform.

Cost notes: the GPipe bubble fraction is ``(S-1)/(T+S-1)`` for S stages and
T microbatches — pick ``n_micro >= 4*n_stages`` to keep it under ~20%. The
final broadcast of outputs off the last stage is a masked `psum` over
``pipe`` (one activation-sized allreduce per step; simple and fully
differentiable).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.parallel.mesh import PIPE_AXIS


def spmd_pipeline(
    stage_fn: Callable,
    x_micro,
    *,
    axis_name: str = PIPE_AXIS,
):
    """Run ``stage_fn`` as a GPipe pipeline over the ``axis_name`` mesh axis.

    Must be called INSIDE a manual region (`shard_map`) where ``axis_name``
    is a collective axis and ``stage_fn`` closes over this device's stage
    parameters (its slice of the layer stack).

    Args:
      stage_fn: ``activation [mb, ...] -> activation [mb, ...]`` — this
        stage's chunk of the network, same signature on every stage.
      x_micro: ``[n_micro, mb, ...]`` microbatched stage-0 input.

    Returns:
      ``[n_micro, mb, ...]`` outputs of the LAST stage, identical on every
      pipe device (masked psum broadcast).
    """
    s = lax.axis_index(axis_name)
    n_stages = lax.psum(1, axis_name)
    n_micro = x_micro.shape[0]
    ticks = n_micro + n_stages - 1

    state = jnp.zeros(x_micro.shape[1:], x_micro.dtype)  # incoming activation
    out_buf = jnp.zeros_like(x_micro)
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        state, out_buf = carry
        # Stage 0 feeds itself from the microbatch queue; later stages from
        # the activation handed over the ring. Clipped reads/writes keep
        # shapes static; bubble results are masked, never stored.
        x_t = lax.dynamic_index_in_dim(
            x_micro, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False
        )
        inp = jnp.where(s == 0, x_t, state)
        out = stage_fn(inp)

        widx = t - (n_stages - 1)  # microbatch finishing at the last stage
        cidx = jnp.clip(widx, 0, n_micro - 1)
        cur = lax.dynamic_index_in_dim(out_buf, cidx, 0, keepdims=False)
        out_buf = lax.dynamic_update_index_in_dim(
            out_buf, jnp.where(widx >= 0, out, cur), cidx, 0
        )
        state = lax.ppermute(out, axis_name, perm)
        return (state, out_buf), None

    (_, out_buf), _ = lax.scan(tick, (state, out_buf), jnp.arange(ticks))

    # Only the last stage holds real outputs; broadcast them to every pipe
    # device so downstream (loss head) runs replicated over `pipe`.
    return lax.psum(jnp.where(s == n_stages - 1, out_buf, 0.0), axis_name)


def stage_slice_size(n_layers: int, n_stages: int) -> int:
    """Layers per stage; n_layers must divide evenly."""
    if n_layers % n_stages != 0:
        raise ValueError(
            f"n_layers ({n_layers}) must be divisible by pipe ({n_stages})"
        )
    return n_layers // n_stages
