"""Average-semantics collective wrappers — the Horovod-core equivalent.

This module is the moral counterpart of Horovod's entire C++ core
(coordinator thread + tensor fusion + MPI/NCCL ops, SURVEY.md §2.3): on TPU
it is ~100 lines because a single SPMD program makes collective order static
and XLA's collective-combining pass does tensor fusion. What remains is the
*semantics* the reference depends on:

* **average, not sum** — ``hvd.allreduce(grad, average=True)`` divides by
  world size after the ring reduction (SURVEY.md §3.5). Every reduction here
  defaults to mean.
* **root broadcast** — ``hvd.broadcast_global_variables(0)``
  (tensorflow2_keras_mnist.py:71) for consistent init / checkpoint restore.
* **metric averaging** — epoch-end cross-worker mean
  (tensorflow2_keras_mnist.py:77).

Two execution contexts, one API:

1. **Traced** (inside `shard_map`/`pmap` with a named mesh axis): pass
   ``axis_name=...`` — lowers to `lax.psum`/`pmean` → ICI collectives.
2. **Eager host-level** (between steps, across processes): omit
   ``axis_name`` — uses `jax.experimental.multihost_utils`; degrades to a
   no-op at ``process_count() == 1`` exactly like Horovod collectives at
   ``size()==1`` (README.md:49-52 single-instance mode).
"""

from __future__ import annotations

from typing import Any, Sequence

import jax

from horovod_tpu import compat, flight
import jax.numpy as jnp
from jax import lax
from jax.experimental import multihost_utils

PyTree = Any


def _maybe_record(kind, value=None, *, tree=None, bucket=None):
    """Feed the flight recorder (flight.py) from a submission site.

    THE one gate every site routes through: when ``HVT_FLIGHT_RECORD``
    is unset, ``flight.RECORDER`` is None and this is a single attribute
    load + None check — the zero-instrumentation-cost contract the tier-1
    tests assert structurally. When recording, the record (kind, dtype,
    shape, payload bytes, bucket id, caller tag) is APPENDED AND FLUSHED
    before the collective blocks, so a wedged rank's final submission is
    already on disk when the supervisor collects the evidence."""
    rec = flight.RECORDER
    if rec is None:
        return
    import math
    import sys

    dtype = shape = nbytes = None
    try:
        if value is not None:
            shape = tuple(jnp.shape(value))
            dt = jnp.result_type(value)
            dtype = str(dt)
            nbytes = int(jnp.dtype(dt).itemsize * math.prod(shape))
        elif tree is not None:
            leaves = jax.tree_util.tree_leaves(tree)
            nbytes = int(sum(
                jnp.dtype(jnp.result_type(l)).itemsize
                * math.prod(jnp.shape(l))
                for l in leaves
            ))
            shape = (len(leaves),)
    except (TypeError, ValueError):
        pass  # unhashable/abstract values: record the kind alone
    code = sys._getframe(2).f_code
    rec.record(
        kind, dtype=dtype, shape=shape, nbytes=nbytes, bucket=bucket,
        tag=getattr(code, "co_qualname", None) or code.co_name,
    )


def _axis_names(axis_name) -> Sequence:
    if isinstance(axis_name, (tuple, list)):
        return tuple(axis_name)
    return (axis_name,)


def allreduce(x, average: bool = True, axis_name=None):
    """Allreduce one array. Mean by default (Horovod-parity semantics).

    Traced context: reduction over the named mesh axis/axes.
    Eager context: reduction across host processes (no-op single-process).
    """
    _maybe_record("allreduce", value=x)
    if axis_name is not None:
        return lax.pmean(x, axis_name) if average else lax.psum(x, axis_name)
    if jax.process_count() == 1:
        return x
    gathered = multihost_utils.process_allgather(jnp.asarray(x))
    return gathered.mean(axis=0) if average else gathered.sum(axis=0)


def allgather(x, axis_name=None, tiled: bool = True):
    """Concatenate per-worker shards along the leading axis
    (≈ ``hvd.allgather``, the third op in Horovod's kernel set,
    SURVEY.md §2.3 TF-custom-ops row)."""
    _maybe_record("allgather", value=x)
    if axis_name is not None:
        return lax.all_gather(x, axis_name, axis=0, tiled=tiled)
    if jax.process_count() == 1:
        return jnp.asarray(x)
    gathered = multihost_utils.process_allgather(jnp.asarray(x))
    return gathered.reshape((-1,) + gathered.shape[2:]) if tiled else gathered


def broadcast(x, root: int = 0, axis_name=None):
    """Broadcast ``x`` from the root worker (≈ ``hvd.broadcast``).

    Traced context: select root's shard via masked psum — every worker ends
    with root's value; XLA lowers this to a single collective.
    Eager context: `multihost_utils.broadcast_one_to_all` with the root
    process as source (the reference only ever uses root=0,
    tensorflow2_keras_mnist.py:71, but the API honors any root)."""
    _maybe_record("broadcast", value=x)
    if axis_name is not None:
        x = jnp.asarray(x)
        names = _axis_names(axis_name)
        idx = lax.axis_index(names[0])
        for name in names[1:]:
            idx = idx * compat.axis_size(name) + lax.axis_index(name)
        mask = (idx == root).astype(x.dtype)
        return lax.psum(x * mask, axis_name)
    if jax.process_count() == 1:
        return jnp.asarray(x)
    return multihost_utils.broadcast_one_to_all(
        x, is_source=jax.process_index() == root
    )


# --- PyTree conveniences (the DistributedOptimizer / broadcast-callback core)


def pmean_pytree(tree: PyTree, axis_name=None) -> PyTree:
    """Average every leaf across workers — the gradient-averaging heart of
    ``hvd.DistributedOptimizer`` (tensorflow2_keras_mnist.py:58) as one line.

    Under SPMD jit the per-tensor fusion/scheduling Horovod implements in C++
    (SURVEY.md §3.5) is handled by XLA's collective combiner. In eager
    host-level mode the whole tree goes through ONE fused collective (the
    moral equivalent of Horovod's tensor-fusion buffer) rather than one
    round-trip per leaf."""
    _maybe_record("pmean_pytree", tree=tree)
    if axis_name is None:
        if jax.process_count() == 1:
            return tree
        gathered = multihost_utils.process_allgather(tree)
        return jax.tree.map(lambda g: g.mean(axis=0), gathered)
    return jax.tree.map(lambda g: allreduce(g, average=True, axis_name=axis_name), tree)


def broadcast_pytree(tree: PyTree, root: int = 0, axis_name=None) -> PyTree:
    """Broadcast every leaf from root — ``hvd.broadcast_global_variables(0)``
    over an arbitrary pytree (model params AND optimizer state; the reference
    broadcasts both, SURVEY.md §7.3)."""
    _maybe_record("broadcast_pytree", tree=tree)
    if axis_name is None and jax.process_count() > 1:
        if _kv_client() is not None:
            # One fused host-level broadcast over the coordination-service
            # KV store (see _kv_client for why it replaces the psum path).
            # Only the ROOT's tree travels — non-root copies are replaced
            # wholesale, so their device→host fetch would be pure waste.
            return broadcast_object(
                jax.device_get(tree)
                if jax.process_index() == root else None,
                root=root,
            )
        return multihost_utils.broadcast_one_to_all(
            tree, is_source=jax.process_index() == root
        )
    return jax.tree.map(lambda x: broadcast(x, root=root, axis_name=axis_name), tree)


# --- host-level object collectives over the coordination-service KV store --
#
# Why not ride broadcast_one_to_all/process_allgather for these? Their
# device path (zero-stack + psum over a 'processes' axis) is observed to be
# UNRELIABLE on this repo's compat floor (jax 0.4.x + gloo CPU collectives:
# nondeterministic all-zero results for host-staged buffers), and object
# movement is control-plane work anyway. jax's distributed runtime carries a
# key-value store on the coordination service — the exact channel gloo uses
# to bootstrap itself — and a blocking KV get is deterministic: set-then-get
# is the broadcast, set-all-then-get-all is the allgather. Keys are
# sequenced per client connection, which is correct under the collective
# calling discipline (every process makes the same sequence of collective
# calls against a given world — the same contract the array collectives
# already require); an elastic rescale swaps the client (fresh service,
# fresh namespace), resetting the sequence on every process together.
# Each round's keys are garbage-collected once every reader has fetched
# (_kv_cleanup), so a long-lived world does not accumulate per-epoch votes
# or park model-sized broadcast payloads in the coordination service.

_KV_CHUNK = 2 * 1024 * 1024  # stay clear of gRPC's default 4 MB message cap
_KV_TIMEOUT_MS = 600_000
_kv_seq = {"client": None, "n": 0}


def _kv_client():
    """The live coordination-service client, or None (no distributed init —
    single-process, or a backend brought up without jax.distributed, or a
    jaxlib without the bytes KV APIs — the multihost_utils array fallback
    one branch away is then the right path)."""
    try:
        from jax._src import distributed

        client = distributed.global_state.client
    except ImportError:  # pragma: no cover — future jax moved the module
        return None
    if client is None or not (
        hasattr(client, "key_value_set_bytes")
        and hasattr(client, "blocking_key_value_get_bytes")
    ):
        return None
    return client


def _kv_next(tag: str) -> str:
    client = _kv_client()
    if client is not _kv_seq["client"]:
        _kv_seq["client"] = client
        _kv_seq["n"] = 0
    _kv_seq["n"] += 1
    return f"hvt/{tag}/{_kv_seq['n']}"


def _kv_put(client, key: str, payload: bytes) -> None:
    import hashlib

    chunks = [
        payload[i : i + _KV_CHUNK]
        for i in range(0, len(payload), _KV_CHUNK)
    ] or [b""]
    for i, chunk in enumerate(chunks):
        client.key_value_set_bytes(f"{key}/c{i}", chunk)
    # Meta lands LAST: a reader that sees it knows every chunk is in place.
    # It carries the payload's sha256 so the reader can prove it reassembled
    # the writer's exact bytes — the elastic commit/sync path moves model
    # state over this channel, and a silently-corrupt transport would
    # otherwise install garbage weights fleet-wide.
    digest = hashlib.sha256(payload).hexdigest()
    client.key_value_set(f"{key}/meta", f"{len(chunks)}:{digest}")


def _kv_get(client, key: str) -> bytes:
    import hashlib

    meta = str(client.blocking_key_value_get(f"{key}/meta", _KV_TIMEOUT_MS))
    n_s, _, digest = meta.partition(":")
    payload = b"".join(
        client.blocking_key_value_get_bytes(f"{key}/c{i}", _KV_TIMEOUT_MS)
        for i in range(int(n_s))
    )
    if digest and hashlib.sha256(payload).hexdigest() != digest:
        raise ValueError(
            f"KV object-collective payload {key!r} failed its sha256 check "
            f"({len(payload)} bytes reassembled) — coordination-service "
            "transport corruption"
        )
    return payload


def _kv_cleanup(client, key: str, *, root: int = 0) -> None:
    """Best-effort removal of a finished round's keys. The barrier proves
    every reader has fetched before the root deletes — without it a root
    racing ahead could delete chunks a slower peer is still blocked on.
    Any failure (a jaxlib predating delete/barrier, a peer death failing
    the barrier) leaves the keys behind, which costs memory in the
    coordination service but never correctness: keys are never reused
    (monotonic sequence) and an elastic rescale drops the whole namespace
    with the old service anyway."""
    try:
        client.wait_at_barrier(f"{key}/done", _KV_TIMEOUT_MS)
        if jax.process_index() == root:
            client.key_value_delete(f"{key}/")
    except Exception:
        pass


def broadcast_object(obj, root: int = 0):
    """``hvd.broadcast_object``: every process adopts the root's arbitrary
    picklable Python object (config dicts, vocabularies, epoch counters,
    committed elastic state — the host-side metadata Horovod moves
    alongside tensors). Travels over the coordination-service KV store
    (see above); ``process_count()==1`` is the identity, like every
    collective here."""
    _maybe_record("broadcast_object")
    import pickle

    import numpy as np

    if jax.process_count() == 1:
        return obj
    client = _kv_client()
    if client is not None:
        key = _kv_next("bcast")
        if jax.process_index() == root:
            _kv_put(client, key, pickle.dumps(obj))
        out = pickle.loads(_kv_get(client, key))
        _kv_cleanup(client, key, root=root)
        return out
    # Fallback (no distributed client): the fixed-width array broadcast.
    payload = pickle.dumps(obj) if jax.process_index() == root else b""
    n = int(
        multihost_utils.broadcast_one_to_all(
            np.int64(len(payload)), is_source=jax.process_index() == root
        )
    )
    buf = np.zeros(n, np.uint8)
    if jax.process_index() == root:
        buf[:] = np.frombuffer(payload, np.uint8)
    buf = multihost_utils.broadcast_one_to_all(
        buf, is_source=jax.process_index() == root
    )
    return pickle.loads(np.asarray(buf).tobytes())


def allgather_object(obj) -> list:
    """``hvd.allgather_object``: every process receives the list of all
    processes' picklable objects, ordered by process index. KV-store
    transport (set mine, read everyone's), like `broadcast_object`."""
    _maybe_record("allgather_object")
    import pickle

    import numpy as np

    if jax.process_count() == 1:
        return [obj]
    client = _kv_client()
    if client is not None:
        key = _kv_next("gather")
        _kv_put(client, f"{key}/r{jax.process_index()}", pickle.dumps(obj))
        out = [
            pickle.loads(_kv_get(client, f"{key}/r{r}"))
            for r in range(jax.process_count())
        ]
        _kv_cleanup(client, key)
        return out
    payload = np.frombuffer(pickle.dumps(obj), np.uint8)
    sizes = multihost_utils.process_allgather(np.int64(len(payload)))
    width = int(np.max(sizes))
    buf = np.zeros(width, np.uint8)
    buf[: len(payload)] = payload
    gathered = multihost_utils.process_allgather(buf)
    return [
        pickle.loads(gathered[i, : int(sizes[i])].tobytes())
        for i in range(jax.process_count())
    ]


def all_to_all(x, axis_name, *, split_axis: int = 0, concat_axis: int = 0,
               tiled: bool = True, axis_index_groups=None):
    """The payload all-to-all entry point — the EP (expert-parallel)
    dispatch/combine wire (ROADMAP item 4).

    MoE dispatch moves each group's routed activations to the expert
    shards that own them and combine moves them back: one all-to-all
    each way, the only collectives whose PAYLOAD is activations rather
    than gradients. Routing them through this entry point (instead of a
    raw ``lax.all_to_all`` at the model layer — `hvt-lint` rule HVT011)
    keeps the EP wire under the same discipline as the gradient wire:
    every submission is flight-recorded (`horovod_tpu.flight`), and the
    compiled program's payload all-to-alls are auditable as a count
    (`hvt-audit --expect alltoalls=N` — rank >= 2 payloads; the rank-1
    scale/column gathers of the quantized wire stay excluded).

    Traced context only (inside shard_map/pmap over ``axis_name``)."""
    _maybe_record("all_to_all", value=x)
    return lax.all_to_all(
        x, axis_name, split_axis=split_axis, concat_axis=concat_axis,
        tiled=tiled, axis_index_groups=axis_index_groups,
    )


# --- Bucketed fusion + hierarchical (ICI/DCN two-hop) gradient reduction ---
#
# Horovod's defining perf feature is tensor fusion: many small gradient
# tensors batched into one collective so the wire sees a handful of large
# transfers instead of one launch per leaf (arXiv:1802.05799 §Horovod's
# fusion buffer). Under SPMD jit XLA's collective combiner does a version of
# this, but the explicit-collective gradient step (wire compression,
# trainer-native accumulation) hand-places its psums — so the fusion must be
# hand-placed too. `flatten_buckets` packs a gradient pytree into a few
# contiguous dtype-homogeneous 1-D buckets (≤ bucket_bytes each, Horovod's
# HOROVOD_FUSION_THRESHOLD role); `unflatten_buckets` restores the tree.
#
# On a multi-slice mesh the data axis spans DCN (orders of magnitude less
# bandwidth than intra-slice ICI), and EQuARX (arXiv:2506.17615) shows
# gradient compression should pay its precision cost only on the slow hop:
# `hierarchical_psum` reduces over the ICI sub-axis in full precision first,
# then over the DCN sub-axis in the wire dtype — same result as the flat
# psum (sum is associative; the cast boundary is the only numerics delta),
# 16-bit bytes only where bandwidth is scarce. `reduce_gradients` composes
# the two: bucket, reduce each bucket (two-hop when dcn > 1), unflatten.
#
# Quantized wires (int8 / fp8, the EQuARX-aggressive tier): a sub-16-bit
# reduction cannot ride a plain all-reduce — int8 partial sums overflow and
# fp8 ones drown in rounding — so each quantized hop is a gather-sum: the
# bucket is scaled by ONE per-bucket scalar (amax/qmax), cast to the wire
# dtype, all-gathered across the hop's groups (the only payload bytes on
# the wire: 1 B/element plus one f32 scale per bucket per shard), then
# dequantized and summed in f32 by every receiver. Error feedback (EQuARX
# residuals): the caller carries a per-shard residual of what quantization
# failed to transmit and adds it back before the next step's quantization —
# the errors telescope, so quantization bias does NOT compound across
# steps. `reduce_gradients(..., residual=...)` threads it per bucket and
# returns the updated residual tree.
#
# Overlap (Horovod's tensor-fusion ORDER trick, arXiv:1802.05799): the
# backward pass produces the LAST layers' gradients first, so issuing the
# bucket reductions in reverse pytree order (``reverse=True``) lets XLA's
# latency-hiding scheduler start a bucket's collective (all-reduce-start /
# all-gather-start on TPU) as soon as its leaves are final, while the
# remaining backward compute is still running — provided the caller keeps
# that backward in the same straight-line computation (see
# trainer.explicit_grads, which peels the last microbatch out of its
# accumulation scan exactly for this).

#: Default fusion-bucket size: Horovod's fusion threshold default (64 MB).
DEFAULT_BUCKET_BYTES = 64 * 1024 * 1024


def flatten_buckets(tree: PyTree, bucket_bytes: int | None = None,
                    *, reverse: bool = False):
    """Pack a pytree into contiguous dtype-homogeneous 1-D buckets.

    Leaves are grouped by dtype (first-appearance order), raveled,
    concatenated, and split into chunks of at most ``bucket_bytes`` — so a
    dtype's leaves cost ``ceil(dtype_bytes / bucket_bytes)`` buckets and the
    whole tree at most ``ceil(total_bytes / bucket_bytes) + n_dtypes - 1``.
    ``reverse=True`` walks the leaves LAST-first (Horovod's fusion order:
    the backward pass finalizes the last layers' gradients first, so the
    first buckets become reducible while earlier layers are still
    computing). Returns ``(buckets, spec)``;
    ``unflatten_buckets(buckets, spec)`` is the exact inverse (shapes,
    dtypes, 0-d leaves, pytree structure all restored) for either order.
    Pure structure — no communication; callers reduce the buckets however
    they like."""
    if bucket_bytes is None:
        bucket_bytes = DEFAULT_BUCKET_BYTES
    bucket_bytes = int(bucket_bytes)
    if bucket_bytes <= 0:
        raise ValueError(f"bucket_bytes must be positive, got {bucket_bytes}")
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes = [jnp.shape(l) for l in leaves]
    dtypes = [jnp.result_type(l) for l in leaves]
    by_dtype: dict = {}  # dtype -> list of leaf indices (order-preserving)
    order = range(len(dtypes) - 1, -1, -1) if reverse else range(len(dtypes))
    for i in order:
        by_dtype.setdefault(jnp.dtype(dtypes[i]), []).append(i)
    buckets = []
    groups = []  # (leaf_indices, n_chunks) per dtype, bucket order
    for dt, idxs in by_dtype.items():
        flat = [jnp.ravel(jnp.asarray(leaves[i], dtype=dt)) for i in idxs]
        vec = flat[0] if len(flat) == 1 else jnp.concatenate(flat)
        per = max(1, bucket_bytes // dt.itemsize)
        cuts = list(range(per, vec.size, per))
        chunks = jnp.split(vec, cuts) if cuts else [vec]
        buckets.extend(chunks)
        groups.append((tuple(idxs), len(chunks)))
    spec = (treedef, tuple(shapes), tuple(dtypes), tuple(groups))
    return buckets, spec


def unflatten_buckets(buckets, spec) -> PyTree:
    """Inverse of `flatten_buckets`: reassemble the original pytree from the
    (possibly reduced/recast) buckets. Bucket dtypes are cast back to each
    leaf's recorded dtype, so a wire-compressed reduction round-trips."""
    import math as _math

    treedef, shapes, dtypes, groups = spec
    leaves: list = [None] * len(shapes)
    pos = 0
    for idxs, n_chunks in groups:
        chunks = buckets[pos : pos + n_chunks]
        pos += n_chunks
        vec = chunks[0] if len(chunks) == 1 else jnp.concatenate(chunks)
        off = 0
        for i in idxs:
            n = int(_math.prod(shapes[i]))
            leaves[i] = vec[off : off + n].reshape(shapes[i]).astype(dtypes[i])
            off += n
    if pos != len(buckets):
        raise ValueError(
            f"unflatten_buckets got {len(buckets)} buckets for a spec "
            f"describing {pos} — bucket list and spec do not match"
        )
    return jax.tree_util.tree_unflatten(treedef, leaves)


# --- ZeRO-1 (shard_update) layout + scatter-mode bucketing -----------------
#
# The sharded weight update (Xu et al., arXiv:2004.13336) shards each
# optimizer-state leaf along its first dp-divisible dimension over the data
# axis (`zero1_shard_dim` — the single source of the rule; training/build.py
# derives the opt-state init shardings from it). Scatter-mode reduction
# (`reduce_gradients(scatter=dp)`) lowers the boundary reduction INTO that
# layout: each dtype-homogeneous bucket is arranged as a [dp, cols] matrix
# whose row s is exactly shard s's slice of every leaf in the bucket
# (`flatten_scatter_buckets`), so one `lax.psum_scatter` hands every shard
# precisely the gradient slice its optimizer shard consumes — ~half the
# wire bytes of reduce-then-slice. Leaves with NO dp-divisible dimension
# (odd biases, scalars) are padded to a dp multiple and ride the SAME
# buckets as everyone else ("tail" pieces): the one reduce-scatter covers
# them too, and their full (replicated-mirror) values come back through a
# small all-gather of just their columns — a two-shot all-reduce, never a
# full-payload all-reduce op.
#
# Per-bucket schedulability (ISSUE 12 — the overlap-cash-in): each bucket
# is assembled ONLY from the leaf pieces it carries (leaf-aligned
# concatenation, never a slice of a whole-tree concat) and each leaf is
# reassembled ONLY from the buckets that carry it. The dataflow therefore
# has no cross-bucket dependency in either direction: inside the peeled
# backward's straight-line region, bucket i's `psum_scatter` can issue as
# soon as its leaves' gradients are final (reverse bucket order =
# last-produced-grads-first), and shard s's optimizer apply for bucket
# i's leaves can start as soon as bucket i lands — while bucket j's
# transfer is still in flight. The cut points are IDENTICAL to a
# concat-then-split at `bucket_bytes` (same bucket count, same values,
# bitwise), so the restructure changes schedulability, not arithmetic.


def zero1_shard_dim(shape, dp: int):
    """The dimension a ZeRO-1 (shard_update) layout shards over the data
    axis: the FIRST dp-divisible dim (dim 0 for the matmul kernels that
    dominate; conv kernels usually shard a channel dim), or None when no
    dim divides — the leaf (and its optimizer mirrors) stays replicated.
    THE shared rule: `training/build.py` derives the opt-state init
    shardings from it and the scatter-mode reduction derives the bucket
    layout — they cannot drift."""
    for i, dim in enumerate(shape):
        if dim % dp == 0:
            return i
    return None


def zero1_partition_spec(shape, dp: int, axis=None):
    """The `PartitionSpec` for a ZeRO-1-sharded leaf of ``shape`` (the
    data axis at `zero1_shard_dim`; fully replicated when no dim
    divides)."""
    from horovod_tpu.parallel import mesh as mesh_lib

    axis = axis or mesh_lib.DATA_AXIS
    i = zero1_shard_dim(shape, dp)
    if i is None:
        return jax.sharding.PartitionSpec()
    spec = [None] * len(shape)
    spec[i] = axis
    return jax.sharding.PartitionSpec(*spec)


def flatten_scatter_buckets(tree: PyTree, dp: int,
                            bucket_bytes: int | None = None,
                            *, reverse: bool = False):
    """Pack a pytree into scatter-ready dtype-homogeneous 1-D buckets.

    Leaves with a dp-divisible dim ("scatter" family) contribute their
    `zero1_shard_dim`-major [dp, size/dp] block matrix; leaves without
    one ("tail" family) are raveled, zero-padded to a dp multiple and
    reshaped likewise — both families share the SAME buckets, so ONE
    tiled ``psum_scatter`` per bucket covers every leaf (tail leaves'
    full values come back through a small all-gather of their columns
    only; see `bucket_tail_spans`). Per dtype the [dp, cols] leaf
    matrices pack greedily into buckets of at most ``bucket_bytes``
    (cut points at exact ``bucket_bytes`` column multiples — identical
    to a concat-then-split), but each bucket is ASSEMBLED only from the
    leaf pieces it carries: the dataflow carries no cross-bucket
    dependency, so bucket i's collective can issue the moment its
    leaves' gradients are final while earlier leaves are still in the
    backward. Returns ``(buckets, spec)``; the spec records, per
    bucket, the ordered ``(leaf_index, column_width)`` pieces."""
    if bucket_bytes is None:
        bucket_bytes = DEFAULT_BUCKET_BYTES
    bucket_bytes = int(bucket_bytes)
    if bucket_bytes <= 0:
        raise ValueError(f"bucket_bytes must be positive, got {bucket_bytes}")
    dp = int(dp)
    if dp < 1:
        raise ValueError(f"scatter shard count must be >= 1, got {dp}")
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes = [jnp.shape(l) for l in leaves]
    dtypes = [jnp.result_type(l) for l in leaves]
    sdims = [zero1_shard_dim(s, dp) for s in shapes]
    by_dtype: dict = {}  # dtype -> leaf indices, order-preserving
    order = range(len(dtypes) - 1, -1, -1) if reverse else range(len(dtypes))
    for i in order:
        by_dtype.setdefault(jnp.dtype(dtypes[i]), []).append(i)
    buckets: list = []
    descs: list = []  # per bucket: tuple of (leaf_index, column_width)
    for dt, idxs in by_dtype.items():
        per = max(1, bucket_bytes // (dp * dt.itemsize))  # columns/bucket
        pieces: list = []
        pdesc: list = []
        cols = 0

        def close(dt=dt):
            nonlocal pieces, pdesc, cols
            if pieces:
                mat = (
                    pieces[0] if len(pieces) == 1
                    else jnp.concatenate(pieces, axis=1)
                )
            else:  # zero-width leaves only
                mat = jnp.zeros((dp, 0), dt)
            buckets.append(jnp.ravel(mat))
            descs.append(tuple(pdesc))
            pieces, pdesc, cols = [], [], 0

        for i in idxs:
            a = jnp.asarray(leaves[i], dtype=dt)
            if sdims[i] is not None:
                m = jnp.moveaxis(a, sdims[i], 0).reshape(dp, -1)
            else:
                v = jnp.ravel(a)
                pad = (-v.size) % dp
                if pad:
                    v = jnp.concatenate([v, jnp.zeros((pad,), dt)])
                m = v.reshape(dp, -1)
            w = m.shape[1]
            if w == 0:
                pdesc.append((i, 0))
                continue
            off = 0
            while off < w:
                take = min(per - cols, w - off)
                pieces.append(
                    m if (off == 0 and take == w) else m[:, off: off + take]
                )
                pdesc.append((i, take))
                cols += take
                off += take
                if cols == per:
                    close()
        if pieces or pdesc:
            close()
    spec = (
        treedef, tuple(shapes), tuple(dtypes), tuple(sdims), dp,
        tuple(descs),
    )
    return buckets, spec


def bucket_families(spec) -> list:
    """Per-bucket family tags for a `flatten_scatter_buckets` spec, in
    bucket order: 'scatter' (every piece has a dp-divisible dim), 'tail'
    (none does), or 'mixed' (both ride the bucket)."""
    sdims = spec[3]
    fams = []
    for pieces in spec[5]:
        kinds = {
            "scatter" if sdims[i] is not None else "tail"
            for i, _w in pieces
        }
        fams.append(kinds.pop() if len(kinds) == 1 else
                    ("mixed" if kinds or len(pieces) else "scatter"))
    return fams


def bucket_tail_spans(spec) -> list:
    """Per bucket, the ordered ``(column_start, width)`` spans holding
    tail-family pieces (leaves with no dp-divisible dim) — the columns
    whose reduced rows must be all-gathered back to full values for the
    replicated optimizer mirrors. Empty tuple = pure-scatter bucket."""
    sdims = spec[3]
    out = []
    for pieces in spec[5]:
        col, spans = 0, []
        for i, w in pieces:
            if sdims[i] is None and w:
                spans.append((col, w))
            col += w
        out.append(tuple(spans))
    return out


def unflatten_scatter_buckets(entries, spec) -> PyTree:
    """Inverse of `flatten_scatter_buckets` AFTER a scatter reduction.

    Per bucket the entry is this shard's LOCAL reduced row (``[cols]``);
    a bucket carrying tail-family pieces takes a ``(local_row,
    gathered)`` pair, where ``gathered`` is the row-major ravel of the
    bucket's tail columns all-gathered back to ``[dp, tail_cols]``
    (`bucket_tail_spans` gives the spans, in the same order). Scatter
    leaves come back as the local zero1 block (shard dim divided by
    dp); tail leaves come back whole (padding stripped). Dtypes are
    restored per leaf. Each leaf is assembled ONLY from the buckets
    that carry it — the per-bucket schedulability contract's consumer
    side."""
    import math as _math

    treedef, shapes, dtypes, sdims, dp, descs = spec
    if len(entries) != len(descs):
        raise ValueError(
            f"unflatten_scatter_buckets got {len(entries)} buckets for a "
            f"spec describing {len(descs)} — bucket list and spec do not "
            "match"
        )
    parts: list[list] = [[] for _ in shapes]
    for entry, pieces in zip(entries, descs):
        if isinstance(entry, (tuple, list)):
            row, gathered = entry
        else:
            row, gathered = entry, None
        tail_cols = sum(w for i, w in pieces if sdims[i] is None)
        gm = None
        if tail_cols:
            if gathered is None:
                raise ValueError(
                    "bucket carries tail-family pieces but its entry is a "
                    "bare local row — pass (local_row, gathered_tails); "
                    "see bucket_tail_spans"
                )
            gm = jnp.reshape(gathered, (dp, tail_cols))
        col = tcol = 0
        for i, w in pieces:
            if w == 0:
                continue
            if sdims[i] is None:
                parts[i].append(gm[:, tcol: tcol + w])
                tcol += w
            else:
                parts[i].append(row[col: col + w])
            col += w
    leaves: list = [None] * len(shapes)
    for i, segs in enumerate(parts):
        if sdims[i] is not None:
            sd = sdims[i]
            rest = tuple(shapes[i][:sd]) + tuple(shapes[i][sd + 1:])
            blk = shapes[i][sd] // dp
            if not segs:
                vec = jnp.zeros((0,), dtypes[i])
            else:
                vec = segs[0] if len(segs) == 1 else jnp.concatenate(segs)
            moved = vec.reshape((blk,) + rest)
            leaves[i] = jnp.moveaxis(moved, 0, sd).astype(dtypes[i])
        else:
            n = int(_math.prod(shapes[i]))
            if not segs:
                flat = jnp.zeros((n,), dtypes[i])
            else:
                mat = (
                    segs[0] if len(segs) == 1
                    else jnp.concatenate(segs, axis=1)
                )
                flat = jnp.ravel(mat)[:n]
            leaves[i] = flat.reshape(shapes[i]).astype(dtypes[i])
    return jax.tree_util.tree_unflatten(treedef, leaves)


def unflatten_scatter_full(buckets, spec) -> PyTree:
    """Inverse of `flatten_scatter_buckets` from FULL (un-scattered)
    ``[dp * cols]`` buckets — the error-feedback residual path, where
    each shard keeps its own full-bucket quantization remainder. Scatter
    leaves un-moveaxis back to their original shape; tail leaves strip
    their padding."""
    import math as _math

    treedef, shapes, dtypes, sdims, dp, descs = spec
    if len(buckets) != len(descs):
        raise ValueError(
            f"unflatten_scatter_full got {len(buckets)} buckets for a "
            f"spec describing {len(descs)} — bucket list and spec do not "
            "match"
        )
    parts: list[list] = [[] for _ in shapes]
    for b, pieces in zip(buckets, descs):
        cols = sum(w for _i, w in pieces)
        m = jnp.reshape(b, (dp, cols))
        col = 0
        for i, w in pieces:
            if w == 0:
                continue
            parts[i].append(m[:, col: col + w])
            col += w
    leaves: list = [None] * len(shapes)
    for i, segs in enumerate(parts):
        if not segs:
            leaves[i] = jnp.zeros(shapes[i], dtypes[i])
            continue
        mat = segs[0] if len(segs) == 1 else jnp.concatenate(segs, axis=1)
        if sdims[i] is not None:
            sd = sdims[i]
            rest = tuple(shapes[i][:sd]) + tuple(shapes[i][sd + 1:])
            moved = mat.reshape((shapes[i][sd],) + rest)
            leaves[i] = jnp.moveaxis(moved, 0, sd).astype(dtypes[i])
        else:
            n = int(_math.prod(shapes[i]))
            leaves[i] = jnp.ravel(mat)[:n].reshape(
                shapes[i]
            ).astype(dtypes[i])
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _slice_zero1_local(tree: PyTree, dp: int, axis_name) -> PyTree:
    """Cut each leaf of a FULLY-REDUCED tree down to this shard's zero1
    block (traced context) — the quantized-wire scatter path, where the
    wire already delivered the whole tree (dense bucket layout, bitwise
    identical to the replicated reduction) and the sharded update only
    consumes the local slice. Leaves with no dp-divisible dim pass
    through replicated."""
    idx = _composite_axis_index(axis_name)

    def cut(l):
        sd = zero1_shard_dim(jnp.shape(l), dp)
        if sd is None:
            return l
        blk = jnp.shape(l)[sd] // dp
        return lax.dynamic_slice_in_dim(l, idx * blk, blk, axis=sd)

    return jax.tree.map(cut, tree)


def _compress16(orig_dtype, wire_dtype) -> bool:
    """True when ``wire_dtype`` is a plain cast wire (16-bit) narrower
    than the value's dtype — the compress-then-reduce hop form."""
    return (
        wire_dtype is not None
        and not is_quantized_wire(wire_dtype)
        and jnp.issubdtype(orig_dtype, jnp.floating)
        and jnp.dtype(wire_dtype).itemsize < jnp.dtype(orig_dtype).itemsize
    )


def _scatter_reduce_bucket(b, axis_name, dcn: int, wire_dtype, extra_axes,
                           *, ici_wire_dtype=None, residual=None):
    """Reduce-scatter ONE flat [dp*cols] scatter-arranged bucket over
    ``axis_name`` (two-hop over the dcn/ici factoring when ``dcn > 1``;
    the 16-bit wire dtype rides the DCN hop — or the single hop when flat
    — exactly like the replicated reduction). ``ici_wire_dtype``
    (`compression_ici`) rides the two-hop's ICI hop: a 16-bit dtype
    casts hop 1, a quantized (int8/fp8) dtype runs hop 1 as a
    per-bucket-scaled quantized reduce-scatter
    (`_quantized_matrix_reduce_scatter`) with the untransmitted
    remainder charged to this shard — single-hop (``dcn <= 1``)
    reductions have no ICI sub-hop, so the knob is inert there.

    ``residual`` (error feedback, full-bucket f32) is added to the
    bucket before any wire; when no quantized hop actually runs the
    residual is transmitted in full and the returned error is zero
    (flush semantics — mass is conserved either way).

    Returns ``(local_row, error)``: this shard's fully-reduced [cols]
    row in the bucket's dtype, and the full-bucket f32 untransmitted
    remainder (None when ``residual`` is None). A quantized DCN wire
    never reaches here (it keeps the dense-layout two-shot; see
    `reduce_gradients`)."""
    orig = b.dtype
    # Trivial (size-1) extra axes are elided STATICALLY: the lowered text
    # is what `hvt-audit` reads, and a singleton-group all-reduce there
    # would read as full-payload gradient traffic that the compiled
    # program never performs.
    extra = tuple(a for a in extra_axes if compat.axis_size(a) > 1)
    if extra:
        b = lax.psum(b, extra)
    if residual is not None:
        # Stay in f32 from here on: casting the residual-carrying value
        # back to a narrower bucket dtype would silently drop residual
        # mass the returned error never charges (the wire predicates
        # below key off ``orig``, the pre-residual dtype, and the final
        # result is cast back to it).
        b = b.astype(jnp.float32) + residual
    err = None
    if dcn <= 1:
        x = b.astype(wire_dtype) if _compress16(orig, wire_dtype) else b
        out = lax.psum_scatter(x, axis_name, tiled=True).astype(orig)
        if residual is not None:
            err = jnp.zeros(b.shape, jnp.float32)
        return out, err
    n = compat.axis_size(axis_name)
    ici = n // dcn
    ici_groups, dcn_groups = _hier_groups(n, dcn)
    cols = b.size // n
    # Rows are ordered by global (o*ici + i) target; hop 1 scatters the
    # ici index, so arrange target-inner-major first.
    t = b.reshape(dcn, ici, cols).transpose(1, 0, 2).reshape(-1)
    if ici > 1:
        # Branch condition is trace-time config (wire dtype + value
        # dtype), identical on every rank: the whole fleet takes the
        # same arm and submits the same collective order.
        if is_quantized_wire(ici_wire_dtype) and jnp.issubdtype(  # hvt: noqa[HVT007] config-uniform
            orig, jnp.floating
        ):
            mat = t.astype(jnp.float32).reshape(ici, dcn * cols)
            part, e1 = _quantized_matrix_reduce_scatter(
                mat, axis_name, ici_wire_dtype,
                axis_index_groups=ici_groups,
            )  # part: [dcn*cols] f32; e1: [ici, dcn*cols] this shard's
            if residual is not None:
                # Back from target-inner-major to bucket order.
                err = e1.reshape(ici, dcn, cols).transpose(
                    1, 0, 2
                ).reshape(-1)
        elif _compress16(orig, ici_wire_dtype):
            part = lax.psum_scatter(
                t.astype(ici_wire_dtype), axis_name,
                axis_index_groups=ici_groups, tiled=True,
            ).astype(orig)
        else:
            part = lax.psum_scatter(
                t, axis_name, axis_index_groups=ici_groups, tiled=True
            )  # [dcn*cols]: partials for targets (·, own ici index)
    else:
        part = t
    y = part.astype(wire_dtype) if _compress16(orig, wire_dtype) else part
    out = lax.psum_scatter(
        y, axis_name, axis_index_groups=dcn_groups, tiled=True
    )
    if residual is not None and err is None:
        err = jnp.zeros(b.shape, jnp.float32)
    return out.astype(orig), err


def _hier_groups(n: int, dcn: int) -> tuple[list, list]:
    """Index groups factoring an axis of size ``n`` as (dcn outer, ici
    inner) — the layout `mesh_utils.create_hybrid_device_mesh` builds, where
    the slice (DCN) factor is the outer block of each factored axis."""
    ici = n // dcn
    ici_groups = [[d * ici + i for i in range(ici)] for d in range(dcn)]
    dcn_groups = [[d * ici + i for d in range(dcn)] for i in range(ici)]
    return ici_groups, dcn_groups


#: Quantized wire formats: dtype -> the format's largest representable
#: magnitude (the per-bucket scale denominator). int8 keeps the symmetric
#: [-127, 127] grid; fp8 is e4m3 (max finite 448 — the gradient-friendly
#: variant; e5m2's extra exponent bits buy nothing once a per-bucket scale
#: normalizes the range).
_QUANTIZED_QMAX = {
    jnp.dtype(jnp.int8): 127.0,
    jnp.dtype(jnp.float8_e4m3fn): 448.0,
}


def is_quantized_wire(wire_dtype) -> bool:
    """True when ``wire_dtype`` needs the gather-sum quantized reduction
    (int8/fp8) rather than a plain cast-then-psum (bf16/fp16)."""
    return (
        wire_dtype is not None and jnp.dtype(wire_dtype) in _QUANTIZED_QMAX
    )


def _quantize(v, wire_dtype):
    """(payload, scale): ``v`` scaled by one per-bucket scalar onto the wire
    grid. ``scale`` is f32; an all-zero bucket quantizes to zeros with
    scale 0 (the dequantized sum is then exactly zero, no 0/0)."""
    qmax = _QUANTIZED_QMAX[jnp.dtype(wire_dtype)]
    amax = jnp.max(jnp.abs(v)).astype(jnp.float32)
    scale = amax / qmax
    inv = jnp.where(scale > 0, 1.0 / scale, 0.0)
    scaled = jnp.clip(v.astype(jnp.float32) * inv, -qmax, qmax)
    if jnp.dtype(wire_dtype) == jnp.dtype(jnp.int8):
        payload = jnp.round(scaled).astype(jnp.int8)
    else:
        payload = scaled.astype(wire_dtype)
    return payload, scale


def _dequantize(payload, scale):
    return payload.astype(jnp.float32) * scale


def _composite_axis_index(axis_name):
    """This shard's position in the (possibly multi-axis) group, row-major
    over the axis tuple — the order `lax.all_gather` stacks group members
    in (verified on the compat floor)."""
    names = _axis_names(axis_name)
    idx = lax.axis_index(names[0])
    for name in names[1:]:
        idx = idx * compat.axis_size(name) + lax.axis_index(name)
    return idx


def _group_size(axis_name, axis_index_groups) -> int:
    if axis_index_groups is not None:
        return len(axis_index_groups[0])
    n = 1
    for name in _axis_names(axis_name):
        n *= compat.axis_size(name)
    return n


def _quantized_gather_sum(v, axis_name, wire_dtype, *,
                          axis_index_groups=None):
    """The PR 7 one-shot gather-sum (kept as the equivalence reference for
    `quantized_group_sum`, and to document what the two-shot replaced):
    every shard all-gathers every other shard's quantized payload and
    dequantize-sums locally — correct, but the receive bytes are
    group_size x the payload. Returns ``(sum_f32, own_error)``."""
    payload, scale = _quantize(v, wire_dtype)
    own = _dequantize(payload, scale)
    gathered = lax.all_gather(
        payload, axis_name, axis_index_groups=axis_index_groups
    )
    scales = lax.all_gather(
        scale, axis_name, axis_index_groups=axis_index_groups
    )
    scales = scales.reshape((-1,) + (1,) * (gathered.ndim - 1))
    total = jnp.sum(gathered.astype(jnp.float32) * scales, axis=0)
    return total, v.astype(jnp.float32) - own


def quantized_group_sum(v, axis_name, wire_dtype, *, axis_index_groups=None,
                        group_position=None):
    """Sum ``v`` across ``axis_name`` (optionally in ``axis_index_groups``)
    with only wire-dtype bytes crossing the interconnect — as a TWO-SHOT
    reduce-scatter + all-gather (the ROADMAP item-2 seam closed).

    Shot 1 (quantized reduce-scatter): the bucket is padded to a
    group-size multiple, cut into one chunk per group member, quantized
    with ONE per-bucket scale and moved by `lax.all_to_all` — every member
    receives each peer's quantized contribution to ITS chunk only and
    dequantize-sums in f32 (sub-16-bit partial sums never exist, so int8
    cannot overflow mid-reduction). Shot 2 (quantized all-gather): each
    member re-quantizes its reduced chunk and all-gathers the (payload,
    scale) pair. Per-member receive bytes are therefore ~2x the payload
    (one all-to-all + one all-gather) instead of the one-shot gather-sum's
    group_size x (`_quantized_gather_sum`, the PR 7 wire this replaces).

    ``group_position`` is this member's index within its group (required
    with ``axis_index_groups``; derived from the axis indices otherwise) —
    the chunk it owns, where the shot-2 re-quantization error is charged.

    Returns ``(sum_f32, own_error)`` where ``own_error`` is THIS shard's
    untransmitted remainder — its shot-1 quantization error everywhere,
    plus the shot-2 re-quantization error of the chunk it owns — so the
    error-feedback telescoping identity is unchanged: summed over the
    group, the errors equal (true sum − delivered sum) exactly."""
    _maybe_record("quantized_group_sum", value=v)
    if group_position is None:
        if axis_index_groups is not None:
            raise ValueError(
                "quantized_group_sum with axis_index_groups needs the "
                "caller's group_position (the member's index within its "
                "group) — it cannot be derived from the axis index alone"
            )
        group_position = _composite_axis_index(axis_name)
    g = _group_size(axis_name, axis_index_groups)
    shape = jnp.shape(v)
    flat = jnp.ravel(v).astype(jnp.float32)
    n = flat.size
    pad = (-n) % g
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    mat = flat.reshape(g, -1)  # row j = the chunk group-member j owns
    # Shot 1: the quantized reduce-scatter (shared with the scatter
    # path's ICI hop, `_quantized_matrix_reduce_scatter`).
    chunk, err1 = _quantized_matrix_reduce_scatter(
        mat, axis_name, wire_dtype, axis_index_groups=axis_index_groups
    )
    # Shot 2: re-quantize the reduced chunk and gather the group's chunks.
    p2, s2 = _quantize(chunk, wire_dtype)
    dq2 = _dequantize(p2, s2)
    gathered = lax.all_gather(
        p2, axis_name, axis_index_groups=axis_index_groups
    )
    s2s = lax.all_gather(s2, axis_name, axis_index_groups=axis_index_groups)
    total = (
        gathered.astype(jnp.float32) * s2s.reshape((-1, 1))
    ).reshape(-1)
    # Untransmitted remainder: shot-1 error on every element this shard
    # fed in, plus the shot-2 error of the chunk it owns (padding
    # contributes exactly zero to both).
    err = err1.at[group_position].add(chunk - dq2)
    total = total[:n].reshape(shape)
    err = err.reshape(-1)[:n].reshape(shape)
    return total, err


def _quantized_matrix_reduce_scatter(mat, axis_name, wire_dtype, *,
                                     axis_index_groups=None):
    """The quantized reduce-scatter shot shared by `quantized_group_sum`
    (shot 1 of the two-shot replicated wire) and the scatter path's
    quantized ICI hop (`_scatter_reduce_bucket` with a quantized
    ``compression_ici``).

    ``mat`` is this member's f32 ``[g, chunk]`` contribution matrix —
    row j is the slice group-member j owns. The whole matrix is
    quantized with ONE per-bucket scale, moved by `lax.all_to_all`
    (every member receives each peer's quantized contribution to ITS
    chunk only — the only payload bytes on the wire) and
    dequantize-summed in f32, so sub-16-bit partial sums never exist.
    Returns ``(chunk_sum_f32, error)``: this member's reduced ``[chunk]``
    row and its full ``[g, chunk]`` untransmitted remainder (what error
    feedback must carry)."""
    payload, scale = _quantize(mat, wire_dtype)
    own = _dequantize(payload, scale)
    recv = lax.all_to_all(
        payload, axis_name, split_axis=0, concat_axis=0,
        axis_index_groups=axis_index_groups, tiled=True,
    )
    scales = lax.all_gather(
        scale, axis_name, axis_index_groups=axis_index_groups
    )
    chunk = jnp.sum(
        recv.astype(jnp.float32) * scales.reshape((-1, 1)), axis=0
    )
    return chunk, mat.astype(jnp.float32) - own


def hierarchical_psum(x, axis_name, dcn: int, *, extra_axes=(),
                      wire_dtype=None, ici_wire_dtype=None):
    """Two-hop psum over ``axis_name`` factored as (dcn outer, ici inner),
    traced context only (inside shard_map/pmap).

    Hop 1 (ICI): sum over ``extra_axes`` and the ici subgroups of
    ``axis_name`` — intra-slice traffic. Full precision by default;
    ``ici_wire_dtype`` (`compression_ici`) puts a wire on this hop too —
    a 16-bit dtype casts it, a quantized (int8/fp8) dtype runs it as
    `quantized_group_sum` over the ici subgroups (EQuARX's aggressive
    tier applied intra-slice, for the topologies where even ICI is the
    bottleneck). Hop 2 (DCN): cast to ``wire_dtype`` (when given), sum
    across the dcn subgroups — the only bytes that cross the slow
    interconnect — and cast back. Equals the flat
    ``psum(x, (axis_name, *extra_axes))`` exactly when both wires are
    None (sum is associative); with a 16-bit wire dtype the delta is the
    cast on the already-reduced partials. A QUANTIZED wire dtype runs
    its hop as `quantized_group_sum` — per-bucket-scaled wire bytes, f32
    receiver-side accumulation; pass ``residual=`` via `reduce_gradients`
    to carry the error feedback, charged PER HOP (each quantized hop
    contributes its own untransmitted remainder, so the telescoping mass
    identity stays exact across the two-level factoring)."""
    _maybe_record("hierarchical_psum", value=x)
    out, _ = _hierarchical_psum_err(
        x, axis_name, dcn, extra_axes=extra_axes, wire_dtype=wire_dtype,
        ici_wire_dtype=ici_wire_dtype,
    )
    return out


def _hierarchical_psum_err(x, axis_name, dcn: int, *, extra_axes=(),
                           wire_dtype=None, ici_wire_dtype=None,
                           residual=None):
    """`hierarchical_psum` body, also returning this shard's quantization
    error (None for residual-free calls). ``residual`` (error feedback)
    is added to the FIRST quantized hop's input before quantization —
    hop 1 when the ICI wire is quantized, hop 2 otherwise — and each
    quantized hop charges its own error, summed into the returned
    remainder (the per-hop telescoping contract). A residual with no
    quantized hop anywhere is flushed: transmitted in full, zero error
    back."""
    n = compat.axis_size(axis_name)
    if n % dcn != 0:
        raise ValueError(
            f"dcn factor {dcn} does not divide axis {axis_name!r} size {n}"
        )
    orig = x.dtype
    floating = jnp.issubdtype(orig, jnp.floating)
    quantize_dcn = is_quantized_wire(wire_dtype) and floating
    quantize_ici = (
        is_quantized_wire(ici_wire_dtype) and floating and n > dcn
    )
    ici_groups, dcn_groups = _hier_groups(n, dcn)
    ici = n // dcn
    if extra_axes:
        x = lax.psum(x, tuple(extra_axes))
    if residual is not None and not (quantize_dcn or quantize_ici):
        # Flush: an exact wire transmits the whole remainder (kept in
        # f32 so no residual mass rounds away uncharged; the result is
        # cast back to ``orig`` at return).
        x = x.astype(jnp.float32) + residual
        residual = None
        err = jnp.zeros(jnp.shape(x), jnp.float32)
    else:
        err = None
    # quantize_ici/quantize_dcn are trace-time config (wire dtypes +
    # value dtype), identical on every rank: the fleet takes the same
    # arm and submits the same collective order.
    if quantize_ici:  # hvt: noqa[HVT007] config-uniform branch
        v = x.astype(jnp.float32)
        if residual is not None:
            v = v + residual
            residual = None  # consumed at the first quantized hop
        # Position within the ici group: groups hold a fixed outer
        # (slice) index d with the inner index i varying — i = global
        # mod ici.
        x, e1 = quantized_group_sum(
            v, axis_name, ici_wire_dtype, axis_index_groups=ici_groups,
            group_position=lax.axis_index(axis_name) % ici,
        )
        err = e1 if err is None else err + e1
    elif n > dcn:  # ici sub-axis is non-trivial
        if _compress16(orig, ici_wire_dtype):
            x = lax.psum(
                x.astype(ici_wire_dtype), axis_name,
                axis_index_groups=ici_groups,
            ).astype(orig)
        else:
            x = lax.psum(x, axis_name, axis_index_groups=ici_groups)
    if quantize_dcn:
        v = x.astype(jnp.float32)
        if residual is not None:
            v = v + residual
        # Position within the dcn group: groups hold a fixed ici index i
        # with the outer (slice) index d varying — d = global // ici.
        total, e2 = quantized_group_sum(
            v, axis_name, wire_dtype, axis_index_groups=dcn_groups,
            group_position=lax.axis_index(axis_name) // ici,
        )
        err = e2 if err is None else err + e2
        return total.astype(orig), err
    if _compress16(orig, wire_dtype):
        x = x.astype(wire_dtype)
    x = lax.psum(x, axis_name, axis_index_groups=dcn_groups)
    return x.astype(orig), err


def reduce_gradients(tree: PyTree, *, data_axis=None, extra_axes=(),
                     dcn: int = 1, wire_dtype=None, ici_wire_dtype=None,
                     bucket_bytes: int | None = None,
                     reverse: bool = False, residual: PyTree | None = None,
                     scatter: int | None = None):
    """The boundary gradient reduction: bucket-fused, hierarchical when the
    mesh is multi-slice, wire-compressed. SUM semantics — callers divide by
    world size (and the accumulation factor) themselves.

    Traced context only (inside the explicit-collective shard_map step).
    ``tree`` is bucketed (`flatten_buckets`), each bucket reduced —
    ``hierarchical_psum`` over (``data_axis`` factored by ``dcn``) +
    ``extra_axes`` when ``dcn > 1``; a flat psum over all axes, cast to
    ``wire_dtype`` first (compress-then-reduce, Horovod Compression.fp16
    semantics) — or a `quantized_group_sum` for int8/fp8 wires — when
    ``dcn == 1``; and the tree restored. The collective count is therefore
    the bucket count: at most
    ``ceil(total_bytes / bucket_bytes) + n_dtypes - 1`` reductions per call
    regardless of how many leaves the model has.

    ``reverse=True`` buckets AND issues the reductions last-leaf-first
    (Horovod's fusion order — overlappable with the producing backward;
    elementwise-identical results for non-quantized wires, since bucket
    boundaries never mix values).

    ``ici_wire_dtype`` (`compression_ici`): a wire for the two-hop
    factoring's ICI hop only (inert when ``dcn <= 1`` or the ici
    sub-axis is trivial) — 16-bit dtypes cast it, int8/fp8 run it
    quantized with the error charged per hop. See `hierarchical_psum`.

    ``residual``: error-feedback state for quantized wires — a pytree
    matching ``tree`` (f32 leaves). It is added to each bucket's
    pre-quantization value and the call returns ``(reduced_tree,
    new_residual_tree)`` where the new residual is this shard's
    untransmitted quantization remainder, summed over the quantized
    hops (per-hop charging keeps the telescoping mass identity exact);
    without it the return is just the reduced tree (and quantization
    bias goes uncorrected). A residual with no quantized hop anywhere
    is flushed (transmitted in full, zero remainder back).

    ``scatter``: the ZeRO-1 (shard_update) shard count — lower the
    reduction INTO the sharded weight-update layout: leaves with a
    dp-divisible dim come back as this shard's LOCAL zero1 block (the
    slice `training/build.py`'s opt-state layout consumes), the rest
    replicated. Non-quantized wires run every bucket as ONE
    `psum_scatter` (two-hop over dcn, wire dtype on the DCN hop, the
    ICI-hop wire when given) — ~half the bytes of reduce-then-slice —
    with tail-family leaves riding the same buckets and their full
    values all-gathered back from just their columns (no full-payload
    all-reduce anywhere). Buckets are leaf-aligned in BOTH directions
    (see `flatten_scatter_buckets`): inside the overlap peel's
    straight-line region each bucket's scatter issues as soon as its
    gradients are final, and each shard's optimizer apply for that
    bucket's leaves can start as soon as it lands. Quantized DCN wires
    keep the dense bucket layout through the two-shot
    `quantized_group_sum` — BITWISE identical to the replicated
    reduction, so the composed trajectory equals the dense control —
    and slice locally (the wire is already ~2x payload; re-cutting
    buckets to the zero1 layout would change per-bucket scales, i.e.
    the training numerics, for zero byte win)."""
    from horovod_tpu.parallel import mesh as mesh_lib

    data_axis = data_axis or mesh_lib.DATA_AXIS
    if scatter is not None and int(scatter) > 1:
        return _reduce_gradients_scatter(
            tree, int(scatter), data_axis=data_axis, extra_axes=extra_axes,
            dcn=dcn, wire_dtype=wire_dtype, ici_wire_dtype=ici_wire_dtype,
            bucket_bytes=bucket_bytes, reverse=reverse, residual=residual,
        )
    buckets, spec = flatten_buckets(tree, bucket_bytes, reverse=reverse)
    res_buckets = [None] * len(buckets)
    if residual is not None:
        res_buckets, _ = flatten_buckets(
            residual, bucket_bytes, reverse=reverse
        )
        # The residual is bucketed by ITS leaves' dtype grouping (all
        # f32); a mixed-dtype gradient tree would group differently and
        # the two bucket lists would silently misalign — require
        # identical boundaries (the trainer casts grads to f32 before
        # reducing, so its buckets always align).
        if [jnp.shape(b) for b in res_buckets] != [
            jnp.shape(b) for b in buckets
        ]:
            raise ValueError(
                "error-feedback residual buckets do not align with the "
                "gradient buckets — the residual (f32 leaves) must "
                "bucket identically to the gradient tree; cast the "
                "gradients to float32 before reduce_gradients"
            )

    def reduce_one(b, r, bucket_id):
        _maybe_record("reduce_gradients", value=b, bucket=bucket_id)
        orig = b.dtype
        if dcn > 1:
            return _hierarchical_psum_err(
                b, data_axis, dcn, extra_axes=extra_axes,
                wire_dtype=wire_dtype, ici_wire_dtype=ici_wire_dtype,
                residual=r,
            )
        if is_quantized_wire(wire_dtype) and jnp.issubdtype(
            orig, jnp.floating
        ):
            v = b.astype(jnp.float32)
            if r is not None:
                v = v + r
            total, err = quantized_group_sum(
                v, (data_axis, *extra_axes), wire_dtype
            )
            return total.astype(orig), err
        if r is not None:
            # Residual with an exact single-hop wire (an ICI-quantized
            # config on a single-slice mesh): flush — transmitted in
            # full (f32 carries the whole remainder), zero back.
            b = b.astype(jnp.float32) + r
        if _compress16(orig, wire_dtype):
            b = b.astype(wire_dtype)
        out = lax.psum(b, (data_axis, *extra_axes)).astype(orig)
        return out, (None if r is None else jnp.zeros(jnp.shape(r),
                                                      jnp.float32))

    # Explicit loop, not a comprehension: reduce_one's flight record
    # derives its caller tag from the frame two levels up, and a
    # comprehension frame would tag the evidence '<listcomp>' (and
    # differently across interpreter versions — PEP 709 inlines it).
    reduced, errors = [], []
    for i, (b, r) in enumerate(zip(buckets, res_buckets)):
        out_b, err_b = reduce_one(b, r, i)
        reduced.append(out_b)
        errors.append(err_b)
    out = unflatten_buckets(list(reduced), spec)
    if residual is None:
        return out
    new_res = unflatten_buckets(
        [
            e if e is not None else jnp.zeros_like(r)
            for e, r in zip(errors, res_buckets)
        ],
        spec,
    )
    # The residual tree mirrors the GRADIENT tree's dtypes through the
    # spec; force f32 leaves (error mass must not round through a 16-bit
    # parameter dtype between steps).
    new_res = jax.tree.map(lambda e: e.astype(jnp.float32), new_res)
    return out, new_res


def _reduce_gradients_scatter(tree: PyTree, dp: int, *, data_axis,
                              extra_axes, dcn, wire_dtype, ici_wire_dtype,
                              bucket_bytes, reverse, residual):
    """`reduce_gradients(scatter=dp)` body — see its docstring. Returns
    the zero1-local tree (scatter leaves as local blocks, tail leaves
    replicated), with the new residual tree appended for error-feedback
    callers."""
    leaves = jax.tree_util.tree_leaves(tree)
    floating = all(
        jnp.issubdtype(jnp.result_type(l), jnp.floating) for l in leaves
    )
    if is_quantized_wire(wire_dtype) and floating:
        # Dense-layout quantized DCN wire (bitwise-identical arithmetic
        # to the replicated path, residual and all), then the free local
        # cut.
        reduced = reduce_gradients(
            tree, data_axis=data_axis, extra_axes=extra_axes, dcn=dcn,
            wire_dtype=wire_dtype, ici_wire_dtype=ici_wire_dtype,
            bucket_bytes=bucket_bytes, reverse=reverse, residual=residual,
        )
        if residual is None:
            return _slice_zero1_local(reduced, dp, data_axis)
        out, new_res = reduced
        return _slice_zero1_local(out, dp, data_axis), new_res
    if residual is not None and not is_quantized_wire(ici_wire_dtype):
        raise ValueError(
            "error-feedback residuals require a quantized wire dtype "
            "(int8/fp8) on one of the hops; non-quantized scatter "
            "reductions are lossless and carry no residual"
        )
    buckets, spec = flatten_scatter_buckets(
        tree, dp, bucket_bytes, reverse=reverse
    )
    res_buckets: list = [None] * len(buckets)
    if residual is not None:
        res_buckets, _ = flatten_scatter_buckets(
            residual, dp, bucket_bytes, reverse=reverse
        )
        if [jnp.shape(b) for b in res_buckets] != [
            jnp.shape(b) for b in buckets
        ]:
            raise ValueError(
                "error-feedback residual buckets do not align with the "
                "gradient buckets — the residual (f32 leaves) must "
                "bucket identically to the gradient tree; cast the "
                "gradients to float32 before reduce_gradients"
            )
    spans = bucket_tail_spans(spec)
    entries: list = []
    errors: list = []
    # Bucket-by-bucket, reverse order already baked into the spec: each
    # loop iteration's collective depends ONLY on its own leaves (leaf-
    # aligned assembly), so inside the overlap peel's straight-line
    # region XLA's latency-hiding scheduler can issue bucket i's
    # psum_scatter while earlier leaves' backward still computes, and
    # start bucket i's shard-local optimizer math as soon as it lands.
    for i, (b, r, sp) in enumerate(zip(buckets, res_buckets, spans)):
        _maybe_record("reduce_gradients_scatter", value=b, bucket=i)
        loc, err = _scatter_reduce_bucket(
            b, data_axis, dcn, wire_dtype, extra_axes,
            ici_wire_dtype=ici_wire_dtype, residual=r,
        )
        if sp:
            # Tail-family pieces (replicated mirrors) need full values
            # back: all-gather JUST their columns — with the scatter
            # above, a two-shot all-reduce that never puts a full
            # payload through one collective.
            tail_local = (
                loc[sp[0][0]: sp[0][0] + sp[0][1]] if len(sp) == 1
                else jnp.concatenate(
                    [loc[c: c + w] for c, w in sp]
                )
            )
            gathered = lax.all_gather(tail_local, data_axis, tiled=True)
            entries.append((loc, gathered))
        else:
            entries.append(loc)
        errors.append(err)
    out = unflatten_scatter_buckets(entries, spec)
    if residual is None:
        return out
    new_res = unflatten_scatter_full(
        [
            e if e is not None else jnp.zeros(jnp.shape(b), jnp.float32)
            for e, b in zip(errors, buckets)
        ],
        spec,
    )
    new_res = jax.tree.map(lambda e: e.astype(jnp.float32), new_res)
    return out, new_res


def metric_mean(metrics: dict, axis_name=None) -> dict:
    """Cross-worker mean of a metrics dict — MetricAverageCallback's op
    (tensorflow2_keras_mnist.py:73-77)."""
    averaged = pmean_pytree(
        {k: jnp.asarray(v, jnp.float32) for k, v in metrics.items()},
        axis_name=axis_name,
    )
    return {k: float(v) for k, v in averaged.items()}
