"""Device-mesh construction and world-size-reactive scaling helpers.

The reference's only topology is a flat ring of N single-GPU workers
(distributed-keras-sample.yaml:3-9). The TPU-native generalization is a named
`jax.sharding.Mesh` with up to five axes — data, fsdp, seq, model(tensor),
expert — so data parallelism (the reference's capability) is the
``('data',)`` special case, and TP/SP/EP slot in without breaking the API
(SURVEY.md §2.2, §5.7).

Axis naming convention used across the framework:

* ``data``   — batch sharding; gradient psum rides this axis (DP).
* ``fsdp``   — parameter/optimizer-state sharding across the data axis group.
* ``seq``    — sequence/context parallelism (ring attention).
* ``model``  — tensor parallelism (heads / hidden sharded).
* ``expert`` — expert parallelism for MoE layers.
"""

from __future__ import annotations

import dataclasses
import math
import os

import jax
import numpy as np
from jax.sharding import Mesh

from horovod_tpu.analysis import registry

# Canonical axis order, outermost (slowest, DCN-adjacent) first. Data/fsdp
# outermost so cross-host traffic is the infrequent gradient reduction;
# pipe next (stage handoffs are point-to-point, once per microbatch tick,
# and tolerate DCN latency — the standard cross-slice axis); model/seq/expert
# collectives (per-layer, per-step) stay on intra-host ICI.
AXES = ("data", "fsdp", "pipe", "seq", "model", "expert")

DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
PIPE_AXIS = "pipe"
SEQ_AXIS = "seq"
MODEL_AXIS = "model"
EXPERT_AXIS = "expert"


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape. -1 means "absorb all remaining devices".

    ``MeshSpec()`` (all defaults) reproduces the reference's pure-DP world:
    every chip is a data-parallel worker, exactly like the 1+3-GPU MPI ring
    (SURVEY.md §2.2 row 1).
    """

    data: int = -1
    fsdp: int = 1
    pipe: int = 1
    seq: int = 1
    model: int = 1
    expert: int = 1

    @classmethod
    def from_string(cls, spec: str | None) -> "MeshSpec":
        """Parse the ``HVT_MESH`` grammar: ``"data=2,seq=4"`` (axis=size
        pairs, missing axes default). None/empty = pure DP."""
        if not spec:
            return cls()
        try:
            sizes = dict(kv.split("=") for kv in spec.split(","))
            return cls(**{k: int(v) for k, v in sizes.items()})
        except (ValueError, TypeError) as e:
            raise ValueError(
                f"bad mesh spec {spec!r} (want 'axis=N,axis=N' with axes "
                f"from {AXES}): {e}"
            ) from None

    def resolve(self, n_devices: int) -> dict[str, int]:
        sizes = dataclasses.asdict(self)
        fixed = [ax for ax, s in sizes.items() if s != -1]
        free = [ax for ax, s in sizes.items() if s == -1]
        if len(free) > 1:
            raise ValueError(f"At most one -1 axis allowed, got {free}")
        prod = math.prod(sizes[ax] for ax in fixed)
        if free:
            if n_devices % prod != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {sizes}"
                )
            sizes[free[0]] = n_devices // prod
        elif prod != n_devices:
            raise ValueError(f"Mesh {sizes} wants {prod} devices, have {n_devices}")
        return sizes


def _hybrid_shapes(shape: tuple, n_slices: int):
    """Factor ``n_slices`` out of the outermost divisible mesh axes.

    Multi-slice systems connect slices over DCN (orders of magnitude less
    bandwidth than ICI), so axes crossing slice boundaries must be the ones
    with the *least* per-step traffic. AXES is already ordered
    DCN-adjacent-first (data/fsdp: one gradient reduction per step; pipe:
    point-to-point handoffs), so greedily assign the slice factor to the
    earliest axes that divide it. Returns ``(dcn_shape, ici_shape)`` with
    elementwise product == ``shape``, or None when no factoring exists.
    """
    dcn = [1] * len(shape)
    remaining = n_slices
    # Only data/fsdp/pipe (AXES[:3]) may cross DCN: per-layer model/seq/
    # expert collectives over DCN would be catastrophic, so a shape that
    # forces them across slices is refused (None -> caller warns + flat).
    for i in range(min(3, len(shape))):
        if remaining == 1:
            break
        g = math.gcd(shape[i], remaining)
        dcn[i] = g
        remaining //= g
    if remaining != 1:
        return None
    ici = tuple(s // d for s, d in zip(shape, dcn))
    return tuple(dcn), ici


def _device_array(devices: np.ndarray, shape: tuple, order: str | None = None):
    """Physical device layout for the mesh.

    ``order='auto'`` (default, or ``HVT_MESH_ORDER`` env): on multi-chip TPU,
    delegate to `jax.experimental.mesh_utils`, which maps mesh axes onto the
    physical ICI torus (rings for each axis ride actual links instead of the
    arbitrary enumeration order a flat reshape gives — on a pod slice,
    reshape-order neighbors can be several hops apart, and every
    ppermute/psum pays that distance). When the devices span multiple
    *slices* (DCN-connected — `device.slice_index` differs), the slice
    count is factored out of the outermost axes (data/fsdp/pipe — the
    low-traffic ones, `_hybrid_shapes`) and `create_hybrid_device_mesh`
    keeps every other axis's collectives inside a slice: model/seq/expert
    traffic rides ICI, only the per-step gradient reduction (or pipe
    handoff) crosses DCN. Falls back to the flat reshape when the topology
    solver rejects the shape, on CPU/virtual devices (where "distance" is
    meaningless and tests rely on enumeration order), or with
    ``order='flat'``.
    """
    order = order or registry.get_str("HVT_MESH_ORDER")
    if order not in ("auto", "flat"):
        raise ValueError(
            f"HVT_MESH_ORDER must be 'auto' or 'flat', got {order!r}"
        )
    if (
        order == "auto"
        and devices.size > 1
        and getattr(devices.flat[0], "platform", "") == "tpu"
    ):
        from jax.experimental import mesh_utils

        slices = {getattr(d, "slice_index", 0) for d in devices.flat}
        try:
            if len(slices) > 1:
                hybrid = _hybrid_shapes(shape, len(slices))
                if hybrid is None:
                    raise ValueError(
                        f"cannot factor {len(slices)} slices out of mesh "
                        f"shape {shape} (no outermost axis divides it)"
                    )
                dcn_shape, ici_shape = hybrid
                return np.asarray(
                    mesh_utils.create_hybrid_device_mesh(
                        ici_shape, dcn_shape, devices=list(devices.flat)
                    )
                )
            return np.asarray(
                mesh_utils.create_device_mesh(
                    shape, devices=list(devices.flat)
                )
            )
        except (ValueError, NotImplementedError, AssertionError) as e:
            import warnings

            # Flat order is always *valid*; it is just potentially slow —
            # say so, or a pod silently pays multi-hop ICI (or per-layer
            # DCN) on every ring.
            warnings.warn(
                f"topology-aware mesh layout failed for shape {shape} "
                f"({e}); falling back to enumeration order — collective "
                f"rings may span multi-hop ICI or DCN paths",
                stacklevel=3,
            )
    return devices.reshape(shape)


def build_mesh(spec: MeshSpec | None = None, devices=None) -> Mesh:
    """Build a Mesh over ``devices`` (default: all) per ``spec``.

    Axis order is the canonical AXES order; size-1 axes are kept so sharding
    rules can always name them — XLA elides trivial collectives, so unused
    axes are free. On multi-chip TPU the physical layout is ICI-topology-
    aware (see `_device_array`).
    """
    spec = spec or MeshSpec()
    devices = np.asarray(devices if devices is not None else jax.devices())
    sizes = spec.resolve(devices.size)
    shape = tuple(sizes[ax] for ax in AXES)
    return Mesh(_device_array(devices, shape), AXES)


def data_parallel_mesh(devices=None) -> Mesh:
    """The reference-equivalent topology: all chips on the ``data`` axis."""
    return build_mesh(MeshSpec(), devices)


def dcn_factor(mesh: Mesh, axis: str = DATA_AXIS) -> int:
    """How many DCN-connected slice groups the named mesh axis spans.

    1 on a single-slice (or CPU/virtual) mesh — the flat psum is then the
    right gradient reduction. >1 means the axis was factored across slices
    by `_device_array`'s hybrid layout (slice factor outermost, matching
    `create_hybrid_device_mesh`), and the hierarchical two-hop reduction
    (`collectives.hierarchical_psum`) can keep full-precision traffic on
    ICI and pay the compression dtype only across DCN.

    The factor is derived from the devices' actual ``slice_index`` layout
    and only trusted when it matches the hybrid contract — the slice id
    constant within each slab of the axis, changing in equal-length
    contiguous outer blocks. Any other arrangement returns 1 (flat
    reduction stays correct; hierarchy would be wrong, not just slow).

    ``HVT_DCN_FACTOR=<n>`` overrides the derivation — the fake-topology
    knob for benchmarking the two-hop path on single-slice hardware (and
    for tests, where CPU devices carry no slice_index)."""
    size = mesh.shape[axis]
    dcn = registry.get_int("HVT_DCN_FACTOR")
    if dcn is not None:
        if dcn < 1 or size % dcn != 0:
            raise ValueError(
                f"HVT_DCN_FACTOR={dcn} must divide the {axis!r} axis size "
                f"({size})"
            )
        return dcn
    if size <= 1:
        return 1
    ax_pos = list(mesh.axis_names).index(axis)
    devs = np.moveaxis(mesh.devices, ax_pos, 0).reshape(size, -1)
    slabs = []
    for i in range(size):
        ids = {int(getattr(d, "slice_index", 0) or 0) for d in devs[i]}
        if len(ids) != 1:
            return 1  # slices cross OTHER axes too — no clean factoring
        slabs.append(next(iter(ids)))
    # Contiguous equal-length outer blocks of distinct slice ids?
    boundaries = [i for i in range(1, size) if slabs[i] != slabs[i - 1]]
    dcn = len(boundaries) + 1
    if dcn == 1:
        return 1
    ici = size // dcn
    if size % dcn != 0 or boundaries != [ici * k for k in range(1, dcn)]:
        return 1
    if len(set(slabs[::ici])) != dcn:
        return 1  # a slice id repeats across blocks — not hybrid-ordered
    return dcn


def dp_size(mesh: Mesh) -> int:
    """Number of data-parallel workers (batch shards) in a mesh."""
    return mesh.shape[DATA_AXIS] * mesh.shape[FSDP_AXIS]


def has_live_model_axes(mesh: Mesh) -> bool:
    """True when any non-data axis (pipe/seq/model/expert) is larger than 1 —
    the condition under which batch layouts can involve more than plain
    data-axis sharding (used to gate the device-cached fit/eval paths)."""
    return any(
        mesh.shape.get(ax, 1) > 1
        for ax in (PIPE_AXIS, SEQ_AXIS, MODEL_AXIS, EXPERT_AXIS)
    )


# --- World-size-reactive hyperparameter helpers (SURVEY.md §5.6) -----------


def scale_lr(base_lr: float, world_size: int | None = None) -> float:
    """Linear LR scaling: ``base × world_size``.

    Reference: ``tf.optimizers.Adam(0.001 * hvd.size())``
    (tensorflow2_keras_mnist.py:55) and ``Adadelta(1.0 * hvd.size())``
    (mnist_keras.py:84), per Goyal et al., arXiv:1706.02677."""
    if world_size is None:
        world_size = jax.device_count()
    return base_lr * world_size


def shard_steps(total_steps: int, world_size: int | None = None) -> int:
    """Per-worker steps so global work is constant: ``total // size``.

    Reference idiom #1: ``steps_per_epoch=500 // hvd.size()``
    (tensorflow2_keras_mnist.py:96)."""
    if world_size is None:
        world_size = jax.device_count()
    return max(1, total_steps // world_size)


def shard_epochs(total_epochs: float, world_size: int | None = None) -> int:
    """Per-worker epochs: ``ceil(total / size)``.

    Reference idiom #2: ``epochs = int(math.ceil(12.0 / hvd.size()))``
    (mnist_keras.py:42). Both division idioms must be expressible
    (SURVEY.md §7.3)."""
    if world_size is None:
        world_size = jax.device_count()
    return max(1, int(math.ceil(total_epochs / world_size)))
