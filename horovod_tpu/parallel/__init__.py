"""Parallelism core: mesh construction, collectives, sharding rules."""

from horovod_tpu.parallel import mesh, collectives, sharding  # noqa: F401
