"""NamedSharding helpers: how arrays meet the mesh.

Replaces the reference's device-placement machinery (one-GPU-per-process
pinning, tensorflow2_keras_mnist.py:28-32) with declarative shardings:
parameters replicated (pure DP, the reference's model) or sharded (FSDP/TP),
batches split along the ``data`` axis.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from horovod_tpu.parallel.mesh import DATA_AXIS, FSDP_AXIS

PyTree = Any


def named_sharding(mesh: Mesh, *axes) -> NamedSharding:
    """Shorthand: ``named_sharding(mesh, 'data', None)`` etc."""
    return NamedSharding(mesh, P(*axes))


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding — DP parameters (the reference's layout:
    every worker holds the full model, SURVEY.md §2.2 row 1)."""
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, ndim: int = 0) -> NamedSharding:
    """Batch split along the combined data axes, rest replicated."""
    if ndim == 0:
        return NamedSharding(mesh, P((DATA_AXIS, FSDP_AXIS)))
    return NamedSharding(mesh, P((DATA_AXIS, FSDP_AXIS), *([None] * (ndim - 1))))


def put_global(x, sharding: NamedSharding):
    """Place one host array under a sharding, single- or multi-process.

    Single-process: a plain sharded device_put. Multi-process: this process
    contributes its local slice and `make_array_from_process_local_data`
    assembles the global logical array. Every staging path in the framework
    funnels through here so the multi-process placement contract lives in
    one place."""
    x = np.asarray(x)
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    return jax.make_array_from_process_local_data(sharding, x)


def shard_batch(batch: PyTree, mesh: Mesh) -> PyTree:
    """Place a host batch onto the mesh, split along the data axis.

    Multi-process, each process contributes its local shard of the global
    batch — the data-plane replacement for per-rank independent feeding
    (the reference feeds each rank separately, tensorflow2_keras_mnist.py:41).
    """
    return jax.tree.map(
        lambda x: put_global(x, batch_sharding(mesh, np.asarray(x).ndim)),
        batch,
    )


def chunk_sharding(mesh: Mesh, ndim: int, lead: int = 1) -> NamedSharding:
    """Sharding for a stack of batches with ``lead`` unsharded leading axes
    — [K, batch, ...] for steps_per_execution scans (lead=1), [C, K, batch,
    ...] for chunked microbatch accumulation (lead=2). The batch axis after
    the leading stack axes splits over data."""
    return NamedSharding(
        mesh,
        P(*([None] * lead), (DATA_AXIS, FSDP_AXIS),
          *([None] * max(0, ndim - lead - 1))),
    )


def shard_chunk(chunk: PyTree, mesh: Mesh, lead: int = 1) -> PyTree:
    """Place a stacked host batch onto the mesh (see chunk_sharding);
    multi-process, each process contributes its local slice of every batch."""
    return jax.tree.map(
        lambda x: put_global(x, chunk_sharding(mesh, np.asarray(x).ndim, lead)),
        chunk,
    )


def replicate(tree: PyTree, mesh: Mesh) -> PyTree:
    """Replicate a pytree across the mesh (params/opt state in pure DP)."""
    sharding = replicated(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)
