"""The serving front-end: one address, many replicas.

`ReplicaSet` is the membership + in-flight ledger — the router's dispatch
key is per-replica in-flight count (least-loaded wins), and the SAME
counter reaching zero is the drain barrier a weight swap waits behind
(`serving.fleet.ServeFleet.swap`). `make_router` builds the HTTP proxy:

* ``POST /v1/generate`` / ``/v1/predict`` — forwarded to the least-loaded
  replica that is neither draining nor dead; NDJSON streams pass through
  line by line (client TTFT is the first line's arrival, which is what
  the router's ``hvt_serve_ttft_seconds`` observes — the fleet-level SLO
  signal the autoscaler consumes);
* connect failures BEFORE any response bytes retry on another replica
  (``hvt_serve_router_retries_total``) and mark the silent one dead —
  the fleet watchdog confirms against the rendezvous coordinator;
  mid-stream failures surface to the client (a retry would replay
  sampled tokens);
* ``GET /healthz`` — per-replica in-flight/draining/dead rollup;
* ``GET /metrics`` — the router's own typed registry: requests by
  route/code (the ``code="500"`` series is pre-materialized at 0 so the
  CI gate ``hvt_serve_requests_total{code="500"} == 0`` reads an
  explicit zero, never an absent series), TTFT/latency histograms,
  per-replica in-flight gauges, retry/swap counters.

No replica available (all draining/dead, or the set is empty) is 503 —
distinct from a replica's own 429 (admission refused), which forwards
verbatim so clients can tell "back off" from "fleet down".
"""

from __future__ import annotations

import itertools
import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from horovod_tpu.obs import core as obs_core
from horovod_tpu.obs import prom as obs_prom

_request_ids = itertools.count(1)


class NoReplicaError(RuntimeError):
    """Nothing admitting traffic — the HTTP layer maps this to 503."""


class Replica:
    """One backend's ledger entry. ``inflight`` is router-side accounting
    (incremented at dispatch, decremented when the last response byte is
    out), so it counts the whole proxied exchange including a slow
    client's stream drain — the honest drain barrier."""

    __slots__ = ("name", "base_url", "inflight", "draining", "dead")

    def __init__(self, name: str, base_url: str):
        self.name = name
        self.base_url = base_url.rstrip("/")
        self.inflight = 0
        self.draining = False
        self.dead = False

    @property
    def available(self) -> bool:
        return not (self.draining or self.dead)


class ReplicaSet:
    """Thread-safe membership + least-loaded pick."""

    def __init__(self):
        self._lock = threading.Lock()
        self._replicas: dict[str, Replica] = {}
        self._rr = itertools.count()  # tie-break rotates, not sticks

    def add(self, name: str, base_url: str) -> Replica:
        with self._lock:
            r = Replica(name, base_url)
            self._replicas[name] = r
            return r

    def remove(self, name: str) -> None:
        with self._lock:
            self._replicas.pop(name, None)

    def get(self, name: str) -> Replica | None:
        with self._lock:
            return self._replicas.get(name)

    def names(self) -> list[str]:
        with self._lock:
            return list(self._replicas)

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [
                {"name": r.name, "url": r.base_url, "inflight": r.inflight,
                 "draining": r.draining, "dead": r.dead}
                for r in self._replicas.values()
            ]

    def live_count(self) -> int:
        with self._lock:
            return sum(1 for r in self._replicas.values() if r.available)

    def drain(self, name: str) -> None:
        with self._lock:
            if name in self._replicas:
                self._replicas[name].draining = True

    def readmit(self, name: str) -> None:
        with self._lock:
            if name in self._replicas:
                r = self._replicas[name]
                r.draining = False
                r.dead = False

    def mark_dead(self, name: str) -> None:
        with self._lock:
            if name in self._replicas:
                self._replicas[name].dead = True

    def wait_drained(self, name: str, timeout: float) -> bool:
        """Poll until ``name`` has zero in flight (or it left the set)."""
        deadline = time.monotonic() + timeout
        while True:
            with self._lock:
                r = self._replicas.get(name)
                if r is None or r.inflight == 0:
                    return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.01)

    def acquire(self, exclude: set[str] | None = None) -> Replica:
        """Pick the least-loaded available replica and count the request
        against it atomically (pick-then-increment under one lock, or two
        racing handlers would both dub the same replica 'least loaded')."""
        with self._lock:
            pool = [
                r for r in self._replicas.values()
                if r.available and r.name not in (exclude or ())
            ]
            if not pool:
                raise NoReplicaError(
                    "no replica available "
                    f"({len(self._replicas)} registered, all "
                    "draining/dead)" if self._replicas else
                    "no replica registered"
                )
            offset = next(self._rr)
            r = min(
                enumerate(pool),
                key=lambda ir: (ir[1].inflight, (ir[0] + offset) % len(pool)),
            )[1]
            r.inflight += 1
            return r

    def release(self, replica: Replica) -> None:
        with self._lock:
            replica.inflight = max(0, replica.inflight - 1)


def make_router(port: int = 0, host: str = "127.0.0.1",
                replicas: ReplicaSet | None = None,
                registry=None):
    """Build (don't start) the front-end proxy. ``server.replicas`` is
    the live `ReplicaSet` the fleet mutates; ``server.metrics_registry``
    the router's own typed registry (dumped to metrics.prom by the fleet
    for the CI metrics gate)."""
    replica_set = replicas if replicas is not None else ReplicaSet()
    reg = registry if registry is not None else obs_core.Registry()

    def _collect(r):
        r.gauge("hvt_serve_replicas", replica_set.live_count())
        for snap in replica_set.snapshot():
            r.gauge(
                "hvt_serve_replica_inflight", snap["inflight"],
                replica=snap["name"],
            )

    reg.register_collector(_collect)
    # The zero-500s CI gate reads this series — materialize it at 0 up
    # front so a clean run exposes an explicit zero instead of absence
    # (run_prom_checks fails absent series by design).
    reg.counter_set(
        "hvt_serve_requests_total", 0, route="/v1/generate", code="500"
    )

    _KNOWN_ROUTES = ("/healthz", "/metrics", "/v1/predict", "/v1/generate")

    def _route(path: str) -> str:
        path = path.split("?", 1)[0]
        return path if path in _KNOWN_ROUTES else "other"

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):
            pass

        def _send(self, code: int, payload: dict):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            reg.counter(
                "hvt_serve_requests_total", route=_route(self.path),
                code=str(code),
            )

        def do_GET(self):
            if self.path == "/metrics":
                obs_prom.write_http(self, reg)
            elif self.path == "/healthz":
                snaps = replica_set.snapshot()
                self._send(200, {
                    "status": "ok" if replica_set.live_count() else
                    "no-replicas",
                    "tier": "router",
                    "replicas": snaps,
                    "live": replica_set.live_count(),
                })
            else:
                self._send(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            from horovod_tpu import trace as trace_lib

            if _route(self.path) == "other":
                self._send(404, {"error": f"no route {self.path}"})
                return
            with trace_lib.span(
                "request", req=next(_request_ids), route=_route(self.path),
                tier="router",
            ):
                self._proxy()

        def _proxy(self):
            t0 = time.perf_counter()
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length)
            tried: set[str] = set()
            while True:
                try:
                    replica = replica_set.acquire(exclude=tried)
                except NoReplicaError as e:
                    self._send(503, {"error": str(e)})
                    return
                tried.add(replica.name)
                try:
                    upstream = self._dial(replica, body)
                except (ConnectionError, OSError,
                        urllib.error.URLError):
                    # The replica never ANSWERED (no bytes reached the
                    # client) — the only point a retry is safe. Mark it,
                    # count the retry, move on; the fleet watchdog
                    # reconciles against the coordinator.
                    replica_set.mark_dead(replica.name)
                    reg.counter("hvt_serve_router_retries_total")
                    replica_set.release(replica)
                    continue
                try:
                    if upstream is not None:
                        self._relay(upstream, t0)
                except (ConnectionError, OSError):
                    # Mid-exchange failure (either side): bytes are out,
                    # a retry would replay them — the truncated stream /
                    # torn socket is the client's signal. NOT the
                    # replica's death sentence: a slow CLIENT breaks the
                    # same way.
                    pass
                finally:
                    replica_set.release(replica)
                return

        def _dial(self, replica: Replica, body: bytes):
            """Open the upstream exchange. Raises only while a retry on
            another replica is still safe; an HTTP error status is an
            ANSWER and forwards verbatim (returns None)."""
            req = urllib.request.Request(
                replica.base_url + self.path, data=body,
                headers={"Content-Type": "application/json"},
            )
            try:
                return urllib.request.urlopen(req, timeout=300)
            except urllib.error.HTTPError as e:
                payload = e.read()
                self.send_response(e.code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)
                reg.counter(
                    "hvt_serve_requests_total", route=_route(self.path),
                    code=str(e.code),
                )
                return None

        def _relay(self, upstream, t0: float):
            with upstream:
                ctype = upstream.headers.get("Content-Type", "")
                if "ndjson" in ctype:
                    # Streaming passthrough: relay line by line; the
                    # first line out IS the client's TTFT.
                    self.send_response(200)
                    self.send_header("Content-Type", ctype)
                    self.end_headers()
                    first = True
                    for line in upstream:
                        self.wfile.write(line)
                        self.wfile.flush()
                        if first:
                            reg.histogram(
                                "hvt_serve_ttft_seconds",
                                time.perf_counter() - t0,
                            )
                            first = False
                else:
                    payload = upstream.read()
                    self.send_response(upstream.status)
                    self.send_header("Content-Type", "application/json")
                    self.send_header(
                        "Content-Length", str(len(payload))
                    )
                    self.end_headers()
                    self.wfile.write(payload)
                    if _route(self.path) == "/v1/generate":
                        reg.histogram(
                            "hvt_serve_ttft_seconds",
                            time.perf_counter() - t0,
                        )
            reg.counter(
                "hvt_serve_requests_total", route=_route(self.path),
                code="200",
            )
            reg.histogram(
                "hvt_serve_request_seconds", time.perf_counter() - t0,
                route=_route(self.path),
            )

    server = ThreadingHTTPServer((host, port), Handler)
    server.replicas = replica_set
    server.metrics_registry = reg
    return server
