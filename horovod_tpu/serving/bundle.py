"""Generation serving bundles: export the compiled decode loop, reload it
anywhere, serve it over HTTP.

The reference's serving story is an export tail: rank 0 saves a SavedModel
with a named predict signature "so that it can be served"
(/root/reference/mnist_keras.py:116-140). `checkpoint.export_serving`
covers that contract for classifiers; this module extends the same role to
the flagship generation stack: the KV-cache prefill + `lax.scan` decode
loop of `models/decoding.make_generate_fn` — greedy or
temperature/top-k/top-p sampling, eos early-stop, ragged prompt lengths —
is serialized **as one StableHLO program** via `jax.export`, with the
weights in msgpack beside it and the byte-BPE tokenizer JSON riding along,
so a serving host needs jax + this module, no flax model code and no
training checkpoint.

Bundle layout (``export_dir/<YYYYmmdd-HHMMSS>/`` — the reference's
timestamped-directory convention):

* ``generate.stablehlo`` — the exported program
  ``(params, prompt [B, T0], rng, lengths [B]) -> tokens [B, new]``;
* ``weights.msgpack``    — the param pytree (msgpack-restorable without a
  template);
* ``generate.json``      — shapes, sampling knobs, eos/pad ids, vocab;
* ``tokenizer.json``     — optional `data.tokenizer.ByteBPETokenizer`.

Ragged prompts are first-class: the program is compiled for one
``[batch_size, prompt_len]`` shape, and per-request prompts of any length
≤ ``prompt_len`` are right-padded server-side with per-row true lengths
passed through — each row generates exactly as if alone at its own length
(models/decoding.py ragged contract), so clients never see the static
shape.

Serve with ``python -m horovod_tpu.launch.serve <bundle_dir>`` — the
server routes ``/v1/generate`` for these bundles (launch/serve.py).
"""

from __future__ import annotations

import json
import os
import shutil
import time

import jax
import numpy as np
from flax import serialization

GEN_GRAPH_FILE = "generate.stablehlo"
GEN_START_FILE = "generate_start.stablehlo"  # streaming bundles
GEN_CONT_FILE = "generate_cont.stablehlo"
GEN_META_FILE = "generate.json"
GEN_WEIGHTS_FILE = "weights.msgpack"
TOKENIZER_FILE = "tokenizer.json"


def export_generate(
    export_dir: str,
    model,
    params,
    *,
    batch_size: int,
    prompt_len: int,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 0.0,
    eos_id: int | None = None,
    pad_id: int = 0,
    tokenizer=None,
    timestamp: str | None = None,
    int8_compute: bool = False,
    quantized_cache: bool = False,
    speculative_gamma: int = 0,
    streaming_chunk: int = 0,
) -> str:
    """Export a generation bundle into ``export_dir/<stamp>/``.

    ``model`` is the *training* `TransformerLM` (or any module
    `make_generate_fn` accepts); ``params`` its param pytree — plain,
    single-host sharded (TP/FSDP assemble transparently), or sharded
    across processes, in which case this is a COLLECTIVE: every process
    must call export_generate, the shards are host-gathered
    (`checkpoint.gather_to_host`), the primary writes the bundle and
    non-primaries return None. ``tokenizer`` is a `ByteBPETokenizer`, a
    path to a saved tokenizer JSON, or None (token-id-only serving).

    The exported program takes params as an ARGUMENT (not baked-in
    constants): the graph stays small, and the weights live once, in
    msgpack. Sampling knobs are compile-time (they shape the program);
    the rng seed and prompts are runtime inputs.
    """
    from horovod_tpu.models.decoding import make_generate_fn

    if prompt_len < 1 or batch_size < 1:
        raise ValueError(
            f"batch_size ({batch_size}) and prompt_len ({prompt_len}) "
            "must be >= 1"
        )
    from horovod_tpu import checkpoint as ckpt
    from horovod_tpu import runtime

    if ckpt.is_cross_process_sharded(params):
        params = ckpt.gather_to_host(params)  # collective — see docstring
        if not runtime.is_primary():
            return None
    # int8_compute / quantized_cache: the decode-family quantization knobs
    # (models/quant.py) baked into the exported program — int8-MXU prefill
    # and/or the int8 K/V cache, the measured serving levers (BASELINE.md).
    # speculative_gamma > 0: the bundle's program is the SPECULATIVE
    # decoder (models/speculative.py, prompt-lookup draft) — greedy-exact
    # output at 2.4-3.3x measured throughput; greedy-only and no eos (the
    # speculative loop's restrictions), ragged lengths supported the same.
    # All validation happens BEFORE the output dir exists, so a rejected
    # export never litters export_dir with an empty timestamped dir.
    if speculative_gamma:
        if temperature != 0.0:
            raise ValueError(
                "speculative bundles are greedy-only (temperature == 0): "
                "the exported program carries no rng input"
            )
        if eos_id is not None:
            raise ValueError(
                "speculative decoding does not support eos early-stop — "
                "export without eos_id or without speculative_gamma"
            )
        if int8_compute:
            raise ValueError(
                "int8_compute is not wired into the speculative loop — "
                "export with one or the other"
            )
        from horovod_tpu.models.speculative import make_speculative_fn

        fn = make_speculative_fn(
            model.clone(quantized_cache=True) if quantized_cache else model,
            max_new_tokens=max_new_tokens, gamma=speculative_gamma,
            include_prompt=False,
        )
    else:
        fn = make_generate_fn(
            model,
            max_new_tokens=max_new_tokens,
            temperature=temperature,
            top_k=top_k,
            top_p=top_p,
            eos_id=eos_id,
            include_prompt=False,
            int8_compute=int8_compute,
            quantized_cache=quantized_cache,
        )
    # streaming_chunk > 0: the bundle carries TWO programs (prefill+first
    # chunk; continue-against-carried-cache) so a server can stream tokens
    # chunk by chunk — `make_chunked_generate_fns`, whose token stream is
    # parity-tested against the one-shot generator. Exclusive with the
    # speculative program (one program shape per bundle).
    start_fn = cont_fn = None
    if streaming_chunk:
        if speculative_gamma:
            raise ValueError(
                "streaming_chunk and speculative_gamma are exclusive — "
                "one program shape per bundle"
            )
        if int8_compute:
            raise ValueError(
                "int8_compute is not wired into the chunked generator — "
                "export with one or the other"
            )
        from horovod_tpu.models.decoding import make_chunked_generate_fns

        start_fn, cont_fn = make_chunked_generate_fns(
            model, max_new_tokens=max_new_tokens, chunk=streaming_chunk,
            temperature=temperature, top_k=top_k, top_p=top_p,
            eos_id=eos_id, quantized_cache=quantized_cache,
        )
    stamp = timestamp or time.strftime("%Y%m%d-%H%M%S")
    out_dir = os.path.join(export_dir, stamp)
    os.makedirs(out_dir, exist_ok=True)
    from jax import export as jax_export

    params = jax.device_get(params)
    param_specs = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(np.shape(a), np.asarray(a).dtype),
        params,
    )
    prompt_spec = jax.ShapeDtypeStruct((batch_size, prompt_len), np.int32)
    rng_spec = (
        None if speculative_gamma else jax.ShapeDtypeStruct(
            np.shape(jax.random.PRNGKey(0)),
            np.asarray(jax.random.PRNGKey(0)).dtype,
        )
    )
    lengths_spec = jax.ShapeDtypeStruct((batch_size,), np.int32)
    from horovod_tpu.checkpoint import _atomic_write

    if streaming_chunk:
        exp_start = jax_export.export(start_fn)(
            param_specs, prompt_spec, rng_spec, lengths_spec
        )
        state_spec = jax.eval_shape(
            start_fn, param_specs, prompt_spec, rng_spec, lengths_spec
        )[1]
        exp_cont = jax_export.export(cont_fn)(param_specs, state_spec)
        _atomic_write(
            os.path.join(out_dir, GEN_START_FILE), exp_start.serialize()
        )
        _atomic_write(
            os.path.join(out_dir, GEN_CONT_FILE), exp_cont.serialize()
        )
    else:
        exported = jax_export.export(fn)(
            param_specs, prompt_spec, rng_spec, lengths_spec
        )
        _atomic_write(
            os.path.join(out_dir, GEN_GRAPH_FILE), exported.serialize()
        )
    _atomic_write(
        os.path.join(out_dir, GEN_WEIGHTS_FILE),
        serialization.to_bytes(params),
    )
    meta = {
        "kind": "generate",
        "batch_size": batch_size,
        "prompt_len": prompt_len,
        "max_new_tokens": max_new_tokens,
        "temperature": temperature,
        "top_k": top_k,
        "top_p": top_p,
        "eos_id": eos_id,
        "pad_id": pad_id,
        "int8_compute": int8_compute,
        "quantized_cache": quantized_cache,
        "speculative_gamma": speculative_gamma,
        "streaming_chunk": streaming_chunk,
        "has_tokenizer": tokenizer is not None,
        "created": stamp,
    }
    # Tokenizer BEFORE the meta that advertises it: a crash between the two
    # writes leaves a bundle whose meta under-promises, never one that lies.
    if tokenizer is not None:
        tok_path = os.path.join(out_dir, TOKENIZER_FILE)
        if isinstance(tokenizer, str):
            shutil.copyfile(tokenizer, tok_path)
        else:
            tokenizer.save(tok_path)
    _atomic_write(
        os.path.join(out_dir, GEN_META_FILE),
        json.dumps(meta, indent=2).encode(),
    )
    return out_dir


def is_generate_bundle(bundle_dir: str) -> bool:
    return os.path.exists(os.path.join(bundle_dir, GEN_META_FILE))


class GenerateBundle:
    """A reloaded generation bundle: tokenize → pad → run → trim → detok.

    ``generate_tokens(prompts, seed)`` takes a list of token-id sequences
    (each of length 1..prompt_len); requests of any row count are split /
    padded to the compiled batch internally. ``generate_text(texts, seed)``
    adds the tokenizer round-trip (requires the bundle to carry one).
    Generations are trimmed at ``eos_id`` when the bundle was exported
    with one.
    """

    def __init__(self, bundle_dir: str):
        from jax import export as jax_export

        self.bundle_dir = bundle_dir
        with open(os.path.join(bundle_dir, GEN_META_FILE)) as f:
            self.meta = json.load(f)
        if self.meta.get("kind") != "generate":
            raise ValueError(f"{bundle_dir} is not a generation bundle")
        if self.meta.get("streaming_chunk"):
            with open(os.path.join(bundle_dir, GEN_START_FILE), "rb") as f:
                self._start = jax.jit(jax_export.deserialize(f.read()).call)
            with open(os.path.join(bundle_dir, GEN_CONT_FILE), "rb") as f:
                self._cont = jax.jit(jax_export.deserialize(f.read()).call)
            self._call = None
        else:
            with open(os.path.join(bundle_dir, GEN_GRAPH_FILE), "rb") as f:
                self._exported = jax_export.deserialize(f.read())
            # jit the deserialized program ONCE: a bare exported.call
            # re-lowers on every invocation (measured seconds per request
            # at LM scale); under jit the compilation caches and repeat
            # calls are a dispatch.
            self._call = jax.jit(self._exported.call)
        with open(os.path.join(bundle_dir, GEN_WEIGHTS_FILE), "rb") as f:
            self._params = serialization.msgpack_restore(f.read())
        # Commit the weights to device ONCE: params are an ARGUMENT of the
        # exported program, and host numpy args would re-transfer the whole
        # model through the interconnect on every request (measured 3.3 s
        # vs 0.08 s per request at d512x8L over a tunneled runtime).
        import jax.numpy as jnp

        self._params = jax.tree.map(jnp.asarray, self._params)
        self.tokenizer = None
        tok_path = os.path.join(bundle_dir, TOKENIZER_FILE)
        if os.path.exists(tok_path):
            from horovod_tpu.data.tokenizer import ByteBPETokenizer

            self.tokenizer = ByteBPETokenizer.load(tok_path)
        elif self.meta.get("has_tokenizer"):
            # Fail fast on an inconsistent bundle (tokenizer.json lost in
            # transfer) instead of silently degrading to token-id-only
            # serving while /healthz advertises a tokenizer.
            raise FileNotFoundError(
                f"{bundle_dir} advertises a tokenizer "
                f"(generate.json has_tokenizer=true) but {TOKENIZER_FILE} "
                "is missing — the bundle is incomplete"
            )

    @property
    def batch_size(self) -> int:
        return int(self.meta["batch_size"])

    @property
    def prompt_len(self) -> int:
        return int(self.meta["prompt_len"])

    def stream_chunks(self, prompts, seed: int = 0, chunk: int = 0):
        """STREAMING generation: yields ``[B_req, chunk]``-shaped lists of
        token ids per dispatch (the cache stays device-resident between
        chunks). Requires a streaming bundle (``streaming_chunk`` at
        export) and at most ``batch_size`` validated prompts; stops early
        once every row has emitted eos (when configured). The
        concatenation of the yielded chunks equals the one-shot
        generation for the same knobs (parity-tested)."""
        k = int(self.meta.get("streaming_chunk") or 0)
        if not k:
            raise ValueError(
                "this bundle was not exported with streaming_chunk — "
                "re-export to stream"
            )
        prompts = self.validate_prompts(prompts)
        b, t0 = self.batch_size, self.prompt_len
        if not prompts or len(prompts) > b:
            raise ValueError(
                f"streaming takes 1..{b} prompts per request, got "
                f"{len(prompts)}"
            )
        n = len(prompts)
        pad = int(self.meta.get("pad_id") or 0)
        padded = np.full((b, t0), pad, np.int32)
        lengths = np.ones((b,), np.int32)
        for i, p in enumerate(prompts):
            padded[i, : len(p)] = p
            lengths[i] = len(p)
        # Same per-group rng discipline as _run: group 0 uses PRNGKey(seed)
        # verbatim (local-parity contract), later groups of an
        # over-batch-size request fold the group index in.
        rng = jax.random.PRNGKey(seed)
        if chunk:
            rng = jax.random.fold_in(rng, chunk)
        tokens, state = self._start(self._params, padded, rng, lengths)
        yield np.asarray(tokens)[:n].tolist()
        total = int(self.meta["max_new_tokens"])
        for _ in range(total // k - 1):
            if self.meta.get("eos_id") is not None and bool(
                np.asarray(state[3])[:n].all()
            ):
                return  # every live row finished — stop dispatching
            tokens, state = self._cont(self._params, state)
            yield np.asarray(tokens)[:n].tolist()

    def _run(self, padded: np.ndarray, lengths: np.ndarray, seed: int,
             chunk: int = 0):
        if self.meta.get("streaming_chunk"):
            # Streaming bundles dispatch via stream_chunks (the one-shot
            # API collects in generate_batch's streaming branch).
            raise RuntimeError("_run is not used for streaming bundles")
        if self.meta.get("speculative_gamma"):
            # Speculative bundles are greedy: no rng input in the program
            # (the seed is ignored — deterministic by construction).
            return np.asarray(
                self._call(
                    self._params,
                    padded.astype(np.int32),
                    None,
                    lengths.astype(np.int32),
                )
            )
        # Chunk 0 uses PRNGKey(seed) verbatim — the documented parity
        # contract with a local `fn(params, prompt, PRNGKey(seed), lens)`
        # call. Later chunks of an over-batch-size request fold the chunk
        # index in so sampled generations don't repeat across chunks.
        rng = jax.random.PRNGKey(seed)
        if chunk:
            rng = jax.random.fold_in(rng, chunk)
        return np.asarray(
            self._call(
                self._params,
                padded.astype(np.int32),
                rng,
                lengths.astype(np.int32),
            )
        )

    def validate_prompts(self, prompts) -> list:
        """Normalize to int32 row arrays; guided error outside 1..T0."""
        t0 = self.prompt_len
        prompts = [np.asarray(p, np.int32).reshape(-1) for p in prompts]
        for i, p in enumerate(prompts):
            if not 1 <= len(p) <= t0:
                raise ValueError(
                    f"prompt {i} has {len(p)} tokens; this bundle serves "
                    f"prompts of 1..{t0} tokens"
                )
        return prompts

    def generate_batch(self, prompts, seed: int = 0, chunk: int = 0) -> list:
        """ONE device call over ≤ batch_size validated prompt rows →
        trimmed generated-id lists. The unit the server's coalescing queue
        dispatches (launch/serve.py). (Streaming bundles run their chunk
        loop here — same token stream, more dispatches.)"""
        b, t0 = self.batch_size, self.prompt_len
        if len(prompts) > b:
            raise ValueError(
                f"{len(prompts)} rows > compiled batch {b}; use "
                "generate_tokens for auto-splitting"
            )
        if self.meta.get("streaming_chunk"):
            # One-shot API over a streaming bundle: collect the chunks
            # (same token stream — chunking is where dispatches cut, not
            # what is computed). The batch-group index threads through so
            # sampled over-batch-size requests don't repeat across groups.
            rows = [[] for _ in prompts]
            for chunk_tokens in self.stream_chunks(
                prompts, seed=seed, chunk=chunk
            ):
                for i, part in enumerate(chunk_tokens):
                    rows[i].extend(part)
            return [self._trim(np.asarray(r)) for r in rows]
        pad = int(self.meta.get("pad_id") or 0)
        n = len(prompts)
        padded = np.full((b, t0), pad, np.int32)
        lengths = np.ones((b,), np.int32)
        for i, p in enumerate(prompts):
            padded[i, : len(p)] = p
            lengths[i] = len(p)
        gen = self._run(padded, lengths, seed, chunk=chunk)[:n]
        return [self._trim(row) for row in gen]

    def generate_tokens(self, prompts, seed: int = 0) -> list:
        """``prompts``: list of token-id sequences → list of generated-id
        lists (prompt not included; trimmed at eos when configured)."""
        b = self.batch_size
        prompts = self.validate_prompts(prompts)
        if not prompts:
            return []
        out: list = []
        for ci, start in enumerate(range(0, len(prompts), b)):
            out.extend(
                self.generate_batch(
                    prompts[start : start + b], seed=seed, chunk=ci
                )
            )
        return out

    def _trim(self, row: np.ndarray) -> list:
        eos = self.meta.get("eos_id")
        row = [int(t) for t in row]
        if eos is None:
            return row
        return row[: row.index(eos)] if eos in row else row

    def generate_text(self, texts, seed: int = 0) -> list:
        if self.tokenizer is None:
            raise ValueError(
                "this bundle has no tokenizer.json — export with "
                "tokenizer=... or POST token ids to /v1/generate instead"
            )
        prompts = [self.tokenizer.encode(t) for t in texts]
        for i, p in enumerate(prompts):
            if len(p) > self.prompt_len:
                raise ValueError(
                    f"text {i} tokenizes to {len(p)} tokens; this bundle "
                    f"serves prompts of up to {self.prompt_len} tokens"
                )
        gen = self.generate_tokens(prompts, seed=seed)
        return [self.tokenizer.decode(g) for g in gen]


def load_generate(bundle_dir: str) -> GenerateBundle:
    """Reload an `export_generate` bundle."""
    return GenerateBundle(bundle_dir)
