"""`ServeFleet`: the elastic replica tier behind one router address.

The same fleet discipline PR 2 built for training — a rendezvous
coordinator owning membership truth, a journal owning history — applied
to inference:

* N replica processes (`python -m horovod_tpu.launch.serve`, continuous
  engine on), each a coordinator MEMBER: sync once at boot, TCP beats
  while serving, a clean ``leave`` on SIGTERM (so the journal tells a
  drain from a crash);
* the front-end router (`serving.router`) owns per-replica in-flight
  accounting; a watchdog reconciles it against the coordinator — a
  member that left or went stale is drained from rotation before its
  socket starts refusing;
* **zero-downtime weight swap** (`swap`): per replica, journaled —
  ``swap_drain`` (stop dispatching, wait in-flight → 0) → POST
  ``/admin/reload`` with the new bundle (checkpoint-sidecar export) →
  readiness probe → ``swap_readmit``. One replica swaps at a time; the
  rest carry the traffic. No request ever lands on a replica mid-swap;
* **autoscale hooks**: with ``HVT_SERVE_AUTOSCALE=dry-run|on`` a poll
  thread feeds the router's own TTFT histogram to
  `launch.policy.ServeAutoscaler` (the PR 16 policy-engine shape:
  freshness-gated, streak + cooldown, every decision journaled as
  ``policy_scale_up``/``policy_scale_down``) and, in ``on`` mode,
  actually spawns/retires replicas.

On `stop()` the router registry is dumped to ``metrics.prom`` beside the
journal (`supervisor.default_metrics_dump_path`), which is what
`launch.job`'s ``metrics_checks:`` gates read — the serve-2replica CI
job asserts TTFT-histogram presence and a zero ``code="500"`` count
from exactly this dump.

CLI (the CI acceptance job's entry): ``python -m horovod_tpu.serving.fleet
--demo --replicas 2 --requests 40 --swap --journal <path>`` self-exports
a tiny streaming bundle, serves it with 2 replicas, drives mid-traffic
load through the router, swaps weights under that load, and exits 0 only
if every request succeeded.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

from horovod_tpu.analysis import registry as knob_registry
from horovod_tpu.obs import prom as obs_prom
from horovod_tpu.serving import router as router_mod


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _http_json(url: str, payload: dict | None = None, timeout: float = 10.0):
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(
        url, data=data,
        headers={"Content-Type": "application/json"} if data else {},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


class _ReplicaProc:
    __slots__ = ("name", "port", "proc")

    def __init__(self, name: str, port: int, proc: subprocess.Popen):
        self.name = name
        self.port = port
        self.proc = proc

    @property
    def base_url(self) -> str:
        return f"http://127.0.0.1:{self.port}"


class ServeFleet:
    """Coordinator + router + N replica subprocesses, one handle.

    ``log_path``: the restart-journal path (None journals nowhere);
    ``continuous=False`` runs the legacy coalescing replicas (the bench
    baseline). ``ready_timeout`` bounds each replica's boot (bundle
    deserialization + first jit can dominate).
    """

    def __init__(self, bundle_dir: str, *, replicas: int = 2,
                 router_port: int = 0, router_host: str = "127.0.0.1",
                 log_path: str | None = None, continuous: bool = True,
                 ready_timeout: float = 120.0, env: dict | None = None):
        from horovod_tpu.elastic.coordinator import Coordinator
        from horovod_tpu.launch.supervisor import RestartLog

        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        self.bundle_dir = bundle_dir
        self.n_replicas = replicas
        self.continuous = continuous
        self.ready_timeout = ready_timeout
        self.env = dict(env or os.environ)
        self.log = RestartLog(log_path)
        self.log.touch()
        self.coord = Coordinator(
            port=0, min_ranks=1, expected=replicas,
            heartbeat_window=10.0, journal=self.log.write,
        ).start()
        self.router = router_mod.make_router(
            port=router_port, host=router_host
        )
        self._router_thread = threading.Thread(
            target=self.router.serve_forever, daemon=True
        )
        self._router_thread.start()
        self.replicas: dict[str, _ReplicaProc] = {}
        self._next_replica = 0
        self._lock = threading.Lock()
        self._stopping = False
        self._watchdog = None
        self._autoscale_thread = None
        self.drain_timeout = knob_registry.get_float(
            "HVT_SERVE_DRAIN_TIMEOUT_S"
        )
        self.swap_timeout = knob_registry.get_float(
            "HVT_SERVE_SWAP_TIMEOUT_S"
        )

    # -- lifecycle --------------------------------------------------------

    @property
    def router_url(self) -> str:
        host, port = self.router.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "ServeFleet":
        self.log.write("serve_start", self.n_replicas,
                       bundle=self.bundle_dir,
                       mode="continuous" if self.continuous else "coalesce")
        for _ in range(self.n_replicas):
            self._spawn_replica()
        self._watchdog = threading.Thread(
            target=self._watch, daemon=True, name="hvt-serve-watchdog"
        )
        self._watchdog.start()
        mode = knob_registry.get_str("HVT_SERVE_AUTOSCALE") or "off"
        if mode != "off":
            self._autoscale_thread = threading.Thread(
                target=self._autoscale_loop, args=(mode,), daemon=True,
                name="hvt-serve-autoscale",
            )
            self._autoscale_thread.start()
        return self

    def _spawn_replica(self) -> str:
        with self._lock:
            name = f"serve-{self._next_replica}"
            self._next_replica += 1
        port = _free_port()
        cmd = [
            sys.executable, "-m", "horovod_tpu.launch.serve",
            self.bundle_dir, "--port", str(port), "--host", "127.0.0.1",
            "--coordinator", self.coord.address, "--member", name,
            "--allow-reload",
        ]
        if self.continuous:
            cmd.append("--continuous")
        proc = subprocess.Popen(cmd, env=self.env)
        rp = _ReplicaProc(name, port, proc)
        with self._lock:
            self.replicas[name] = rp
        self._wait_ready(rp)
        self.router.replicas.add(name, rp.base_url)
        self.log.write("serve_replica_up", port, member=name)
        return name

    def _wait_ready(self, rp: _ReplicaProc) -> None:
        deadline = time.monotonic() + self.ready_timeout
        while time.monotonic() < deadline:
            if rp.proc.poll() is not None:
                raise RuntimeError(
                    f"replica {rp.name} exited rc={rp.proc.returncode} "
                    "during boot"
                )
            try:
                _http_json(rp.base_url + "/healthz", timeout=2.0)
                return
            except (OSError, urllib.error.URLError):
                time.sleep(0.1)
        raise TimeoutError(
            f"replica {rp.name} not serving after {self.ready_timeout}s"
        )

    def _watch(self) -> None:
        """Reconcile the router against coordinator truth + child exits:
        a member that left cleanly, went heartbeat-stale, or whose
        process died is drained from rotation and journaled."""
        while not self._stopping:
            time.sleep(0.25)
            if self._stopping:
                return
            for stale in self.coord.stale_members(10.0):
                self.coord.mark_dead(stale, reason="beat-stale")
            with self._lock:
                known = dict(self.replicas)
            for name, rp in known.items():
                gone = rp.proc.poll() is not None
                # "unknown" = hasn't synced yet (still booting) — only a
                # member the coordinator has SEEN depart counts as left.
                left = self.coord.member_status(name)[0] in (
                    "left", "dead"
                )
                if gone or left:
                    self.router.replicas.drain(name)
                    self.router.replicas.wait_drained(
                        name, self.drain_timeout
                    )
                    self.router.replicas.remove(name)
                    with self._lock:
                        self.replicas.pop(name, None)
                    self.log.write(
                        "serve_replica_down", rp.port, member=name,
                        reason="exit" if gone else "leave",
                    )

    # -- weight swap ------------------------------------------------------

    def swap(self, new_bundle_dir: str) -> bool:
        """Zero-downtime weight swap: drain → reload → readmit, one
        replica at a time, each step journaled. Returns False (and
        readmits on the OLD weights) if any replica fails its step —
        never leaves a replica out of rotation."""
        ok = True
        for name in list(self.router.replicas.names()):
            rp = self.replicas.get(name)
            if rp is None:
                continue
            self.log.write("swap_drain", rp.port, member=name,
                           bundle=new_bundle_dir)
            self.router.replicas.drain(name)
            drained = self.router.replicas.wait_drained(
                name, self.drain_timeout
            )
            if not drained:
                self.log.write("swap_abort", rp.port, member=name,
                               reason="drain-timeout")
                self.router.replicas.readmit(name)
                ok = False
                continue
            try:
                _http_json(
                    rp.base_url + "/admin/reload",
                    {"bundle_dir": new_bundle_dir},
                    timeout=self.swap_timeout,
                )
                _http_json(rp.base_url + "/healthz", timeout=10.0)
            except Exception as e:
                self.log.write("swap_abort", rp.port, member=name,
                               reason=f"{type(e).__name__}: {e}")
                self.router.replicas.readmit(name)  # old weights, but up
                ok = False
                continue
            self.router.replicas.readmit(name)
            self.log.write("swap_readmit", rp.port, member=name,
                           bundle=new_bundle_dir)
        if ok:
            self.bundle_dir = new_bundle_dir
            self.router.metrics_registry.counter("hvt_serve_swaps_total")
            self.log.write("swap", len(self.replicas),
                           bundle=new_bundle_dir)
        return ok

    # -- autoscale --------------------------------------------------------

    def scale_up(self) -> str | None:
        """Autoscaler actuator: one more replica (bounded by 2x the
        configured fleet so a runaway signal can't fork-bomb the host)."""
        with self._lock:
            if len(self.replicas) >= 2 * self.n_replicas:
                return None
        return self._spawn_replica()

    def scale_down(self) -> str | None:
        """Autoscaler actuator: drain + SIGTERM the newest replica
        (never below one)."""
        with self._lock:
            if len(self.replicas) <= 1:
                return None
            name = sorted(self.replicas)[-1]
            rp = self.replicas[name]
        self.router.replicas.drain(name)
        self.router.replicas.wait_drained(name, self.drain_timeout)
        rp.proc.send_signal(signal.SIGTERM)
        return name

    def _autoscale_loop(self, mode: str) -> None:
        from horovod_tpu.launch.policy import ServeAutoscaler

        scaler = ServeAutoscaler()
        while not self._stopping:
            time.sleep(1.0)
            if self._stopping:
                return
            series = obs_prom.parse_text(
                obs_prom.render(self.router.metrics_registry)
            )
            action = scaler.observe(series)
            if action is None:
                continue
            if mode == "dry-run":
                self.log.write(f"policy_scale_{action}", 0,
                               action=f"scale_{action}", outcome="dry-run")
                continue
            moved = (
                self.scale_up() if action == "up" else self.scale_down()
            )
            self.log.write(
                f"policy_scale_{action}", 1 if moved else 0,
                action=f"scale_{action}",
                outcome=moved or ("at-max" if action == "up" else "at-min"),
            )

    # -- shutdown ---------------------------------------------------------

    def stop(self) -> None:
        self._stopping = True
        with self._lock:
            procs = list(self.replicas.values())
        for rp in procs:
            if rp.proc.poll() is None:
                rp.proc.send_signal(signal.SIGTERM)
        for rp in procs:
            try:
                rp.proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                rp.proc.kill()
                rp.proc.wait(timeout=10)
        self.router.shutdown()
        self.coord.stop()
        self.log.write("serve_stop", len(procs))
        self._dump_metrics()

    def _dump_metrics(self) -> None:
        from horovod_tpu.checkpoint import _atomic_write
        from horovod_tpu.launch.supervisor import default_metrics_dump_path

        path = default_metrics_dump_path(None, self.log.path)
        if path is None:
            return
        try:
            _atomic_write(
                path,
                obs_prom.render(self.router.metrics_registry).encode(),
            )
        except OSError:
            pass  # best-effort, like the supervisor's dump


# -- CLI / demo harness ----------------------------------------------------


def _export_demo_bundle(out_dir: str, seed: int = 0) -> str:
    """A tiny greedy streaming LM bundle — the CI job's self-contained
    model (no checkpoint needed in the container)."""
    import jax
    import jax.numpy as jnp

    from horovod_tpu import serving
    from horovod_tpu.models.transformer import TransformerLM

    model = TransformerLM(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, dropout=0.0
    )
    params = model.init(
        jax.random.PRNGKey(seed), jnp.zeros((4, 8), jnp.int32)
    )["params"]
    return serving.export_generate(
        out_dir, model, params, batch_size=4, prompt_len=8,
        max_new_tokens=8, streaming_chunk=2,
        timestamp=f"demo-{seed}",
    )


def _drive_load(router_url: str, n_requests: int, n_threads: int = 4):
    """Closed-loop smoke traffic: every request must succeed. Returns
    (ok_count, fail_count, failures)."""
    results: list[tuple[bool, str]] = []
    lock = threading.Lock()
    idx = iter(range(n_requests))

    def worker():
        while True:
            with lock:
                i = next(idx, None)
            if i is None:
                return
            prompt = [1 + (i + j) % 60 for j in range(1 + i % 6)]
            stream = i % 2 == 0
            try:
                if stream:
                    req = urllib.request.Request(
                        router_url + "/v1/generate",
                        data=json.dumps(
                            {"prompt": [prompt], "stream": True}
                        ).encode(),
                        headers={"Content-Type": "application/json"},
                    )
                    with urllib.request.urlopen(req, timeout=60) as resp:
                        last = None
                        for line in resp:
                            last = json.loads(line)
                    okay = bool(last and last.get("done"))
                    detail = "" if okay else f"no done line: {last}"
                else:
                    out = _http_json(
                        router_url + "/v1/generate",
                        {"prompt": [prompt]}, timeout=60,
                    )
                    okay = bool(out.get("tokens"))
                    detail = "" if okay else f"empty tokens: {out}"
            except Exception as e:
                okay, detail = False, f"{type(e).__name__}: {e}"
            with lock:
                results.append((okay, detail))

    threads = [
        threading.Thread(target=worker, daemon=True)
        for _ in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    fails = [d for ok, d in results if not ok]
    return len(results) - len(fails), len(fails), fails


def main(argv=None) -> int:
    import argparse
    import tempfile

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("bundle_dir", nargs="?", default=None,
                   help="generation bundle to serve (omit with --demo)")
    p.add_argument("--replicas", type=int,
                   default=knob_registry.get_int("HVT_SERVE_REPLICAS"))
    p.add_argument("--port", type=int, default=0,
                   help="router port (0 = ephemeral, printed at boot)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--journal", default=None, metavar="PATH",
                   help="restart-journal path (membership + swap events; "
                   "metrics.prom lands beside it at stop)")
    p.add_argument("--coalesce", action="store_true",
                   help="legacy coalescing replicas (the bench baseline) "
                   "instead of the continuous engine")
    p.add_argument("--demo", action="store_true",
                   help="self-export a tiny streaming bundle and serve it "
                   "(the CI acceptance job)")
    p.add_argument("--requests", type=int, default=0, metavar="N",
                   help="drive N smoke requests through the router, then "
                   "stop; exit 1 unless ALL succeed")
    p.add_argument("--swap", action="store_true",
                   help="with --requests: re-export the demo bundle and "
                   "zero-downtime swap it in mid-traffic")
    args = p.parse_args(argv)

    tmp = None
    if args.demo:
        tmp = tempfile.mkdtemp(prefix="hvt-serve-demo-")
        bundle = _export_demo_bundle(tmp, seed=0)
    elif args.bundle_dir:
        bundle = args.bundle_dir
    else:
        p.error("pass a bundle_dir or --demo")

    fleet = ServeFleet(
        bundle, replicas=args.replicas, router_port=args.port,
        router_host=args.host, log_path=args.journal,
        continuous=not args.coalesce,
    ).start()
    print(f"router on {fleet.router_url} "
          f"({args.replicas} replicas)", flush=True)

    if not args.requests:
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            pass
        finally:
            fleet.stop()
        return 0

    swap_result = None
    try:
        half = args.requests // 2
        ok1, fail1, fails1 = _drive_load(fleet.router_url, half)
        if args.swap:
            # Swap under live traffic: keep load flowing in the
            # background while the fleet drains/reloads one replica at
            # a time — the zero-downtime claim under test.
            bg: dict = {}

            def bg_load():
                bg["out"] = _drive_load(
                    fleet.router_url, args.requests - half
                )

            t = threading.Thread(target=bg_load)
            t.start()
            swap_result = fleet.swap(
                _export_demo_bundle(tmp, seed=1) if args.demo
                else bundle
            )
            t.join()
            ok2, fail2, fails2 = bg["out"]
        else:
            ok2, fail2, fails2 = _drive_load(
                fleet.router_url, args.requests - half
            )
    finally:
        fleet.stop()
        if tmp is not None:
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)
    report = {
        "requests": args.requests, "ok": ok1 + ok2,
        "failed": fail1 + fail2, "swap": swap_result,
        "failures": (fails1 + fails2)[:5],
    }
    print(json.dumps(report), flush=True)
    if fail1 + fail2 or (args.swap and swap_result is not True):
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
