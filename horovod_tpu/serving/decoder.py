"""`ChunkedBundleDecoder`: the row-splice adapter the engine steps.

A streaming bundle carries two compiled programs
(`models/decoding.make_chunked_generate_fns`):

* ``start(params, prompt [B, T0], rng, lengths [B]) -> (tokens, state)``
  — prefill + first ``chunk`` tokens;
* ``cont(params, state) -> (tokens, state)`` — the next ``chunk``
  tokens against the carried cache.

Both are compiled for ONE static ``[B, T0]`` shape, and the decode state
is a per-row pytree: ``(cache, last_tok, rng, done)`` where every cache
leaf, ``last_tok`` and ``done`` carry a leading batch axis. The ragged
contract (each row generates exactly as if alone at its own length) is
what makes continuous batching legitimate as ROW SPLICING: to admit a
sequence mid-flight, run ``start`` on a fresh batch with the new prompts
in it, then copy the admitted rows' slices of (cache, tok, done) into
the live state. The live batch never stops; admission costs one prefill
dispatch, not a drain.

The one leaf that is NOT per-row is the rng (shape ``[2]``, shared by
the whole batch). Splicing it would corrupt every live row, so the live
rng is kept as-is and freshness comes from folding a monotone admission
counter into each prefill's seed. Greedy bundles are bit-exact either
way; sampled bundles draw valid (per-step fresh) but not
seed-reproducible-per-request samples — the documented trade of a
shared-rng compiled program.

Free/retired slots keep computing garbage until the next admission
overwrites them — harmless (the cache index clamps at the boundary via
``dynamic_update_slice``) and cheaper than a masked program shape.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


class ChunkedBundleDecoder:
    """Step/splice interface over a streaming `GenerateBundle`.

    The engine owns WHICH rows are live; this class owns HOW a batch of
    rows advances one chunk and how fresh rows enter a live state. All
    methods are eager host-side calls around the two jitted programs —
    no obs/trace here (the engine annotates its own spans).
    """

    def __init__(self, bundle):
        chunk = int(bundle.meta.get("streaming_chunk") or 0)
        if not chunk:
            raise ValueError(
                "continuous batching needs a streaming bundle "
                "(export_generate(..., streaming_chunk=K)) — this bundle "
                "carries the one-shot program only"
            )
        self.bundle = bundle
        self.chunk = chunk
        self.batch_size = bundle.batch_size
        self.prompt_len = bundle.prompt_len
        self.max_new_tokens = int(bundle.meta["max_new_tokens"])
        self.total_chunks = self.max_new_tokens // chunk
        self.eos_id = bundle.meta.get("eos_id")
        self.pad_id = int(bundle.meta.get("pad_id") or 0)
        # One fused select program instead of an eager dispatch per
        # state leaf — eager splices cost more than a decode step and
        # dominate the tick. The row set rides in as a fixed-shape
        # (perm, mask) pair so EVERY admission count hits the same
        # cached executable; a per-row-count scatter would recompile
        # mid-traffic on the first 2-row, 3-row, ... admission, stalling
        # the whole live batch behind XLA.
        self._splice_fn = jax.jit(self._splice_impl)

    def prefill(self, prompts, seed: int, admission: int):
        """Run the start program with ``prompts`` packed into rows
        ``0..len(prompts)-1`` of a full batch (pad rows elsewhere).
        ``admission`` is the engine's monotone admission counter, folded
        into the seed so consecutive sampled prefills draw fresh streams.
        Returns ``(tokens [B, chunk] np, fresh_state)``."""
        b, t0 = self.batch_size, self.prompt_len
        if not 1 <= len(prompts) <= b:
            raise ValueError(
                f"prefill takes 1..{b} prompts, got {len(prompts)}"
            )
        padded = np.full((b, t0), self.pad_id, np.int32)
        lengths = np.ones((b,), np.int32)
        for i, p in enumerate(prompts):
            padded[i, : len(p)] = p
            lengths[i] = len(p)
        rng = jax.random.fold_in(jax.random.PRNGKey(seed), admission)
        tokens, state = self.bundle._start(
            self.bundle._params, padded, rng, lengths
        )
        return np.asarray(tokens), state

    def splice(self, live_state, fresh_state, src_rows, dst_rows):
        """Copy rows ``src_rows`` of ``fresh_state`` into rows
        ``dst_rows`` of ``live_state`` across every per-row leaf (cache,
        last_tok, done). The live rng is kept (see module docstring).
        Returns the new live state."""
        if len(src_rows) != len(dst_rows):
            raise ValueError(
                f"src/dst row counts differ: {src_rows} vs {dst_rows}"
            )
        perm = np.zeros((self.batch_size,), np.int32)
        mask = np.zeros((self.batch_size,), bool)
        for s, d in zip(src_rows, dst_rows):
            perm[d] = s
            mask[d] = True
        return self._splice_fn(live_state, fresh_state, perm, mask)

    @staticmethod
    def _splice_impl(live_state, fresh_state, perm, mask):
        cache_l, tok_l, rng_l, done_l = live_state
        cache_f, tok_f, _, done_f = fresh_state

        def put(a, b):
            m = mask.reshape((-1,) + (1,) * (a.ndim - 1))
            return jnp.where(m, jnp.take(b, perm, axis=0), a)

        return (
            jax.tree.map(put, cache_l, cache_f),
            put(tok_l, tok_f),
            rng_l,
            put(done_l, done_f),
        )

    def step(self, state):
        """One cont dispatch: every live row advances ``chunk`` tokens.
        Returns ``(tokens [B, chunk] np, state)``."""
        tokens, state = self.bundle._cont(self.bundle._params, state)
        return np.asarray(tokens), state

    def done_flags(self, state) -> np.ndarray:
        """Per-row eos-done booleans (all-False when no eos_id)."""
        return np.asarray(state[3])
