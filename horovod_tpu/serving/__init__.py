"""The serving tier: export/reload bundles, and the production hot path.

Grown from the single-module ``serving.py`` (whose bundle surface lives
on unchanged in `serving.bundle` — every ``from horovod_tpu import
serving; serving.export_generate(...)`` call keeps working) into the
subsystem the north star asks for ("serves heavy traffic from millions
of users"):

* `bundle`  — export the compiled decode loop, reload it anywhere (the
  original module: `export_generate`, `GenerateBundle`, `load_generate`);
* `blocks`  — the paged KV-cache accounting: fixed-size token blocks, a
  free-list allocator that refuses admission instead of OOMing, and
  per-sequence block tables;
* `decoder` — `ChunkedBundleDecoder`, the row-splice adapter that turns a
  streaming bundle's two compiled programs (prefill+first-chunk,
  continue) into an admit/evict-capable step decoder;
* `engine`  — `ContinuousBatchingEngine`: the per-decode-step scheduler
  (admit into free capacity, retire finished rows immediately, one
  device dispatch per step for every live sequence);
* `router`  — the front-end: per-replica in-flight accounting,
  least-loaded dispatch, drain/readmit, failover retry;
* `fleet`   — the elastic replica fleet: rendezvous-coordinated
  membership, zero-downtime weight swap (drain → swap → readmit,
  journaled), and the TTFT-driven autoscale hook.

HTTP serving of a single replica stays in `horovod_tpu.launch.serve`;
`python -m horovod_tpu.serving.fleet` (or ``hvt-launch serve``) runs the
multi-replica tier.
"""

from horovod_tpu.serving.bundle import (  # noqa: F401 — the public surface
    GEN_CONT_FILE,
    GEN_GRAPH_FILE,
    GEN_META_FILE,
    GEN_START_FILE,
    GEN_WEIGHTS_FILE,
    TOKENIZER_FILE,
    GenerateBundle,
    export_generate,
    is_generate_bundle,
    load_generate,
)

__all__ = [
    "GEN_CONT_FILE",
    "GEN_GRAPH_FILE",
    "GEN_META_FILE",
    "GEN_START_FILE",
    "GEN_WEIGHTS_FILE",
    "TOKENIZER_FILE",
    "GenerateBundle",
    "export_generate",
    "is_generate_bundle",
    "load_generate",
]
