"""`ContinuousBatchingEngine`: the per-decode-step scheduler.

The legacy coalescing queue (launch/serve.py `_Batcher`) dispatches a
batch and holds every row hostage until the SLOWEST one finishes — a
long generation in row 0 is pure tail latency for the short request that
landed in row 3, and a request arriving one tick late waits a full
batch-generation for the next flush. This engine schedules at CHUNK
granularity instead (vLLM's continuous batching, arXiv 2309.06180,
restated over a static-shape compiled decoder):

* every tick, finished rows retire IMMEDIATELY (their KV blocks return
  to the allocator, their slot frees);
* waiting sequences admit into free slots the same tick — one prefill
  dispatch splices their rows into the live state
  (`decoder.ChunkedBundleDecoder.splice`) without stopping the batch;
* one ``cont`` dispatch then advances every live row by one chunk.

Admission is gated by the paged KV accounting (`blocks.BlockAllocator`):
a sequence enters only when its whole-lifetime block reservation fits,
waits in a bounded FIFO otherwise (strict FIFO — the head never starves
behind smaller latecomers), and overflows as `AdmissionError` (HTTP 429)
once the queue is full. The engine never OOMs mid-decode; it says no at
the door.

Threading: handler threads call `submit` (cheap: validate, reserve a
queue position, wake the scheduler); ONE scheduler thread runs `tick`
(admit → step → retire) and is the only mutator of the live decode
state and the slot table, so the hot path needs no lock around device
dispatches. `tick` is public and the thread optional
(``start_thread=False``) — the scheduler unit tests drive ticks by hand.

Observability: each tick emits a ``decode`` span with a ``step`` child
carrying admitted/evicted counts (hvt-trace attributes TTFT tail to
scheduling vs compute), plus a caller-timed ``queue_wait`` span per
admission. Metric mirroring to the typed registry lives in the server's
scrape collector (launch/serve.py), reading `stats()`.
"""

from __future__ import annotations

import collections
import queue
import threading
import time

from horovod_tpu import trace as trace_lib
from horovod_tpu.serving.blocks import BlockAllocator, OutOfBlocksError
from horovod_tpu.serving.decoder import ChunkedBundleDecoder


class AdmissionError(RuntimeError):
    """Wait queue full — the HTTP layer maps this to 429."""


class SeqRequest:
    """One submitted sequence: the handle a handler thread holds.

    ``iter_chunks()`` yields trimmed token-id lists as the scheduler
    delivers them (the streaming path); ``result(timeout)`` blocks for
    the full trimmed generation. Timestamps (`submitted`, `first_token`,
    `finished`) are engine-stamped monotonic clocks for TTFT/TPOT.
    """

    _SENTINEL = None

    def __init__(self, prompt, stream: bool):
        self.prompt = prompt
        self.stream = stream
        self.tokens: list[int] = []  # trimmed — eos and after never enter
        self.chunks_done = 0
        self.eos_seen = False
        self.table = None  # BlockTable once reserved
        self.slot = None  # live batch row once admitted
        self.error: Exception | None = None
        self.submitted = time.monotonic()
        self.first_token: float | None = None
        self.finished: float | None = None
        self._done = threading.Event()
        self._chunks: queue.Queue = queue.Queue()

    def _deliver(self, piece: list[int]) -> None:
        if piece:
            if self.first_token is None:
                self.first_token = time.monotonic()
            self.tokens.extend(piece)
            if self.stream:
                self._chunks.put(piece)

    def _finish(self, error: Exception | None = None) -> None:
        self.error = error
        self.finished = time.monotonic()
        self._chunks.put(self._SENTINEL)
        self._done.set()

    def result(self, timeout: float | None = None) -> list[int]:
        if not self._done.wait(timeout):
            raise TimeoutError("generation still in flight")
        if self.error is not None:
            raise self.error
        return self.tokens

    def iter_chunks(self):
        while True:
            piece = self._chunks.get()
            if piece is self._SENTINEL:
                if self.error is not None:
                    raise self.error
                return
            yield piece


class ContinuousBatchingEngine:
    """Admit/step/retire scheduler over one streaming bundle.

    ``max_seqs`` caps live rows (0 → the compiled batch size);
    ``kv_blocks`` sizes the paged-KV budget (0 → exactly enough for
    ``max_seqs`` worst-case sequences — the knob exists to be set LOWER,
    making admission the memory gate); ``queue_depth`` bounds the wait
    queue (beyond it: 429). Per-request seeds are not honored — the
    compiled state carries ONE rng for the whole batch (see
    decoder module docstring); ``seed`` salts every prefill via the
    admission counter.
    """

    def __init__(
        self,
        bundle,
        *,
        max_seqs: int = 0,
        block_tokens: int = 16,
        kv_blocks: int = 0,
        queue_depth: int = 64,
        seed: int = 0,
        start_thread: bool = True,
    ):
        self.decoder = ChunkedBundleDecoder(bundle)
        b = self.decoder.batch_size
        self.max_seqs = min(max_seqs, b) if max_seqs > 0 else b
        if queue_depth < 1:
            raise ValueError(f"queue_depth must be >= 1, got {queue_depth}")
        self.queue_depth = queue_depth
        self.seed = seed
        worst = self.decoder.prompt_len + self.decoder.max_new_tokens
        if kv_blocks <= 0:
            kv_blocks = self.max_seqs * (
                -(-worst // block_tokens)
            )
        self.allocator = BlockAllocator(kv_blocks, block_tokens)
        self._slots: list[SeqRequest | None] = [None] * self.max_seqs
        self._state = None  # live decode pytree; scheduler-thread-only
        self._wait: collections.deque[SeqRequest] = collections.deque()
        self._cond = threading.Condition()
        self._admissions = 0  # monotone; salts each prefill's rng
        self._stop = False
        self._stats = {
            "admitted_total": 0,
            "retired_total": 0,
            "rejected_total": 0,
            "device_calls_total": 0,
            "prefill_calls_total": 0,
        }
        self._thread = None
        if start_thread:
            self._thread = threading.Thread(
                target=self._loop, name="hvt-serve-engine", daemon=True
            )
            self._thread.start()

    # -- handler-thread surface ------------------------------------------

    def submit(self, prompt, *, stream: bool = False) -> SeqRequest:
        """Validate and enqueue one prompt. Raises ``ValueError`` for a
        prompt the bundle can never serve (HTTP 400) and
        `AdmissionError` when the wait queue is full (HTTP 429)."""
        prompt = self.decoder.bundle.validate_prompts([prompt])[0]
        # A sequence larger than the WHOLE block budget can never admit —
        # reject now (400) instead of queueing forever.
        need = len(prompt) + self.decoder.max_new_tokens
        if self.allocator.blocks_for(need) > self.allocator.num_blocks:
            raise ValueError(
                f"sequence needs {self.allocator.blocks_for(need)} KV "
                f"blocks, budget is {self.allocator.num_blocks} — raise "
                "HVT_SERVE_KV_BLOCKS or shorten the request"
            )
        req = SeqRequest(prompt, stream)
        with self._cond:
            if len(self._wait) >= self.queue_depth:
                self._stats["rejected_total"] += 1
                raise AdmissionError(
                    f"serving queue full ({self.queue_depth} waiting) — "
                    "retry with backoff"
                )
            self._wait.append(req)
            self._cond.notify()
        return req

    def stats(self) -> dict:
        """Point-in-time counters + gauges for the scrape collector."""
        with self._cond:
            live = sum(1 for s in self._slots if s is not None)
            out = dict(self._stats)
            out.update(
                live_seqs=live,
                queue_depth=len(self._wait),
                kv_blocks_free=self.allocator.free_blocks,
                kv_blocks_used=self.allocator.used_blocks,
                kv_blocks_total=self.allocator.num_blocks,
            )
        return out

    def drain(self, timeout: float) -> bool:
        """Wait until no sequence is live or waiting (the swap-drain
        barrier). Returns False on timeout — callers journal and decide."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._cond:
                if not self._wait and all(
                    s is None for s in self._slots
                ):
                    return True
            time.sleep(0.005)
        with self._cond:
            return not self._wait and all(s is None for s in self._slots)

    def stop(self) -> None:
        """Stop the scheduler thread; in-flight sequences fail out."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
        err = RuntimeError("serving engine stopped")
        with self._cond:
            doomed = [s for s in self._slots if s is not None]
            doomed += list(self._wait)
            self._wait.clear()
            self._slots = [None] * self.max_seqs
        for r in doomed:
            if r.table is not None and not r.table.freed:
                self.allocator.free(r.table)
            r._finish(err)

    # -- scheduler thread -------------------------------------------------

    def _has_work(self) -> bool:
        return bool(self._wait) or any(
            s is not None for s in self._slots
        )

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._stop and not self._has_work():
                    self._cond.wait(timeout=0.1)
                if self._stop:
                    return
            self.tick()

    def tick(self) -> dict:
        """One scheduling step: admit → step → retire. Returns counts
        (the unit tests' observable). Scheduler-thread only."""
        with self._cond:
            live0 = sum(1 for s in self._slots if s is not None)
        with trace_lib.span("decode", rows=live0):
            t0w, t0p = time.time(), time.perf_counter()
            admitted = self._admit()
            self._step()
            evicted = self._retire()
            with self._cond:
                live = sum(1 for s in self._slots if s is not None)
            # The `step` child hvt-trace keys on: was this tick's time
            # scheduling churn (admitted/evicted) or steady compute?
            trace_lib.emit_span(
                "step", t0w, time.perf_counter() - t0p,
                admitted=admitted, evicted=evicted, live=live,
            )
        return {"admitted": admitted, "evicted": evicted, "live": live}

    def _admit(self) -> int:
        """Move waiting sequences into free slots, strict FIFO, as far
        as slots AND blocks allow; one prefill dispatch splices them in
        and delivers their first chunk (the TTFT edge)."""
        batch: list[SeqRequest] = []
        slots: list[int] = []
        with self._cond:
            free = [i for i, s in enumerate(self._slots) if s is None]
            while self._wait and free:
                head = self._wait[0]
                need = len(head.prompt) + self.decoder.max_new_tokens
                try:
                    head.table = self.allocator.reserve(need)
                except OutOfBlocksError:
                    break  # head waits for retirements; FIFO holds
                self._wait.popleft()
                head.slot = free.pop(0)
                batch.append(head)
                slots.append(head.slot)
                self._slots[head.slot] = head
        if not batch:
            return 0
        admission = self._admissions
        self._admissions += 1
        tokens, fresh = self.decoder.prefill(
            [r.prompt for r in batch], self.seed, admission
        )
        if self._state is None:
            # First admission: the fresh state IS the live state, but the
            # requests sit in fresh rows 0..n-1 — move them to their slots
            # through the same splice path (src != dst in general).
            self._state = fresh
            src_extra = list(range(len(batch)))
            if slots != src_extra:
                self._state = self.decoder.splice(
                    fresh, fresh, src_extra, slots
                )
        else:
            self._state = self.decoder.splice(
                self._state, fresh, list(range(len(batch))), slots
            )
        self._stats["prefill_calls_total"] += 1
        self._stats["device_calls_total"] += 1
        now = time.time()
        for i, r in enumerate(batch):
            trace_lib.emit_span(
                "queue_wait",
                now - (time.monotonic() - r.submitted),
                time.monotonic() - r.submitted,
                slot=r.slot,
            )
            r.chunks_done = 1
            r._deliver(self._trimmed(r, tokens[i].tolist()))
            self._stats["admitted_total"] += 1
        return len(batch)

    def _step(self) -> bool:
        """One cont dispatch advances every live row by one chunk."""
        with self._cond:
            live = [
                (i, s) for i, s in enumerate(self._slots) if s is not None
            ]
        if not live or self._state is None:
            return False
        tokens, self._state = self.decoder.step(self._state)
        self._stats["device_calls_total"] += 1
        for slot, r in live:
            r.chunks_done += 1
            r._deliver(self._trimmed(r, tokens[slot].tolist()))
        return True

    def _trimmed(self, r: SeqRequest, piece: list[int]) -> list[int]:
        """Cut the chunk at eos (host-side mirror of the device done
        flag) so clients only ever see real generation."""
        if r.eos_seen:
            return []
        eos = self.decoder.eos_id
        if eos is not None and eos in piece:
            r.eos_seen = True
            return piece[: piece.index(eos)]
        return piece

    def _retire(self) -> int:
        """Free finished rows — same tick they finish. Their KV blocks
        return to the allocator; next tick's _admit can reuse both."""
        retired = 0
        with self._cond:
            live = [
                (i, s) for i, s in enumerate(self._slots) if s is not None
            ]
        for slot, r in live:
            if r.eos_seen or r.chunks_done >= self.decoder.total_chunks:
                with self._cond:
                    self._slots[slot] = None
                self.allocator.free(r.table)
                self._stats["retired_total"] += 1
                r._finish()
                retired += 1
        return retired
