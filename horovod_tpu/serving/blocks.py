"""Paged KV-cache accounting: fixed-size token blocks + per-sequence tables.

The compiled decoders keep each sequence's K/V physically contiguous
([B, T0+new, H, D] per layer — static shapes are the deal with XLA), so
what pages here is the ADMISSION BUDGET, not the device layout: the
vLLM-style discipline that a sequence may only enter the batch when a
whole-lifetime block reservation (prompt + full generation budget,
rounded up to ``block_tokens``) fits the configured HBM budget, and that
retiring a sequence returns its exact blocks for immediate reuse. The
allocator is the one place serving capacity is decided — the engine
refuses admission (HTTP 429 once the wait queue is also full) instead of
letting the runtime OOM mid-decode, which on TPU takes the whole replica
down. The device-side paged attention kernel that would let these blocks
be physically scattered is the recorded enabler on ROADMAP item 5; this
module's table layout (sequence → ordered block ids) is already the one
that kernel consumes.

Sizing math (the README "Serving" walkthrough): one block holds
``block_tokens`` tokens of K/V for every layer, so a bundle serving
prompts up to T0 with N new tokens needs
``ceil((T0 + N) / block_tokens)`` blocks per sequence, and a budget of
``kv_blocks`` admits ``kv_blocks // that`` concurrent sequences.
"""

from __future__ import annotations

import threading


class OutOfBlocksError(RuntimeError):
    """The reservation does not fit the configured block budget."""


class BlockTable:
    """One sequence's ordered block ids — the unit `BlockAllocator.free`
    takes back. ``token_capacity`` is what the reservation covers; the
    table refuses to be freed twice (a double-free would let two live
    sequences alias one block's budget)."""

    __slots__ = ("block_ids", "block_tokens", "freed")

    def __init__(self, block_ids: list[int], block_tokens: int):
        self.block_ids = list(block_ids)
        self.block_tokens = block_tokens
        self.freed = False

    @property
    def num_blocks(self) -> int:
        return len(self.block_ids)

    @property
    def token_capacity(self) -> int:
        return len(self.block_ids) * self.block_tokens

    def __repr__(self) -> str:  # debugging/journal readability
        return (
            f"BlockTable(blocks={self.block_ids}, "
            f"capacity={self.token_capacity})"
        )


class BlockAllocator:
    """Free-list allocator over ``num_blocks`` KV blocks of
    ``block_tokens`` tokens each.

    ``reserve(n_tokens)`` hands out a `BlockTable` covering
    ``ceil(n_tokens / block_tokens)`` blocks or raises
    `OutOfBlocksError` — the caller (the engine's admission step) queues
    the sequence and retries as retirements free blocks. A reservation
    larger than the WHOLE budget can never succeed and raises
    ``ValueError`` immediately so the request 400s instead of queueing
    forever. Thread-safe: handler threads reserve, the scheduler thread
    frees.
    """

    def __init__(self, num_blocks: int, block_tokens: int):
        if num_blocks < 1 or block_tokens < 1:
            raise ValueError(
                f"num_blocks ({num_blocks}) and block_tokens "
                f"({block_tokens}) must be >= 1"
            )
        self.num_blocks = num_blocks
        self.block_tokens = block_tokens
        self._lock = threading.Lock()
        # LIFO free list: a just-retired sequence's blocks are the
        # warmest candidates for the next admission.
        self._free = list(range(num_blocks - 1, -1, -1))

    def blocks_for(self, n_tokens: int) -> int:
        if n_tokens < 1:
            raise ValueError(f"n_tokens must be >= 1, got {n_tokens}")
        return -(-n_tokens // self.block_tokens)

    @property
    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.num_blocks - self.free_blocks

    def can_reserve(self, n_tokens: int) -> bool:
        return self.blocks_for(n_tokens) <= self.free_blocks

    def reserve(self, n_tokens: int) -> BlockTable:
        need = self.blocks_for(n_tokens)
        if need > self.num_blocks:
            raise ValueError(
                f"a {n_tokens}-token sequence needs {need} KV blocks but "
                f"the whole budget is {self.num_blocks} "
                f"(block_tokens={self.block_tokens}) — raise "
                "HVT_SERVE_KV_BLOCKS or shorten the request"
            )
        with self._lock:
            if need > len(self._free):
                raise OutOfBlocksError(
                    f"need {need} KV blocks, {len(self._free)} free "
                    f"(budget {self.num_blocks})"
                )
            ids = [self._free.pop() for _ in range(need)]
        return BlockTable(ids, self.block_tokens)

    def free(self, table: BlockTable) -> None:
        with self._lock:
            if table.freed:
                raise ValueError(
                    f"double free of {table!r} — a freed table's blocks "
                    "may already back another sequence"
                )
            table.freed = True
            self._free.extend(reversed(table.block_ids))
            if len(self._free) > self.num_blocks:
                raise AssertionError(
                    "free list larger than the budget — a table was "
                    "freed that this allocator never handed out"
                )
