"""Trainer.build — parameter/optimizer-state initialization and layout.

Split out of trainer.py (round 5): lazy Keras-style build from the first
batch, module-loss label synthesis, TP/FSDP param placement from
param_specs, optimizer-mirror shardings, and the ZeRO-1 (shard_update)
opt-state layout. One entry point: `build_state(trainer, sample_x,
sample_y)` — the body of ``Trainer.build``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from horovod_tpu.parallel import collectives
from horovod_tpu.parallel import mesh as mesh_lib
from horovod_tpu.parallel import sharding as sharding_lib
from horovod_tpu.training.train_state import (
    TrainState,
    _aggregate_sown_metrics,
    _param_shaped_matcher,
)


def build_state(trainer, sample_x: np.ndarray, sample_y=None) -> TrainState:
    """Initialize parameters (lazy, from the first batch — like Keras
    building on first fit).

    With ``loss='module'`` the init passes labels so the module traces
    its fused-loss branch (see below): ``sample_y`` when given, else
    labels synthesized as ``zeros_like(sample_x)`` — valid for the LM
    family, where labels share the token batch's shape/dtype. Models
    whose labels differ from their inputs in dtype/shape/structure must
    pass ``sample_y`` (``fit`` always does)."""
    if trainer.state is not None:
        return trainer.state
    rng = jax.random.PRNGKey(trainer.seed)
    init_rng, dropout_rng, state_rng = jax.random.split(rng, 3)
    # Init batch sized to the data-parallel degree: models that carry
    # internal sharding constraints need the batch dim divisible by it.
    # Leaf-wise so pytree (dict-input) samples build like flat ones.
    n = trainer.dp_size

    def size_to_dp(a):
        a = np.asarray(a)
        if len(a) < n:
            a = np.concatenate([a] * (-(-n // len(a))))
        return jnp.asarray(a[:n])

    sized_x = jax.tree.map(size_to_dp, sample_x)
    # loss='module' contract: init with labels so the module traces its
    # fused-loss branch — otherwise build() materializes the dense
    # [B, T, vocab] logits that the fused head exists to avoid, making
    # init the OOM point at long-context scale even though train/eval
    # steps are fused. Real labels when the caller has them; the
    # zeros_like fallback matches the LM family's labels-share-the-
    # token-batch contract (models/transformer.py `__call__`).
    init_kwargs = {}
    synthesized_labels = False
    if trainer._module_loss:
        if sample_y is not None:
            init_kwargs["labels"] = jax.tree.map(size_to_dp, sample_y)
        else:
            init_kwargs["labels"] = jax.tree.map(jnp.zeros_like, sized_x)
            synthesized_labels = True
    try:
        variables = trainer.module.init(
            {"params": init_rng, "dropout": dropout_rng},
            sized_x,
            train=False,
            **init_kwargs,
        )
    except Exception as e:
        if synthesized_labels:
            # The zeros_like fallback assumes LM-style labels (same
            # shape/dtype as the token batch). For any other module the
            # trace fails opaquely deep inside init — name the fix.
            # Mutating args (not re-wrapping) keeps the exception type
            # even for types with non-string constructors.
            hint = (
                "\n\nhorovod_tpu hint: build() was called with "
                "loss='module' and no sample_y, so labels were "
                "synthesized as zeros_like(sample_x) (the LM-family "
                "contract). If this module's labels differ from its "
                "inputs in shape/dtype, pass sample_y to build() — "
                "fit() does this automatically."
            )
            head = str(e.args[0]) if e.args else str(e)
            e.args = (head + hint,) + tuple(e.args[1:])
        raise
    params = variables["params"]
    # Sown per-apply channels never persist in the carried state: values
    # are produced fresh each step ('losses' → objective, 'metrics' →
    # observability). Their presence at init DOES reveal the metric
    # names, which sizes the epoch accumulator — which is why 'metrics'
    # sows must be UNCONDITIONAL (not train-gated): a name that appears
    # only at train time couldn't be discovered here, and the step
    # checks for that drift loudly (see train_step).
    trainer._metric_names = tuple(
        sorted(_aggregate_sown_metrics(variables.get("metrics", {})))
    )
    reserved = {"loss", "accuracy"} & set(trainer._metric_names)
    if reserved:
        raise ValueError(
            f"module sows 'metrics' entries named {sorted(reserved)}, "
            "which would silently overwrite the Trainer's own "
            "loss/accuracy in every log and sink — rename the sow"
        )
    model_state = {
        k: v
        for k, v in variables.items()
        if k not in ("params", "losses", "metrics")
    }
    trainer._mutable = sorted(model_state.keys())
    if trainer.param_specs is not None:
        specs = (
            trainer.param_specs(params, trainer.mesh)
            if callable(trainer.param_specs)
            else trainer.param_specs
        )
        trainer._param_shardings = jax.tree.map(
            lambda s: jax.sharding.NamedSharding(trainer.mesh, s),
            specs,
            is_leaf=lambda s: isinstance(s, jax.sharding.PartitionSpec),
        )
        params = jax.device_put(params, trainer._param_shardings)
        # Optimizer mirrors (momenta etc.) must carry the param layout.
        # Sharding propagation can't deliver it — `init` is zeros_like,
        # which reads only shapes, so XLA sees an input-free computation —
        # hence explicit out_shardings: any opt-state subtree that is
        # param-shaped gets the param shardings, the rest replicate.
        rep = sharding_lib.replicated(trainer.mesh)
        param_shaped = _param_shaped_matcher(params)
        opt_shardings = jax.tree.map(
            lambda sub: trainer._param_shardings if param_shaped(sub) else rep,
            jax.eval_shape(trainer.tx.init, params),
            is_leaf=param_shaped,
        )
        opt_state = jax.jit(trainer.tx.init, out_shardings=opt_shardings)(params)
        state = TrainState(
            step=jax.device_put(jnp.zeros((), jnp.int32), rep),
            params=params,
            opt_state=opt_state,
            rng=jax.device_put(state_rng, rep),
            model_state=sharding_lib.replicate(model_state, trainer.mesh)
            if model_state
            else None,
        )
        trainer.state = state
    elif (
        trainer.shard_update
        and trainer.mesh.shape.get(mesh_lib.DATA_AXIS, 1) > 1
    ):
        # ZeRO-1 (arXiv:2004.13336): replicated params, optimizer state
        # sharded over the data axis at each leaf's first dp-divisible
        # dim — `collectives.zero1_shard_dim`, the SAME rule the
        # scatter-mode boundary reduction derives its bucket layout from
        # (reduce_gradients(scatter=dp)), so the reduced gradient slices
        # land exactly on these mirrors — and, with the leaf-aligned
        # buckets, land bucket-by-bucket: each mirror's update is
        # schedulable as soon as the bucket carrying its leaf arrives,
        # the fused per-shard apply the trainer's zero1 pin compiles.
        # Leaves with NO dp-divisible dim keep replicated mirrors; the
        # scatter path pads them onto the same buckets and all-gathers
        # just their columns back. On the implicit (K=1, uncompressed)
        # path the jitted step still compiles the paper's transformation
        # purely from these init shardings.
        dp = trainer.mesh.shape[mesh_lib.DATA_AXIS]
        rep = sharding_lib.replicated(trainer.mesh)
        param_shaped = _param_shaped_matcher(params)

        def zero1(shape):
            return jax.sharding.NamedSharding(
                trainer.mesh, collectives.zero1_partition_spec(shape, dp)
            )

        def mirror_shardings(shapes):
            return jax.tree.map(
                lambda sub: jax.tree.map(lambda l: zero1(l.shape), sub)
                if param_shaped(sub)
                else rep,
                shapes,
                is_leaf=param_shaped,
            )

        shapes = jax.eval_shape(trainer.tx.init, params)
        if getattr(trainer, "_ef", False):
            # Quantized-wire error feedback composed with ZeRO-1: the
            # residual is PER-SHARD state ([n_shards, *param], dim-0 over
            # the data axes — the same placement as the replicated-layout
            # EF branch below, and the one n_shards-x-model-sized leaf
            # that must never materialize dense); the wrapped inner
            # state takes the zero1 mirrors.
            shard0 = jax.sharding.NamedSharding(
                trainer.mesh,
                jax.sharding.PartitionSpec(
                    (mesh_lib.DATA_AXIS, mesh_lib.FSDP_AXIS)
                ),
            )
            opt_shardings = shapes.replace(
                ef_residual=jax.tree.map(
                    lambda _: shard0, shapes.ef_residual
                ),
                inner=mirror_shardings(shapes.inner),
            )
        else:
            opt_shardings = mirror_shardings(shapes)
        params = jax.device_put(params, rep)
        opt_state = jax.jit(trainer.tx.init, out_shardings=opt_shardings)(
            params
        )
        state = TrainState(
            step=jax.device_put(jnp.zeros((), jnp.int32), rep),
            params=params,
            opt_state=opt_state,
            rng=jax.device_put(state_rng, rep),
            model_state=sharding_lib.replicate(model_state, trainer.mesh)
            if model_state
            else None,
        )
        trainer.state = state
    else:
        if getattr(trainer, "_ef", False):
            # The error-feedback residual is PER-SHARD state, not a
            # replica: its leading axis is the shard axis, placed over the
            # data axes so each shard owns exactly its own remainder row.
            # It is also the one n_shards-x-model-sized leaf in the state,
            # so it must NEVER materialize dense: init the opt state under
            # jit with the residual's out_sharding set — XLA writes each
            # device's rows only — and keep it out of replicate() below
            # (which would stage full copies on every device).
            rep = sharding_lib.replicated(trainer.mesh)
            shard0 = jax.sharding.NamedSharding(
                trainer.mesh,
                jax.sharding.PartitionSpec(
                    (mesh_lib.DATA_AXIS, mesh_lib.FSDP_AXIS)
                ),
            )
            shapes = jax.eval_shape(trainer.tx.init, params)
            out_sh = jax.tree.map(lambda _: rep, shapes)
            out_sh = out_sh.replace(
                ef_residual=jax.tree.map(
                    lambda _: shard0, shapes.ef_residual
                )
            )
            opt_state = jax.jit(trainer.tx.init, out_shardings=out_sh)(
                params
            )
            res = opt_state.ef_residual
            opt_state = opt_state.replace(ef_residual=None)
        else:
            opt_state, res = trainer.tx.init(params), None
        state = TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=opt_state,
            rng=state_rng,
            model_state=model_state or None,
        )
        state = sharding_lib.replicate(state, trainer.mesh)
        if res is not None:
            state = state.replace(
                opt_state=state.opt_state.replace(ef_residual=res)
            )
        trainer.state = state
    return trainer.state
