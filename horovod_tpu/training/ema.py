"""Exponential moving average of the parameters — the EMA callback.

Split out of callbacks.py (round 5); `ExponentialMovingAverage` is
re-exported there, so ``hvt.callbacks.ExponentialMovingAverage`` is
unchanged. See the class docstring for semantics (device-resident shadow,
zero-debias, layout-following durability through the single-file or
sharded checkpoint formats).
"""

from __future__ import annotations

import os
import re
import shutil

import jax
import numpy as np

from horovod_tpu import runtime
from horovod_tpu.parallel import collectives
from horovod_tpu.training.callbacks import Callback


class ExponentialMovingAverage(Callback):
    """Polyak/EMA weight averaging — evaluate and export with a smoothed
    copy of the parameters (beyond-parity; the standard large-batch
    companion to the LR-scaling recipe the reference uses).

    After every train-step execution: ``ema ← decay·ema + (1−decay)·params``
    as one jitted donated update, so the shadow copy lives on device and
    costs one fused elementwise pass per execution — no host traffic.
    Granularity follows the fit path: per step on the streamed path, per
    `steps_per_execution` chunk, per EPOCH on ``cache='device'`` (where
    on_batch_end fires once per epoch) — pick ``decay`` for the cadence.

    ``zero_debias=True`` applies the Adam-style correction
    ``ema / (1 − decay^t)`` when reading (`ema_params`), so early reads are
    unbiased even though the shadow starts at zero. Default starts the
    shadow AT the initial params (no bias, no correction needed).

    Read access: ``ema_params`` (debiased), or the ``averaged(trainer)``
    context manager which swaps the EMA weights into ``trainer.state`` for
    an eval/export block and restores the live weights after:

        with ema.averaged(trainer):
            trainer.evaluate(x_test, y_test)

    Durability: pass ``checkpoint_dir`` to persist the shadow alongside the
    model checkpoints and restore it on the next fit() — without this, a
    preemption/restart resumes the MODEL from its checkpoint but would
    silently restart the shadow from the restored weights, quietly
    discarding the accumulated average. The format follows the shadow's
    layout, mirroring ModelCheckpoint's discipline: replicated/single-host
    shadows are a primary-written atomic ``ema.msgpack``; shadows sharded
    ACROSS processes (multi-host TP/FSDP/pipe — the shadow always carries
    the params' shardings) use the sharded directory format
    (``ema.shards/``, every process writes its shard, restored with
    ``reshard=True`` so a topology change between runs still resumes the
    average).
    """

    def __init__(self, decay: float = 0.999, zero_debias: bool = False,
                 checkpoint_dir: str | None = None):
        if not 0.0 < decay < 1.0:
            raise ValueError(f"decay must be in (0, 1), got {decay}")
        self.decay = decay
        self.zero_debias = zero_debias
        self.checkpoint_dir = checkpoint_dir
        self._ema = None
        self._count = 0
        self._pending = None
        self._update = jax.jit(
            lambda e, p: jax.tree.map(
                lambda a, b: self.decay * a + (1.0 - self.decay) * b, e, p
            ),
            donate_argnums=(0,),
        )

    def _ckpt_path(self) -> str:
        return os.path.join(self.checkpoint_dir, "ema.msgpack")

    def _sharded_path(self, epoch: int) -> str:
        # Per-epoch directories (ModelCheckpoint's discipline): an
        # in-place multi-writer update of one directory could mix epochs
        # across processes after a mid-write crash and still LOOK
        # complete; per-epoch dirs + newest-complete discovery make torn
        # writes harmless. Old dirs are pruned as training advances.
        return os.path.join(self.checkpoint_dir, f"ema-{epoch}.shards")

    _SHARDED_RE = re.compile(r"ema-(\d+)\.shards$")

    def _newest_complete_shards(self) -> str | None:
        from horovod_tpu import checkpoint

        best = None
        try:
            names = os.listdir(self.checkpoint_dir)
        except OSError:
            return None
        for name in names:
            m = self._SHARDED_RE.match(name)
            if not m:
                continue
            path = os.path.join(self.checkpoint_dir, name)
            if checkpoint._sharded_complete(path):
                if best is None or int(m.group(1)) > best[0]:
                    best = (int(m.group(1)), path)
        return best[1] if best else None

    def _restore_sharded_shadow(self, path: str, params):
        """Resume the shadow from the sharded directory format: every
        process reads (restore_sharded is process-local file reads, no
        collectives), ``reshard=True`` so a checkpoint saved under a
        different topology/layout still resumes, and the restored leaves
        land directly on the params' shardings (the template)."""
        from horovod_tpu import checkpoint

        try:
            payload = checkpoint.restore_sharded(
                path, {"shadow": params, "count": 0}, reshard=True,
            )
        except Exception as e:
            raise RuntimeError(
                f"EMA shadow restore failed ({path}): "
                f"{type(e).__name__}: {e} — delete the directory to "
                "restart the average"
            ) from e
        self._ema = payload["shadow"]
        self._count = int(payload["count"])

    def on_train_begin(self, logs=None):
        params = self.trainer.state.params
        if self._ema is None and self.checkpoint_dir is not None:
            from horovod_tpu import checkpoint

            # The PRIMARY's view of the directory decides (checkpoint_dir
            # may be a host-local path on a pod) and the outcome is
            # broadcast so every process takes the same branch —
            # mirroring restore_latest_and_broadcast's discipline. Either
            # persisted format resumes, whatever today's layout is: the
            # sharded directory restores with reshard=True, the single
            # file restores on the primary and broadcasts.
            found = "none"
            if runtime.is_primary():
                shards = self._newest_complete_shards()
                if shards is not None:
                    found = shards
                elif os.path.exists(self._ckpt_path()):
                    found = "file"
            if jax.process_count() > 1:
                found = collectives.broadcast_object(found)
            if found not in ("none", "file"):
                self._restore_sharded_shadow(found, params)
            elif found == "file":
                count = 0
                err = None
                if runtime.is_primary():
                    try:
                        payload = checkpoint.restore(
                            self._ckpt_path(), {"shadow": params, "count": 0}
                        )
                        shadow = jax.tree.map(np.asarray, payload["shadow"])
                        count = int(payload["count"])
                    except Exception as e:  # stale/incompatible file
                        err = f"{type(e).__name__}: {e}"
                        shadow = None
                else:
                    shadow = jax.tree.map(
                        lambda l: np.zeros(l.shape, l.dtype), params
                    )
                if jax.process_count() > 1:
                    # The primary's restore outcome travels BEFORE the
                    # pytree broadcast, so a failed restore raises on EVERY
                    # rank together instead of stranding the others in the
                    # collective (restore_latest_and_broadcast's torn-flag
                    # discipline).
                    err = collectives.broadcast_object(err)
                if err is not None:
                    raise RuntimeError(
                        f"EMA shadow restore failed ({self._ckpt_path()}): "
                        f"{err} — delete the file to restart the average"
                    )
                if jax.process_count() > 1:
                    # ORDER MATTERS: broadcast on the HOST first so every
                    # process holds identical values, THEN device_put — a
                    # device_put onto a cross-process sharding is itself a
                    # collective (it verifies value equality across
                    # processes), so placing divergent pre-broadcast values
                    # would fail, and any asymmetry between the primary's
                    # and the others' paths here deadlocks the fleet.
                    shadow = collectives.broadcast_pytree(shadow)
                    count = int(collectives.broadcast_object(count))
                # The shadow must carry the params' shardings: a bare
                # device_put would commit it to one device and the next
                # donated _update would see incompatible placements.
                self._ema = jax.tree.map(
                    lambda t, p: jax.device_put(
                        t, p.sharding if isinstance(p, jax.Array) else None
                    ),
                    shadow, params,
                )
                self._count = count
        if self._ema is None:
            self._ema = (
                jax.tree.map(jax.numpy.zeros_like, params)
                if self.zero_debias
                else jax.tree.map(lambda a: a + 0, params)  # device copy
            )
            self._count = 0

    def on_batch_end(self, batch: int, logs=None):
        self._ema = self._update(self._ema, self.trainer.state.params)
        self._count += 1

    def on_epoch_end(self, epoch: int, logs=None):
        if self.checkpoint_dir is None:
            return
        from horovod_tpu import checkpoint

        # Format follows the shadow's layout (ModelCheckpoint's rule):
        # cross-process sharded shadows (the shadow carries the params'
        # shardings) write the sharded directory from EVERY process;
        # otherwise the primary writes the single file. Async with at most
        # one write in flight either way: the fetch + serialization run
        # off-thread instead of stalling every epoch boundary.
        payload = {"shadow": self._ema, "count": self._count}
        if checkpoint.is_cross_process_sharded(self._ema):
            if self._pending is not None:
                self._pending.join()
            # Prune superseded epoch dirs (primary; lockstep SPMD epochs
            # bound writer skew to the previous epoch, which the join
            # above already finished for THIS process).
            if runtime.is_primary():
                import shutil

                for name in os.listdir(self.checkpoint_dir):
                    m = self._SHARDED_RE.match(name)
                    if m and int(m.group(1)) < epoch - 1:
                        shutil.rmtree(
                            os.path.join(self.checkpoint_dir, name),
                            ignore_errors=True,
                        )
            self._pending = checkpoint.save_sharded_async(
                self._sharded_path(epoch), payload
            )
        elif runtime.is_primary():
            if self._pending is not None:
                self._pending.join()
            self._pending = checkpoint.save_async(self._ckpt_path(), payload)

    def on_train_end(self, logs=None):
        if self._pending is not None:
            self._pending.join()
            self._pending = None

    @property
    def ema_params(self):
        if self._ema is None:
            raise RuntimeError("EMA not initialized — runs at fit()")
        if self.zero_debias and self._count > 0:
            corr = 1.0 - self.decay ** self._count
            return jax.tree.map(lambda a: a / corr, self._ema)
        # Fresh buffers, never the live shadow: the next update DONATES the
        # shadow's buffers, so a returned reference would turn into a
        # deleted jax.Array if training continues (e.g. a second fit() with
        # this callback, or reading mid-training).
        return jax.tree.map(lambda a: a + 0, self._ema)

    def averaged(self, trainer=None):
        """Context manager: trainer.state carries the EMA weights inside
        the block, the live weights after."""
        import contextlib

        trainer = trainer or self.trainer

        @contextlib.contextmanager
        def swap():
            live = trainer.state.params
            trainer.state = trainer.state.replace(params=self.ema_params)
            try:
                yield
            finally:
                trainer.state = trainer.state.replace(params=live)

        return swap()

