"""Callback protocol — parity surface for ``hvd.callbacks.*`` + Keras I/O.

The four callbacks the reference exercises (SURVEY.md §2.4 rows 4-6 and the
rank-0 I/O pair, tensorflow2_keras_mnist.py:67-92):

* BroadcastGlobalVariablesCallback(0) — consistent init / restored-checkpoint
  sync from the root worker.
* MetricAverageCallback — epoch-end cross-worker metric mean; must run
  before metric-consuming callbacks (ordering note at
  tensorflow2_keras_mnist.py:75-76 — preserved here because callbacks run in
  list order).
* LearningRateWarmupCallback — ramp lr from base to base×size over the first
  warmup epochs (Goyal et al. 1706.02677, cited at
  tensorflow2_keras_mnist.py:81).
* ModelCheckpoint / ScalarLogger — rank-0-only per-epoch checkpoints and
  scalar logs ("save only on worker 0 to prevent other workers from
  corrupting them", tensorflow2_keras_mnist.py:85).
"""

from __future__ import annotations

import json
import os
import time

import jax

from horovod_tpu import runtime
from horovod_tpu.parallel import collectives, sharding


class Callback:
    """Base callback; hooks mirror the Keras/Horovod set the reference uses."""

    trainer = None

    def set_trainer(self, trainer):
        self.trainer = trainer

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch: int, logs=None):
        pass

    def on_epoch_end(self, epoch: int, logs=None):
        pass

    def on_batch_end(self, batch: int, logs=None):
        pass


class BroadcastGlobalVariablesCallback(Callback):
    """Broadcast the full TrainState (params AND optimizer state — the
    reference's 'global variables' include optimizer slots, SURVEY.md §7.3)
    from the root process at train begin.

    Needed when training starts from random weights or a restored checkpoint
    (comment parity: tensorflow2_keras_mnist.py:68-70). Within one process
    SPMD replication already guarantees identical values on every chip; the
    broadcast is the cross-process sync."""

    def __init__(self, root_rank: int = 0):
        if root_rank != 0:
            raise NotImplementedError("root_rank=0 only (matches the reference)")
        self.root_rank = root_rank

    def on_train_begin(self, logs=None):
        if jax.process_count() == 1:
            return
        state = collectives.broadcast_pytree(jax.device_get(self.trainer.state))
        self.trainer.state = sharding.replicate(state, self.trainer.mesh)


class MetricAverageCallback(Callback):
    """Epoch-end cross-worker mean of logged metrics
    (tensorflow2_keras_mnist.py:73-77).

    Under SPMD jit, step metrics are already computed over the *global*
    batch, so device metrics are identical on every process — this callback
    additionally averages host-side entries (e.g. epoch_time_s) and is the
    documented extension point for non-SPMD metrics. Keep it ahead of
    metric-consuming callbacks in the list, as the reference requires."""

    def on_epoch_end(self, epoch: int, logs=None):
        if logs is None or jax.process_count() == 1:
            return
        logs.update(collectives.metric_mean(logs))


class LearningRateWarmupCallback(Callback):
    """Ramp the effective LR from ``base`` to ``base × world_size`` over the
    first ``warmup_epochs`` epochs (tensorflow2_keras_mnist.py:78-82).

    The optimizer is constructed with the *scaled* LR (``scale_lr(base)``,
    reference line :55); this callback multiplies the update by
    s(e) ∈ [1/size, 1], so epoch 0 starts at the base LR and the ramp ends at
    the scaled LR — the exact semantics of Horovod's warmup callback at
    epoch granularity."""

    def __init__(self, warmup_epochs: int = 3, world_size: int | None = None, verbose: int = 0):
        self.warmup_epochs = warmup_epochs
        self.world_size = world_size
        self.verbose = verbose

    def on_epoch_begin(self, epoch: int, logs=None):
        size = self.world_size or runtime.size()
        if epoch >= self.warmup_epochs or size == 1:
            scale = 1.0
        else:
            frac = epoch / self.warmup_epochs
            scale = (1.0 + frac * (size - 1)) / size
        self.trainer.update_scale = scale
        if self.verbose and runtime.is_primary() and epoch <= self.warmup_epochs:
            print(f"LearningRateWarmup: epoch {epoch} lr scale {scale:.4f}")


class ModelCheckpoint(Callback):
    """Per-epoch full-state checkpoint, written by the primary process only
    (tensorflow2_keras_mnist.py:86-88; single-writer discipline §5.2).

    ``filepath`` may contain ``{epoch}`` like Keras's
    ``'checkpoint-{epoch}.h5'`` template; the payload is always msgpack
    regardless of extension, and resume discovery
    (`checkpoint.latest_checkpoint`) accepts any extension."""

    def __init__(self, filepath: str):
        self.filepath = filepath

    def on_epoch_end(self, epoch: int, logs=None):
        if not runtime.is_primary():
            return
        from horovod_tpu import checkpoint

        path = self.filepath.format(epoch=epoch + 1)
        checkpoint.save(path, self.trainer.state)


class ScalarLogger(Callback):
    """Rank-0 scalar event log (TensorBoard-role observability, §5.1).

    Writes JSONL events (one line per scalar) compatible with simple
    dashboards; per-batch or per-epoch frequency mirrors
    ``TensorBoard(update_freq='batch')`` (tensorflow2_keras_mnist.py:89).
    ``log_every`` thins batch records (1 = every batch); epoch records are
    always written."""

    def __init__(self, log_dir: str, update_freq: str = "epoch", log_every: int = 1):
        self.log_dir = log_dir
        self.update_freq = update_freq
        self.log_every = max(1, log_every)
        self._fh = None
        self._step = 0

    def _writer(self):
        if self._fh is None:
            os.makedirs(self.log_dir, exist_ok=True)
            self._fh = open(os.path.join(self.log_dir, "events.jsonl"), "a")
        return self._fh

    def _emit(self, tag_prefix: str, logs: dict, step: int):
        if not runtime.is_primary() or not logs:
            return
        record = {"wall_time": time.time(), "step": step}
        for k, v in logs.items():
            try:
                record[f"{tag_prefix}{k}"] = float(v)
            except (TypeError, ValueError):
                continue
        fh = self._writer()
        fh.write(json.dumps(record) + "\n")
        fh.flush()

    def on_batch_end(self, batch: int, logs=None):
        self._step += 1
        if self.update_freq == "batch" and self._step % self.log_every == 0:
            self._emit("batch/", jax.device_get(logs) if logs else {}, self._step)

    def on_epoch_end(self, epoch: int, logs=None):
        self._emit("epoch/", logs or {}, epoch + 1)

    def on_train_end(self, logs=None):
        if self._fh:
            self._fh.close()
            self._fh = None


# Keras-name alias: the reference registers this under TensorBoard.
TensorBoard = ScalarLogger
