"""Callback protocol — parity surface for ``hvd.callbacks.*`` + Keras I/O.

The four callbacks the reference exercises (SURVEY.md §2.4 rows 4-6 and the
rank-0 I/O pair, tensorflow2_keras_mnist.py:67-92):

* BroadcastGlobalVariablesCallback(0) — consistent init / restored-checkpoint
  sync from the root worker.
* MetricAverageCallback — epoch-end cross-worker metric mean; must run
  before metric-consuming callbacks (ordering note at
  tensorflow2_keras_mnist.py:75-76 — preserved here because callbacks run in
  list order).
* LearningRateWarmupCallback — ramp lr from base to base×size over the first
  warmup epochs (Goyal et al. 1706.02677, cited at
  tensorflow2_keras_mnist.py:81).
* ModelCheckpoint / ScalarLogger — rank-0-only per-epoch checkpoints and
  scalar logs ("save only on worker 0 to prevent other workers from
  corrupting them", tensorflow2_keras_mnist.py:85).
"""

from __future__ import annotations

import json
import os
import re
import signal
import time

import jax
import numpy as np

from horovod_tpu import runtime
from horovod_tpu.analysis import registry
from horovod_tpu.parallel import collectives, sharding


class Callback:
    """Base callback; hooks mirror the Keras/Horovod set the reference uses."""

    trainer = None

    def set_trainer(self, trainer):
        self.trainer = trainer

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch: int, logs=None):
        pass

    def on_epoch_end(self, epoch: int, logs=None):
        pass

    def on_batch_end(self, batch: int, logs=None):
        pass


def agree_any(flag: bool) -> bool:
    """Cross-process agreement on a local boolean: True on ANY process →
    True on EVERY process. Entered by every process at the same point (it
    is a collective), so the whole fleet takes the same branch regardless
    of which processes observed the local condition — the pattern behind
    `PreemptionCheckpointCallback`'s signal agreement and the elastic
    membership agreement (`horovod_tpu.elastic.ElasticStateCallback`)."""
    if jax.process_count() == 1:
        return bool(flag)
    return any(collectives.allgather_object(bool(flag)))


class BroadcastGlobalVariablesCallback(Callback):
    """Broadcast the full TrainState (params AND optimizer state — the
    reference's 'global variables' include optimizer slots, SURVEY.md §7.3)
    from the root process at train begin.

    Needed when training starts from random weights or a restored checkpoint
    (comment parity: tensorflow2_keras_mnist.py:68-70). Within one process
    SPMD replication already guarantees identical values on every chip; the
    broadcast is the cross-process sync."""

    def __init__(self, root_rank: int = 0):
        self.root_rank = root_rank

    def on_train_begin(self, logs=None):
        if jax.process_count() == 1:
            return
        from horovod_tpu import checkpoint

        # Leaf-wise with each leaf keeping its own sharding: replicated
        # leaves (the reference's DP state) sync from the root; leaves
        # sharded ACROSS processes (pipe/TP stages) are left in place — they
        # cannot be host-gathered and were materialized identically on every
        # process by the deterministic SPMD init (checkpoint._host_syncable).
        self.trainer.state = checkpoint.broadcast_parameters(
            self.trainer.state, self.root_rank
        )


class MetricAverageCallback(Callback):
    """Epoch-end cross-worker mean of logged metrics
    (tensorflow2_keras_mnist.py:73-77).

    Under SPMD jit, step metrics are already computed over the *global*
    batch, so device metrics are identical on every process — this callback
    additionally averages host-side entries (e.g. epoch_time_s) and is the
    documented extension point for non-SPMD metrics. Keep it ahead of
    metric-consuming callbacks in the list, as the reference requires."""

    def on_epoch_end(self, epoch: int, logs=None):
        if logs is None or jax.process_count() == 1:
            return
        logs.update(collectives.metric_mean(logs))


class LearningRateWarmupCallback(Callback):
    """Ramp the effective LR from ``base`` to ``base × world_size`` over the
    first ``warmup_epochs`` epochs (tensorflow2_keras_mnist.py:78-82).

    The optimizer is constructed with the *scaled* LR (``scale_lr(base)``,
    reference line :55); this callback multiplies the update by
    s(e) ∈ [1/size, 1], so epoch 0 starts at the base LR and the ramp ends at
    the scaled LR — the exact semantics of Horovod's warmup callback at
    epoch granularity."""

    def __init__(self, warmup_epochs: int = 3, world_size: int | None = None, verbose: int = 0):
        self.warmup_epochs = warmup_epochs
        self.world_size = world_size
        self.verbose = verbose

    def on_epoch_begin(self, epoch: int, logs=None):
        size = self.world_size or runtime.size()
        if epoch >= self.warmup_epochs or size == 1:
            scale = 1.0
        else:
            frac = epoch / self.warmup_epochs
            scale = (1.0 + frac * (size - 1)) / size
        self.trainer.update_scale = scale
        if self.verbose and runtime.is_primary() and epoch <= self.warmup_epochs:
            print(f"LearningRateWarmup: epoch {epoch} lr scale {scale:.4f}")


class LearningRateScheduleCallback(Callback):
    """Scale the effective LR by ``multiplier`` within an epoch range —
    the ``hvd.callbacks.LearningRateScheduleCallback`` surface (present in
    Horovod 0.18.1 alongside the warmup callback, which subclasses it there;
    the reference scripts use only the warmup form).

    ``multiplier``: a float, or a callable ``epoch -> float`` (evaluated at
    epoch granularity — the reference stack never drives sub-epoch
    schedules). Outside ``[start_epoch, end_epoch)`` the callback leaves the
    scale untouched.

    Composition: MULTIPLIES into ``trainer.update_scale`` (which the Trainer
    resets to 1.0 each epoch), so Horovod's documented stacking — a warmup
    callback followed by schedule callbacks with later ``start_epoch`` —
    composes in callback-list order. Horovod's ``momentum_correction`` knob
    has no analogue here by construction: the scale multiplies the
    optimizer's *update* (not a stored lr hyperparameter), which is exactly
    the corrected behavior for momentum methods."""

    def __init__(
        self,
        multiplier,
        start_epoch: int = 0,
        end_epoch: int | None = None,
        verbose: int = 0,
    ):
        self.multiplier = multiplier
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.verbose = verbose

    def on_epoch_begin(self, epoch: int, logs=None):
        if epoch < self.start_epoch:
            return
        if self.end_epoch is not None and epoch >= self.end_epoch:
            return
        m = self.multiplier(epoch) if callable(self.multiplier) else self.multiplier
        self.trainer.update_scale *= float(m)
        if self.verbose and runtime.is_primary():
            print(
                f"LearningRateSchedule: epoch {epoch} "
                f"lr scale {self.trainer.update_scale:.4f}"
            )


def save_state(filepath_template: str, epoch: int, state, *,
               async_save: bool = False, pending=None, step: int = 0,
               cursor: dict | None = None):
    """One TrainState save with the checkpoint ROUTING shared by
    `ModelCheckpoint` and `PreemptionCheckpointCallback`: single-file
    (primary-writer-only) for host-syncable state, the sharded directory
    format when state is cross-process sharded (every process writes its
    shard). Returns the async handle when ``async_save`` (after joining
    ``pending``), else None.

    ``step`` selects the boundary the save represents. ``step == 0``
    (default): the END of 0-based epoch ``epoch`` — file
    ``checkpoint-{epoch+1}``, manifest ``(epoch+1, 0)``, the historical
    behavior. ``step > 0``: a MID-epoch save DURING epoch ``epoch`` after
    ``step`` optimizer steps — the file is ``checkpoint-{epoch}`` (it
    monotonically advances the previous boundary's artifact; atomic
    replace, strictly newer progress) and the manifest records
    ``(epoch, step)``, so a relaunch resumes at the committed step
    instead of replaying the epoch. Mid-epoch saves require host-syncable
    (single-file) state: the sharded directory format cannot overwrite
    in place without a torn-mix window across processes."""
    from horovod_tpu import checkpoint

    sharded = checkpoint.is_cross_process_sharded(state)
    if sharded and step:
        raise ValueError(
            "mid-epoch checkpoints (save_every_steps) support single-file "
            "(host-syncable) state only: overwriting a sharded checkpoint "
            "dir in place could mix shard files from two saves. Use the "
            "elastic commit cadence (commit_every_steps) for step-granular "
            "recovery of cross-process-sharded state."
        )
    if not sharded and not runtime.is_primary():
        return None
    completed = epoch + 1 if step == 0 else epoch
    path = filepath_template.format(epoch=completed)
    progress = (completed, step)
    if sharded:
        # Consistent across processes: shardings are SPMD-global state.
        root, _ = os.path.splitext(path)
        path = root + checkpoint.SHARDED_SUFFIX
        do_save = checkpoint.save_sharded
        do_async = checkpoint.save_sharded_async
    else:
        do_save = checkpoint.save
        do_async = checkpoint.save_async
    if async_save:
        if pending is not None:
            pending.join()
        return do_async(path, state, progress=progress, cursor=cursor)
    do_save(path, state, progress=progress, cursor=cursor)
    return None


class ModelCheckpoint(Callback):
    """Per-epoch full-state checkpoint, written by the primary process only
    (tensorflow2_keras_mnist.py:86-88; single-writer discipline §5.2).

    ``filepath`` may contain ``{epoch}`` like Keras's
    ``'checkpoint-{epoch}.h5'`` template; the payload is always msgpack
    regardless of extension, and resume discovery
    (`checkpoint.latest_checkpoint`) accepts any extension.

    ``async_save=True`` hides the checkpoint stall: the state is snapshot on
    device and fetched/serialized on a background thread while the next
    epoch trains (`checkpoint.save_async`). At most one write is in flight —
    the previous epoch's write is joined first, so files land in order — and
    the final write is joined at train end.

    Cross-process-sharded state (pipeline/TP/FSDP spanning hosts) routes to
    the sharded directory format: EVERY process writes its own shard file
    (`checkpoint.save_sharded`), so the primary-only gate applies only to
    single-file checkpoints — the single-writer discipline then holds
    per-file (each process owns exactly one path, §5.2).

    ``save_every_steps=N`` ADDITIONALLY saves every N optimizer steps
    within an epoch (0 = epoch cadence only, the default; env default
    ``HVT_SAVE_EVERY_STEPS`` — the job-spec surface). A mid-epoch save
    advances the CURRENT epoch's artifact in place (atomic replace) with
    an ``(epoch, step)`` progress manifest, so a supervised restart
    resumes at the committed optimizer step
    (`checkpoint.restore_latest_and_broadcast(with_step=True)` →
    ``fit(initial_epoch=, initial_step=)``) instead of replaying the
    epoch — the checkpoint-file twin of the elastic
    ``commit_every_steps`` cadence, and accumulation-aligned for the
    same reason (``on_batch_end`` fires once per optimizer step).
    Single-file (host-syncable) state only — `save_state` refuses the
    sharded format mid-epoch. Cadence counts from the fit's resume step,
    so a resumed epoch doesn't instantly re-save."""

    def __init__(self, filepath: str, async_save: bool = False,
                 save_every_steps: int | None = None):
        self.filepath = filepath
        self.async_save = async_save
        if save_every_steps is None:
            save_every_steps = registry.get_int("HVT_SAVE_EVERY_STEPS")
        self.save_every_steps = max(0, int(save_every_steps))
        self._pending = None
        self._epoch = 0
        self._last_save_step = 0

    def on_epoch_begin(self, epoch: int, logs=None):
        self._epoch = epoch
        self._last_save_step = 0
        if self.trainer is not None and epoch == getattr(
            self.trainer, "_resume_epoch", 0
        ):
            self._last_save_step = int(
                getattr(self.trainer, "_resume_step", 0)
            )

    def on_batch_end(self, batch: int, logs=None):
        if not self.save_every_steps:
            return
        done = batch + 1
        # >= (not ==): steps_per_execution chunks stride the index, so a
        # chunk passing the cadence saves at its end — same contract as
        # the elastic commit cadence.
        if done - self._last_save_step < self.save_every_steps:
            return
        self._last_save_step = done
        self._pending = save_state(
            self.filepath, self._epoch, self.trainer.state,
            async_save=self.async_save, pending=self._pending, step=done,
            # The durable data-stream cursor rides the progress manifest
            # (stream-format-versioned — see Trainer.stream_cursor).
            cursor=self._cursor(self._epoch, done),
        )

    def on_epoch_end(self, epoch: int, logs=None):
        self._pending = save_state(
            self.filepath, epoch, self.trainer.state,
            async_save=self.async_save, pending=self._pending,
            cursor=self._cursor(epoch + 1, 0),
        )

    def _cursor(self, epoch: int, step: int):
        fn = getattr(self.trainer, "stream_cursor", None)
        return fn(epoch, step) if callable(fn) else None

    def on_train_end(self, logs=None):
        if self._pending is not None:
            self._pending.join()
            self._pending = None


class PreemptionCheckpointCallback(Callback):
    """Preemption-graceful training — the §5.3 stretch the reference lacks.

    The reference's fault model is pure fail-stop: a reclaimed node kills
    the MPI job and everything since the last per-epoch checkpoint is lost
    (SURVEY.md §5.3). Gang-scheduled TPU slices get a *grace window* first
    (SIGTERM → deadline → SIGKILL); this callback turns that window into a
    clean save-and-stop:

    * the signal handler only sets a flag — all real work happens at the
      next epoch boundary, OUTSIDE collectives and XLA dispatch, so the
      handler is async-signal-safe by construction;
    * at every epoch end the flag is agreed cross-process
      (`allgather_object` — ANY process's signal stops the WHOLE fleet at
      the same epoch, so a signal that reaches processes at different
      times cannot strand some of them in a collective);
    * on agreement: one final checkpoint (`save_state` — same single-file
      /sharded routing as `ModelCheckpoint`), ``trainer.stop_training``,
      and optionally a distinct exit status.

    Granularity is the epoch: bound epoch wall-clock (steps_per_epoch)
    below the platform's grace window. Resume is the standard idiom —
    `checkpoint.restore_latest_and_broadcast` + ``initial_epoch`` (the
    examples do this automatically), so a preempted job relaunches and
    continues as if it had completed the epoch normally.

    ``exit_code``: when set (143 = 128+SIGTERM is the convention), a
    SystemExit with that status is raised from ``on_train_end``, letting a
    supervisor distinguish "preemption, state saved" from a crash — safe
    at any list position: the Trainer runs EVERY callback's on_train_end
    (writer flushes, async-save joins) before propagating the first raise.
    Default None: fit() returns normally with ``callback.preempted ==
    True``.

    Handlers install at train begin and restore at train end; Python
    delivers signals to the main thread, so fit() must run there (it does
    in every launcher path)."""

    def __init__(self, filepath: str, signals=(signal.SIGTERM,),
                 exit_code: int | None = None, verbose: int = 1):
        self.filepath = filepath
        self.signals = tuple(signals)
        self.exit_code = exit_code
        self.verbose = verbose
        self.preempted = False
        self._hit = False
        self._old: dict = {}

    def on_train_begin(self, logs=None):
        self._hit = False
        self.preempted = False
        for s in self.signals:
            self._old[s] = signal.signal(s, self._handler)

    def _handler(self, signum, frame):
        self._hit = True

    def on_epoch_end(self, epoch: int, logs=None):
        # Collective agreement — entered by every process every epoch,
        # so the fleet takes the same branch regardless of which
        # processes the signal has reached so far.
        hit = agree_any(self._hit)
        if not hit:
            return
        # Stamp the durable stream cursor like every other checkpoint
        # writer: the preemption restart is exactly the path that needs
        # the engine/geometry/format record to refuse a re-anchored
        # resume loudly (data/stream.py).
        fn = getattr(self.trainer, "stream_cursor", None)
        save_state(
            self.filepath, epoch, self.trainer.state,
            cursor=fn(epoch + 1, 0) if callable(fn) else None,
        )
        self.trainer.stop_training = True
        self.preempted = True
        if self.verbose and runtime.is_primary():
            print(
                f"PreemptionCheckpoint: signal received — epoch {epoch + 1} "
                f"saved, stopping training"
            )

    def on_train_end(self, logs=None):
        for s, h in self._old.items():
            signal.signal(s, h)
        self._old = {}
        if self.preempted and self.exit_code is not None:
            raise SystemExit(self.exit_code)



class ScalarLogger(Callback):
    """Rank-0 scalar event log (TensorBoard-role observability, §5.1).

    Writes TWO formats side by side: real TensorBoard event files
    (`horovod_tpu.tbevents`, so ``tensorboard --logdir`` plots the run —
    format parity with ``TensorBoard(update_freq='batch')``,
    tensorflow2_keras_mnist.py:89) and JSONL (one line per record, the CI
    gate's input). ``log_every`` thins batch records (1 = every batch);
    epoch records are always written. When ``metrics.init`` was called with
    ``sync_tensorboard=True``, epoch scalars are additionally pushed to the
    platform metrics sink (the gradient_utils sync contract,
    mnist_keras.py:22-23).

    Durability: batch records are buffered (fetching device values per batch
    would serialize TPU async dispatch) and flushed when either
    ``flush_every`` records accumulate or ``flush_secs`` seconds pass since
    the last flush — so a mid-epoch crash loses at most ``flush_secs`` worth
    of batch records, not an unbounded count."""

    def __init__(
        self,
        log_dir: str,
        update_freq: str = "epoch",
        log_every: int = 1,
        flush_every: int = 100,
        flush_secs: float = 10.0,
    ):
        self.log_dir = log_dir
        self.update_freq = update_freq
        self.log_every = max(1, log_every)
        self.flush_every = max(1, flush_every)
        self.flush_secs = flush_secs
        self._last_flush = time.time()
        self._fh = None
        self._step = 0
        # Per-batch records hold device arrays until flushed — fetching
        # (device_get) per batch would force a host sync every step and
        # serialize the dispatch pipeline (the async-dispatch overlap is
        # where TPU step-time hides). flush_every bounds how many batch
        # records a mid-epoch crash can lose.
        self._pending: list[tuple[int, float, dict]] = []

    def _writer(self):
        if self._fh is None:
            os.makedirs(self.log_dir, exist_ok=True)
            self._fh = open(os.path.join(self.log_dir, "events.jsonl"), "a")
        return self._fh

    def _tb(self):
        if getattr(self, "_tb_writer", None) is None:
            from horovod_tpu.tbevents import TBEventWriter

            self._tb_writer = TBEventWriter(self.log_dir)
        return self._tb_writer

    def _emit(self, tag_prefix: str, logs: dict, step: int, wall_time=None):
        if not runtime.is_primary() or not logs:
            return
        wall = wall_time or time.time()
        record = {"wall_time": wall, "step": step}
        scalars = {}
        for k, v in logs.items():
            try:
                scalars[k] = float(v)
            except (TypeError, ValueError):
                continue
            record[f"{tag_prefix}{k}"] = scalars[k]
        self._writer().write(json.dumps(record) + "\n")
        if scalars:
            self._tb().scalars(
                {f"{tag_prefix}{k}": v for k, v in scalars.items()},
                step, wall_time=wall,
            )
        if tag_prefix == "epoch/" and scalars:
            from horovod_tpu import metrics

            if metrics.sync_tensorboard_enabled():
                # The gradient_utils sync contract: TB epoch scalars flow to
                # the platform sink under their plain names (the CI gate
                # consumes e.g. "loss", config.yaml:9-11).
                for k, v in scalars.items():
                    metrics.push(k, v, step=step)

    def _flush_pending(self):
        if self._pending:
            rows = jax.device_get([logs for _, _, logs in self._pending])
            for (step, wall, _), logs in zip(self._pending, rows):
                self._emit("batch/", logs, step, wall_time=wall)
            self._pending = []
        if self._fh:
            self._fh.flush()
        if getattr(self, "_tb_writer", None) is not None:
            self._tb_writer.flush()
        self._last_flush = time.time()

    def on_train_begin(self, logs=None):
        # Resume continuity: batch step numbering picks up from the restored
        # state's step counter, so a relaunched run's batch/* records extend
        # the previous run's series instead of colliding with it.
        if self._step == 0 and getattr(self.trainer, "state", None) is not None:
            self._step = int(jax.device_get(self.trainer.state.step))

    def on_batch_end(self, batch: int, logs=None):
        self._step += 1
        if self.update_freq == "batch" and self._step % self.log_every == 0 and logs:
            if runtime.is_primary():
                now = time.time()
                self._pending.append((self._step, now, logs))
                if (
                    len(self._pending) >= self.flush_every
                    or now - self._last_flush >= self.flush_secs
                ):
                    self._flush_pending()

    def on_epoch_end(self, epoch: int, logs=None):
        self._flush_pending()
        self._emit("epoch/", logs or {}, epoch + 1)
        if self._fh:
            self._fh.flush()

    def on_train_end(self, logs=None):
        self._flush_pending()
        if self._fh:
            self._fh.close()
            self._fh = None
        if getattr(self, "_tb_writer", None) is not None:
            self._tb_writer.close()
            self._tb_writer = None


class HeartbeatCallback(Callback):
    """Touch a per-rank liveness file so the restart supervisor
    (`launch/supervisor.py`) can tell a *hung* fleet from a slow one — a
    rank wedged in a collective produces no exit code at all (SURVEY.md
    §5.3's undetectable failure mode; arXiv:1810.11112).

    The supervisor exports ``HVT_HEARTBEAT_DIR`` to every rank; ``fit()``
    auto-installs this callback when the variable is set
    (`env_callbacks`), so entry scripts need no changes. Beats land at
    train/epoch boundaries unconditionally and at batch ends throttled to
    ``interval`` seconds (a per-batch utime would be noise; a heartbeat
    only needs to be fresher than the supervisor's timeout). The file is
    ``rank-<process rank>`` — per-rank so a shared dir works multi-host
    and staleness is judged on the NEWEST beat (one live writer proves
    the host loop is advancing).

    Beating is deliberately synchronous with the training loop — no
    background timer thread, which would keep a wedged main thread
    looking alive. Consequence for timeout sizing: the beat-free span is
    a full EPOCH on the device-cached fit path (its batch callbacks fire
    once per epoch), and post-fit work (export, final eval) does not
    beat at all — the supervisor's ``heartbeat_timeout`` must exceed
    both."""

    def __init__(self, directory: str, interval: float = 1.0):
        self.directory = directory
        self.interval = interval
        self._last = 0.0

    def _beat(self, force: bool = False):
        now = time.time()
        if not force and now - self._last < self.interval:
            return
        try:
            os.makedirs(self.directory, exist_ok=True)
            path = os.path.join(self.directory, f"rank-{runtime.rank()}")
            with open(path, "a"):
                os.utime(path, None)
        except OSError:
            # A torn-down heartbeat dir must never kill training itself.
            return
        self._last = now

    def on_train_begin(self, logs=None):
        self._beat(force=True)

    def on_epoch_begin(self, epoch: int, logs=None):
        self._beat(force=True)

    def on_batch_end(self, batch: int, logs=None):
        self._beat()

    def on_epoch_end(self, epoch: int, logs=None):
        self._beat(force=True)


def env_callbacks() -> list:
    """Callbacks the environment asks for — appended by ``fit()`` to the
    user's list on every path, so launcher-level machinery reaches into
    training without entry-script changes:

    * ``HVT_HEARTBEAT_DIR`` (set by the supervisor) → `HeartbeatCallback`
    * ``HVT_FAULT`` (the deterministic chaos knob) →
      `testing.faults.FaultInjectionCallback`
    """
    out: list = []
    hb_dir = registry.get_str(runtime.ENV_HEARTBEAT_DIR)
    if hb_dir:
        out.append(HeartbeatCallback(hb_dir))
    if registry.get_str("HVT_FAULT"):
        from horovod_tpu.testing.faults import FaultInjectionCallback

        out.append(FaultInjectionCallback.from_env())
    return out


class MetricsPushCallback(Callback):
    """Push epoch-end logs to the platform metrics sink (§5.5 channel 1).

    The role gradient_utils plays in the reference (mnist_keras.py:22-23,
    consumed by the CI loss gate, config.yaml:8-11): every epoch-end scalar
    goes to `horovod_tpu.metrics`, whose JSONL stream the CI gate
    (`horovod_tpu.launch.ci_gate`) aggregates. Place it AFTER
    MetricAverageCallback so pushed values are fleet averages."""

    def on_epoch_end(self, epoch: int, logs=None):
        from horovod_tpu import metrics

        for k, v in (logs or {}).items():
            try:
                metrics.push(k, float(v), step=epoch + 1)
            except (TypeError, ValueError):
                continue


# Keras-name alias: the reference registers this under TensorBoard.
TensorBoard = ScalarLogger


# ExponentialMovingAverage lives in training/ema.py (round-5 split);
# re-exported here so the public path hvt.callbacks.ExponentialMovingAverage
# is unchanged. Imported late: ema.py imports Callback from this module.
from horovod_tpu.training.ema import ExponentialMovingAverage  # noqa: E402,F401
