"""Keras-fit-like training loop around one jitted SPMD step.

This is the L4+L3 replacement (SURVEY.md §1): what the reference assembles
from Keras ``compile``/``fit`` + Horovod's DistributedOptimizer and callbacks
(tensorflow2_keras_mnist.py:62-96) becomes a `Trainer` owning a single jitted
train step: forward → loss(mean over **global** batch) → grad → update. With
the batch sharded along the mesh's data axis and parameters replicated, XLA
compiles the gradient all-reduce into the step (SURVEY.md §3.5: the entire
Horovod C++ hot path collapses into the compiled program).

Batch-size semantics (Horovod parity): ``batch_size`` is **per-worker**
(per-chip), exactly like the reference's ``batch(128)`` on every rank
(tensorflow2_keras_mnist.py:41); the global batch is
``batch_size × dp_size``. LR scaling by ``size`` (mesh.scale_lr) therefore
carries over unchanged.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Sequence

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np
import optax

from horovod_tpu import runtime
from horovod_tpu.data.loader import ArrayDataset, training_pipeline
from horovod_tpu.parallel import mesh as mesh_lib
from horovod_tpu.parallel import sharding as sharding_lib
from horovod_tpu.training.optimizer import compression_dtype

PyTree = Any


@flax.struct.dataclass
class TrainState:
    """The full broadcastable training state.

    Horovod's BroadcastGlobalVariablesCallback covers model *and* optimizer
    variables (SURVEY.md §7.3); keeping them in one pytree makes
    broadcast/checkpoint cover both by construction. ``model_state`` holds
    non-parameter variable collections (e.g. BatchNorm ``batch_stats``);
    under SPMD jit those statistics are computed over the *global* batch, so
    cross-replica BN sync — an extra op in GPU data-parallel stacks — is the
    default semantics here."""

    step: jax.Array
    params: PyTree
    opt_state: PyTree
    rng: jax.Array
    model_state: PyTree = None


def _resolve_loss(loss) -> Callable:
    """Map Keras-style loss names to fused-logits implementations.

    Covers both reference losses: SparseCategoricalCrossentropy
    (tensorflow2_keras_mnist.py:63) and categorical_crossentropy
    (mnist_keras.py:89)."""
    if callable(loss):
        return loss
    # 'module': the module computes its own loss — apply(x, labels=y)
    # returns (per_token_loss, per_token_correct). The contract of the fused
    # chunked-CE head (TransformerLM(fused_head_chunks=...), ops/fused_ce.py),
    # where materializing logits for a Trainer-side loss would defeat the op.
    if loss == "module":
        return None
    # Upcast at the loss boundary: models may emit 16-bit logits to halve
    # long-sequence HBM (TransformerLM logits_dtype) — the f32 cast fuses
    # into the logsumexp chain, so statistics are f32-accurate without a
    # materialized f32 copy. No-op for f32 logits.
    if loss in ("sparse_categorical_crossentropy", "sparse_ce"):
        return lambda logits, labels: optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), labels
        )
    if loss in ("categorical_crossentropy", "ce"):
        return lambda logits, labels: optax.softmax_cross_entropy(
            logits.astype(jnp.float32), labels
        )
    raise ValueError(f"unknown loss {loss!r}")


def _accuracy(logits, labels):
    pred = jnp.argmax(logits, axis=-1)
    if labels.ndim == logits.ndim:  # one-hot
        labels = jnp.argmax(labels, axis=-1)
    return (pred == labels).astype(jnp.float32).mean()


def _aggregate_sown_metrics(sown) -> dict:
    """Collapse a sown 'metrics' collection to ``{name: scalar}``: leaves
    sharing their final sow name (e.g. every MoE layer's 'moe_drop_rate')
    are averaged. This is the module→Trainer observability channel — any
    scalar a module sows into 'metrics' lands in the step metrics, the
    epoch logs, and every metrics sink, with no Trainer changes."""
    out: dict = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(sown)[0]:
        names = [p.key for p in path if isinstance(p, jax.tree_util.DictKey)]
        if names:
            out.setdefault(names[-1], []).append(
                jnp.asarray(leaf, jnp.float32)
            )
    return {k: jnp.mean(jnp.stack(v)) for k, v in out.items()}


def _param_shaped_matcher(params):
    """Predicate: is a subtree exactly param-shaped (same treedef, same leaf
    shapes)? Used to find the optimizer-state mirrors (momenta etc.) that
    must carry a parameter-derived sharding."""
    params_def = jax.tree.structure(params)
    params_shapes = jax.tree.leaves(jax.tree.map(lambda p: p.shape, params))

    def param_shaped(subtree) -> bool:
        try:
            if jax.tree.structure(subtree) != params_def:
                return False
            return (
                jax.tree.leaves(jax.tree.map(lambda l: l.shape, subtree))
                == params_shapes
            )
        except Exception:
            return False

    return param_shaped


def _run_train_end(callbacks) -> None:
    """on_train_end for the SUCCESS path: every hook runs even when an
    earlier one raises (PreemptionCheckpointCallback's SystemExit must not
    skip a later ModelCheckpoint's async-save join — its daemon thread
    would be killed at interpreter exit with the write half-done); the
    first raised exception propagates after all hooks ran."""
    first: BaseException | None = None
    for cb in callbacks:
        try:
            cb.on_train_end()
        except BaseException as e:
            if first is None:
                first = e
    if first is not None:
        raise first


def _teardown_callbacks(callbacks) -> None:
    """Best-effort on_train_end while a training error unwinds: teardown
    hooks (signal-handler restoration, writer flush/close, async-save
    joins) must still run — a PreemptionCheckpointCallback left installed
    after a crash would silently swallow the NEXT real SIGTERM — but their
    own failures (including the preemption callback's SystemExit) must not
    mask the original error."""
    for cb in callbacks:
        try:
            cb.on_train_end()
        except BaseException:
            pass


class Trainer:
    """compile+fit+evaluate+predict for a flax module over a device mesh.

    Args:
      module: a flax linen module; ``module.apply({'params': p}, x, train=...)``
        must return logits. Modules may accept a ``train`` kwarg and a
        ``dropout`` rng (both reference models use dropout).
      optimizer: an optax transformation — typically
        ``hvt.DistributedOptimizer(optax.adam(hvt.scale_lr(1e-3)))``.
      loss: Keras-style name or ``fn(logits, labels) -> per-example loss``.
      mesh: device mesh; defaults to all chips on the data axis (the
        reference's pure-DP topology).
      seed: init/dropout seed.
    """

    def __init__(
        self,
        module,
        optimizer: optax.GradientTransformation,
        loss="sparse_categorical_crossentropy",
        mesh=None,
        seed: int = 0,
        param_specs=None,
        batch_specs=None,
        steps_per_execution: int = 1,
        shard_update: bool = False,
    ):
        self.module = module
        self.tx = optimizer
        self.loss_fn = _resolve_loss(loss)
        self._module_loss = loss == "module"
        self.mesh = mesh if mesh is not None else mesh_lib.data_parallel_mesh()
        self.seed = seed
        # param_specs: callable (params, mesh) -> PartitionSpec pytree, or a
        # spec pytree — TP/FSDP parameter layout (e.g.
        # models.transformer.param_specs). None = replicated (pure DP, the
        # reference's layout).
        self.param_specs = param_specs
        self._param_shardings = None
        # batch_specs: PartitionSpec pytree matching the batch structure —
        # e.g. P(('data','fsdp'), 'seq') for sequence-sharded LM tokens.
        # None = shard dim 0 along the data axes.
        self.batch_specs = batch_specs
        self.state: TrainState | None = None
        # Non-'params' variable collections to thread through training
        # (e.g. ['batch_stats']); discovered at build() — before the first
        # (lazily-traced) _train_step call, so the closures see it static.
        self._mutable: list[str] = []
        # Update scale multiplies the optimizer's update — the knob the LR
        # callbacks turn (scaling the update by s is equivalent to scaling
        # the LR by s for the reference optimizers). Reset to 1.0 at every
        # epoch begin, before callbacks run: warmup ASSIGNS its ramp value,
        # schedule callbacks MULTIPLY — so Horovod's warmup→decay stacking
        # composes in callback-list order.
        self.update_scale: float = 1.0
        self.stop_training = False
        self.history: list[dict] = []
        # Keras's steps_per_execution: K > 1 compiles a lax.scan over K train
        # steps into ONE executable, so dispatch + input-transfer overhead is
        # paid once per K steps instead of per step. Semantics trade-off
        # (identical to Keras): on_batch_end callbacks fire once per
        # execution, with the last step's metrics.
        self.steps_per_execution = max(1, int(steps_per_execution))
        # Names of module-sown 'metrics' scalars (discovered at build());
        # sizes the epoch metric accumulator alongside loss/accuracy.
        self._metric_names: tuple = ()
        # Gradient wire compression (DistributedOptimizer(compression=...)):
        # honoured by computing gradients in an explicit-collective shard_map
        # whose psum runs on the 16-bit dtype (_compressed_grads). Only the
        # replicated-parameter (pure-DP/FSDP-free) layout is supported — with
        # sharded params the gradient traffic is layout-dependent and the
        # implicit SPMD reduction must stay in charge.
        self._comm_dtype = compression_dtype(optimizer)
        if self._comm_dtype is not None and param_specs is not None:
            raise ValueError(
                "DistributedOptimizer(compression=...) requires replicated "
                "parameters (param_specs=None); sharded-parameter layouts "
                "keep XLA's implicit f32 gradient reduction"
            )
        # ZeRO-1 / cross-replica weight-update sharding (Xu et al.,
        # arXiv:2004.13336 — PAPERS.md): keep the MODEL replicated (pure-DP
        # forward/backward, the reference's layout) but shard the optimizer
        # state — and therefore the weight update — across the data axis.
        # Delivered the XLA-native way the paper describes: the opt-state
        # leaves get P('data') dim-0 shardings at init, and GSPMD turns the
        # step's gradient reduction into reduce-scatter + the param update
        # into an all-gather. Per-device optimizer memory drops ~1/dp (for
        # Adam, opt state is 2× params — the dominant state at scale).
        self.shard_update = shard_update
        if shard_update and param_specs is not None:
            raise ValueError(
                "shard_update (ZeRO-1) targets the replicated-parameter "
                "layout; with param_specs the optimizer mirrors already "
                "follow the fsdp/tp sharding — compose via the fsdp axis "
                "instead"
            )
        if shard_update and self._comm_dtype is not None:
            raise ValueError(
                "shard_update does not compose with wire compression's "
                "explicit-collective step (whose hand-rolled psum assumes "
                "replicated optimizer state) — pick one"
            )

        def forward_loss(variables, x, y, rng):
            """Shared train-mode forward: (core_loss+aux, acc, updated, sown
            metrics) under either loss contract — Trainer-side loss_fn on
            logits, or loss='module' (apply(x, labels=y) → per-token
            (loss, correct), the fused-CE head's path)."""
            kwargs = {"labels": y} if self._module_loss else {}
            out, updated = self.module.apply(
                variables, x, train=True, **kwargs,
                rngs={"dropout": rng},
                mutable=self._mutable + ["losses", "metrics"],
            )
            sown = updated.pop("losses", {})
            sm = _aggregate_sown_metrics(updated.pop("metrics", {}))
            aux = sum(
                (jnp.sum(v) for v in jax.tree.leaves(sown)),
                jnp.zeros((), jnp.float32),
            )
            if self._module_loss:
                loss_vec, correct = out
                loss, acc = loss_vec.mean() + aux, correct.mean()
            else:
                loss = self.loss_fn(out, y).mean() + aux
                acc = _accuracy(out, y)
            return loss, acc, (dict(updated) if updated else None), sm

        def compressed_grads(state: TrainState, x, y, step_rng):
            """(loss, acc, model_state, grads) with the cross-worker gradient
            reduction made explicit: a psum over the data axes on the 16-bit
            wire dtype (Horovod Compression.fp16 semantics — compress, ring
            allreduce-SUM on the wire, decompress, then average). Everything
            else matches the SPMD loss_of path: per-shard loss means combine
            to the global-batch mean because shards are equal-sized.

            Contract deltas vs the SPMD path (both only observable with
            non-iid extras, never with the plain CE objective):
            * sown 'losses' must be batch-MEAN-style (magnitude independent
              of batch size — like models/moe.py's load-balance mean): the
              per-shard means average to the global mean exactly. A
              batch-SUM-style sow would contribute 1/n_shards of its SPMD
              weight here.
            * BatchNorm running variance is the mean of per-shard batch
              variances, which drops the between-shard-means term (law of
              total variance) vs the SPMD path's exact global-batch
              variance. Identical for iid shards (the sharded loader's
              case); an underestimate only for systematically skewed
              shards."""
            comm = self._comm_dtype
            data_axes = (mesh_lib.DATA_AXIS, mesh_lib.FSDP_AXIS)

            def local(params, ms, x, y):
                # Distinct dropout per shard (the SPMD path's global mask is
                # partitioned; here each shard must draw its own).
                shard_rng = jax.random.fold_in(
                    step_rng, jax.lax.axis_index(data_axes)
                )

                def loss_of(params):
                    loss, acc, upd, sm = forward_loss(
                        {"params": params, **(ms or {})}, x, y, shard_rng
                    )
                    return loss, (acc, upd if upd is not None else ms, sm)

                (loss, (acc, new_ms, sm)), grads = jax.value_and_grad(
                    loss_of, has_aux=True
                )(params)
                inv_n = 1.0 / jax.lax.psum(1, data_axes)
                grads = jax.tree.map(
                    lambda g: jax.lax.psum(g.astype(comm), data_axes)
                    .astype(g.dtype) * inv_n,
                    grads,
                )
                loss = jax.lax.pmean(loss, data_axes)
                acc = jax.lax.pmean(acc, data_axes)
                sm = jax.tree.map(lambda v: jax.lax.pmean(v, data_axes), sm)
                if new_ms is not None:
                    # Cross-shard mean of updated statistics; non-float
                    # leaves (step counters) are shard-invariant already.
                    # For BN this is mean-of-shard-means (exact) and
                    # mean-of-shard-variances (iid-exact; see docstring).
                    new_ms = jax.tree.map(
                        lambda v: jax.lax.pmean(v, data_axes)
                        if jnp.issubdtype(jnp.asarray(v).dtype, jnp.floating)
                        else v,
                        new_ms,
                    )
                return loss, acc, new_ms, sm, grads

            P = jax.sharding.PartitionSpec
            return jax.shard_map(
                local,
                mesh=self.mesh,
                in_specs=(P(), P(), P(data_axes), P(data_axes)),
                out_specs=(P(), P(), P(), P(), P()),
                check_vma=False,
            )(state.params, state.model_state, x, y)

        def train_step(state: TrainState, batch, update_scale, metric_acc):
            x, y = batch
            step_rng = jax.random.fold_in(state.rng, state.step)

            def loss_of(params):
                # 'losses' is the auxiliary-objective channel: any value a
                # module sows there during training (e.g. MoE load-balance
                # loss, models/moe.py) is added to the objective. Requested
                # as mutable unconditionally — it costs nothing when unused,
                # and is never carried in model_state (sown per-apply).
                # Contract: sow batch-MEAN-style values (batch-size
                # independent) so the compressed_grads path weights them
                # identically (see its docstring). 'metrics' is the sown
                # OBSERVABILITY channel: scalar values land in the step
                # metrics / epoch logs / sinks (e.g. MoE router drop-rate,
                # models/moe.py) — see _aggregate_sown_metrics.
                loss, acc, upd, sm = forward_loss(
                    {"params": params, **(state.model_state or {})},
                    x, y, step_rng,
                )
                return loss, (
                    acc, upd if upd is not None else state.model_state, sm
                )

            if self._comm_dtype is not None:
                loss, acc, model_state, sown_metrics, grads = compressed_grads(
                    state, x, y, step_rng
                )
            else:
                (loss, (acc, model_state, sown_metrics)), grads = (
                    jax.value_and_grad(loss_of, has_aux=True)(state.params)
                )
            updates, opt_state = self.tx.update(grads, state.opt_state, state.params)
            updates = jax.tree.map(lambda u: u * update_scale, updates)
            params = optax.apply_updates(state.params, updates)
            if self._param_shardings is not None:
                # Pin the TP/FSDP layout so XLA's propagation can't drift the
                # updated params away from their declared placement.
                params = jax.lax.with_sharding_constraint(
                    params, self._param_shardings
                )
            new_state = state.replace(
                step=state.step + 1, params=params, opt_state=opt_state,
                model_state=model_state,
            )
            if tuple(sorted(sown_metrics)) != self._metric_names:
                # Trace-time (keys are Python): a train-gated sow would
                # otherwise surface as an opaque pytree mismatch in the
                # accumulator add below.
                raise ValueError(
                    f"sown 'metrics' names at train time "
                    f"{sorted(sown_metrics)} differ from those discovered "
                    f"at build() {list(self._metric_names)} — 'metrics' "
                    "sows must be unconditional (not gated on train)"
                )
            metrics = {"loss": loss, "accuracy": acc, **sown_metrics}
            # Epoch metric sums accumulate inside the compiled step: per-step
            # host fetches (or even per-step host-side adds) each cost a
            # dispatch/transfer round-trip, which dominates wall-clock on a
            # networked TPU; this way an epoch ends with ONE few-scalar fetch.
            new_acc = jax.tree.map(jnp.add, metric_acc, metrics)
            return new_state, metrics, new_acc

        def train_epoch(
            state: TrainState, data, epoch_seed, update_scale, metric_acc,
            steps: int, per_chip_batch: int,
        ):
            """One epoch over a DEVICE-RESIDENT dataset, fully on-device.

            ``data`` leaves are [n_shards, per_shard_n, ...], example axis
            sharded over the data axes — the dataset lives in HBM. Each epoch
            draws a fresh per-shard permutation (sharded RNG is
            shard-local under partitionable threefry) and scans ``steps``
            train steps, gathering each chip's ``per_chip_batch`` examples
            from its own shard — zero host↔device traffic inside the epoch.
            Per-shard independent shuffles are the reference's own sampling
            semantics (every rank shuffles independently,
            tensorflow2_keras_mnist.py:37-41), with the improvement that
            shards partition the data so an epoch sees each example once."""
            first = jax.tree.leaves(data)[0]
            n_shards, per_n = first.shape[0], first.shape[1]
            u = jax.random.uniform(epoch_seed, (n_shards, per_n))
            order = jnp.argsort(u, axis=1)  # row-wise → shard-local

            # Materialize the epoch's shuffle ONCE: one per-shard row gather
            # of the rows this epoch will actually consume (bandwidth-bound,
            # amortized over every step), so the per-step read is a
            # contiguous dynamic slice — random per-step row gathers are
            # latency-bound on TPU and were the e2e step's input cost
            # (0.68 ms/step at CIFAR shapes vs ~0 after; round 2 measured
            # them at 31% of the MNIST step). The gather runs over FLATTENED
            # trailing dims (~9x a multi-dim-trailing gather,
            # benchmarks/conv_profile.py). HBM cost: a second copy of the
            # CONSUMED prefix (the full dataset when steps cover the epoch),
            # live alongside `data` for the epoch — the device-cached path
            # trades HBM for zero per-step host/latency cost by design; use
            # the streamed fit path when the dataset crowds HBM.
            need = steps * per_chip_batch
            shuffled = jax.tree.map(
                lambda a: jax.vmap(
                    lambda rows, ii: jnp.take(rows, ii, axis=0)
                )(
                    a.reshape(a.shape[0], a.shape[1], -1), order[:, :need]
                ).reshape((a.shape[0], need) + a.shape[2:]),
                data,
            )

            def body(carry, t):
                state, acc = carry
                batch = jax.tree.map(
                    lambda a: jax.lax.dynamic_slice_in_dim(
                        a, t * per_chip_batch, per_chip_batch, axis=1
                    ).reshape((n_shards * per_chip_batch,) + a.shape[2:]),
                    shuffled,
                )
                state, metrics, acc = train_step(state, batch, update_scale, acc)
                return (state, acc), metrics

            (state, metric_acc), metrics = jax.lax.scan(
                body, (state, metric_acc), jnp.arange(steps)
            )
            last = jax.tree.map(lambda m: m[-1], metrics)
            return state, last, metric_acc

        def train_chunk(state: TrainState, batches, update_scale, metric_acc):
            """K stacked batches ([K, ...] leaves) through K chained steps in
            one compiled program (scan keeps the trace size constant)."""

            def body(carry, batch):
                state, acc = carry
                state, metrics, acc = train_step(state, batch, update_scale, acc)
                return (state, acc), metrics

            (state, metric_acc), metrics = jax.lax.scan(
                body, (state, metric_acc), batches
            )
            last = jax.tree.map(lambda m: m[-1], metrics)
            return state, last, metric_acc

        def _eval_variables(state: TrainState):
            return {"params": state.params, **(state.model_state or {})}

        def eval_step(state: TrainState, batch):
            # Masked sums (mask zeroes padding) so full-dataset metrics are
            # exact even when the tail batch is padded to the global shape.
            # The per-example mask broadcasts over any trailing loss dims
            # (sequence models produce per-token losses [G, T]); `count`
            # then counts tokens, keeping the mean per-token.
            x, y, mask = batch
            if self._module_loss:
                loss_vec, correct = self.module.apply(
                    _eval_variables(state), x, train=False, labels=y
                )
            else:
                logits = self.module.apply(
                    _eval_variables(state), x, train=False
                )
                loss_vec = self.loss_fn(logits, y)
                pred = jnp.argmax(logits, axis=-1)
                labels = jnp.argmax(y, axis=-1) if y.ndim == logits.ndim else y
                correct = (pred == labels).astype(jnp.float32)
            w = mask.reshape(mask.shape + (1,) * (loss_vec.ndim - 1))
            w = jnp.broadcast_to(w, loss_vec.shape)
            return {
                "loss_sum": (loss_vec * w).sum(),
                "correct_sum": (correct * w).sum(),
                "count": w.sum(),
            }

        def eval_epoch(state: TrainState, data, steps: int, per_chip_batch: int):
            """Whole-dataset eval over a DEVICE-RESIDENT (padded + masked)
            eval set: one dispatch, one 3-scalar fetch — instead of
            restreaming the test set from the host every epoch."""
            xs, ys, masks = data  # [n_shards, per_n(, ...)] leaves

            def body(acc, t):
                def take(a):
                    sl = jax.lax.dynamic_slice_in_dim(
                        a, t * per_chip_batch, per_chip_batch, axis=1
                    )
                    return sl.reshape((-1,) + sl.shape[2:])

                m = eval_step(state, (take(xs), take(ys), take(masks)))
                return jax.tree.map(jnp.add, acc, m), None

            zero = {
                "loss_sum": jnp.zeros((), jnp.float32),
                "correct_sum": jnp.zeros((), jnp.float32),
                "count": jnp.zeros((), jnp.float32),
            }
            acc, _ = jax.lax.scan(body, zero, jnp.arange(steps))
            return acc

        def predict_step(state: TrainState, x):
            logits = self.module.apply(_eval_variables(state), x, train=False)
            return jax.nn.softmax(logits, axis=-1)

        self._train_step = jax.jit(train_step, donate_argnums=(0,))
        self._train_chunk = jax.jit(train_chunk, donate_argnums=(0,))
        self._train_epoch = jax.jit(
            train_epoch, static_argnums=(5, 6), donate_argnums=(0,)
        )
        self._eval_step = jax.jit(eval_step)
        self._eval_epoch = jax.jit(eval_epoch, static_argnums=(2, 3))
        # Staged eval sets for evaluate(cache='device'), keyed by the host
        # arrays' identity. Entries hold strong references to those arrays,
        # so a cached id cannot be recycled by the allocator while its
        # staging is alive.
        self._eval_cache: dict = {}
        # Replicated output → fully addressable on every process, so
        # device_get works in multi-host runs too.
        self._predict_step = jax.jit(
            predict_step, out_shardings=sharding_lib.replicated(self.mesh)
        )

    # --- state management ---------------------------------------------------

    @property
    def dp_size(self) -> int:
        return mesh_lib.dp_size(self.mesh)

    @property
    def metric_names(self) -> tuple:
        """All per-step metric keys: loss/accuracy plus any module-sown
        'metrics' scalars (available after build())."""
        return ("loss", "accuracy") + self._metric_names

    def zero_metrics(self) -> dict:
        """A zero accumulator matching the step metrics' structure."""
        return {n: jnp.zeros((), jnp.float32) for n in self.metric_names}

    def build(self, sample_x: np.ndarray, sample_y=None) -> TrainState:
        """Initialize parameters (lazy, from the first batch — like Keras
        building on first fit).

        With ``loss='module'`` the init passes labels so the module traces
        its fused-loss branch (see below): ``sample_y`` when given, else
        labels synthesized as ``zeros_like(sample_x)`` — valid for the LM
        family, where labels share the token batch's shape/dtype. Models
        whose labels differ from their inputs in dtype/shape/structure must
        pass ``sample_y`` (``fit`` always does)."""
        if self.state is not None:
            return self.state
        rng = jax.random.PRNGKey(self.seed)
        init_rng, dropout_rng, state_rng = jax.random.split(rng, 3)
        # Init batch sized to the data-parallel degree: models that carry
        # internal sharding constraints need the batch dim divisible by it.
        # Leaf-wise so pytree (dict-input) samples build like flat ones.
        n = self.dp_size

        def size_to_dp(a):
            a = np.asarray(a)
            if len(a) < n:
                a = np.concatenate([a] * (-(-n // len(a))))
            return jnp.asarray(a[:n])

        sized_x = jax.tree.map(size_to_dp, sample_x)
        # loss='module' contract: init with labels so the module traces its
        # fused-loss branch — otherwise build() materializes the dense
        # [B, T, vocab] logits that the fused head exists to avoid, making
        # init the OOM point at long-context scale even though train/eval
        # steps are fused. Real labels when the caller has them; the
        # zeros_like fallback matches the LM family's labels-share-the-
        # token-batch contract (models/transformer.py `__call__`).
        init_kwargs = {}
        synthesized_labels = False
        if self._module_loss:
            if sample_y is not None:
                init_kwargs["labels"] = jax.tree.map(size_to_dp, sample_y)
            else:
                init_kwargs["labels"] = jax.tree.map(jnp.zeros_like, sized_x)
                synthesized_labels = True
        try:
            variables = self.module.init(
                {"params": init_rng, "dropout": dropout_rng},
                sized_x,
                train=False,
                **init_kwargs,
            )
        except Exception as e:
            if synthesized_labels:
                # The zeros_like fallback assumes LM-style labels (same
                # shape/dtype as the token batch). For any other module the
                # trace fails opaquely deep inside init — name the fix.
                # Mutating args (not re-wrapping) keeps the exception type
                # even for types with non-string constructors.
                hint = (
                    "\n\nhorovod_tpu hint: build() was called with "
                    "loss='module' and no sample_y, so labels were "
                    "synthesized as zeros_like(sample_x) (the LM-family "
                    "contract). If this module's labels differ from its "
                    "inputs in shape/dtype, pass sample_y to build() — "
                    "fit() does this automatically."
                )
                head = str(e.args[0]) if e.args else str(e)
                e.args = (head + hint,) + tuple(e.args[1:])
            raise
        params = variables["params"]
        # Sown per-apply channels never persist in the carried state: values
        # are produced fresh each step ('losses' → objective, 'metrics' →
        # observability). Their presence at init DOES reveal the metric
        # names, which sizes the epoch accumulator — which is why 'metrics'
        # sows must be UNCONDITIONAL (not train-gated): a name that appears
        # only at train time couldn't be discovered here, and the step
        # checks for that drift loudly (see train_step).
        self._metric_names = tuple(
            sorted(_aggregate_sown_metrics(variables.get("metrics", {})))
        )
        reserved = {"loss", "accuracy"} & set(self._metric_names)
        if reserved:
            raise ValueError(
                f"module sows 'metrics' entries named {sorted(reserved)}, "
                "which would silently overwrite the Trainer's own "
                "loss/accuracy in every log and sink — rename the sow"
            )
        model_state = {
            k: v
            for k, v in variables.items()
            if k not in ("params", "losses", "metrics")
        }
        self._mutable = sorted(model_state.keys())
        if self.param_specs is not None:
            specs = (
                self.param_specs(params, self.mesh)
                if callable(self.param_specs)
                else self.param_specs
            )
            self._param_shardings = jax.tree.map(
                lambda s: jax.sharding.NamedSharding(self.mesh, s),
                specs,
                is_leaf=lambda s: isinstance(s, jax.sharding.PartitionSpec),
            )
            params = jax.device_put(params, self._param_shardings)
            # Optimizer mirrors (momenta etc.) must carry the param layout.
            # Sharding propagation can't deliver it — `init` is zeros_like,
            # which reads only shapes, so XLA sees an input-free computation —
            # hence explicit out_shardings: any opt-state subtree that is
            # param-shaped gets the param shardings, the rest replicate.
            rep = sharding_lib.replicated(self.mesh)
            param_shaped = _param_shaped_matcher(params)
            opt_shardings = jax.tree.map(
                lambda sub: self._param_shardings if param_shaped(sub) else rep,
                jax.eval_shape(self.tx.init, params),
                is_leaf=param_shaped,
            )
            opt_state = jax.jit(self.tx.init, out_shardings=opt_shardings)(params)
            state = TrainState(
                step=jax.device_put(jnp.zeros((), jnp.int32), rep),
                params=params,
                opt_state=opt_state,
                rng=jax.device_put(state_rng, rep),
                model_state=sharding_lib.replicate(model_state, self.mesh)
                if model_state
                else None,
            )
            self.state = state
        elif (
            self.shard_update
            and self.mesh.shape.get(mesh_lib.DATA_AXIS, 1) > 1
        ):
            # ZeRO-1 (arXiv:2004.13336): replicated params, optimizer state
            # sharded dim-0 over the data axis. The jitted step then
            # compiles the paper's transformation — gradients reduce-scatter
            # into the update shard each replica owns, and the applied
            # params all-gather back — purely from these init shardings.
            dp = self.mesh.shape[mesh_lib.DATA_AXIS]
            rep = sharding_lib.replicated(self.mesh)
            param_shaped = _param_shaped_matcher(params)

            def zero1(shape):
                # First dp-divisible dim carries the shard (dim 0 for the
                # matmul kernels that dominate; conv kernels usually shard
                # their channel dims); nothing divisible → replicate.
                for i, dim in enumerate(shape):
                    if dim % dp == 0:
                        spec = [None] * len(shape)
                        spec[i] = mesh_lib.DATA_AXIS
                        return jax.sharding.NamedSharding(
                            self.mesh, jax.sharding.PartitionSpec(*spec)
                        )
                return rep

            opt_shardings = jax.tree.map(
                lambda sub: jax.tree.map(lambda l: zero1(l.shape), sub)
                if param_shaped(sub)
                else rep,
                jax.eval_shape(self.tx.init, params),
                is_leaf=param_shaped,
            )
            params = jax.device_put(params, rep)
            opt_state = jax.jit(self.tx.init, out_shardings=opt_shardings)(
                params
            )
            state = TrainState(
                step=jax.device_put(jnp.zeros((), jnp.int32), rep),
                params=params,
                opt_state=opt_state,
                rng=jax.device_put(state_rng, rep),
                model_state=sharding_lib.replicate(model_state, self.mesh)
                if model_state
                else None,
            )
            self.state = state
        else:
            state = TrainState(
                step=jnp.zeros((), jnp.int32),
                params=params,
                opt_state=self.tx.init(params),
                rng=state_rng,
                model_state=model_state or None,
            )
            self.state = sharding_lib.replicate(state, self.mesh)
        return self.state

    def _shard(self, batch):
        if self.batch_specs is not None:
            specs = tuple(self.batch_specs)

            def put(x, spec):
                return sharding_lib.put_global(
                    x, jax.sharding.NamedSharding(self.mesh, spec)
                )

            def put_part(part, spec):
                # One batch part against its spec: a single PartitionSpec
                # broadcasts over a pytree part (dict-input models), a
                # matching spec pytree maps pairwise.
                if isinstance(spec, jax.sharding.PartitionSpec):
                    return jax.tree.map(lambda a: put(a, spec), part)
                return jax.tree.map(put, part, spec)

            if not isinstance(batch, (tuple, list)):
                return put_part(batch, specs[0])  # predict: bare x
            if len(batch) == len(specs) + 1:
                # evaluate() appends a per-example mask: batch-sharded only.
                last = tuple(specs[-1])
                specs = specs + (
                    jax.sharding.PartitionSpec(*last[:1]) if last
                    else jax.sharding.PartitionSpec(),
                )
            return tuple(
                put_part(x, spec) for x, spec in zip(batch, specs)
            )
        return sharding_lib.shard_batch(batch, self.mesh)

    def _feed_groups(self) -> tuple[int, int]:
        """(n_groups, my_group): how processes map onto the data axis.

        Processes feed batches in ``min(world, dp_size)`` distinct groups.
        With dp >= world (the usual DP deployment) every process is its own
        group. With dp < world (model-parallel-only meshes spanning
        processes, e.g. pipe=2 over 2 hosts) several processes share one
        data shard and MUST feed identical rows — the batch is logically
        replicated across the non-data axes, and divergent per-process
        contributions would silently give each device different contents
        for the same global array."""
        world = runtime.process_count()
        dp = self.dp_size
        groups = min(world, dp)
        if world % groups != 0 or (dp >= world and dp % world != 0):
            # e.g. 3 processes over dp=2: some rank would straddle two data
            # shards and the grouping below would slice out-of-range rows —
            # fail loudly instead of feeding wrong data.
            raise ValueError(
                f"process count ({world}) and data-parallel degree ({dp}) "
                "must divide one another for a coherent feeding layout"
            )
        per_group = world // groups
        return groups, runtime.process_rank() // per_group

    def _local_slice(self, arr, global_batch: int):
        """This feed-group's share of a globally-indexed batch — what
        `make_array_from_process_local_data` expects as the local
        contribution (each example fed exactly once across the data axis;
        processes sharing a data shard contribute identical rows)."""
        if runtime.process_count() == 1:
            return arr
        groups, group = self._feed_groups()
        local = global_batch // groups
        return arr[group * local : (group + 1) * local]

    # --- Keras-parity verbs -------------------------------------------------

    def fit(
        self,
        dataset=None,
        *,
        x=None,
        y=None,
        batch_size: int = 128,
        epochs: int = 1,
        initial_epoch: int = 0,
        steps_per_epoch: int | None = None,
        callbacks: Sequence = (),
        validation_data=None,
        shuffle_buffer: int | None = None,
        verbose: int | None = None,
        cache: str | None = None,
    ) -> list[dict]:
        """Train. Either pass a batched ``ArrayDataset``/iterable of
        ``(x, y)`` numpy batches (the TF2 script's idiom,
        tensorflow2_keras_mnist.py:96) or raw ``x``/``y`` arrays with a
        per-worker ``batch_size`` (the TF1 script's idiom,
        mnist_keras.py:107-112).

        ``initial_epoch`` is the Keras resume idiom: epoch numbering (and
        LR-warmup position, checkpoint names) continues from a restored run —
        pair it with `checkpoint.restore_latest_and_broadcast`.

        ``cache='device'`` (with ``x``/``y``) stages the whole dataset into
        HBM once, sharded over the data axes, and runs shuffling + batching +
        training fully on-device: ONE dispatch and ONE metrics fetch per
        epoch, zero per-step host involvement. This is the TPU-native answer
        to input-bound training (datasets at MNIST/CIFAR scale are trivially
        HBM-resident); on_batch_end callbacks fire once per epoch with the
        last step's metrics."""
        if verbose is None:
            verbose = 1 if runtime.is_primary() else 0
        if isinstance(x, list):
            # Keras-parity: a plain list of example rows is one array input
            # (the pre-pytree behavior); dict/tuple inputs stay pytrees.
            x = np.asarray(x)
        if cache == "device":
            if x is None or y is None:
                raise ValueError("cache='device' needs x=/y= arrays")
            if len(jax.tree_util.tree_leaves(x)) != 1:
                raise ValueError(
                    "cache='device' stages a single input array; pytree "
                    "(dict/tuple) inputs use the streamed fit path"
                )
            if self.batch_specs is not None and mesh_lib.has_live_model_axes(
                self.mesh
            ):
                # The staged layout shards the batch dim only; custom batch
                # layouts over live non-data axes (e.g. seq-sharded tokens)
                # need the streamed path's batch_specs handling.
                raise ValueError(
                    "cache='device' supports data-sharded batches only; "
                    "use the streamed fit path with batch_specs meshes"
                )
            return self._fit_device_cached(
                x, y, batch_size, epochs, initial_epoch, steps_per_epoch,
                callbacks, validation_data, verbose,
            )
        if cache is not None:
            raise ValueError(f"unknown cache mode {cache!r}")

        groups, group = self._feed_groups()
        close_input = lambda: None  # noqa: E731
        if dataset is None:
            if x is None or y is None:
                raise ValueError("pass either dataset= or x=/y=")
            ds = ArrayDataset((x, y)).shard(group, groups)
            n_local = ds.num_examples
            # Global batch = per-worker batch × dp_size; each feed group
            # contributes its share (see _feed_groups for the dp < world
            # case, where processes sharing a shard feed identical rows).
            local_batch = batch_size * self.dp_size // groups
            if steps_per_epoch is None:
                steps_per_epoch = max(1, n_local // local_batch)
            # Batch assembly runs in the native C++ producer thread when
            # available (overlapping shuffle/gather with the device step),
            # pure Python otherwise — same semantics either way.
            dataset, close_input = training_pipeline(
                ds.arrays, local_batch, seed=self.seed,
                shuffle_buffer=shuffle_buffer, structure=ds.structure,
            )
        elif steps_per_epoch is None:
            raise ValueError("steps_per_epoch is required with a dataset")

        it = iter(dataset)
        first = next(it)
        self.build(first[0], first[1])

        for cb in callbacks:
            cb.set_trainer(self)
        try:
            # on_train_begin sits INSIDE the teardown scope: an early
            # installer (e.g. PreemptionCheckpointCallback's signal
            # handler) must be torn down even when a LATER callback's
            # begin hook raises.
            for cb in callbacks:
                cb.on_train_begin()

            pending = first
            # Zero metric accumulator, committed to the mesh's replicated
            # sharding ONCE: a fresh uncommitted jnp.zeros each epoch would
            # give the first step of every epoch a different input-sharding
            # signature than the chained steps, ping-ponging between two
            # executables.
            zero_acc = sharding_lib.replicate(self.zero_metrics(), self.mesh)
            # HVT_PROFILE=<dir> captures a jax.profiler trace of the training
            # loop (XLA op + ICI collective timing) — the Horovod-Timeline
            # env-var contract, primary-process-gated (trace.py).
            from horovod_tpu import trace as trace_lib

            with trace_lib.maybe_trace(trace_lib.profile_dir()):
                self._fit_epochs(
                    it, pending, zero_acc, epochs, initial_epoch,
                    steps_per_epoch, callbacks, validation_data, batch_size,
                    verbose,
                )
        except BaseException:
            close_input()
            _teardown_callbacks(callbacks)
            raise
        close_input()
        _run_train_end(callbacks)
        return self.history

    def _stage_sharded(self, arr, per_shard: int):
        """Stage one host array as [n_shards, per_shard, ...] in HBM,
        example-sharded over the data axes: shard s takes rows
        [s*per_shard, (s+1)*per_shard); multi-process, each feed group
        contributes the rows for its chips (processes sharing a data shard
        stage identical rows — see _feed_groups)."""
        groups, group = self._feed_groups()
        local_shards = self.dp_size // groups
        arr = np.asarray(arr)
        lo = group * local_shards * per_shard
        hi = (group + 1) * local_shards * per_shard
        local = arr[lo:hi].reshape((local_shards, per_shard) + arr.shape[1:])
        spec = jax.sharding.PartitionSpec(
            (mesh_lib.DATA_AXIS, mesh_lib.FSDP_AXIS),
            *([None] * arr.ndim),
        )
        return sharding_lib.put_global(
            local, jax.sharding.NamedSharding(self.mesh, spec)
        )

    def _stage_device_dataset(self, x, y):
        """Stage (x, y) into HBM as [n_shards, per_shard_n, ...] leaves,
        example-sharded over the data axes (truncated to divide evenly)."""
        n_shards = self.dp_size
        n = (len(x) // n_shards) * n_shards
        if n == 0:
            raise ValueError(f"need at least {n_shards} examples")
        per_shard = n // n_shards
        return (
            self._stage_sharded(np.asarray(x)[:n], per_shard),
            self._stage_sharded(np.asarray(y)[:n], per_shard),
        ), per_shard

    def _fit_device_cached(
        self, x, y, batch_size, epochs, initial_epoch, steps_per_epoch,
        callbacks, validation_data, verbose,
    ):
        from horovod_tpu import trace as trace_lib

        data, per_shard = self._stage_device_dataset(x, y)
        max_steps = per_shard // batch_size
        if max_steps == 0:
            raise ValueError(
                f"per-shard examples ({per_shard}) < per-chip batch "
                f"({batch_size})"
            )
        steps = min(steps_per_epoch or max_steps, max_steps)
        self.build(
            np.asarray(x[: self.dp_size]), np.asarray(y[: self.dp_size])
        )

        for cb in callbacks:
            cb.set_trainer(self)
        try:
            # Inside the teardown scope — see the streamed fit path's note.
            for cb in callbacks:
                cb.on_train_begin()
            zero_acc = sharding_lib.replicate(self.zero_metrics(), self.mesh)
            epoch_key = jax.random.PRNGKey(self.seed + 1)
            with trace_lib.maybe_trace(trace_lib.profile_dir()):
                for epoch in range(initial_epoch, epochs):
                    if self.stop_training:
                        break
                    # Fresh scale each epoch: LR callbacks compose into it
                    # in list order (warmup assigns, schedules multiply).
                    self.update_scale = 1.0
                    for cb in callbacks:
                        cb.on_epoch_begin(epoch)
                    t0 = time.perf_counter()
                    scale = jnp.asarray(self.update_scale, jnp.float32)
                    self.state, metrics, metric_acc = self._train_epoch(
                        self.state, data, jax.random.fold_in(epoch_key, epoch),
                        scale, zero_acc, steps, batch_size,
                    )
                    for cb in callbacks:
                        cb.on_batch_end(steps - 1, metrics)
                    self._finish_epoch(
                        epoch, epochs, metric_acc, steps, t0, callbacks,
                        validation_data, batch_size, verbose,
                        # Device-cached training implies device-cached
                        # validation.
                        val_cache="device",
                    )
        except BaseException:
            _teardown_callbacks(callbacks)
            raise
        _run_train_end(callbacks)
        return self.history

    def _finish_epoch(
        self, epoch, epochs, metric_acc, steps, t0, callbacks,
        validation_data, batch_size, verbose, val_cache=None,
    ):
        """Epoch bookkeeping shared by every fit path: ONE host fetch of the
        in-step metric sums, optional validation, callbacks, history."""
        sums = jax.device_get(metric_acc)
        logs = {k: float(v) / steps for k, v in sums.items()}
        logs["epoch_time_s"] = time.perf_counter() - t0
        if validation_data is not None:
            val = self.evaluate(
                validation_data[0], validation_data[1],
                batch_size=batch_size, verbose=0, cache=val_cache,
            )
            logs.update({f"val_{k}": v for k, v in val.items()})
        for cb in callbacks:
            cb.on_epoch_end(epoch, logs)
        self.history.append(logs)
        if verbose:
            shown = {k: round(v, 4) for k, v in logs.items()}
            print(f"Epoch {epoch + 1}/{epochs} - {shown}")

    def _shard_chunk(self, chunk):
        """Place a [K, batch, ...] stack of K batches (steps_per_execution)
        onto the mesh — the scan axis stays unsharded."""
        if self.batch_specs is not None:
            specs = tuple(self.batch_specs)

            def put(x, spec):
                return sharding_lib.put_global(
                    x,
                    jax.sharding.NamedSharding(
                        self.mesh, jax.sharding.PartitionSpec(None, *tuple(spec))
                    ),
                )

            return tuple(put(x, spec) for x, spec in zip(chunk, specs))
        return sharding_lib.shard_chunk(chunk, self.mesh)

    def _fit_epochs(
        self, it, pending, zero_acc, epochs, initial_epoch, steps_per_epoch,
        callbacks, validation_data, batch_size, verbose,
    ):
        from horovod_tpu.data.prefetch import DevicePrefetcher

        # Per-epoch execution plan: full steps_per_execution chunks plus one
        # remainder chunk (a second, smaller executable) when K doesn't
        # divide the epoch.
        spe = min(self.steps_per_execution, steps_per_epoch)
        plan = [spe] * (steps_per_epoch // spe)
        if steps_per_epoch % spe:
            plan.append(steps_per_epoch % spe)
        buffered = [pending]

        def host_chunks():
            # Host-side assembly of the execution units: single batches when
            # K == 1, [K, ...] stacks otherwise.
            for _ in range(initial_epoch, epochs):
                for k in plan:
                    batches = [
                        buffered.pop() if buffered else next(it)
                        for _ in range(k)
                    ]
                    if spe == 1:
                        yield batches[0]
                    else:
                        # Stack K batches leaf-wise — pytree batches (dict
                        # inputs, multi-input models) stack like flat ones.
                        yield jax.tree.map(
                            lambda *xs: np.stack(xs), *batches
                        )

        # Batches are staged onto the devices by a background thread while
        # the current step computes — transfer enqueue never blocks dispatch.
        run = self._train_step if spe == 1 else self._train_chunk
        prefetcher = DevicePrefetcher(
            host_chunks(), self._shard if spe == 1 else self._shard_chunk
        )
        try:
            for epoch in range(initial_epoch, epochs):
                if self.stop_training:
                    break
                # Fresh scale each epoch (see _fit_device_cached note).
                self.update_scale = 1.0
                for cb in callbacks:
                    cb.on_epoch_begin(epoch)
                t0 = time.perf_counter()
                scale = jnp.asarray(self.update_scale, jnp.float32)
                metric_acc = zero_acc
                step = 0
                for k in plan:
                    chunk = next(prefetcher)
                    self.state, metrics, metric_acc = run(
                        self.state, chunk, scale, metric_acc
                    )
                    step += k
                    # Once per execution, with the last step's metrics —
                    # Keras's steps_per_execution callback semantics.
                    for cb in callbacks:
                        cb.on_batch_end(step - 1, metrics)
                self._finish_epoch(
                    epoch, epochs, metric_acc, steps_per_epoch, t0, callbacks,
                    validation_data, batch_size, verbose,
                )
        finally:
            prefetcher.close()

    def _evaluate_device_cached(self, x, y, batch_size: int) -> dict:
        """evaluate() over a device-resident eval set: stage once (padded to
        full batches, padding masked), then each call is ONE dispatch + one
        3-scalar fetch. The per-epoch validation pass stops restreaming the
        test set from the host every epoch.

        Caching is by the host arrays' identity: do not mutate ``x``/``y``
        in place while cached, or stale staged data is evaluated."""
        key = (id(x), id(y), batch_size)
        if key not in self._eval_cache:
            n = len(x)
            n_shards = self.dp_size
            per = -(-n // (n_shards * batch_size)) * batch_size  # ceil→pad
            pad_n = per * n_shards
            mask = np.zeros(pad_n, np.float32)
            mask[:n] = 1.0

            def padded(a):
                # Repeat a REAL example into the padded tail (like the
                # streamed path): all-zero rows could produce non-finite
                # losses in input-normalizing models, and NaN*0 = NaN would
                # poison the masked sums.
                a = np.asarray(a)
                out = np.concatenate(
                    [a, np.repeat(a[-1:], pad_n - n, axis=0)]
                )
                return out

            data = (
                self._stage_sharded(padded(x), per),
                self._stage_sharded(padded(y), per),
                self._stage_sharded(mask, per),
            )
            # Keep x/y referenced so their ids stay unique while cached.
            self._eval_cache[key] = (data, per // batch_size, (x, y))
            if len(self._eval_cache) > 4:  # bound device memory
                self._eval_cache.pop(next(iter(self._eval_cache)))
        data, steps, _ = self._eval_cache[key]
        m = jax.device_get(
            self._eval_epoch(self.state, data, steps, batch_size)
        )
        return {
            "loss": float(m["loss_sum"]) / float(m["count"]),
            "accuracy": float(m["correct_sum"]) / float(m["count"]),
        }

    def evaluate(
        self, x, y, batch_size: int = 128, verbose: int = 0,
        cache: str | None = None,
    ) -> dict:
        """Full-dataset eval on the mesh. Unlike the reference (every rank
        redundantly evaluates the full test set, SURVEY.md §3.2), the eval
        batch is sharded across chips — same result, 1/size the work.
        ``cache='device'`` keeps the (padded, masked) eval set in HBM and
        runs the whole pass as one compiled scan."""
        if self.state is None:
            raise RuntimeError("call fit() or build() first")
        if (
            cache == "device"
            and self.batch_specs is not None
            and mesh_lib.has_live_model_axes(self.mesh)
        ):
            # Custom batch layouts over LIVE non-data axes (e.g. seq-sharded
            # tokens) need _shard's spec handling; the cached path stages
            # batch-dim-only. With those axes trivial the layouts coincide —
            # same condition as fit(cache='device')'s guard.
            cache = None
        if isinstance(x, list):
            x = np.asarray(x)  # list-of-rows = one array input (see fit)
        if cache == "device":
            if len(jax.tree_util.tree_leaves(x)) != 1:
                raise ValueError(
                    "cache='device' stages a single input array; pytree "
                    "(dict/tuple) inputs use the streamed eval path"
                )
            result = self._evaluate_device_cached(x, y, batch_size)
            if verbose and runtime.is_primary():
                print(f"eval - {({k: round(v, 4) for k, v in result.items()})}")
            return result
        if cache is not None:
            raise ValueError(f"unknown cache mode {cache!r}")
        # x may be a pytree (dict-input models, e.g. seq2seq) — slice, pad
        # and shard leaf-wise; y/mask stay flat arrays.
        n = len(jax.tree_util.tree_leaves(x)[0])
        global_batch = batch_size * self.dp_size
        loss_sum = correct_sum = count = 0.0
        for start in range(0, n, global_batch):
            xb, bs = self._slice_pad(x, start, global_batch)
            yb, _ = self._slice_pad(y, start, global_batch)
            mask = np.ones((global_batch,), np.float32)
            mask[bs:] = 0.0
            batch = tuple(
                jax.tree.map(
                    lambda a: self._local_slice(a, global_batch), part
                )
                for part in (xb, yb, mask)
            )
            m = jax.device_get(self._eval_step(self.state, self._shard(batch)))
            loss_sum += float(m["loss_sum"])
            correct_sum += float(m["correct_sum"])
            count += float(m["count"])
        result = {"loss": loss_sum / count, "accuracy": correct_sum / count}
        if verbose and runtime.is_primary():
            print(f"eval - {({k: round(v, 4) for k, v in result.items()})}")
        return result

    def _slice_pad(self, part, start: int, global_batch: int):
        """(batch slice padded to the compiled shape, true row count) for
        one batch part — leaf-wise, so pytree (dict-input) parts feed like
        flat arrays. ONE implementation of the multi-process padding
        contract, shared by evaluate and predict."""
        sliced = jax.tree.map(
            lambda a: np.asarray(a[start : start + global_batch]), part
        )
        bs = len(jax.tree_util.tree_leaves(sliced)[0])
        if bs < global_batch:
            pad = global_batch - bs
            sliced = jax.tree.map(
                lambda a: np.concatenate([a, np.repeat(a[-1:], pad, 0)]),
                sliced,
            )
        return sliced, bs

    def predict(self, x, batch_size: int = 128) -> np.ndarray:
        """Class probabilities (softmax applied here, keeping the serving
        contract input→prob, mnist_keras.py:133-134). ``x`` may be a pytree
        (dict-input models) — slice/pad/shard run leaf-wise, like
        `evaluate`."""
        if self.state is None:
            raise RuntimeError("call fit() or build() first")
        if isinstance(x, list):
            x = np.asarray(x)  # list-of-rows = one array input (see fit)
        out = []
        global_batch = batch_size * self.dp_size
        n = len(jax.tree_util.tree_leaves(x)[0])
        for start in range(0, n, global_batch):
            xb, bs = self._slice_pad(x, start, global_batch)
            xb = jax.tree.map(
                lambda a: self._local_slice(a, global_batch), xb
            )
            probs = jax.device_get(self._predict_step(self.state, self._shard(xb)))
            out.append(probs[:bs])
        return np.concatenate(out, axis=0)
