"""Keras-fit-like training loop around one jitted SPMD step.

This is the L4+L3 replacement (SURVEY.md §1): what the reference assembles
from Keras ``compile``/``fit`` + Horovod's DistributedOptimizer and callbacks
(tensorflow2_keras_mnist.py:62-96) becomes a `Trainer` owning a single jitted
train step: forward → loss(mean over **global** batch) → grad → update. With
the batch sharded along the mesh's data axis and parameters replicated, XLA
compiles the gradient all-reduce into the step (SURVEY.md §3.5: the entire
Horovod C++ hot path collapses into the compiled program).

Batch-size semantics (Horovod parity): ``batch_size`` is **per-worker**
(per-chip), exactly like the reference's ``batch(128)`` on every rank
(tensorflow2_keras_mnist.py:41); the global batch is
``batch_size × dp_size``. LR scaling by ``size`` (mesh.scale_lr) therefore
carries over unchanged.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Sequence

import flax.struct
import jax

from horovod_tpu import compat
from horovod_tpu.analysis import registry
import jax.numpy as jnp
import numpy as np
import optax

from horovod_tpu import runtime
from horovod_tpu.parallel import collectives
from horovod_tpu.parallel import mesh as mesh_lib
from horovod_tpu.parallel import sharding as sharding_lib
from horovod_tpu.training.optimizer import (
    accumulation_spec,
    compression_dtype,
    compression_error_feedback,
    compression_ici_dtype,
    error_feedback_wrap,
)

PyTree = Any

from horovod_tpu.training import build as build_lib
from horovod_tpu.training import feeding
from horovod_tpu.training.train_state import (  # noqa: F401 — re-exported:
    TrainState,          # the public state dataclass
    _accuracy,
    _aggregate_sown_metrics,
    _param_shaped_matcher,
    _resolve_loss,
    _run_train_end,
    _teardown_callbacks,
)

def _adapt_ef_residual(host_state, built_state):
    """Re-cut an error-feedback residual snapshot onto a new world size.

    The residual's leading axis is the old world's shard count; after an
    elastic reshard the new world's differs, and unlike every other state
    leaf there is no "correct" per-shard value to re-slice — the residual
    is untransmitted gradient MASS, and error-feedback correctness only
    needs the TOTAL eventually added back. Conserve it: sum the old
    shards' remainders and spread the total evenly over the new shard
    axis. Same-shape snapshots (plain restarts) pass through untouched."""
    try:
        host_res = host_state.opt_state.ef_residual
        built_res = built_state.opt_state.ef_residual
    except AttributeError:
        # Snapshot predates EF (or carries a bare inner state): leave it
        # to install_state's structural check to report.
        return host_state

    def recut(h, b):
        h = np.asarray(h)
        shape = jnp.shape(b)
        if h.shape == tuple(shape):
            return h
        if h.ndim == len(shape) and h.shape[1:] == tuple(shape)[1:]:
            total = h.sum(axis=0)
            return np.broadcast_to(
                total / shape[0], tuple(shape)
            ).astype(h.dtype).copy()
        return h  # unrelated mismatch — let install_state raise

    adapted = jax.tree.map(recut, host_res, built_res)
    return host_state.replace(
        opt_state=host_state.opt_state.replace(ef_residual=adapted)
    )


class Trainer:
    """compile+fit+evaluate+predict for a flax module over a device mesh.

    Args:
      module: a flax linen module; ``module.apply({'params': p}, x, train=...)``
        must return logits. Modules may accept a ``train`` kwarg and a
        ``dropout`` rng (both reference models use dropout).
      optimizer: an optax transformation — typically
        ``hvt.DistributedOptimizer(optax.adam(hvt.scale_lr(1e-3)))``.
      loss: Keras-style name or ``fn(logits, labels) -> per-example loss``.
      mesh: device mesh; defaults to all chips on the data axis (the
        reference's pure-DP topology).
      seed: init/dropout seed.
    """

    def __init__(
        self,
        module,
        optimizer: optax.GradientTransformation,
        loss="sparse_categorical_crossentropy",
        mesh=None,
        seed: int = 0,
        param_specs=None,
        batch_specs=None,
        steps_per_execution: int = 1,
        shard_update: bool = False,
        bucket_bytes: int | None = None,
        overlap_reduction: bool | None = None,
        bucket_order: str | None = None,
    ):
        self.module = module
        self.tx = optimizer
        self.loss_fn = _resolve_loss(loss)
        self._module_loss = loss == "module"
        self.mesh = mesh if mesh is not None else mesh_lib.data_parallel_mesh()
        self.seed = seed
        # param_specs: callable (params, mesh) -> PartitionSpec pytree, or a
        # spec pytree — TP/FSDP parameter layout (e.g.
        # models.transformer.param_specs). None = replicated (pure DP, the
        # reference's layout).
        self.param_specs = param_specs
        self._param_shardings = None
        # batch_specs: PartitionSpec pytree matching the batch structure —
        # e.g. P(('data','fsdp'), 'seq') for sequence-sharded LM tokens.
        # None = shard dim 0 along the data axes.
        self.batch_specs = batch_specs
        self.state: TrainState | None = None
        # Non-'params' variable collections to thread through training
        # (e.g. ['batch_stats']); discovered at build() — before the first
        # (lazily-traced) _train_step call, so the closures see it static.
        self._mutable: list[str] = []
        # Update scale multiplies the optimizer's update — the knob the LR
        # callbacks turn (scaling the update by s is equivalent to scaling
        # the LR by s for the reference optimizers). Reset to 1.0 at every
        # epoch begin, before callbacks run: warmup ASSIGNS its ramp value,
        # schedule callbacks MULTIPLY — so Horovod's warmup→decay stacking
        # composes in callback-list order.
        self.update_scale: float = 1.0
        self.stop_training = False
        self.history: list[dict] = []
        # Where the CURRENT fit resumed — (initial_epoch, initial_step)
        # after normalization (feeding._normalize_resume). Resume-aware
        # callbacks (the elastic commit/rescale cadences) read these to
        # measure step cadences from the true resume point.
        self._resume_epoch = 0
        self._resume_step = 0
        # Geometry of the CURRENT fit's data stream (set by the feeding
        # paths) — what `stream_cursor` stamps into the durable cursors
        # that ride checkpoint manifests and elastic commits.
        self._stream_geometry: dict | None = None
        # Keras's steps_per_execution: K > 1 compiles a lax.scan over K train
        # steps into ONE executable, so dispatch + input-transfer overhead is
        # paid once per K steps instead of per step. Semantics trade-off
        # (identical to Keras): on_batch_end callbacks fire once per
        # execution, with the last step's metrics.
        self.steps_per_execution = max(1, int(steps_per_execution))
        # Names of module-sown 'metrics' scalars (discovered at build());
        # sizes the epoch metric accumulator alongside loss/accuracy.
        self._metric_names: tuple = ()
        # Gradient wire compression (DistributedOptimizer(compression=...)):
        # honoured by computing gradients in an explicit-collective shard_map
        # whose psum runs on the 16-bit dtype (_compressed_grads). Only the
        # replicated-parameter (pure-DP/FSDP-free) layout is supported — with
        # sharded params the gradient traffic is layout-dependent and the
        # implicit SPMD reduction must stay in charge.
        self._comm_dtype = compression_dtype(optimizer)
        # ICI-hop wire (DistributedOptimizer(compression_ici=...)): rides
        # the hierarchical two-hop reduction's intra-slice hop only —
        # inert on single-slice meshes (dcn == 1), where there is no
        # factoring to put it on.
        self._ici_dtype = compression_ici_dtype(optimizer)
        if (
            self._comm_dtype is not None or self._ici_dtype is not None
        ) and param_specs is not None:
            raise ValueError(
                "DistributedOptimizer(compression=/compression_ici=...) "
                "requires replicated parameters (param_specs=None); "
                "sharded-parameter layouts keep XLA's implicit f32 "
                "gradient reduction"
            )
        # Gradient accumulation (DistributedOptimizer(backward_passes_per_
        # step=K)): the Trainer runs the K microbatch passes INSIDE one
        # compiled step — local f32 grad accumulation, exactly one
        # cross-worker reduction and one optimizer apply per K passes — so
        # the MultiSteps wrap (zero updates + a params-sized accumulator in
        # opt_state) is swapped for the unwrapped inner transformation (see
        # optimizer.accumulation_spec). Each train step then consumes a
        # [K, batch, ...] microbatch stack.
        self._accum = accumulation_spec(optimizer)
        self._accum_steps = self._accum.k if self._accum is not None else 1
        if self._accum is not None:
            if param_specs is not None:
                raise ValueError(
                    "DistributedOptimizer(backward_passes_per_step=K) "
                    "requires replicated parameters (param_specs=None): "
                    "the accumulating step's explicit boundary reduction "
                    "assumes the pure-DP gradient layout"
                )
            if batch_specs is not None:
                raise ValueError(
                    "backward_passes_per_step does not compose with custom "
                    "batch_specs — the microbatch stack is sharded along "
                    "the data axes only"
                )
            self.tx = self._accum.inner
        # Boundary-reduction fusion buckets (Horovod's tensor-fusion
        # threshold): the explicit-collective step reduces gradients as a
        # few contiguous dtype-homogeneous buckets of at most this many
        # bytes, instead of one collective per leaf.
        self._bucket_bytes = int(
            bucket_bytes
            or registry.get_int("HVT_BUCKET_BYTES")
            or collectives.DEFAULT_BUCKET_BYTES
        )
        # Overlap the boundary reduction with the tail of the backward
        # (Horovod's tensor-fusion + overlap design, arXiv:1802.05799):
        # the LAST microbatch of the accumulation scan is peeled into the
        # step's straight-line computation, so its backward and the
        # bucket-wise reduction sit in ONE schedulable region — XLA's
        # latency-hiding scheduler can then start a bucket's collective
        # (async all-reduce/all-gather start/done pairs on TPU) as soon as
        # that bucket's gradients are final, while earlier layers'
        # backward still computes. Identical arithmetic to the serialized
        # form (same addition order, same bucket values) — structure only.
        self._overlap = (
            bool(overlap_reduction)
            if overlap_reduction is not None
            else registry.get_flag("HVT_OVERLAP_REDUCTION")
        )
        # Bucket issue order: 'reverse' (default) walks the gradient leaves
        # last-first, so the first-issued buckets are the ones the backward
        # produces first — the order that makes the overlap above real.
        order = bucket_order or registry.get_str("HVT_BUCKET_ORDER")
        if order not in ("reverse", "forward"):
            raise ValueError(
                f"bucket_order must be 'reverse' or 'forward', got {order!r}"
            )
        self._bucket_reverse = order == "reverse"
        # The explicit-collective step runs whenever any of its features
        # is requested: a wire dtype (either hop), accumulation (K > 1).
        # Everything else keeps the implicit SPMD reduction.
        self._explicit_step = (
            self._comm_dtype is not None
            or self._ici_dtype is not None
            or self._accum_steps > 1
        )
        # Multi-slice factor of the data axis (1 on single-slice meshes):
        # when > 1, the boundary reduction runs two-hop — ICI sub-axis in
        # full precision (or the compression_ici wire), DCN sub-axis in
        # the compression dtype (EQuARX-style DCN-side quantization).
        # Only consulted by the explicit-collective step; the default
        # SPMD path leaves reduction placement to XLA.
        self._dcn = (
            mesh_lib.dcn_factor(self.mesh) if self._explicit_step else 1
        )
        # ZeRO-1 / cross-replica weight-update sharding (Xu et al.,
        # arXiv:2004.13336 — PAPERS.md): keep the MODEL replicated (pure-DP
        # forward/backward, the reference's layout) but shard the optimizer
        # state — and therefore the weight update — across the data axis.
        # Delivered the XLA-native way the paper describes: the opt-state
        # leaves get P('data') dim-0 shardings at init, and GSPMD turns the
        # step's gradient reduction into reduce-scatter + the param update
        # into an all-gather. Per-device optimizer memory drops ~1/dp (for
        # Adam, opt state is 2× params — the dominant state at scale).
        self.shard_update = shard_update
        if shard_update and param_specs is not None:
            raise ValueError(
                "shard_update (ZeRO-1) targets the replicated-parameter "
                "layout; with param_specs the optimizer mirrors already "
                "follow the fsdp/tp sharding — compose via the fsdp axis "
                "instead"
            )
        # shard_update COMPOSES with backward_passes_per_step, wire
        # compression and the overlap peel (the former three fail-fasts):
        # the explicit-collective step's boundary reduction lowers into
        # the sharded weight-update layout via
        # `collectives.reduce_gradients(scatter=dp)` — dtype-homogeneous
        # buckets arranged so one psum_scatter per bucket hands every
        # shard exactly the gradient slice its zero1 optimizer mirror
        # consumes (quantized wires keep the dense bucket layout —
        # bitwise-identical to the replicated reduction — and slice
        # locally; see the collectives docstring). The K-microbatch scan,
        # reverse bucket order and the overlap peel are untouched: the
        # scatter happens at the same single call site.
        self._scatter = (
            self.mesh.shape.get(mesh_lib.DATA_AXIS, 1) if shard_update
            else 1
        )
        # Quantized-wire error feedback (compression='int8'/'fp8' on
        # EITHER hop, with error_feedback=True): the per-shard
        # untransmitted quantization remainder lives in opt_state
        # (`ErrorFeedbackState`, one [n_shards, *param] f32 leaf per
        # parameter, leading axis sharded over the data axes) so
        # checkpoints, broadcasts and elastic commits carry it with no
        # extra plumbing. The step reads it into the boundary reduction
        # and writes the new remainder back — charged per hop when both
        # hops quantize. Deliberately NOT gated on self._dcn: a
        # quantized ICI wire on a single-slice mesh carries a residual
        # that provably flushes to zeros each step (pure overhead), but
        # making the opt-state STRUCTURE depend on the topology would
        # break every cross-topology state surface (an elastic rescale
        # across a slice boundary, a checkpoint restored on a different
        # slice count) — don't set compression_ici on single-slice
        # fleets instead.
        self._ef = (
            collectives.is_quantized_wire(self._comm_dtype)
            or collectives.is_quantized_wire(self._ici_dtype)
        ) and compression_error_feedback(optimizer)
        if self._ef:
            self.tx = error_feedback_wrap(
                self.tx, mesh_lib.dp_size(self.mesh)
            )

        def forward_loss(variables, x, y, rng):
            """Shared train-mode forward: (core_loss+aux, acc, updated, sown
            metrics) under either loss contract — Trainer-side loss_fn on
            logits, or loss='module' (apply(x, labels=y) → per-token
            (loss, correct), the fused-CE head's path)."""
            kwargs = {"labels": y} if self._module_loss else {}
            out, updated = self.module.apply(
                variables, x, train=True, **kwargs,
                rngs={"dropout": rng},
                mutable=self._mutable + ["losses", "metrics"],
            )
            sown = updated.pop("losses", {})
            sm = _aggregate_sown_metrics(updated.pop("metrics", {}))
            aux = sum(
                (jnp.sum(v) for v in jax.tree.leaves(sown)),
                jnp.zeros((), jnp.float32),
            )
            if self._module_loss:
                loss_vec, correct = out
                loss, acc = loss_vec.mean() + aux, correct.mean()
            else:
                loss = self.loss_fn(out, y).mean() + aux
                acc = _accuracy(out, y)
            return loss, acc, (dict(updated) if updated else None), sm

        def explicit_grads(state: TrainState, xs, ys, step_rng, residual):
            """(loss, acc, model_state, sown_metrics, grads, new_residual)
            with the cross-worker gradient reduction made explicit — the
            K-microbatch accumulating, bucket-fused, wire-compressed,
            backward-overlapped step.

            ``xs``/``ys`` leaves are [K, G, ...] microbatch stacks (K =
            backward_passes_per_step; the plain-compression K == 1 case is
            stacked to [1, G, ...] by train_step). Each microbatch runs
            forward/backward per shard producing LOCAL gradients — no
            reduction — accumulated in f32 on device; then exactly ONE
            boundary reduction per optimizer step: the gradient pytree is
            packed into a handful of contiguous dtype-homogeneous buckets
            (Horovod tensor-fusion semantics, `collectives.
            reduce_gradients`), each bucket psum'd in the 16-bit wire
            dtype when compression is on (compress, ring allreduce-SUM on
            the wire, decompress, then average) — or gather-summed with a
            per-bucket scale for int8/fp8 wires — and two-hop on a
            multi-slice mesh — the ICI sub-axis in full precision, only
            the DCN sub-axis in the compression dtype (EQuARX-style).
            Horovod's accumulation contract holds: the K grads are SUMMED
            (``average_aggregated_gradients=False``, the default) or
            averaged; reported loss/accuracy are the mean over the K
            microbatches (what one K·B-batch step would report).

            Overlap (HVT_OVERLAP_REDUCTION, default on): microbatches
            0..K-2 accumulate inside a `lax.scan`, but the LAST
            microbatch's forward/backward is peeled into the step's
            straight-line region, immediately followed by the bucket-wise
            boundary reduction issued in reverse bucket order
            (last-produced gradients first, HVT_BUCKET_ORDER). A
            collective after a scan can never start before the scan
            returns; with the peel, each bucket's reduction depends only
            on that bucket's leaves, so XLA's latency-hiding scheduler is
            free to overlap bucket i's ICI/DCN transfer with the
            still-running backward of earlier layers — Horovod's
            tensor-fusion + overlap design (arXiv:1802.05799) as compiled
            structure. On the ZeRO-1 composed path the same holds for
            the scatter-form reduction: buckets are leaf-aligned in both
            directions (`collectives.flatten_scatter_buckets`), so each
            bucket's `psum_scatter` issues inside this peeled region as
            its gradients finalize AND the per-shard optimizer apply for
            its leaves (train_step's zero1-pinned update) is schedulable
            as soon as it lands — no full-tree barrier between scatter
            and update. Arithmetic is IDENTICAL to the serialized form
            (same addition order, same bucket contents): the knob changes
            schedulability, not semantics.

            ``residual``/``new_residual``: the quantized-wire
            error-feedback state (None unless compression='int8'/'fp8'
            with error_feedback) — [n_shards, *param] f32 leaves, this
            shard's slice added to the pre-quantization bucket values and
            replaced by the new untransmitted remainder.

            Contract deltas vs the SPMD path (both only observable with
            non-iid extras, never with the plain CE objective):
            * sown 'losses' must be batch-MEAN-style (magnitude independent
              of batch size — like models/moe.py's load-balance mean): the
              per-shard means average to the global mean exactly. A
              batch-SUM-style sow would contribute 1/n_shards of its SPMD
              weight here.
            * BatchNorm running variance is the mean of per-shard batch
              variances, which drops the between-shard-means term (law of
              total variance) vs the SPMD path's exact global-batch
              variance. Identical for iid shards (the sharded loader's
              case); an underestimate only for systematically skewed
              shards. With K > 1 the running stats additionally step once
              per MICROBATCH (momentum applied K times per optimizer
              step), the standard accumulation behavior."""
            comm = self._comm_dtype
            K = self._accum_steps
            avg_k = self._accum.average if self._accum is not None else False
            data_axes = (mesh_lib.DATA_AXIS, mesh_lib.FSDP_AXIS)

            def local(params, ms, xs, ys, res):
                # Distinct dropout per shard (the SPMD path's global mask is
                # partitioned; here each shard must draw its own), and per
                # microbatch when accumulating.
                shard_rng = jax.random.fold_in(
                    step_rng, jax.lax.axis_index(data_axes)
                )

                def loss_of(params, xb, yb, ms, rng):
                    loss, acc, upd, sm = forward_loss(
                        {"params": params, **(ms or {})}, xb, yb, rng
                    )
                    return loss, (acc, upd if upd is not None else ms, sm)

                grad_fn = jax.value_and_grad(loss_of, has_aux=True)
                x0 = jax.tree.map(lambda a: a[0], xs)
                y0 = jax.tree.map(lambda a: a[0], ys)
                # K == 1 keeps the pre-accumulation rng stream bit-exact.
                rng0 = (
                    shard_rng if K == 1
                    else jax.random.fold_in(shard_rng, 0)
                )
                (loss, (acc, new_ms, sm)), grads = grad_fn(
                    params, x0, y0, ms, rng0
                )
                # Local accumulation in f32: microbatch grads sum without
                # precision loss even for bf16-param models.
                grads = jax.tree.map(
                    lambda g: g.astype(jnp.float32), grads
                )
                # Overlap structure: peel the LAST microbatch out of the
                # scan so its backward and the bucket reductions share one
                # straight-line region (see the docstring); the scan then
                # covers microbatches 1..K-2 only. Serialized form (knob
                # off) scans 1..K-1 — same additions, same results.
                peel = self._overlap and K > 1
                n_scan = K - 1 - (1 if peel else 0)
                if n_scan > 0:
                    def micro(carry, inp):
                        g_acc, ms_c, loss_s, acc_s, sm_s = carry
                        k, xb, yb = inp
                        (l, (a, ms_c, smk)), g = grad_fn(
                            params, xb, yb, ms_c,
                            jax.random.fold_in(shard_rng, k),
                        )
                        g_acc = jax.tree.map(
                            lambda A, G: A + G.astype(jnp.float32), g_acc, g
                        )
                        return (
                            g_acc, ms_c, loss_s + l, acc_s + a,
                            jax.tree.map(jnp.add, sm_s, smk),
                        ), None

                    (grads, new_ms, loss, acc, sm), _ = jax.lax.scan(
                        micro, (grads, new_ms, loss, acc, sm),
                        (
                            jnp.arange(1, 1 + n_scan),
                            jax.tree.map(
                                lambda a: a[1 : 1 + n_scan], xs
                            ),
                            jax.tree.map(
                                lambda a: a[1 : 1 + n_scan], ys
                            ),
                        ),
                    )
                if peel:
                    xl = jax.tree.map(lambda a: a[K - 1], xs)
                    yl = jax.tree.map(lambda a: a[K - 1], ys)
                    (l, (a, new_ms, smk)), g = grad_fn(
                        params, xl, yl, new_ms,
                        jax.random.fold_in(shard_rng, K - 1),
                    )
                    grads = jax.tree.map(
                        lambda A, G: A + G.astype(jnp.float32), grads, g
                    )
                    loss, acc = loss + l, acc + a
                    sm = jax.tree.map(jnp.add, sm, smk)
                if K > 1:
                    loss, acc = loss / K, acc / K
                    sm = jax.tree.map(lambda v: v / K, sm)
                # THE one cross-worker reduction of the optimizer step —
                # bucket-wise, reverse-ordered, error-feedback-corrected.
                res_in = (
                    None if res is None
                    else jax.tree.map(lambda r: r[0], res)
                )
                reduced = collectives.reduce_gradients(
                    grads,
                    data_axis=mesh_lib.DATA_AXIS,
                    extra_axes=(mesh_lib.FSDP_AXIS,),
                    dcn=self._dcn,
                    wire_dtype=comm,
                    ici_wire_dtype=self._ici_dtype,
                    bucket_bytes=self._bucket_bytes,
                    reverse=self._bucket_reverse,
                    residual=res_in,
                    # ZeRO-1 composition: scatter the reduction into the
                    # sharded weight-update layout — each shard receives
                    # only ITS zero1 slice of the divisible leaves (the
                    # rest replicated), matching build's opt mirrors.
                    # Buckets are leaf-aligned in both directions
                    # (flatten_scatter_buckets), so inside this peeled
                    # straight-line region bucket i's psum_scatter can
                    # issue as soon as its leaves' gradients are final
                    # and the downstream per-shard optimizer math for
                    # bucket i's leaves can start as soon as it lands —
                    # the per-bucket backward-overlapped schedule.
                    scatter=self._scatter if self._scatter > 1 else None,
                )
                if res is None:
                    grads, new_res = reduced, None
                else:
                    grads, err = reduced
                    new_res = jax.tree.map(lambda r: r[None], err)
                # Sum → Horovod semantics: divide by world size (mean over
                # workers) and, only with average_aggregated_gradients, by
                # K (mean over passes; the default keeps the K-pass SUM).
                denom = jax.lax.psum(1, data_axes) * (K if avg_k else 1)
                grads = jax.tree.map(
                    lambda g, p: (g / denom).astype(p.dtype), grads, params
                )
                loss = jax.lax.pmean(loss, data_axes)
                acc = jax.lax.pmean(acc, data_axes)
                sm = jax.tree.map(lambda v: jax.lax.pmean(v, data_axes), sm)
                if new_ms is not None:
                    # Cross-shard mean of updated statistics; non-float
                    # leaves (step counters) are shard-invariant already.
                    # For BN this is mean-of-shard-means (exact) and
                    # mean-of-shard-variances (iid-exact; see docstring).
                    new_ms = jax.tree.map(
                        lambda v: jax.lax.pmean(v, data_axes)
                        if jnp.issubdtype(jnp.asarray(v).dtype, jnp.floating)
                        else v,
                        new_ms,
                    )
                return loss, acc, new_ms, sm, grads, new_res

            P = jax.sharding.PartitionSpec
            stacked = P(None, data_axes)
            sharded0 = P(data_axes)  # residual: leading shard axis
            if self._scatter > 1:
                # ZeRO-1: the boundary reduction hands each shard its
                # zero1 slice, so the grads leave the shard_map SHARDED
                # over the data axis at each leaf's zero1 dim — exactly
                # the layout the opt-state mirrors carry.
                grads_spec = jax.tree.map(
                    lambda p: collectives.zero1_partition_spec(
                        jnp.shape(p), self._scatter
                    ),
                    state.params,
                )
            else:
                grads_spec = P()
            return compat.shard_map(
                local,
                mesh=self.mesh,
                in_specs=(P(), P(), stacked, stacked, sharded0),
                out_specs=(P(), P(), P(), P(), grads_spec, sharded0),
                check_vma=False,
            )(state.params, state.model_state, xs, ys, residual)

        def train_step(state: TrainState, batch, update_scale, metric_acc):
            x, y = batch
            step_rng = jax.random.fold_in(state.rng, state.step)

            def loss_of(params):
                # 'losses' is the auxiliary-objective channel: any value a
                # module sows there during training (e.g. MoE load-balance
                # loss, models/moe.py) is added to the objective. Requested
                # as mutable unconditionally — it costs nothing when unused,
                # and is never carried in model_state (sown per-apply).
                # Contract: sow batch-MEAN-style values (batch-size
                # independent) so the compressed_grads path weights them
                # identically (see its docstring). 'metrics' is the sown
                # OBSERVABILITY channel: scalar values land in the step
                # metrics / epoch logs / sinks (e.g. MoE router drop-rate,
                # models/moe.py) — see _aggregate_sown_metrics.
                loss, acc, upd, sm = forward_loss(
                    {"params": params, **(state.model_state or {})},
                    x, y, step_rng,
                )
                return loss, (
                    acc, upd if upd is not None else state.model_state, sm
                )

            if self._explicit_step:
                if self._accum_steps > 1:
                    sx, sy = x, y  # already [K, G, ...] microbatch stacks
                else:
                    # Plain compression: one microbatch, stacked to [1, G].
                    sx = jax.tree.map(lambda a: a[None], x)
                    sy = jax.tree.map(lambda a: a[None], y)
                residual = (
                    state.opt_state.ef_residual if self._ef else None
                )
                (loss, acc, model_state, sown_metrics, grads,
                 new_residual) = explicit_grads(
                    state, sx, sy, step_rng, residual
                )
            else:
                new_residual = None
                (loss, (acc, model_state, sown_metrics)), grads = (
                    jax.value_and_grad(loss_of, has_aux=True)(state.params)
                )
            updates, opt_state = self.tx.update(grads, state.opt_state, state.params)
            if self._ef:
                # Install the boundary reduction's new untransmitted
                # remainder (the EF wrapper's update passed the old one
                # through untouched).
                opt_state = opt_state.replace(ef_residual=new_residual)
            updates = jax.tree.map(lambda u: u * update_scale, updates)
            if self._scatter > 1 and self._explicit_step:
                # Composed ZeRO-1 path: pin the zero1 layout on the
                # updates so the replication boundary is the param
                # all-gather AFTER the sharded optimizer math —
                # propagation must not re-replicate the scattered
                # gradients and optimizer mirrors instead. The optimizer
                # math itself is per-leaf elementwise dataflow over the
                # scattered gradients, so with leaf-aligned buckets each
                # bucket's shard-local apply (and its param all-gather
                # below) is schedulable the moment THAT bucket's scatter
                # lands — the fused per-shard apply of the weight-update
                # -sharding end state (arXiv:2004.13336), as compiled
                # structure.
                updates = jax.lax.with_sharding_constraint(
                    updates,
                    jax.tree.map(
                        lambda p: jax.sharding.NamedSharding(
                            self.mesh,
                            collectives.zero1_partition_spec(
                                jnp.shape(p), self._scatter
                            ),
                        ),
                        state.params,
                    ),
                )
            params = optax.apply_updates(state.params, updates)
            if self._scatter > 1:
                # ZeRO-1 (implicit or composed): the updated params must
                # come back REPLICATED. Left to propagation, XLA keeps
                # them data-sharded — deferring the all-gather into the
                # NEXT step — which breaks the step's own closure
                # contract (params re-enter replicated: a silent second
                # executable per fit, AOT reuse errors) and every state
                # surface that assumes the built layout (checkpoint
                # broadcast, elastic commit's sharded-leaf detection).
                # The constraint places the update all-gather inside the
                # step, where ZeRO-1 pays it by design.
                params = jax.lax.with_sharding_constraint(
                    params, sharding_lib.replicated(self.mesh)
                )
            if self._param_shardings is not None:
                # Pin the TP/FSDP layout so XLA's propagation can't drift the
                # updated params away from their declared placement.
                params = jax.lax.with_sharding_constraint(
                    params, self._param_shardings
                )
            new_state = state.replace(
                step=state.step + 1, params=params, opt_state=opt_state,
                model_state=model_state,
            )
            if tuple(sorted(sown_metrics)) != self._metric_names:
                # Trace-time (keys are Python): a train-gated sow would
                # otherwise surface as an opaque pytree mismatch in the
                # accumulator add below.
                raise ValueError(
                    f"sown 'metrics' names at train time "
                    f"{sorted(sown_metrics)} differ from those discovered "
                    f"at build() {list(self._metric_names)} — 'metrics' "
                    "sows must be unconditional (not gated on train)"
                )
            metrics = {"loss": loss, "accuracy": acc, **sown_metrics}
            # Epoch metric sums accumulate inside the compiled step: per-step
            # host fetches (or even per-step host-side adds) each cost a
            # dispatch/transfer round-trip, which dominates wall-clock on a
            # networked TPU; this way an epoch ends with ONE few-scalar fetch.
            new_acc = jax.tree.map(jnp.add, metric_acc, metrics)
            return new_state, metrics, new_acc

        def train_epoch(
            state: TrainState, data, epoch_seed, update_scale, metric_acc,
            steps: int, per_chip_batch: int, start=0,
        ):
            """One epoch CHUNK over a DEVICE-RESIDENT dataset, on-device.

            ``data`` leaves are [n_shards, per_shard_n, ...], example axis
            sharded over the data axes — the dataset lives in HBM. Each epoch
            draws a fresh per-shard permutation (sharded RNG is
            shard-local under partitionable threefry) and scans ``steps``
            train steps, gathering each chip's ``per_chip_batch`` examples
            from its own shard — zero host↔device traffic inside the epoch.
            Per-shard independent shuffles are the reference's own sampling
            semantics (every rank shuffles independently,
            tensorflow2_keras_mnist.py:37-41), with the improvement that
            shards partition the data so an epoch sees each example once.

            ``start`` begins the chunk MID-epoch at optimizer step
            ``start`` (the `fit(initial_step=)` resume contract AND the
            step-chunked epoch cadence, ``HVT_EPOCH_CHUNK_STEPS``): the
            permutation is a pure function of ``epoch_seed``, so any
            chunk regenerates the uninterrupted epoch's exact order and
            the gather/scan below simply cover steps [start, start +
            steps) — rows outside the window are never gathered.
            ``start`` is a DYNAMIC argument (``steps`` is the static
            chunk length), so every same-length chunk of an epoch shares
            ONE compiled executable — an epoch split into C chunks costs
            at most two programs (full + remainder), not C."""
            first = jax.tree.leaves(data)[0]
            n_shards, per_n = first.shape[0], first.shape[1]
            K = self._accum_steps  # microbatches consumed per optimizer step
            u = jax.random.uniform(epoch_seed, (n_shards, per_n))
            order = jnp.argsort(u, axis=1)  # row-wise → shard-local

            # Materialize the epoch's shuffle ONCE: one per-shard row gather
            # of the rows this epoch will actually consume (bandwidth-bound,
            # amortized over every step), so the per-step read is a
            # contiguous dynamic slice — random per-step row gathers are
            # latency-bound on TPU and were the e2e step's input cost
            # (0.68 ms/step at CIFAR shapes vs ~0 after; round 2 measured
            # them at 31% of the MNIST step). The gather runs over FLATTENED
            # trailing dims (~9x a multi-dim-trailing gather,
            # benchmarks/conv_profile.py). HBM cost: a second copy of the
            # CONSUMED prefix (the full dataset when steps cover the epoch),
            # live alongside `data` for the epoch — the device-cached path
            # trades HBM for zero per-step host/latency cost by design; use
            # the streamed fit path when the dataset crowds HBM.
            lo = jnp.asarray(start, jnp.int32) * (per_chip_batch * K)
            width = steps * per_chip_batch * K  # static: chunk row count
            window = jax.lax.dynamic_slice_in_dim(order, lo, width, axis=1)
            shuffled = jax.tree.map(
                lambda a: jax.vmap(
                    lambda rows, ii: jnp.take(rows, ii, axis=0)
                )(
                    a.reshape(a.shape[0], a.shape[1], -1), window
                ).reshape((a.shape[0], width) + a.shape[2:]),
                data,
            )

            def body(carry, t):
                state, acc = carry
                if K == 1:
                    batch = jax.tree.map(
                        lambda a: jax.lax.dynamic_slice_in_dim(
                            a, t * per_chip_batch, per_chip_batch, axis=1
                        ).reshape((n_shards * per_chip_batch,) + a.shape[2:]),
                        shuffled,
                    )
                else:
                    # One optimizer step consumes K contiguous microbatches
                    # per shard, restacked to the [K, global_batch, ...]
                    # layout the accumulating step expects.
                    def take(a):
                        sl = jax.lax.dynamic_slice_in_dim(
                            a, t * K * per_chip_batch, K * per_chip_batch,
                            axis=1,
                        ).reshape(
                            (n_shards, K, per_chip_batch) + a.shape[2:]
                        )
                        return jnp.moveaxis(sl, 1, 0).reshape(
                            (K, n_shards * per_chip_batch) + a.shape[2:]
                        )

                    batch = jax.tree.map(take, shuffled)
                state, metrics, acc = train_step(state, batch, update_scale, acc)
                return (state, acc), metrics

            (state, metric_acc), metrics = jax.lax.scan(
                body, (state, metric_acc), jnp.arange(steps)
            )
            last = jax.tree.map(lambda m: m[-1], metrics)
            return state, last, metric_acc

        def train_chunk(state: TrainState, batches, update_scale, metric_acc):
            """K stacked batches ([K, ...] leaves) through K chained steps in
            one compiled program (scan keeps the trace size constant)."""

            def body(carry, batch):
                state, acc = carry
                state, metrics, acc = train_step(state, batch, update_scale, acc)
                return (state, acc), metrics

            (state, metric_acc), metrics = jax.lax.scan(
                body, (state, metric_acc), batches
            )
            last = jax.tree.map(lambda m: m[-1], metrics)
            return state, last, metric_acc

        def _eval_variables(state: TrainState):
            return {"params": state.params, **(state.model_state or {})}

        def eval_step(state: TrainState, batch):
            # Masked sums (mask zeroes padding) so full-dataset metrics are
            # exact even when the tail batch is padded to the global shape.
            # The per-example mask broadcasts over any trailing loss dims
            # (sequence models produce per-token losses [G, T]); `count`
            # then counts tokens, keeping the mean per-token.
            x, y, mask = batch
            if self._module_loss:
                loss_vec, correct = self.module.apply(
                    _eval_variables(state), x, train=False, labels=y
                )
            else:
                logits = self.module.apply(
                    _eval_variables(state), x, train=False
                )
                loss_vec = self.loss_fn(logits, y)
                pred = jnp.argmax(logits, axis=-1)
                labels = jnp.argmax(y, axis=-1) if y.ndim == logits.ndim else y
                correct = (pred == labels).astype(jnp.float32)
            w = mask.reshape(mask.shape + (1,) * (loss_vec.ndim - 1))
            w = jnp.broadcast_to(w, loss_vec.shape)
            return {
                "loss_sum": (loss_vec * w).sum(),
                "correct_sum": (correct * w).sum(),
                "count": w.sum(),
            }

        def eval_epoch(state: TrainState, data, steps: int, per_chip_batch: int):
            """Whole-dataset eval over a DEVICE-RESIDENT (padded + masked)
            eval set: one dispatch, one 3-scalar fetch — instead of
            restreaming the test set from the host every epoch."""
            xs, ys, masks = data  # [n_shards, per_n(, ...)] leaves

            def body(acc, t):
                def take(a):
                    sl = jax.lax.dynamic_slice_in_dim(
                        a, t * per_chip_batch, per_chip_batch, axis=1
                    )
                    return sl.reshape((-1,) + sl.shape[2:])

                m = eval_step(state, (take(xs), take(ys), take(masks)))
                return jax.tree.map(jnp.add, acc, m), None

            zero = {
                "loss_sum": jnp.zeros((), jnp.float32),
                "correct_sum": jnp.zeros((), jnp.float32),
                "count": jnp.zeros((), jnp.float32),
            }
            acc, _ = jax.lax.scan(body, zero, jnp.arange(steps))
            return acc

        def predict_step(state: TrainState, x):
            logits = self.module.apply(_eval_variables(state), x, train=False)
            return jax.nn.softmax(logits, axis=-1)

        # Error-feedback states must NOT donate the TrainState: the
        # [n_shards, ...] dim-0-sharded residual gets input→output
        # donation-aliased, and on this jax floor (0.4.37 CPU) an
        # executable carrying that aliasing SEGFAULTS when reloaded from
        # the persistent compilation cache (reproduced: second
        # same-process int8+EF fit dies inside the deserialized step;
        # clean with donation off or error_feedback=False). EF already
        # pays a params-sized residual; the lost donation costs one more
        # transient state copy.
        state_donate = () if self._ef else (0,)
        self._train_step = jax.jit(train_step, donate_argnums=state_donate)
        self._train_chunk = jax.jit(train_chunk, donate_argnums=state_donate)
        # Streamed-fit variants that ALSO donate the batch: each prefetched
        # chunk is consumed exactly once, so its transfer buffer returns to
        # the allocator at dispatch — with the double-buffered prefetcher
        # (data/prefetch.py) two batch-sized buffers alternate instead of
        # accumulating. Bench/tests reuse batches across calls and must
        # keep the non-donating forms above.
        self._train_step_donated = jax.jit(
            train_step, donate_argnums=state_donate + (1,)
        )
        self._train_chunk_donated = jax.jit(
            train_chunk, donate_argnums=state_donate + (1,)
        )
        # `start` (argnum 7) is DYNAMIC: every same-length chunk of a
        # step-chunked epoch (HVT_EPOCH_CHUNK_STEPS) and every resume
        # offset reuses one executable per chunk length.
        self._train_epoch = jax.jit(
            train_epoch, static_argnums=(5, 6),
            donate_argnums=state_donate,
        )
        self._eval_step = jax.jit(eval_step)
        self._eval_epoch = jax.jit(eval_epoch, static_argnums=(2, 3))
        # Staged eval sets for evaluate(cache='device'), keyed by the host
        # arrays' identity. Entries hold strong references to those arrays,
        # so a cached id cannot be recycled by the allocator while its
        # staging is alive.
        self._eval_cache: dict = {}
        # Replicated output → fully addressable on every process, so
        # device_get works in multi-host runs too.
        self._predict_step = jax.jit(
            predict_step, out_shardings=sharding_lib.replicated(self.mesh)
        )
    # --- state management ---------------------------------------------------

    @property
    def dp_size(self) -> int:
        return mesh_lib.dp_size(self.mesh)

    @property
    def metric_names(self) -> tuple:
        """All per-step metric keys: loss/accuracy plus any module-sown
        'metrics' scalars (available after build())."""
        return ("loss", "accuracy") + self._metric_names

    def zero_metrics(self) -> dict:
        """A zero accumulator matching the step metrics' structure."""
        return {n: jnp.zeros((), jnp.float32) for n in self.metric_names}

    def build(self, sample_x: np.ndarray, sample_y=None) -> TrainState:
        """Initialize parameters (lazy, from the first batch — like Keras
        building on first fit); see `training.build.build_state` for the
        full contract (module-loss labels, TP/FSDP placement, ZeRO-1)."""
        return build_lib.build_state(self, sample_x, sample_y)

    def install_state(self, host_state) -> TrainState:
        """Adopt a host-side TrainState snapshot onto this trainer's mesh —
        the elastic restore hook (`horovod_tpu.elastic.ElasticState`).

        ``host_state`` must structurally match the built state (same
        module/optimizer — the committed snapshot of a prior generation of
        the SAME job); each array leaf is placed with the freshly built
        leaf's sharding, so the snapshot follows whatever layout this
        world's build chose (replicated pure-DP, ZeRO-1 shards, ...).
        Call after `build()`; returns the installed state."""
        if self.state is None:
            raise RuntimeError("call build() before install_state()")
        if self._ef:
            host_state = _adapt_ef_residual(host_state, self.state)

        def place(host_leaf, built_leaf):
            if isinstance(built_leaf, jax.Array):
                arr = np.asarray(host_leaf)
                if arr.shape != built_leaf.shape:
                    raise ValueError(
                        f"snapshot leaf shape {arr.shape} != built shape "
                        f"{built_leaf.shape} — the committed state belongs "
                        "to a different model configuration"
                    )
                arr = arr.astype(built_leaf.dtype)
                if not built_leaf.sharding.is_fully_addressable:
                    # Cross-process target layout (ZeRO-1 opt shards after
                    # a rescale, multi-host TP/FSDP): place only the
                    # shards THIS process owns, slicing them out of the
                    # dense snapshot — device_put of a host array onto a
                    # non-addressable sharding is not portable across the
                    # supported jax range. The trailing reshape undoes
                    # ascontiguousarray's 0-d → (1,) promotion.
                    return jax.make_array_from_callback(
                        arr.shape, built_leaf.sharding,
                        lambda idx, a=arr: np.ascontiguousarray(
                            a[idx]
                        ).reshape(np.shape(a[idx])),
                    )
                return jax.device_put(arr, built_leaf.sharding)
            return host_leaf

        self.state = jax.tree.map(place, host_state, self.state)
        return self.state

    def reduction_program(self, params):
        """(jitted fn, gradient-shaped zeros, lowered text) of THIS
        trainer's boundary gradient reduction in isolation — the same
        `collectives.reduce_gradients` program the explicit step embeds
        (bucketing, order, dcn two-hop, wire dtypes, ZeRO-1 scatter, all
        from the trainer's config). The single attribution source for
        "how much of a step is comm": bench.py's step_ms.comm legs and
        the live `StepPhaseSampler` both time exactly this program, so
        offline BENCH_* rows and the live ``hvt_step_phase_ms{comm}``
        gauge are the same measurement at different cadences."""
        import jax.numpy as jnp

        P = jax.sharding.PartitionSpec
        grads = jax.tree.map(
            lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params
        )
        scatter = self._scatter

        def red(g):
            out = collectives.reduce_gradients(
                g,
                data_axis=mesh_lib.DATA_AXIS,
                extra_axes=(mesh_lib.FSDP_AXIS,),
                dcn=self._dcn,
                wire_dtype=self._comm_dtype,
                ici_wire_dtype=self._ici_dtype,
                bucket_bytes=self._bucket_bytes,
                reverse=self._bucket_reverse,
                scatter=scatter if scatter > 1 else None,
            )
            # Scalar data-dependency on every reduced bucket (honest
            # fetch; see bench._timed).
            t = sum(
                jnp.sum(l.astype(jnp.float32)) for l in jax.tree.leaves(out)
            )
            if scatter > 1:
                # Scattered outputs differ per shard; one scalar psum
                # makes the fetch replicated (scalar ops never count as
                # payload in the byte accounting).
                t = jax.lax.psum(
                    t, (mesh_lib.DATA_AXIS, mesh_lib.FSDP_AXIS)
                )
            return t

        f = jax.jit(compat.shard_map(
            red, mesh=self.mesh, in_specs=(jax.sharding.PartitionSpec(),),
            out_specs=P(), check_vma=False,
        ))
        return f, grads, f.lower(grads).as_text()

    def stream_cursor(self, epoch: int, step: int) -> dict | None:
        """The durable stream cursor for training position "``step``
        optimizer steps into epoch ``epoch``" of the CURRENT fit, as a
        serializable dict (`data.stream.StreamCursor`) — None before any
        fit established a stream geometry.

        Because every feeding path anchors its per-epoch order to a pure
        function of ``(trainer.seed, epoch)``, this cursor plus the same
        fit-call shape fully reconstructs the data position:
        ``fit(initial_epoch=cursor['epoch'], initial_step=
        cursor['step'])`` resumes byte-exactly. The cursor rides the
        checkpoint progress manifests (`checkpoint.save(cursor=)` — the
        `ModelCheckpoint` path stamps it automatically) and elastic
        commits (`ElasticState.cursor`), recording the stream-format
        version so a resume against an INCOMPATIBLE derivation is
        refused loudly (`stream.StreamCursorError`), never silently
        re-anchored."""
        if self._stream_geometry is None:
            return None
        from horovod_tpu.data import stream as stream_lib

        return stream_lib.StreamCursor(
            kind="fit", seed=int(self.seed), epoch=int(epoch),
            step=int(step), position=dict(self._stream_geometry),
        ).to_dict()

    # --- feeding / verbs — bodies live in training/feeding.py --------------

    def _shard(self, batch):
        return feeding.shard_batch(self, batch)

    def _shard_chunk(self, chunk, lead: int = 1):
        return feeding.shard_chunk(self, chunk, lead)

    def _feed_groups(self) -> tuple[int, int]:
        return feeding.feed_groups(self)

    def _local_slice(self, arr, global_batch: int):
        return feeding.local_slice(self, arr, global_batch)

    def _stage_device_dataset(self, x, y):
        return feeding.stage_device_dataset(self, x, y)

    def fit(self, dataset=None, **kwargs) -> list[dict]:
        """Train — the Keras-fit role; full contract in
        `training.feeding.run_fit` (streamed + device-cached paths)."""
        return feeding.run_fit(self, dataset, **kwargs)

    def evaluate(self, x, y, batch_size: int = 128, verbose: int = 0,
                 cache: str | None = None) -> dict:
        """Sharded full-dataset eval; see `training.feeding.run_evaluate`."""
        return feeding.run_evaluate(self, x, y, batch_size, verbose, cache)

    def predict(self, x, batch_size: int = 128) -> np.ndarray:
        """Class probabilities (input→prob serving contract); see
        `training.feeding.run_predict`."""
        return feeding.run_predict(self, x, batch_size)


class StepPhaseSampler:
    """Live per-step phase timing for the trainer-side metrics exporter
    (``HVT_METRICS_PORT``): every ``HVT_METRICS_EVERY`` optimizer steps,
    refresh the ``hvt_step_phase_ms{total,compute,comm,input}``,
    ``hvt_examples_per_sec``, ``hvt_mfu`` and ``hvt_step_seconds``
    series from a drained measurement window — the bench-time
    ``step_ms`` accounting (PR 7/12), live.

    Measurement contract, matching bench.py's discipline exactly:

    * **total** — wall-clock across the window, blocked at BOTH edges
      (`jax.block_until_ready` on the newest state): with async dispatch
      the python loop runs ahead of the device, so only a drained window
      is an honest mean step time. The drain is the sampler's only
      recurring pipeline cost — one bubble per window, which the bench
      overhead A/B gates at <= 2% of ``step_ms.total``
      (``BENCH_MODEL=zero1``).
    * **comm** — the isolated boundary-reduction program
      (`Trainer.reduction_program` — the SAME attribution bench trusts),
      compiled once at the first sample, then re-timed every
      ``comm_refresh`` samples (default 8) and CACHED in between: the
      comm split is structural (buckets, wires, topology) and drifts at
      network-degradation timescales, while re-timing it every window
      was the dominant recurring sampler cost (a full isolated
      reduction per window blew the 2% overhead budget on comm-heavy
      steps). The published comm gauge therefore refreshes every
      ``comm_refresh x every`` optimizer steps.
    * **input** — host time the fit loop spent blocked on the prefetcher
      (`add_input_wait`), amortized per step.
    * **compute** — the remainder, clamped >= 0; phases are clamped to
      sum to total (the PR 7 coherence rule — bench exits non-zero on
      phase > total, the live gauges clamp instead: an observability
      surface must not kill training over a scheduling blip).
    * **mfu** — XLA cost-model FLOPs of the compiled step executable
      (per optimizer step) against `trace.resolve_peak_flops` x chips.
      Custom-call kernels (flash attention, fused CE) are opaque to the
      cost model, so this gauge UNDER-counts for those models — a live
      trend signal; the calibrated BENCH_* rows stay the MFU headline.

    The first ``maybe_sample`` call only opens the window (and pays the
    one-time warmups: reduction-program compile, step-flops cost
    analysis) — gauges appear from the second sample point on. All
    emission goes through `horovod_tpu.obs`; nothing here runs inside a
    traced body (HVT009)."""

    def __init__(self, trainer: "Trainer", examples_per_step: int,
                 every: int | None = None, comm_refresh: int = 8):
        self.trainer = trainer
        self.examples_per_step = int(examples_per_step)
        if every is None:
            every = registry.get_int("HVT_METRICS_EVERY") or 32
        self.every = max(1, int(every))
        self.comm_refresh = max(1, int(comm_refresh))
        self._steps = 0            # optimizer steps since the window edge
        self._input_s = 0.0        # host input-wait inside the window
        self._step_call_s = 0.0    # host time inside step calls (window)
        self._window_t0 = None     # None until the first drained edge
        self._step_shapes = None   # ShapeDtypeStructs of the step args
        self._steps_per_exec = 1
        self._comm = None          # (jitted fn, zero grads) once warmed
        self._comm_s = 0.0         # cached isolated-comm seconds
        self._flops = None         # FLOPs per optimizer step (cost model)
        self._peak = None          # (per-chip peak, source)
        self.samples = 0
        self.skew_probe = SkewProbe.maybe()

    # -- hooks the feeding loops call ---------------------------------------

    def capture_step_args(self, run, args, steps_per_exec: int) -> None:
        """Record the jitted step callable + its arg SHAPES (taken before
        the batch is donated) so the first sample can cost-analyze the
        executable. Cheap (one tree.map); called once per fit."""
        if self._step_shapes is not None:
            return
        mesh_devices = set(self.trainer.mesh.devices.flat)

        def struct(a):
            if isinstance(a, jax.Array):
                sh = a.sharding
                if set(sh.device_set) != mesh_devices:
                    # Uncommitted scalars (the update-scale arg) sit on
                    # one device until jit broadcasts them; lowering
                    # needs the POST-commit placement — replicated over
                    # the step's mesh — or the shapes are incompatible.
                    sh = jax.sharding.NamedSharding(
                        self.trainer.mesh, jax.sharding.PartitionSpec()
                    )
                return jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sh)
            return a

        self._run = run
        self._step_shapes = jax.tree.map(struct, args)
        self._steps_per_exec = max(1, int(steps_per_exec))

    def add_input_wait(self, seconds: float) -> None:
        self._input_s += seconds

    def add_step_time(self, seconds: float) -> None:
        """Host time spent INSIDE the step call (the feeding loops time
        each dispatch when the sampler is on). On a synchronous-dispatch
        backend this is where a victim rank's barrier wait hides — the
        `SkewProbe`'s blocked-time signal needs it (the drain alone
        reads ~0 for everyone there)."""
        self._step_call_s += seconds

    def maybe_sample(self, state, steps: int) -> None:
        """After each execution's dispatch: account ``steps`` optimizer
        steps; at the cadence boundary, drain and publish."""
        from horovod_tpu import obs

        obs.counter("hvt_optimizer_steps_total", steps)
        self._steps += steps
        if self._window_t0 is not None and self._steps < self.every:
            return
        t_drain = time.perf_counter()
        jax.block_until_ready(state)
        now = time.perf_counter()
        drain_s = now - t_drain
        if self._window_t0 is None:
            # First edge: one-time warmups OUTSIDE any window, so their
            # cost never pollutes a published step time.
            self._warmup(state)
            self._window_t0 = time.perf_counter()
            self._steps = 0
            self._input_s = 0.0
            self._step_call_s = 0.0
            return
        total_s = (now - self._window_t0) / self._steps
        input_s = min(self._input_s / self._steps, total_s)
        comm_s = min(self._timed_comm(), total_s - input_s)
        compute_s = max(0.0, total_s - comm_s - input_s)
        obs.gauge("hvt_step_phase_ms", total_s * 1e3, phase="total")
        obs.gauge("hvt_step_phase_ms", compute_s * 1e3, phase="compute")
        obs.gauge("hvt_step_phase_ms", comm_s * 1e3, phase="comm")
        obs.gauge("hvt_step_phase_ms", input_s * 1e3, phase="input")
        obs.histogram("hvt_step_seconds", total_s)
        obs.gauge(
            "hvt_examples_per_sec", self.examples_per_step / total_s
        )
        obs.gauge("hvt_accum_k", self.trainer._accum_steps)
        peak, _src = self._peak
        if peak and self._flops:
            n_chips = int(self.trainer.mesh.devices.size)
            obs.gauge("hvt_peak_flops_per_chip", peak)
            obs.gauge(
                "hvt_mfu", self._flops / total_s / (peak * n_chips)
            )
        obs.counter("hvt_step_samples_total")
        self.samples += 1
        if self.skew_probe is not None:
            # One tiny allgather of host timings per sample window —
            # OUTSIDE the published window (the re-edge below restarts
            # the clock after it), its cost charged to the sampler and
            # covered by the bench sampler-overhead A/B gate. The
            # signal is per-step BLOCKED time: host seconds inside the
            # step calls plus the drain, covering both dispatch regimes
            # (SkewProbe docstring).
            self.skew_probe.publish(
                (self._step_call_s + drain_s) / self._steps
            )
        # Re-edge AFTER the sampling work: the published step time
        # measures training, not the sampler; the sampler's own cost is
        # what the bench overhead A/B measures.
        self._window_t0 = time.perf_counter()
        self._steps = 0
        self._input_s = 0.0
        self._step_call_s = 0.0

    # -- internals ----------------------------------------------------------

    def _warmup(self, state) -> None:
        from horovod_tpu import trace as trace_lib

        self._peak = trace_lib.resolve_peak_flops(calibrate=True)
        try:
            f, grads, _text = self.trainer.reduction_program(state.params)
            jax.block_until_ready(f(grads))  # compile + settle
            self._comm = (f, grads)
            t0 = time.perf_counter()
            jax.block_until_ready(f(grads))
            self._comm_s = time.perf_counter() - t0  # warm cache
        except Exception:
            self._comm = None  # attribution degrades to comm=0, loudly
            # visible as compute==total; never kills training.
        if self._step_shapes is not None:
            try:
                compiled = self._run.lower(*self._step_shapes).compile()
                flops = trace_lib.compiled_cost_flops(compiled)
                if flops:
                    self._flops = flops / self._steps_per_exec
            except Exception:
                self._flops = None

    def _timed_comm(self) -> float:
        if self._comm is None:
            return 0.0
        if self.samples % self.comm_refresh:
            return self._comm_s  # cached between refreshes (docstring)
        from horovod_tpu import trace as trace_lib

        f, grads = self._comm
        with trace_lib.span("reduction"):
            t0 = time.perf_counter()
            jax.block_until_ready(f(grads))
            self._comm_s = time.perf_counter() - t0
        return self._comm_s


class SkewProbe:
    """Live cross-rank straggler detection riding the `StepPhaseSampler`
    cadence (the offline counterpart is ``hvt-trace skew``,
    obs/timeline.py).

    The honest live skew signal is NOT each rank's own step time — a
    data-parallel fleet is paced by its slowest rank, so every rank's
    drained window reads fleet speed. What discriminates is per-step
    BLOCKED time: host seconds spent inside the step call plus the
    window-edge drain (``add_step_time`` + the ``block_until_ready``).
    Whichever dispatch regime the backend is in — synchronous (the
    step call blocks through the collective; the victims' CALLS run
    long) or async (the calls return at enqueue; the victims' DRAIN
    runs long) — the ranks waiting on the straggler carry the extra
    blocked time, while the straggler itself (sleeping, starved, or
    busy elsewhere BETWEEN steps) blocks least. So every sample window,
    each rank contributes ``(rank, blocked s/step, wall time)`` to ONE
    tiny host allgather (`collectives.allgather_object` — the KV-store
    transport, a few dozen bytes), and every rank publishes:

    * ``hvt_step_skew_ms``   — max − median of the fleet's per-step
      blocked times;
    * ``hvt_straggler_rank`` — the rank with the SMALLEST blocked time
      (deterministic lowest-rank tie-break; read it together with the
      skew gauge — at ~0 skew the "straggler" is just the fastest of
      equals);
    * ``hvt_barrier_wait_ms`` — this rank's blocked time beyond the
      fleet minimum (stragglers read ~0 while everyone else pays).

    A rank slow INSIDE its own compute is invisible here (every rank
    then blocks equally — sync or async); that case needs real per-op
    profiles (``POST /profile``), not host timing.

    Cadence safety: every rank's sampler fires at the same optimizer
    step counts (same ``HVT_METRICS_EVERY``, SPMD feeding), so the
    allgather is submission-order-agreed by construction. Off unless
    the trainer exporter is on (the probe only exists inside the
    sampler) AND the run is multi-process; ``HVT_SKEW_PROBE=0`` is the
    kill switch. Cost: one object allgather per sample window, outside
    the published timing window, charged to the sampler overhead the
    bench A/B gates."""

    def __init__(self):
        self.rank = runtime.process_rank()

    @staticmethod
    def maybe() -> "SkewProbe | None":
        if not registry.get_flag("HVT_SKEW_PROBE"):
            return None
        if jax.process_count() <= 1:
            return None  # nothing to be skewed against
        return SkewProbe()

    def publish(self, blocked_s: float) -> None:
        from horovod_tpu import obs

        rows = collectives.allgather_object(
            (self.rank, float(blocked_s), time.time())
        )
        waits = {int(r): float(d) for r, d, _t in rows}
        vals = sorted(waits.values())
        med = vals[len(vals) // 2] if len(vals) % 2 else (
            (vals[len(vals) // 2 - 1] + vals[len(vals) // 2]) / 2.0
        )
        straggler = min(waits, key=lambda r: (waits[r], r))
        obs.gauge("hvt_step_skew_ms", (vals[-1] - med) * 1e3)
        obs.gauge("hvt_straggler_rank", straggler)
        obs.gauge(
            "hvt_barrier_wait_ms", (waits[self.rank] - vals[0]) * 1e3
        )
