"""TrainState and the Trainer's loss/metric/callback helpers.

Split out of trainer.py (round 5): the state dataclass every subsystem
broadcasts/checkpoints, the Keras-style loss resolution, sown-metric
aggregation, and the callback teardown discipline.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import flax.struct
import jax
import jax.numpy as jnp
import optax


PyTree = Any


@flax.struct.dataclass
class TrainState:
    """The full broadcastable training state.

    Horovod's BroadcastGlobalVariablesCallback covers model *and* optimizer
    variables (SURVEY.md §7.3); keeping them in one pytree makes
    broadcast/checkpoint cover both by construction. ``model_state`` holds
    non-parameter variable collections (e.g. BatchNorm ``batch_stats``);
    under SPMD jit those statistics are computed over the *global* batch, so
    cross-replica BN sync — an extra op in GPU data-parallel stacks — is the
    default semantics here."""

    step: jax.Array
    params: PyTree
    opt_state: PyTree
    rng: jax.Array
    model_state: PyTree = None


def _resolve_loss(loss) -> Callable:
    """Map Keras-style loss names to fused-logits implementations.

    Covers both reference losses: SparseCategoricalCrossentropy
    (tensorflow2_keras_mnist.py:63) and categorical_crossentropy
    (mnist_keras.py:89)."""
    if callable(loss):
        return loss
    # 'module': the module computes its own loss — apply(x, labels=y)
    # returns (per_token_loss, per_token_correct). The contract of the fused
    # chunked-CE head (TransformerLM(fused_head_chunks=...), ops/fused_ce.py),
    # where materializing logits for a Trainer-side loss would defeat the op.
    if loss == "module":
        return None
    # Upcast at the loss boundary: models may emit 16-bit logits to halve
    # long-sequence HBM (TransformerLM logits_dtype) — the f32 cast fuses
    # into the logsumexp chain, so statistics are f32-accurate without a
    # materialized f32 copy. No-op for f32 logits.
    if loss in ("sparse_categorical_crossentropy", "sparse_ce"):
        return lambda logits, labels: optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), labels
        )
    if loss in ("categorical_crossentropy", "ce"):
        return lambda logits, labels: optax.softmax_cross_entropy(
            logits.astype(jnp.float32), labels
        )
    raise ValueError(f"unknown loss {loss!r}")


def _accuracy(logits, labels):
    pred = jnp.argmax(logits, axis=-1)
    if labels.ndim == logits.ndim:  # one-hot
        labels = jnp.argmax(labels, axis=-1)
    return (pred == labels).astype(jnp.float32).mean()


def _aggregate_sown_metrics(sown) -> dict:
    """Collapse a sown 'metrics' collection to ``{name: scalar}``: leaves
    sharing their final sow name (e.g. every MoE layer's 'moe_drop_rate')
    are averaged. This is the module→Trainer observability channel — any
    scalar a module sows into 'metrics' lands in the step metrics, the
    epoch logs, and every metrics sink, with no Trainer changes."""
    out: dict = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(sown)[0]:
        names = [p.key for p in path if isinstance(p, jax.tree_util.DictKey)]
        if names:
            out.setdefault(names[-1], []).append(
                jnp.asarray(leaf, jnp.float32)
            )
    return {k: jnp.mean(jnp.stack(v)) for k, v in out.items()}


def _param_shaped_matcher(params):
    """Predicate: is a subtree exactly param-shaped (same treedef, same leaf
    shapes)? Used to find the optimizer-state mirrors (momenta etc.) that
    must carry a parameter-derived sharding."""
    params_def = jax.tree.structure(params)
    params_shapes = jax.tree.leaves(jax.tree.map(lambda p: p.shape, params))

    def param_shaped(subtree) -> bool:
        try:
            if jax.tree.structure(subtree) != params_def:
                return False
            return (
                jax.tree.leaves(jax.tree.map(lambda l: l.shape, subtree))
                == params_shapes
            )
        except Exception:
            return False

    return param_shaped


def _run_train_end(callbacks) -> None:
    """on_train_end for the SUCCESS path: every hook runs even when an
    earlier one raises (PreemptionCheckpointCallback's SystemExit must not
    skip a later ModelCheckpoint's async-save join — its daemon thread
    would be killed at interpreter exit with the write half-done); the
    first raised exception propagates after all hooks ran."""
    first: BaseException | None = None
    for cb in callbacks:
        try:
            cb.on_train_end()
        except BaseException as e:
            if first is None:
                first = e
    if first is not None:
        raise first


def _teardown_callbacks(callbacks) -> None:
    """Best-effort on_train_end while a training error unwinds: teardown
    hooks (signal-handler restoration, writer flush/close, async-save
    joins) must still run — a PreemptionCheckpointCallback left installed
    after a crash would silently swallow the NEXT real SIGTERM — but their
    own failures (including the preemption callback's SystemExit) must not
    mask the original error."""
    for cb in callbacks:
        try:
            cb.on_train_end()
        except BaseException:
            pass
