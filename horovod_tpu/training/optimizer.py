"""DistributedOptimizer — gradient-averaging wrap of any optax optimizer.

Parity target: ``hvd.DistributedOptimizer(opt)``
(tensorflow2_keras_mnist.py:58, mnist_keras.py:87) whose contract is:
intercept the gradients of any wrapped optimizer and **average** (never sum)
them across workers before the update (SURVEY.md §3.5).

TPU-native architecture note: under SPMD ``jit`` with a batch sharded along
the ``data`` axis and a loss that is the mean over the *global* batch, XLA
inserts (and fuses, and schedules) the gradient all-reduce automatically —
Horovod's coordinator thread, readiness negotiation and tensor-fusion buffer
(SURVEY.md §2.3) have no equivalent because there is nothing to negotiate at
runtime. ``DistributedOptimizer(opt)`` with the default ``axis_name=None``
therefore wraps for *API parity* and documents intent; pass an explicit
``axis_name`` when stepping inside ``shard_map``/``pmap``, where the mean
must be requested by name.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.struct
import jax
import jax.numpy as jnp
import optax

from horovod_tpu.parallel.collectives import allreduce, is_quantized_wire


_COMPRESSION_DTYPES = {
    # Horovod's `compression=Compression.fp16` knob (part of the 0.18.1
    # DistributedOptimizer signature): halve the bytes each gradient moves
    # over the interconnect. On TPU the native 16-bit format is bfloat16
    # (same exponent range as f32 — no loss-scaling needed); fp16 is
    # accepted for API familiarity.
    "none": None,
    "fp16": jnp.float16,
    "bf16": jnp.bfloat16,
    "bfloat16": jnp.bfloat16,
    "float16": jnp.float16,
    # EQuARX-aggressive quantized wires (arXiv:2506.17615): 4x/4x fewer
    # bytes than f32, reduced as a per-bucket-scaled gather-sum (see
    # collectives.quantized_group_sum — a plain int8 all-reduce would
    # overflow its partial sums). Pair with error feedback (the default)
    # so the quantization bias telescopes instead of compounding.
    "int8": jnp.int8,
    "fp8": jnp.float8_e4m3fn,
}


@dataclasses.dataclass(frozen=True)
class AccumulationSpec:
    """What a ``backward_passes_per_step > 1`` request means for the
    compiled SPMD path — the `Trainer`-side contract (see
    `accumulation_spec`).

    ``k``: microbatch passes per optimizer step. ``average``: Horovod's
    ``average_aggregated_gradients`` (False = the K grads are SUMMED, the
    Horovod default). ``inner``: the transformation *before* the
    `optax.MultiSteps` wrap — the Trainer applies it once per K microbatch
    passes with the already-accumulated gradient, so the MultiSteps state
    (a params-sized accumulator persisted in opt_state plus the zero-update
    machinery) never exists on that path."""

    k: int
    average: bool
    inner: optax.GradientTransformation


class Compression:
    """Horovod's ``hvd.Compression`` enum, for drop-in familiarity:
    ``DistributedOptimizer(opt, compression=hvt.Compression.fp16)``.
    Values are the string knobs `DistributedOptimizer` accepts (bf16 is the
    TPU-native 16-bit wire format; fp16 kept for API parity; int8/fp8 are
    the quantized gather-sum wires with error feedback)."""

    none = "none"
    fp16 = "fp16"
    bf16 = "bf16"
    int8 = "int8"
    fp8 = "fp8"


@flax.struct.dataclass
class ErrorFeedbackState:
    """Optimizer-state wrapper carrying the quantized-wire error-feedback
    residual alongside the wrapped optimizer's own state.

    ``ef_residual``: a params-structured pytree of f32 leaves with ONE
    leading shard axis — ``[n_shards, *param_shape]``, sharded over the
    data axes — holding each shard's untransmitted quantization remainder
    (what `collectives.reduce_gradients` returned last step). Living in
    ``opt_state`` makes it ride every existing state surface for free:
    checkpoint save/restore, `broadcast_parameters`, elastic
    commit/sync/reshard. ``inner`` is the wrapped transformation's state."""

    ef_residual: Any
    inner: Any


def DistributedOptimizer(
    optimizer: optax.GradientTransformation,
    axis_name=None,
    average: bool = True,
    backward_passes_per_step: int = 1,
    average_aggregated_gradients: bool = False,
    compression: str = "none",
    compression_ici: str = "none",
    error_feedback: bool = True,
) -> optax.GradientTransformation:
    """Wrap ``optimizer`` so updates consume cross-worker-averaged gradients.

    Args:
      optimizer: any ``optax.GradientTransformation`` (the reference wraps
        Adam and Adadelta; any optimizer must work — SURVEY.md §2.4 row 3).
      axis_name: mesh axis (or tuple) to reduce over when used inside a
        mapped context (``shard_map``/``pmap``). ``None`` = SPMD-jit mode:
        the reduction is already implied by the sharded global-batch loss.
      average: Horovod-parity default True (mean). False gives sum.
      backward_passes_per_step: Horovod's gradient-accumulation argument —
        N backward passes are aggregated before one optimizer update (the
        effective batch is N× larger). Two execution forms, one contract:
        used standalone (or with an explicit ``axis_name``) the result is
        an `optax.MultiSteps` wrap — a plain GradientTransformation
        (checkpoint/broadcast-friendly) that zero-updates N-1 of N calls.
        Handed to `Trainer` in the default SPMD mode, the wrap is bypassed
        (see `accumulation_spec`): the Trainer runs the N microbatch
        passes inside ONE compiled step, accumulating local grads in f32,
        with exactly one cross-worker reduction (bucket-fused,
        hierarchical on multi-slice meshes) and one optimizer apply at the
        boundary — gradient communication per sample drops N×.
      average_aggregated_gradients: Horovod-parity default False — the N
        accumulated gradients are SUMMED (Horovod's
        ``average_aggregated_gradients`` default); True averages them.
      compression: ``'none'`` | ``'bf16'`` | ``'fp16'`` — cast each gradient
        to the 16-bit dtype for the cross-worker reduction and back after
        (Horovod's ``Compression.fp16`` role: half the ICI/DCN bytes).
        With an explicit ``axis_name`` the cast+reduce happens here in
        ``update``. In the default SPMD-jit mode the gradient reduction is
        placed by XLA inside the backward pass, before this wrapper sees a
        tensor — so the request is *tagged* on the returned transformation
        (see `compression_dtype`) and `Trainer` honours it by computing
        gradients in an explicit-collective `shard_map` step whose psum
        runs on the 16-bit wire dtype (trainer.py `_compressed_grads`).
        ``'int8'`` | ``'fp8'``: the EQuARX-aggressive quantized wires —
        per-bucket-scaled gather-sum reduction (1 B/element on the wire;
        on a multi-slice mesh the quantization applies to the DCN hop
        only, like the bf16 path) with error-feedback residuals carried in
        the optimizer state (`ErrorFeedbackState`). Trainer-only (the
        default SPMD-jit mode): a plain ``axis_name`` all-reduce cannot
        sum int8 partials without overflow, so that combination is
        rejected loudly.
      compression_ici: like ``compression``, but for the ICI hop of the
        hierarchical two-hop reduction only (EQuARX's aggressive tier
        applied intra-slice — for topologies where even ICI bandwidth is
        the bottleneck). Inert on single-slice meshes (``dcn == 1``:
        there is no two-hop factoring to put it on). int8/fp8 run the
        ICI hop as the per-bucket-scaled quantized reduce-scatter, with
        the untransmitted remainder charged PER HOP into the same
        error-feedback residual as ``compression`` (the telescoping mass
        identity stays exact across the factoring); bf16/fp16 cast the
        hop. Trainer-only for the quantized tier, like ``compression``.
      error_feedback: int8/fp8 only (either hop) — carry each shard's
        untransmitted quantization remainder and add it back before the
        next step's quantization (errors telescope; the wire bias does
        not compound across steps). Default True; False is the ablation
        knob the compression A/B measures. Ignored for non-quantized
        wires.
    """
    if compression not in _COMPRESSION_DTYPES:
        raise ValueError(
            f"unknown compression {compression!r}; "
            f"expected one of {sorted(_COMPRESSION_DTYPES)}"
        )
    if compression_ici not in _COMPRESSION_DTYPES:
        raise ValueError(
            f"unknown compression_ici {compression_ici!r}; "
            f"expected one of {sorted(_COMPRESSION_DTYPES)}"
        )
    comm_dtype = _COMPRESSION_DTYPES[compression]
    ici_dtype = _COMPRESSION_DTYPES[compression_ici]
    if is_quantized_wire(comm_dtype) and axis_name is not None:
        raise ValueError(
            f"compression={compression!r} needs the Trainer's "
            "explicit-collective step (a gather-sum reduction with "
            "per-bucket scales); with an explicit axis_name the update-side "
            "all-reduce would sum raw int8/fp8 partials — overflow. Use "
            "bf16/fp16 here, or drop axis_name and run under Trainer"
        )
    if ici_dtype is not None and axis_name is not None:
        raise ValueError(
            f"compression_ici={compression_ici!r} targets the Trainer's "
            "explicit-collective two-hop reduction (the ICI sub-hop); an "
            "update-side axis_name all-reduce has no hop to put it on — "
            "drop axis_name and run under Trainer"
        )

    def init_fn(params):
        return optimizer.init(params)

    def _reduce(g):
        orig = g.dtype
        if comm_dtype is not None and g.dtype == jnp.float32:
            g = g.astype(comm_dtype)
        g = allreduce(g, average=average, axis_name=axis_name)
        return g.astype(orig)

    def update_fn(updates, state, params=None, **extra):
        if axis_name is not None:
            updates = jax.tree.map(_reduce, updates)
        return optimizer.update(updates, state, params, **extra)

    tx = optax.GradientTransformation(init_fn, update_fn)
    if backward_passes_per_step > 1:
        # Standalone (no Trainer) contract: `optax.MultiSteps` accumulates
        # the MEAN of the N microbatch gradients and emits zero updates on
        # the first N-1 passes. Horovod's default is the SUM of the N
        # passes (average_aggregated_gradients=False), so the sum contract
        # pre-scales the mean by N before the wrapped optimizer sees it.
        inner = tx
        if not average_aggregated_gradients:
            tx = optax.chain(optax.scale(float(backward_passes_per_step)), tx)
        ms = optax.MultiSteps(
            tx, every_k_schedule=backward_passes_per_step
        ).gradient_transformation()

        def ms_update(updates, state, params=None, **extra):
            return ms.update(updates, state, params, **extra)

        tx = optax.GradientTransformation(ms.init, ms_update)
        if axis_name is None:
            # SPMD-jit mode: Trainer runs TRUE accumulation — K microbatch
            # forward/backward passes inside ONE compiled step, local f32
            # grad accumulation, exactly one cross-worker reduction and one
            # optimizer apply at the boundary (communication per sample
            # drops K×; effective batch K·B in the same device memory).
            # The tag hands Trainer the knob AND the unwrapped inner
            # transformation (see AccumulationSpec); standalone users of
            # this GradientTransformation keep the MultiSteps semantics
            # above unchanged.
            tx.update._hvt_accum = AccumulationSpec(
                k=backward_passes_per_step,
                average=average_aggregated_gradients,
                inner=inner,
            )
    if (comm_dtype is not None or ici_dtype is not None) and (
        axis_name is None
    ):
        # SPMD-jit mode: the reduction these dtypes apply to lives inside
        # the compiled step, not here. Tag the transformation so Trainer
        # selects its explicit-collective (shard_map) gradient path, where
        # the psum really runs on the wire traffic. Tagging the plain
        # update function keeps the result an ordinary
        # GradientTransformation.
        if comm_dtype is not None:
            tx.update._hvt_compression = comm_dtype
        if ici_dtype is not None:
            tx.update._hvt_compression_ici = ici_dtype
        tx.update._hvt_error_feedback = bool(
            error_feedback and (
                is_quantized_wire(comm_dtype) or is_quantized_wire(ici_dtype)
            )
        )
    return tx


def compression_dtype(tx: optax.GradientTransformation):
    """The wire dtype a `DistributedOptimizer` requested for the compiled
    SPMD path (16-bit cast dtypes or the int8/fp8 quantized wires), or
    None. Trainer uses this to switch its train step to the
    explicit-collective gradient reduction."""
    return getattr(tx.update, "_hvt_compression", None)


def compression_ici_dtype(tx: optax.GradientTransformation):
    """The ICI-hop wire dtype a `DistributedOptimizer(compression_ici=)`
    requested for the hierarchical two-hop reduction, or None. Inert on
    single-slice meshes (no two-hop factoring); Trainer threads it into
    `collectives.reduce_gradients(ici_wire_dtype=)`."""
    return getattr(tx.update, "_hvt_compression_ici", None)


def compression_error_feedback(tx: optax.GradientTransformation) -> bool:
    """True when a quantized-wire `DistributedOptimizer` asked for error
    feedback — Trainer then wraps the optimizer state in
    `ErrorFeedbackState` and threads the residual through the boundary
    reduction."""
    return bool(getattr(tx.update, "_hvt_error_feedback", False))


def error_feedback_wrap(
    inner: optax.GradientTransformation, n_shards: int
) -> optax.GradientTransformation:
    """Wrap ``inner`` so its state rides inside an `ErrorFeedbackState`
    with a zero-initialized ``[n_shards, *param]`` f32 residual per
    parameter. The TRAINER owns the residual's read/write (it happens
    inside the explicit-collective step, not in ``update``); this wrapper
    only gives the residual a home in ``opt_state`` so every state surface
    (checkpoint, broadcast, elastic commit) carries it by construction.
    Standalone ``update`` calls pass the residual through untouched."""

    def init_fn(params):
        res = jax.tree.map(
            lambda p: jnp.zeros((n_shards,) + jnp.shape(p), jnp.float32),
            params,
        )
        return ErrorFeedbackState(ef_residual=res, inner=inner.init(params))

    def update_fn(updates, state, params=None, **extra):
        updates, inner_state = inner.update(
            updates, state.inner, params, **extra
        )
        return updates, state.replace(inner=inner_state)

    return optax.GradientTransformation(init_fn, update_fn)


def accumulation_spec(tx: optax.GradientTransformation):
    """The `AccumulationSpec` a ``backward_passes_per_step > 1``
    `DistributedOptimizer` tagged for the compiled SPMD path, or None.
    Trainer uses this to (a) switch its train step to the K-microbatch
    accumulating explicit-collective form and (b) swap the MultiSteps wrap
    for the unwrapped inner transformation — the accumulation then lives
    in the step's scan, not in a params-sized opt_state buffer."""
    return getattr(tx.update, "_hvt_accum", None)
