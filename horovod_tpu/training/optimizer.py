"""DistributedOptimizer — gradient-averaging wrap of any optax optimizer.

Parity target: ``hvd.DistributedOptimizer(opt)``
(tensorflow2_keras_mnist.py:58, mnist_keras.py:87) whose contract is:
intercept the gradients of any wrapped optimizer and **average** (never sum)
them across workers before the update (SURVEY.md §3.5).

TPU-native architecture note: under SPMD ``jit`` with a batch sharded along
the ``data`` axis and a loss that is the mean over the *global* batch, XLA
inserts (and fuses, and schedules) the gradient all-reduce automatically —
Horovod's coordinator thread, readiness negotiation and tensor-fusion buffer
(SURVEY.md §2.3) have no equivalent because there is nothing to negotiate at
runtime. ``DistributedOptimizer(opt)`` with the default ``axis_name=None``
therefore wraps for *API parity* and documents intent; pass an explicit
``axis_name`` when stepping inside ``shard_map``/``pmap``, where the mean
must be requested by name.
"""

from __future__ import annotations

import jax
import optax

from horovod_tpu.parallel.collectives import allreduce, pmean_pytree


def DistributedOptimizer(
    optimizer: optax.GradientTransformation,
    axis_name=None,
    average: bool = True,
) -> optax.GradientTransformation:
    """Wrap ``optimizer`` so updates consume cross-worker-averaged gradients.

    Args:
      optimizer: any ``optax.GradientTransformation`` (the reference wraps
        Adam and Adadelta; any optimizer must work — SURVEY.md §2.4 row 3).
      axis_name: mesh axis (or tuple) to reduce over when used inside a
        mapped context (``shard_map``/``pmap``). ``None`` = SPMD-jit mode:
        the reduction is already implied by the sharded global-batch loss.
      average: Horovod-parity default True (mean). False gives sum.
    """

    def init_fn(params):
        return optimizer.init(params)

    def update_fn(updates, state, params=None, **extra):
        if axis_name is not None:
            if average:
                updates = pmean_pytree(updates, axis_name)
            else:
                updates = jax.tree.map(
                    lambda g: allreduce(g, average=False, axis_name=axis_name),
                    updates,
                )
        return optimizer.update(updates, state, params, **extra)

    return optax.GradientTransformation(init_fn, update_fn)
