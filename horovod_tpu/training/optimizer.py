"""DistributedOptimizer — gradient-averaging wrap of any optax optimizer.

Parity target: ``hvd.DistributedOptimizer(opt)``
(tensorflow2_keras_mnist.py:58, mnist_keras.py:87) whose contract is:
intercept the gradients of any wrapped optimizer and **average** (never sum)
them across workers before the update (SURVEY.md §3.5).

TPU-native architecture note: under SPMD ``jit`` with a batch sharded along
the ``data`` axis and a loss that is the mean over the *global* batch, XLA
inserts (and fuses, and schedules) the gradient all-reduce automatically —
Horovod's coordinator thread, readiness negotiation and tensor-fusion buffer
(SURVEY.md §2.3) have no equivalent because there is nothing to negotiate at
runtime. ``DistributedOptimizer(opt)`` with the default ``axis_name=None``
therefore wraps for *API parity* and documents intent; pass an explicit
``axis_name`` when stepping inside ``shard_map``/``pmap``, where the mean
must be requested by name.
"""

from __future__ import annotations

import jax
import optax

from horovod_tpu.parallel.collectives import allreduce, pmean_pytree


def DistributedOptimizer(
    optimizer: optax.GradientTransformation,
    axis_name=None,
    average: bool = True,
    backward_passes_per_step: int = 1,
    average_aggregated_gradients: bool = False,
) -> optax.GradientTransformation:
    """Wrap ``optimizer`` so updates consume cross-worker-averaged gradients.

    Args:
      optimizer: any ``optax.GradientTransformation`` (the reference wraps
        Adam and Adadelta; any optimizer must work — SURVEY.md §2.4 row 3).
      axis_name: mesh axis (or tuple) to reduce over when used inside a
        mapped context (``shard_map``/``pmap``). ``None`` = SPMD-jit mode:
        the reduction is already implied by the sharded global-batch loss.
      average: Horovod-parity default True (mean). False gives sum.
      backward_passes_per_step: Horovod's gradient-accumulation argument —
        N backward passes are aggregated before one optimizer update (the
        effective batch is N× larger). Built on `optax.MultiSteps`, so the
        result stays a plain GradientTransformation
        (checkpoint/broadcast-friendly).
      average_aggregated_gradients: Horovod-parity default False — the N
        accumulated gradients are SUMMED (Horovod's
        ``average_aggregated_gradients`` default); True averages them.
    """

    def init_fn(params):
        return optimizer.init(params)

    def update_fn(updates, state, params=None, **extra):
        if axis_name is not None:
            if average:
                updates = pmean_pytree(updates, axis_name)
            else:
                updates = jax.tree.map(
                    lambda g: allreduce(g, average=False, axis_name=axis_name),
                    updates,
                )
        return optimizer.update(updates, state, params, **extra)

    tx = optax.GradientTransformation(init_fn, update_fn)
    if backward_passes_per_step > 1:
        # MultiSteps accumulates the MEAN of the N microbatch gradients and
        # emits zero updates on the first N-1 passes. Horovod's default is
        # the SUM of the N passes (average_aggregated_gradients=False), so
        # the sum contract pre-scales the mean by N before the wrapped
        # optimizer sees it.
        if not average_aggregated_gradients:
            tx = optax.chain(optax.scale(float(backward_passes_per_step)), tx)
        return optax.MultiSteps(
            tx, every_k_schedule=backward_passes_per_step
        ).gradient_transformation()
    return tx
