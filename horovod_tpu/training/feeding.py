"""The Trainer's feeding, evaluation and prediction paths.

Split out of trainer.py (round 5). Everything input-side lives here: batch
sharding onto the mesh (custom batch_specs included), the multi-process
feed-group layout, the streamed fit path (prefetched, steps_per_execution
chunking), the device-cached fit/eval paths (datasets staged into HBM,
whole epochs as one dispatch), epoch bookkeeping, and the padded/masked
slice contract shared by evaluate and predict. Functions take the Trainer
instance; the Trainer's public verbs delegate here.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from horovod_tpu import runtime
from horovod_tpu.data.loader import ArrayDataset, training_pipeline
from horovod_tpu.parallel import mesh as mesh_lib
from horovod_tpu.parallel import sharding as sharding_lib
from horovod_tpu.training.train_state import (
    _run_train_end,
    _teardown_callbacks,
)


def _with_env_callbacks(callbacks):
    """User callbacks + env-requested ones (heartbeat / fault injection —
    `callbacks.env_callbacks`). Appended last so liveness/chaos hooks see
    the epoch state the user's callbacks produced; applied on every fit
    path so supervised launches need no entry-script changes."""
    from horovod_tpu.training import callbacks as callbacks_lib

    return list(callbacks) + callbacks_lib.env_callbacks()


def shard_batch(trainer, batch):
    if trainer.batch_specs is not None:
        specs = tuple(trainer.batch_specs)

        def put(x, spec):
            return sharding_lib.put_global(
                x, jax.sharding.NamedSharding(trainer.mesh, spec)
            )

        def put_part(part, spec):
            # One batch part against its spec: a single PartitionSpec
            # broadcasts over a pytree part (dict-input models), a
            # matching spec pytree maps pairwise.
            if isinstance(spec, jax.sharding.PartitionSpec):
                return jax.tree.map(lambda a: put(a, spec), part)
            return jax.tree.map(put, part, spec)

        if not isinstance(batch, (tuple, list)):
            return put_part(batch, specs[0])  # predict: bare x
        if len(batch) == len(specs) + 1:
            # evaluate() appends a per-example mask: batch-sharded only.
            last = tuple(specs[-1])
            specs = specs + (
                jax.sharding.PartitionSpec(*last[:1]) if last
                else jax.sharding.PartitionSpec(),
            )
        return tuple(
            put_part(x, spec) for x, spec in zip(batch, specs)
        )
    return sharding_lib.shard_batch(batch, trainer.mesh)

def feed_groups(trainer) -> tuple[int, int]:
    """(n_groups, my_group): how processes map onto the data axis.

    Processes feed batches in ``min(world, dp_size)`` distinct groups.
    With dp >= world (the usual DP deployment) every process is its own
    group. With dp < world (model-parallel-only meshes spanning
    processes, e.g. pipe=2 over 2 hosts) several processes share one
    data shard and MUST feed identical rows — the batch is logically
    replicated across the non-data axes, and divergent per-process
    contributions would silently give each device different contents
    for the same global array."""
    world = runtime.process_count()
    dp = trainer.dp_size
    groups = min(world, dp)
    if world % groups != 0 or (dp >= world and dp % world != 0):
        # e.g. 3 processes over dp=2: some rank would straddle two data
        # shards and the grouping below would slice out-of-range rows —
        # fail loudly instead of feeding wrong data.
        raise ValueError(
            f"process count ({world}) and data-parallel degree ({dp}) "
            "must divide one another for a coherent feeding layout"
        )
    per_group = world // groups
    return groups, runtime.process_rank() // per_group

def local_slice(trainer, arr, global_batch: int):
    """This feed-group's share of a globally-indexed batch — what
    `make_array_from_process_local_data` expects as the local
    contribution (each example fed exactly once across the data axis;
    processes sharing a data shard contribute identical rows)."""
    if runtime.process_count() == 1:
        return arr
    groups, group = feed_groups(trainer)
    local = global_batch // groups
    return arr[group * local : (group + 1) * local]

def stage_sharded(trainer, arr, per_shard: int):
    """Stage one host array as [n_shards, per_shard, ...] in HBM,
    example-sharded over the data axes: shard s takes rows
    [s*per_shard, (s+1)*per_shard); multi-process, each feed group
    contributes the rows for its chips (processes sharing a data shard
    stage identical rows — see _feed_groups)."""
    groups, group = feed_groups(trainer)
    local_shards = trainer.dp_size // groups
    arr = np.asarray(arr)
    lo = group * local_shards * per_shard
    hi = (group + 1) * local_shards * per_shard
    local = arr[lo:hi].reshape((local_shards, per_shard) + arr.shape[1:])
    spec = jax.sharding.PartitionSpec(
        (mesh_lib.DATA_AXIS, mesh_lib.FSDP_AXIS),
        *([None] * arr.ndim),
    )
    return sharding_lib.put_global(
        local, jax.sharding.NamedSharding(trainer.mesh, spec)
    )

def stage_device_dataset(trainer, x, y):
    """Stage (x, y) into HBM as [n_shards, per_shard_n, ...] leaves,
    example-sharded over the data axes (truncated to divide evenly)."""
    n_shards = trainer.dp_size
    n = (len(x) // n_shards) * n_shards
    if n == 0:
        raise ValueError(f"need at least {n_shards} examples")
    per_shard = n // n_shards
    return (
        stage_sharded(trainer, np.asarray(x)[:n], per_shard),
        stage_sharded(trainer, np.asarray(y)[:n], per_shard),
    ), per_shard

def shard_chunk(trainer, chunk, lead: int = 1):
    """Place a stacked host batch onto the mesh — ``lead`` unsharded
    leading axes ([K, batch, ...] for steps_per_execution scans, lead=1;
    [C, K, batch, ...] for chunked microbatch-accumulation feeds, lead=2);
    the scan/microbatch axes stay unsharded."""
    if trainer.batch_specs is not None:
        specs = tuple(trainer.batch_specs)

        def put(x, spec):
            return sharding_lib.put_global(
                x,
                jax.sharding.NamedSharding(
                    trainer.mesh,
                    jax.sharding.PartitionSpec(
                        *([None] * lead), *tuple(spec)
                    ),
                ),
            )

        return tuple(put(x, spec) for x, spec in zip(chunk, specs))
    return sharding_lib.shard_chunk(chunk, trainer.mesh, lead)

def slice_pad(trainer, part, start: int, global_batch: int):
    """(batch slice padded to the compiled shape, true row count) for
    one batch part — leaf-wise, so pytree (dict-input) parts feed like
    flat arrays. ONE implementation of the multi-process padding
    contract, shared by evaluate and predict."""
    sliced = jax.tree.map(
        lambda a: np.asarray(a[start : start + global_batch]), part
    )
    bs = len(jax.tree_util.tree_leaves(sliced)[0])
    if bs < global_batch:
        pad = global_batch - bs
        sliced = jax.tree.map(
            lambda a: np.concatenate([a, np.repeat(a[-1:], pad, 0)]),
            sliced,
        )
    return sliced, bs

def finish_epoch(trainer, epoch, epochs, metric_acc, steps, t0, callbacks,
    validation_data, batch_size, verbose, val_cache=None,
):
    """Epoch bookkeeping shared by every fit path: ONE host fetch of the
    in-step metric sums, optional validation, callbacks, history."""
    sums = jax.device_get(metric_acc)
    logs = {k: float(v) / steps for k, v in sums.items()}
    logs["epoch_time_s"] = time.perf_counter() - t0
    if validation_data is not None:
        val = run_evaluate(trainer, 
            validation_data[0], validation_data[1],
            batch_size=batch_size, verbose=0, cache=val_cache,
        )
        logs.update({f"val_{k}": v for k, v in val.items()})
    for cb in callbacks:
        cb.on_epoch_end(epoch, logs)
    trainer.history.append(logs)
    if verbose:
        shown = {k: round(v, 4) for k, v in logs.items()}
        print(f"Epoch {epoch + 1}/{epochs} - {shown}")

def _accepts_anchoring(batches_fn) -> bool:
    """Whether a duck-typed ``batches`` hook takes the anchored
    ``start_epoch``/``batches_per_epoch`` keywords (explicitly or via
    ``**kwargs``) — decided from the signature so a TypeError raised
    INSIDE the source is never mistaken for 'not anchored'."""
    import inspect

    try:
        params = inspect.signature(batches_fn).parameters
    except (TypeError, ValueError):
        return False
    if any(
        p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values()
    ):
        return True
    return {"start_epoch", "batches_per_epoch"} <= set(params)


def _normalize_resume(initial_epoch: int, initial_step: int,
                      steps_per_epoch: int) -> tuple[int, int]:
    """Canonicalize a resume point against this run's epoch geometry: a
    step at or past the epoch's end rolls into the next epoch (a commit
    taken at the last step boundary of an epoch IS the next epoch's
    start), so callers may hand back exactly what the elastic commit or
    checkpoint manifest recorded without special-casing the boundary."""
    initial_epoch = int(initial_epoch)
    initial_step = int(initial_step)
    if initial_step < 0:
        raise ValueError(f"initial_step must be >= 0, got {initial_step}")
    if initial_step and steps_per_epoch:
        initial_epoch += initial_step // steps_per_epoch
        initial_step %= steps_per_epoch
    return initial_epoch, initial_step


def run_fit(trainer,
    dataset=None,
    *,
    x=None,
    y=None,
    batch_size: int = 128,
    epochs: int = 1,
    initial_epoch: int = 0,
    initial_step: int = 0,
    steps_per_epoch: int | None = None,
    callbacks: Sequence = (),
    validation_data=None,
    shuffle_buffer: int | None = None,
    verbose: int | None = None,
    cache: str | None = None,
) -> list[dict]:
    """Train. Either pass a batched ``ArrayDataset``/iterable of
    ``(x, y)`` numpy batches (the TF2 script's idiom,
    tensorflow2_keras_mnist.py:96) or raw ``x``/``y`` arrays with a
    per-worker ``batch_size`` (the TF1 script's idiom,
    mnist_keras.py:107-112).

    ``initial_epoch`` is the Keras resume idiom: epoch numbering (and
    LR-warmup position, checkpoint names) continues from a restored run —
    pair it with `checkpoint.restore_latest_and_broadcast`.

    ``initial_step`` resumes MID-epoch, at optimizer step S of
    ``initial_epoch`` — the step-granular recovery contract
    (`horovod_tpu.elastic`, step-carrying checkpoint manifests). The data
    iterator is deterministically fast-forwarded by exactly ``S × K``
    microbatches (K = ``backward_passes_per_step``) without materializing
    the skipped batches, so the resumed run consumes byte-identically the
    batches an uninterrupted run of the same fit call would have consumed
    from step S on — on every feeding path (streamed, device-cached,
    ``steps_per_execution`` chunks), and stably across an
    `ArrayDataset.reshard` (the cut is defined in optimizer steps, not
    bytes). A step at or past ``steps_per_epoch`` rolls into the next
    epoch. User-supplied ``dataset=`` iterables without an
    `ArrayDataset.batches`-style skip hook are fast-forwarded by drawing
    and discarding (correct, but materializes the skipped batches).

    Anchoring: every feeding path is EPOCH-ANCHORED (durable stream
    cursors, `data/stream.py`) — each epoch's order is a pure function
    of ``(trainer.seed, epoch)``, so a resumed fit regenerates exactly
    the stream an uninterrupted run would have consumed from
    ``(initial_epoch, initial_step)`` on, INCLUDING when the epochs
    before it were consumed by a process that no longer exists (the
    formerly re-anchoring case, closed by ISSUE 8). This holds for the
    streamed ``x=``/``y=`` path (python and native engines alike),
    ``cache='device'`` (pure (seed, epoch) permutation, as before), and
    ``dataset=`` sources exposing the anchored ``batches(skip=,
    start_epoch=, batches_per_epoch=)`` hook (`ArrayDataset`,
    `FileDataset.pairs_stream`, `PackedLMStream`); bare ``batches(
    skip=)`` sources keep the PR 5 contract (exact within the resume
    epoch, the source owns its own cross-epoch anchoring).

    ``cache='device'`` (with ``x``/``y``) stages the whole dataset into
    HBM once, sharded over the data axes, and runs shuffling + batching +
    training fully on-device: ONE dispatch and ONE metrics fetch per
    epoch, zero per-step host involvement. This is the TPU-native answer
    to input-bound training (datasets at MNIST/CIFAR scale are trivially
    HBM-resident); on_batch_end callbacks fire once per epoch with the
    last step's metrics."""
    if verbose is None:
        verbose = 1 if runtime.is_primary() else 0
    if isinstance(x, list):
        # Keras-parity: a plain list of example rows is one array input
        # (the pre-pytree behavior); dict/tuple inputs stay pytrees.
        x = np.asarray(x)
    if cache == "device":
        if x is None or y is None:
            raise ValueError("cache='device' needs x=/y= arrays")
        if len(jax.tree_util.tree_leaves(x)) != 1:
            raise ValueError(
                "cache='device' stages a single input array; pytree "
                "(dict/tuple) inputs use the streamed fit path"
            )
        if trainer.batch_specs is not None and mesh_lib.has_live_model_axes(
            trainer.mesh
        ):
            # The staged layout shards the batch dim only; custom batch
            # layouts over live non-data axes (e.g. seq-sharded tokens)
            # need the streamed path's batch_specs handling.
            raise ValueError(
                "cache='device' supports data-sharded batches only; "
                "use the streamed fit path with batch_specs meshes"
            )
        return fit_device_cached(trainer,
            x, y, batch_size, epochs, initial_epoch, steps_per_epoch,
            callbacks, validation_data, verbose, initial_step,
        )
    if cache is not None:
        raise ValueError(f"unknown cache mode {cache!r}")

    groups, group = feed_groups(trainer)
    close_input = lambda: None  # noqa: E731
    if dataset is None:
        if x is None or y is None:
            raise ValueError("pass either dataset= or x=/y=")
        ds = ArrayDataset((x, y)).shard(group, groups)
        n_local = ds.num_examples
        # Global batch = per-worker batch × dp_size; each feed group
        # contributes its share (see _feed_groups for the dp < world
        # case, where processes sharing a shard feed identical rows).
        local_batch = batch_size * trainer.dp_size // groups
        if steps_per_epoch is None:
            # steps_per_epoch counts OPTIMIZER steps; with gradient
            # accumulation each one consumes K microbatches.
            steps_per_epoch = max(
                1, n_local // (local_batch * trainer._accum_steps)
            )
        initial_epoch, initial_step = _normalize_resume(
            initial_epoch, initial_step, steps_per_epoch
        )
        # Batch assembly runs in the native C++ producer thread when
        # available (overlapping shuffle/gather with the device step),
        # pure Python otherwise — same semantics either way. The stream
        # is EPOCH-ANCHORED (start_epoch/batches_per_epoch): every
        # epoch's order is a pure function of (seed, epoch), so a resume
        # at (initial_epoch, initial_step) regenerates byte-identically
        # what the uninterrupted run consumed from that position on —
        # including when the epochs before it were consumed by a process
        # that no longer exists (the durable-cursor contract,
        # data/stream.py) — whichever engine is active.
        engine: dict = {}
        dataset, close_input = training_pipeline(
            ds.arrays, local_batch, seed=trainer.seed,
            shuffle_buffer=shuffle_buffer, structure=ds.structure,
            skip_batches=initial_step * trainer._accum_steps,
            start_epoch=initial_epoch,
            batches_per_epoch=steps_per_epoch * trainer._accum_steps,
            engine_out=engine,
        )
        # Full stream geometry for the durable cursor: the ENGINE is
        # part of it (python and native anchored streams are different
        # byte streams), as are the batch/row counts.
        trainer._stream_geometry = {
            "path": "streamed",
            "engine": engine.get("engine"),
            "accum": trainer._accum_steps,
            "steps_per_epoch": steps_per_epoch,
            "batch_size": local_batch,
            "n_examples": n_local,
            "shuffle_buffer": shuffle_buffer,
        }
        it = iter(dataset)
    elif steps_per_epoch is None:
        raise ValueError("steps_per_epoch is required with a dataset")
    else:
        initial_epoch, initial_step = _normalize_resume(
            initial_epoch, initial_step, steps_per_epoch
        )
        skip = initial_step * trainer._accum_steps
        # dataset= sources: the geometry the trainer can see (the
        # source's own cursor surface carries the rest — seed, shard
        # spec, row counts).
        trainer._stream_geometry = {
            "path": "streamed",
            "engine": "dataset",
            "accum": trainer._accum_steps,
            "steps_per_epoch": steps_per_epoch,
        }
        if hasattr(dataset, "batches"):
            # ArrayDataset-style source (ArrayDataset, FilePairs,
            # PackedLMStream, any duck-typed `batches(skip=, start_epoch=,
            # batches_per_epoch=)`): index-level fast-forward, nothing
            # materialized, and EPOCH-ANCHORED — the stream starts at the
            # resume epoch's exact position (reshard-stable: the stream
            # is a pure function of seed + shard geometry + epoch).
            # Capability is probed from the SIGNATURE, not by catching
            # TypeError around the call — a TypeError raised inside a
            # broken anchored source must surface, not silently degrade
            # the resume to an unanchored stream.
            if _accepts_anchoring(dataset.batches):
                it = dataset.batches(
                    skip=skip, start_epoch=initial_epoch,
                    batches_per_epoch=(
                        steps_per_epoch * trainer._accum_steps
                    ),
                )
            else:
                # Pre-anchoring source with a bare `batches(skip=)` hook:
                # exact within the resume epoch (the PR 5 contract);
                # cross-epoch anchoring is the source's own business.
                it = dataset.batches(skip=skip) if skip else iter(dataset)
        else:
            it = iter(dataset)
            # Generic iterables expose no skip hook: draw and discard
            # (documented materializing fallback — still deterministic).
            for _ in range(skip):
                next(it)

    # Where this fit resumes, for resume-aware callbacks (the elastic
    # callback aligns its commit/rescale cadences to the resume step).
    trainer._resume_epoch, trainer._resume_step = initial_epoch, initial_step
    first = next(it)
    trainer.build(first[0], first[1])

    callbacks = _with_env_callbacks(callbacks)
    for cb in callbacks:
        cb.set_trainer(trainer)
    try:
        # on_train_begin sits INSIDE the teardown scope: an early
        # installer (e.g. PreemptionCheckpointCallback's signal
        # handler) must be torn down even when a LATER callback's
        # begin hook raises.
        for cb in callbacks:
            cb.on_train_begin()

        pending = first
        # Zero metric accumulator, committed to the mesh's replicated
        # sharding ONCE: a fresh uncommitted jnp.zeros each epoch would
        # give the first step of every epoch a different input-sharding
        # signature than the chained steps, ping-ponging between two
        # executables.
        zero_acc = sharding_lib.replicate(trainer.zero_metrics(), trainer.mesh)
        # HVT_PROFILE=<dir> captures a jax.profiler trace of the training
        # loop (XLA op + ICI collective timing) — the Horovod-Timeline
        # env-var contract, primary-process-gated (trace.py).
        from horovod_tpu import trace as trace_lib

        with trace_lib.maybe_trace(trace_lib.profile_dir()):
            fit_epochs(trainer,
                it, pending, zero_acc, epochs, initial_epoch,
                steps_per_epoch, callbacks, validation_data, batch_size,
                verbose, initial_step,
            )
    except BaseException:
        close_input()
        _teardown_callbacks(callbacks)
        raise
    close_input()
    _run_train_end(callbacks)
    return trainer.history

def _maybe_step_sampler(trainer):
    """The live step-phase sampler, when the trainer-side metrics
    exporter is on (`HVT_METRICS_PORT` — obs/server.py): None otherwise,
    so the default fit path carries ZERO instrumentation cost. The
    examples-per-step figure is inferred from the first chunk's shapes
    (`capture_step_args` time)."""
    from horovod_tpu.obs import server as obs_server

    if obs_server.ensure_trainer_exporter() is None:
        return None
    from horovod_tpu.training.trainer import StepPhaseSampler

    return StepPhaseSampler(trainer, 0)


def fit_epochs(trainer, it, pending, zero_acc, epochs, initial_epoch, steps_per_epoch,
    callbacks, validation_data, batch_size, verbose, initial_step=0,
):
    from horovod_tpu import trace as trace_lib
    from horovod_tpu.data.prefetch import DevicePrefetcher

    # Per-epoch execution plan: full steps_per_execution chunks plus one
    # remainder chunk (a second, smaller executable) when K doesn't
    # divide the epoch. The RESUME epoch (initial_step > 0) covers only
    # its remaining steps — the iterator was already fast-forwarded past
    # the first initial_step·accum microbatches — so its plan (and hence
    # the host-chunk assembly below) is shorter than the steady-state
    # epochs'.
    spe = min(trainer.steps_per_execution, steps_per_epoch)

    def plan_for(epoch):
        steps = steps_per_epoch - (
            initial_step if epoch == initial_epoch else 0
        )
        plan = [spe] * (steps // spe)
        if steps % spe:
            plan.append(steps % spe)
        return plan

    buffered = [pending]
    # Microbatches per optimizer step (backward_passes_per_step): each
    # execution unit carries accum microbatches per step, stacked on a
    # leading axis the accumulating train step scans over.
    accum = trainer._accum_steps

    def host_chunks():
        # Host-side assembly of the execution units: single batches when
        # spe*accum == 1, [accum, ...] microbatch stacks per step, and
        # [spe(, accum), ...] stacks of steps.
        for epoch in range(initial_epoch, epochs):
            for k in plan_for(epoch):
                batches = [
                    buffered.pop() if buffered else next(it)
                    for _ in range(k * accum)
                ]
                # Stack leaf-wise — pytree batches (dict inputs,
                # multi-input models) stack like flat ones.
                if accum > 1:
                    steps = [
                        jax.tree.map(
                            lambda *xs: np.stack(xs),
                            *batches[i * accum : (i + 1) * accum],
                        )
                        for i in range(k)
                    ]
                else:
                    steps = batches
                if spe == 1:
                    yield steps[0]
                else:
                    yield jax.tree.map(lambda *xs: np.stack(xs), *steps)

    # Batches are staged onto the devices by a background thread while
    # the current step computes — transfer enqueue never blocks dispatch.
    # The step DONATES each batch (every prefetched chunk is consumed
    # exactly once), so with the default depth of 2 the path is true
    # double buffering: two batch-sized device buffers alternate between
    # "being transferred" and "being consumed", and the consumed one's
    # memory returns to the allocator at dispatch instead of piling up
    # behind the queue. HVT_PREFETCH_DEPTH deepens the queue for bursty
    # producers.
    from horovod_tpu.analysis import registry

    depth = registry.get_int("HVT_PREFETCH_DEPTH") or 2
    run = (
        trainer._train_step_donated if spe == 1
        else trainer._train_chunk_donated
    )
    if spe == 1:
        place = (
            trainer._shard if accum == 1
            else lambda b: trainer._shard_chunk(b, 1)
        )
    else:
        place = lambda b: trainer._shard_chunk(b, 2 if accum > 1 else 1)  # noqa: E731
    prefetcher = DevicePrefetcher(host_chunks(), place, depth=depth)
    sampler = _maybe_step_sampler(trainer)
    try:
        for epoch in range(initial_epoch, epochs):
            if trainer.stop_training:
                break
            # Fresh scale each epoch (see _fit_device_cached note).
            trainer.update_scale = 1.0
            for cb in callbacks:
                cb.on_epoch_begin(epoch)
            t0 = time.perf_counter()
            scale = jnp.asarray(trainer.update_scale, jnp.float32)
            metric_acc = zero_acc
            # Batch indices are TRUE within-epoch optimizer steps: a
            # resumed epoch's first on_batch_end fires with the step it
            # actually trained, so step-keyed cadences (elastic commits,
            # step-targeted faults) stay aligned across a resume.
            start = initial_step if epoch == initial_epoch else 0
            step = start
            for k in plan_for(epoch):
                if sampler is not None:
                    t_in = time.perf_counter()
                    chunk = next(prefetcher)
                    sampler.add_input_wait(time.perf_counter() - t_in)
                    if sampler._step_shapes is None:
                        # First chunk: derive examples per OPTIMIZER step
                        # from the placed shapes ([spe?, K?, G, ...]) and
                        # snapshot the step args for the cost-model MFU.
                        leaf = jax.tree_util.tree_leaves(chunk[0])[0]
                        lead = 1 + (1 if spe > 1 else 0) + (
                            1 if accum > 1 else 0
                        )
                        rows = int(np.prod(leaf.shape[:lead]))
                        sampler.examples_per_step = rows // (
                            leaf.shape[0] if spe > 1 else 1
                        )
                        # k, not spe: the FIRST chunk of a resumed epoch
                        # can be a remainder chunk with fewer steps, and
                        # the captured executable's FLOPs must divide by
                        # the step count of the program actually
                        # captured or hvt_mfu mis-scales for the run.
                        sampler.capture_step_args(
                            run, (trainer.state, chunk, scale, metric_acc),
                            k,
                        )
                else:
                    chunk = next(prefetcher)
                t_run = time.perf_counter() if sampler is not None else 0.0
                with trace_lib.span("step", epoch=epoch, step=step,
                                    steps=k):
                    trainer.state, metrics, metric_acc = run(
                        trainer.state, chunk, scale, metric_acc
                    )
                if sampler is not None:
                    # Step-call host time feeds the SkewProbe's blocked
                    # signal (sync-dispatch backends block HERE, not in
                    # the drain).
                    sampler.add_step_time(time.perf_counter() - t_run)
                    sampler.maybe_sample(trainer.state, k)
                step += k
                # Once per execution, with the last step's metrics —
                # Keras's steps_per_execution callback semantics.
                for cb in callbacks:
                    cb.on_batch_end(step - 1, metrics)
            finish_epoch(trainer,
                epoch, epochs, metric_acc, steps_per_epoch - start, t0,
                callbacks, validation_data, batch_size, verbose,
            )
    finally:
        prefetcher.close()

def fit_device_cached(trainer, x, y, batch_size, epochs, initial_epoch, steps_per_epoch,
    callbacks, validation_data, verbose, initial_step=0,
):
    from horovod_tpu import trace as trace_lib

    data, per_shard = stage_device_dataset(trainer, x, y)
    # One optimizer step consumes accum_steps microbatches of batch_size.
    max_steps = per_shard // (batch_size * trainer._accum_steps)
    if max_steps == 0:
        raise ValueError(
            f"per-shard examples ({per_shard}) < per-chip batch "
            f"({batch_size}) x backward_passes_per_step "
            f"({trainer._accum_steps})"
        )
    steps = min(steps_per_epoch or max_steps, max_steps)
    # Mid-epoch resume: the epoch's shuffle is a pure function of
    # (seed, epoch) — fold_in below — so the resume epoch regenerates the
    # SAME permutation and the compiled epoch program simply starts its
    # gather/scan at step `initial_step`: batches byte-identical to the
    # uninterrupted epoch's steps S.., no skipped batch ever gathered.
    initial_epoch, initial_step = _normalize_resume(
        initial_epoch, initial_step, steps
    )
    trainer._resume_epoch, trainer._resume_step = initial_epoch, initial_step
    trainer._stream_geometry = {
        "path": "device",
        "accum": trainer._accum_steps,
        "steps_per_epoch": steps,
        "batch_size": batch_size,
    }
    trainer.build(
        np.asarray(x[: trainer.dp_size]), np.asarray(y[: trainer.dp_size])
    )

    callbacks = _with_env_callbacks(callbacks)
    for cb in callbacks:
        cb.set_trainer(trainer)
    # Step-chunked epoch executables (HVT_EPOCH_CHUNK_STEPS): split each
    # on-device epoch into compiled chunks of C optimizer steps so
    # on_batch_end fires per chunk — sub-epoch commit/rescale/save
    # cadences (elastic commit_every_steps, HVT_SAVE_EVERY_STEPS) work on
    # the device-cached path too. `start` is a dynamic jit argument, so
    # the whole epoch costs at most two executables (full chunk +
    # remainder), independent of the chunk count. 0 = whole-epoch program
    # (the historical single-dispatch behavior).
    from horovod_tpu.analysis import registry

    chunk = registry.get_int("HVT_EPOCH_CHUNK_STEPS") or 0
    sampler = _maybe_step_sampler(trainer)
    if sampler is not None:
        # Device-cached feeding has no host input leg by construction;
        # examples/step is the staged geometry's.
        sampler.examples_per_step = (
            trainer.dp_size * batch_size * trainer._accum_steps
        )
    try:
        # Inside the teardown scope — see the streamed fit path's note.
        for cb in callbacks:
            cb.on_train_begin()
        zero_acc = sharding_lib.replicate(trainer.zero_metrics(), trainer.mesh)
        epoch_key = jax.random.PRNGKey(trainer.seed + 1)
        with trace_lib.maybe_trace(trace_lib.profile_dir()):
            for epoch in range(initial_epoch, epochs):
                if trainer.stop_training:
                    break
                # Fresh scale each epoch: LR callbacks compose into it
                # in list order (warmup assigns, schedules multiply).
                trainer.update_scale = 1.0
                for cb in callbacks:
                    cb.on_epoch_begin(epoch)
                t0 = time.perf_counter()
                scale = jnp.asarray(trainer.update_scale, jnp.float32)
                start = initial_step if epoch == initial_epoch else 0
                c = chunk if chunk > 0 else steps - start
                metric_acc = zero_acc
                at = start
                while at < steps:
                    n = min(c, steps - at)
                    t_run = (
                        time.perf_counter() if sampler is not None else 0.0
                    )
                    with trace_lib.span("step", epoch=epoch, step=at,
                                        steps=n):
                        trainer.state, metrics, metric_acc = (
                            trainer._train_epoch(
                                trainer.state, data,
                                jax.random.fold_in(epoch_key, epoch),
                                scale, metric_acc, n, batch_size, at,
                            )
                        )
                    if sampler is not None:
                        sampler.add_step_time(time.perf_counter() - t_run)
                        sampler.maybe_sample(trainer.state, n)
                    at += n
                    # Once per chunk, with the chunk's last step metrics
                    # and the TRUE within-epoch step index — the
                    # steps_per_execution callback contract.
                    for cb in callbacks:
                        cb.on_batch_end(at - 1, metrics)
                finish_epoch(trainer,
                    epoch, epochs, metric_acc, steps - start, t0, callbacks,
                    validation_data, batch_size, verbose,
                    # Device-cached training implies device-cached
                    # validation.
                    val_cache="device",
                )
    except BaseException:
        _teardown_callbacks(callbacks)
        raise
    _run_train_end(callbacks)
    return trainer.history

def evaluate_device_cached(trainer, x, y, batch_size: int) -> dict:
    """evaluate() over a device-resident eval set: stage once (padded to
    full batches, padding masked), then each call is ONE dispatch + one
    3-scalar fetch. The per-epoch validation pass stops restreaming the
    test set from the host every epoch.

    Caching is by the host arrays' identity: do not mutate ``x``/``y``
    in place while cached, or stale staged data is evaluated."""
    key = (id(x), id(y), batch_size)
    if key not in trainer._eval_cache:
        n = len(x)
        n_shards = trainer.dp_size
        per = -(-n // (n_shards * batch_size)) * batch_size  # ceil→pad
        pad_n = per * n_shards
        mask = np.zeros(pad_n, np.float32)
        mask[:n] = 1.0

        def padded(a):
            # Repeat a REAL example into the padded tail (like the
            # streamed path): all-zero rows could produce non-finite
            # losses in input-normalizing models, and NaN*0 = NaN would
            # poison the masked sums.
            a = np.asarray(a)
            out = np.concatenate(
                [a, np.repeat(a[-1:], pad_n - n, axis=0)]
            )
            return out

        data = (
            stage_sharded(trainer, padded(x), per),
            stage_sharded(trainer, padded(y), per),
            stage_sharded(trainer, mask, per),
        )
        # Keep x/y referenced so their ids stay unique while cached.
        trainer._eval_cache[key] = (data, per // batch_size, (x, y))
        if len(trainer._eval_cache) > 4:  # bound device memory
            trainer._eval_cache.pop(next(iter(trainer._eval_cache)))
    data, steps, _ = trainer._eval_cache[key]
    m = jax.device_get(
        trainer._eval_epoch(trainer.state, data, steps, batch_size)
    )
    return {
        "loss": float(m["loss_sum"]) / float(m["count"]),
        "accuracy": float(m["correct_sum"]) / float(m["count"]),
    }

def run_evaluate(trainer, x, y, batch_size: int = 128, verbose: int = 0,
    cache: str | None = None,
) -> dict:
    """Full-dataset eval on the mesh. Unlike the reference (every rank
    redundantly evaluates the full test set, SURVEY.md §3.2), the eval
    batch is sharded across chips — same result, 1/size the work.
    ``cache='device'`` keeps the (padded, masked) eval set in HBM and
    runs the whole pass as one compiled scan."""
    if trainer.state is None:
        raise RuntimeError("call fit() or build() first")
    if (
        cache == "device"
        and trainer.batch_specs is not None
        and mesh_lib.has_live_model_axes(trainer.mesh)
    ):
        # Custom batch layouts over LIVE non-data axes (e.g. seq-sharded
        # tokens) need _shard's spec handling; the cached path stages
        # batch-dim-only. With those axes trivial the layouts coincide —
        # same condition as fit(cache='device')'s guard.
        cache = None
    if isinstance(x, list):
        x = np.asarray(x)  # list-of-rows = one array input (see fit)
    if cache == "device":
        if len(jax.tree_util.tree_leaves(x)) != 1:
            raise ValueError(
                "cache='device' stages a single input array; pytree "
                "(dict/tuple) inputs use the streamed eval path"
            )
        result = evaluate_device_cached(trainer, x, y, batch_size)
        if verbose and runtime.is_primary():
            print(f"eval - {({k: round(v, 4) for k, v in result.items()})}")
        return result
    if cache is not None:
        raise ValueError(f"unknown cache mode {cache!r}")
    # x may be a pytree (dict-input models, e.g. seq2seq) — slice, pad
    # and shard leaf-wise; y/mask stay flat arrays.
    n = len(jax.tree_util.tree_leaves(x)[0])
    global_batch = batch_size * trainer.dp_size
    loss_sum = correct_sum = count = 0.0
    for start in range(0, n, global_batch):
        xb, bs = slice_pad(trainer, x, start, global_batch)
        yb, _ = slice_pad(trainer, y, start, global_batch)
        mask = np.ones((global_batch,), np.float32)
        mask[bs:] = 0.0
        batch = tuple(
            jax.tree.map(
                lambda a: local_slice(trainer, a, global_batch), part
            )
            for part in (xb, yb, mask)
        )
        m = jax.device_get(trainer._eval_step(trainer.state, shard_batch(trainer, batch)))
        loss_sum += float(m["loss_sum"])
        correct_sum += float(m["correct_sum"])
        count += float(m["count"])
    result = {"loss": loss_sum / count, "accuracy": correct_sum / count}
    if verbose and runtime.is_primary():
        print(f"eval - {({k: round(v, 4) for k, v in result.items()})}")
    return result

def run_predict(trainer, x, batch_size: int = 128) -> np.ndarray:
    """Class probabilities (softmax applied here, keeping the serving
    contract input→prob, mnist_keras.py:133-134). ``x`` may be a pytree
    (dict-input models) — slice/pad/shard run leaf-wise, like
    `evaluate`."""
    if trainer.state is None:
        raise RuntimeError("call fit() or build() first")
    if isinstance(x, list):
        x = np.asarray(x)  # list-of-rows = one array input (see fit)
    out = []
    global_batch = batch_size * trainer.dp_size
    n = len(jax.tree_util.tree_leaves(x)[0])
    for start in range(0, n, global_batch):
        xb, bs = slice_pad(trainer, x, start, global_batch)
        xb = jax.tree.map(
            lambda a: local_slice(trainer, a, global_batch), xb
        )
        probs = jax.device_get(trainer._predict_step(trainer.state, shard_batch(trainer, xb)))
        out.append(probs[:bs])
    return np.concatenate(out, axis=0)
