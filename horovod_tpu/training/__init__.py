"""Training layer: optimizer wrap, Keras-like fit loop, callback protocol."""

from horovod_tpu.training.optimizer import DistributedOptimizer  # noqa: F401
from horovod_tpu.training import callbacks  # noqa: F401
from horovod_tpu.training.trainer import Trainer, TrainState  # noqa: F401
