"""Per-process collective flight recorder — the runtime evidence trail
behind ``hvt-sched replay`` (hvt-sched, the verification layer's runtime
side).

Horovod's coordinator (arXiv:1802.05799) exists because a single rank
submitting its collectives in a different order deadlocks the fleet —
and this framework deliberately dropped the coordinator, trusting the
SPMD program + the static analyzers to keep submission order agreed.
When that trust is misplaced the observable symptom is a HANG: no exit
code, stale heartbeats, and (until now) no record of WHAT each rank was
doing when it wedged. This module is the black box: with
``HVT_FLIGHT_RECORD=<dir>`` set, every submission site in
`parallel.collectives` appends one bounded record — seq, kind, dtype,
shape, payload bytes, fusion-bucket id, caller tag — to this process's
``<dir>/flight-<member>.jsonl``, and ``hvt-sched replay <dir>``
cross-checks N ranks' records to name the first divergent submission.

Contracts:

* **Zero cost off.** Unset ``HVT_FLIGHT_RECORD`` leaves the module-level
  ``RECORDER`` at ``None``; every submission site in collectives.py
  routes through ONE gate (``collectives._maybe_record``) whose off-path
  is a single ``is None`` check — no string formatting, no frame walks,
  no I/O. Asserted structurally by the tier-1 tests.
* **Write-through.** Each record is appended (and flushed) to the JSONL
  file BEFORE the collective blocks, so a rank wedged inside a native
  collective — the one failure mode that can never run a dump handler —
  still leaves its final submission on disk. The in-memory ring (bounded
  by ``HVT_FLIGHT_RECORD_SIZE``) is what explicit dumps rewrite.
* **Dump triggers.** SIGTERM (handler chained in front of whatever was
  installed — the supervisor's hang teardown SIGTERMs the fleet first),
  ``POST /flightrecord`` on the trainer metrics exporter (obs/server),
  and the supervisor's hang classification, which copies every member's
  file into a per-attempt quarantine dir before the relaunch truncates
  them (`collect`).
* **Submission time vs trace time.** Eager host-level collectives
  (broadcast_object, the elastic sync/gather transport) record at CALL
  time — per submission, the runtime evidence. Collectives inside a
  traced step (reduce_gradients' buckets) record at TRACE time — once
  per compile, a program-order witness, tagged by the same caller-tag
  mechanism.

Deliberately stdlib-only: the supervisor (which never imports jax) and
the ``hvt-sched replay`` CLI both import this module.
"""

from __future__ import annotations

import collections
import json
import os
import shutil
import signal
import threading
import time

from horovod_tpu.analysis import registry

ENV_RECORD = "HVT_FLIGHT_RECORD"
ENV_SIZE = "HVT_FLIGHT_RECORD_SIZE"

#: The live recorder, or None when recording is off. Submission sites
#: check this ONE name — the whole off-path instrumentation cost.
RECORDER = None


def member_label() -> str:
    """Stable per-process identity for the record filename: the elastic
    member id when launched elastically, else the launcher-assigned rank,
    else the pid (standalone runs)."""
    member = registry.get_str("HVT_ELASTIC_MEMBER")
    if member:
        return member
    for knob in ("HVT_PROCESS_ID", "HVT_LOCAL_RANK"):
        raw = registry.get_raw(knob)
        if raw is not None:
            return f"rank{int(raw)}"
    return f"pid{os.getpid()}"


class FlightRecorder:
    """Bounded per-process submission recorder (see module docstring).

    ``records`` is a ring of at most ``size`` dicts; the JSONL file is
    append-on-record (write-through) and rewritten from the ring by
    `dump`/`swap_last_two` — so the file always carries at least the
    ring, and the tail is on disk even when the process dies without a
    handler running."""

    def __init__(self, path: str, size: int = 512):
        self.path = path
        self.size = max(2, int(size))
        self.seq = 0
        self.records: collections.deque = collections.deque(maxlen=self.size)
        self._lock = threading.Lock()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        # Truncate on open: a fresh process starts a fresh record. This is
        # a diagnostic stream, not a checkpoint artifact — a torn write
        # costs one record of evidence, never correctness.
        self._fh = open(path, "w")  # hvt: noqa[HVT005] diagnostic stream

    @property
    def count(self) -> int:
        return len(self.records)

    def record(self, kind: str, *, dtype=None, shape=None, nbytes=None,
               bucket=None, tag=None) -> None:
        rec = {"kind": str(kind)}
        if dtype is not None:
            rec["dtype"] = str(dtype)
        if shape is not None:
            rec["shape"] = list(shape)
        if nbytes is not None:
            rec["bytes"] = int(nbytes)
        if bucket is not None:
            rec["bucket"] = int(bucket)
        if tag is not None:
            rec["tag"] = str(tag)
        rec["t"] = time.time()
        with self._lock:
            # seq is assigned UNDER the lock: replay keys records by it,
            # so two threads racing a read-then-increment would collapse
            # into one seq and fake a 'missing' divergence at the gap.
            rec["seq"] = self.seq
            self.seq += 1
            self.records.append(rec)
            try:
                self._fh.write(json.dumps(rec) + "\n")
                self._fh.flush()
            except (OSError, ValueError):
                pass  # evidence is best-effort; never take down training

    def swap_last_two(self) -> bool:
        """Swap the op payloads (everything but seq/t) of the last two
        recorded submissions — the `reorder` fault kind's seeded
        divergence: this rank's record now claims it submitted the ops
        in the opposite order, which is exactly what a real mismatched
        submission looks like to `hvt-sched replay`."""
        with self._lock:
            if len(self.records) < 2:
                return False
            a, b = self.records[-2], self.records[-1]
            keep = ("seq", "t")
            pa = {k: v for k, v in a.items() if k not in keep}
            pb = {k: v for k, v in b.items() if k not in keep}
            for k in pa:
                a.pop(k, None)
            for k in pb:
                b.pop(k, None)
            a.update(pb)
            b.update(pa)
            self._rewrite_locked()
        return True

    def _rewrite_locked(self) -> None:
        try:
            self._fh.seek(0)
            self._fh.truncate()
            for rec in self.records:
                self._fh.write(json.dumps(rec) + "\n")
            self._fh.flush()
        except (OSError, ValueError):
            pass

    def dump(self) -> str:
        """Rewrite the file from the ring (idempotent) and return its
        path — the SIGTERM / POST /flightrecord trigger."""
        with self._lock:
            self._rewrite_locked()
        return self.path

    def close(self) -> None:
        with self._lock:
            self._rewrite_locked()
            try:
                self._fh.close()
            except OSError:
                pass


_prev_sigterm = None
_handler_installed = False


def _sigterm_dump(signum, frame):  # pragma: no cover — signal path
    rec = RECORDER
    if rec is not None:
        try:
            rec.dump()
        except Exception:
            pass
    prev = _prev_sigterm
    if callable(prev):
        prev(signum, frame)
    elif prev != signal.SIG_IGN:
        # SIG_DFL — or None, getsignal's answer when the prior handler
        # was installed from C (absl/XLA runtimes): restore the default
        # and re-deliver so termination semantics (and the 143 exit-code
        # convention) are preserved; a process that dumped its ring must
        # still DIE on SIGTERM. Only an explicit SIG_IGN keeps ignoring.
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        os.kill(os.getpid(), signal.SIGTERM)


def _install_sigterm_dump() -> None:
    global _prev_sigterm, _handler_installed
    if _handler_installed:
        return
    try:
        _prev_sigterm = signal.getsignal(signal.SIGTERM)
        signal.signal(signal.SIGTERM, _sigterm_dump)
        _handler_installed = True
    except (ValueError, OSError):  # non-main thread / exotic platform
        pass


def enable(directory: str | None = None, size: int | None = None):
    """Start this process's recorder (idempotent). ``directory`` defaults
    to ``HVT_FLIGHT_RECORD``; returns the recorder, or None when the knob
    is unset (recording stays off — the zero-cost default)."""
    global RECORDER
    if RECORDER is not None:
        return RECORDER
    directory = directory or registry.get_str(ENV_RECORD)
    if not directory:
        return None
    if size is None:
        size = registry.get_int(ENV_SIZE) or 512
    path = os.path.join(directory, f"flight-{member_label()}.jsonl")
    RECORDER = FlightRecorder(path, size)
    _install_sigterm_dump()
    return RECORDER


def disable() -> None:
    """Stop and drop the recorder (tests)."""
    global RECORDER
    if RECORDER is not None:
        RECORDER.close()
        RECORDER = None


# --- collection (the supervisor's hang path) --------------------------------


def record_files(directory: str) -> list:
    """The per-member record files under ``directory``, name-sorted."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    return [
        os.path.join(directory, n) for n in sorted(names)
        if n.startswith("flight-") and n.endswith(".jsonl")
    ]


def collect(directory: str, dest: str) -> list:
    """Quarantine-copy every member's record file into ``dest`` — the
    supervisor's hang-classification hook. Copies (never moves): the
    relaunch truncates the live files on its own, and the copies are
    what ``hvt-sched replay`` examines post-mortem. Returns the copied
    paths (empty when there was nothing to collect)."""
    files = record_files(directory)
    if not files:
        return []
    os.makedirs(dest, exist_ok=True)
    out = []
    for src in files:
        target = os.path.join(dest, os.path.basename(src))
        try:
            shutil.copyfile(src, target)
        except OSError:
            continue
        out.append(target)
    return out


# --- replay cross-check (hvt-sched replay) ----------------------------------


def read_records(path: str) -> list:
    """Parse one record file; torn tail lines are skipped (a SIGKILL can
    land mid-append — the preceding records are the evidence)."""
    out = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict) and "seq" in rec and "kind" in rec:
                    out.append(rec)
    except OSError:
        return []
    return out


def load_members(directory: str) -> dict:
    """``{member label: [records]}`` for every ``flight-<member>.jsonl``
    under ``directory`` — the replay cross-check's input shape, shared by
    `hvt-sched replay` and the supervisor policy engine's hang triage."""
    out = {}
    for path in record_files(directory):
        label = os.path.basename(path)[len("flight-"):-len(".jsonl")]
        out[label] = read_records(path)
    return out


def replay_verdict(by_member: dict) -> dict | None:
    """Machine-shaped verdict of the replay cross-check over
    `load_members` output — what `hvt-sched replay` prints and what the
    policy engine journals into the restart journal before a relaunch:

    * ``None`` — nothing to cross-check (fewer than two members);
    * ``{"status": "agree", "members": N}`` — every member matches
      op-for-op;
    * ``{"status": "diverged", "members": N, "member_a", "member_b",
      "seq", "kind", "op_a", "op_b"}`` — `first_divergence`'s witness
      with the ops pre-formatted (`format_op`), JSON-journal-safe."""
    if len(by_member) < 2:
        return None
    div = first_divergence(by_member)
    if div is None:
        return {"status": "agree", "members": len(by_member)}
    return {
        "status": "diverged",
        "members": len(by_member),
        "member_a": div["member_a"],
        "member_b": div["member_b"],
        "seq": div["seq"],
        "kind": div["kind"],
        "op_a": format_op(div["op_a"]),
        "op_b": format_op(div["op_b"]),
    }


def op_key(rec: dict) -> tuple:
    """What must MATCH across ranks for a submission to agree: the op's
    identity (kind/dtype/shape/bucket/caller tag). Payload BYTES are
    deliberately excluded — object collectives legitimately move
    different byte counts per rank (allgather_object contributions)."""
    shape = rec.get("shape")
    return (
        rec.get("kind"),
        rec.get("dtype"),
        tuple(shape) if shape is not None else None,
        rec.get("bucket"),
        rec.get("tag"),
    )


def format_op(rec: dict | None) -> str:
    if rec is None:
        return "(no submission)"
    parts = [str(rec.get("kind"))]
    if rec.get("dtype") is not None or rec.get("shape") is not None:
        dims = "x".join(str(d) for d in (rec.get("shape") or ()))
        parts.append(f"{rec.get('dtype') or '?'}[{dims}]")
    if rec.get("bucket") is not None:
        parts.append(f"bucket={rec['bucket']}")
    if rec.get("tag"):
        parts.append(f"@{rec['tag']}")
    return " ".join(parts)


def first_divergence(by_member: dict) -> dict | None:
    """Cross-check N members' record lists (``{label: [records]}``) and
    return the first divergent submission, or None when every member
    agrees.

    Alignment is by the records' own ``seq`` (ring truncation keeps seq
    monotonic), starting at the latest FIRST seq any non-empty member
    still holds: one member's ring may have dropped early history while
    a natively-wedged peer's write-through file kept it all — coverage
    asymmetry is not divergence, so only the commonly-covered window is
    compared. A member with NO records at all still diverges at its
    peers' first submission (a rank that never submitted is the
    verdict, not a window artifact). The lexicographically-first member
    is the reference; the first in-window seq where any member's op
    identity differs — or where exactly one side has a submission at
    all (missing/extra) — is the verdict: ``{seq, kind:
    mismatch|missing|extra, member_a, member_b, op_a, op_b}``."""
    labels = sorted(by_member)
    if len(labels) < 2:
        return None
    maps = {lb: {r["seq"]: r for r in by_member[lb]} for lb in labels}
    # The window is computed over NON-empty members only: one member's
    # empty record must not re-expose another's ring-truncated head as
    # a false 'missing' — the empty member itself still diverges at the
    # window's first seq (its silence IS the verdict).
    starts = [min(m) for m in maps.values() if m]
    start = max(starts) if starts else 0
    all_seqs = sorted(
        {s for m in maps.values() for s in m if s >= start}
    )
    ref = labels[0]
    for s in all_seqs:
        a = maps[ref].get(s)
        for lb in labels[1:]:
            b = maps[lb].get(s)
            if a is None and b is None:
                continue
            if a is None or b is None:
                return {
                    "seq": s,
                    "kind": "missing" if b is None else "extra",
                    "member_a": ref, "member_b": lb,
                    "op_a": a, "op_b": b,
                }
            if op_key(a) != op_key(b):
                return {
                    "seq": s, "kind": "mismatch",
                    "member_a": ref, "member_b": lb,
                    "op_a": a, "op_b": b,
                }
    return None


def context_window(records: list, seq: int, window: int = 3) -> list:
    """The records within ``window`` submissions of ``seq`` — the
    per-rank context `hvt-sched replay` prints around the divergence."""
    return [r for r in records if abs(r["seq"] - seq) <= window]


def _has_rank_identity() -> bool:
    """Whether this process is a launched RANK (the launcher/supervisor
    assigns one of these) rather than the supervisor/launcher itself —
    which imports this package too, inherits ``HVT_FLIGHT_RECORD`` from
    the job shell, and must NOT leave an empty pid-named record that
    pollutes the hang collection."""
    return any(
        registry.get_raw(k) is not None
        for k in ("HVT_ELASTIC_MEMBER", "HVT_PROCESS_ID", "HVT_LOCAL_RANK")
    )


# Recording starts at import when the knob is set AND this process is a
# launched rank: the launcher's children inherit HVT_FLIGHT_RECORD and
# begin recording before the first collective, with no entry-script
# changes. Standalone (no-launcher) processes enable at `runtime.init`
# instead — the supervisor never calls either.
if _has_rank_identity():
    enable()
