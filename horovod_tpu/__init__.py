"""horovod_tpu — a TPU-native distributed training framework.

A brand-new JAX/XLA framework with the full capability surface of the Horovod
data-parallel example suite (``weikaolun/horovod-distributed-example``), built
TPU-first: SPMD over a `jax.sharding.Mesh`, XLA collectives over ICI/DCN, and
compiler-scheduled (not runtime-negotiated) gradient reduction.

Public API mirrors the Horovod surface the reference exercises
(see SURVEY.md §2.4; reference call sites tensorflow2_keras_mnist.py:25,32,55,58
and mnist_keras.py:30,35,42,84,87):

    import horovod_tpu as hvt

    hvt.init()                      # hvd.init()       — process/device bootstrap
    hvt.rank(), hvt.size()          # hvd.rank()/size  — topology queries
    hvt.local_rank()                # hvd.local_rank() — per-host ordinal
    hvt.DistributedOptimizer(opt)   # gradient-AVERAGING wrap of any optax optimizer
    hvt.broadcast_parameters(tree)  # hvd.broadcast_global_variables(0)
    hvt.callbacks.*                 # Broadcast / MetricAverage / LRWarmup callbacks

Where Horovod needs a C++ coordinator thread, tensor-fusion buffers and NCCL
rings to negotiate collectives between N independent processes, this framework
expresses the training step as a single SPMD program: collective order is
static, fusion is an XLA pass, and the "coordinator" is the compiler.
"""

from horovod_tpu import runtime
from horovod_tpu.runtime import (
    init,
    shutdown,
    is_initialized,
    rank,
    size,
    local_rank,
    local_size,
    process_rank,
    process_count,
    is_primary,
)
from horovod_tpu.parallel import mesh as mesh_lib
from horovod_tpu.parallel.mesh import (
    MeshSpec,
    build_mesh,
    data_parallel_mesh,
    scale_lr,
    shard_steps,
    shard_epochs,
)
from horovod_tpu.parallel import collectives
from horovod_tpu.parallel.collectives import (
    allreduce,
    allgather,
    broadcast,
    pmean_pytree,
    broadcast_pytree,
    broadcast_object,
    allgather_object,
)
from horovod_tpu.training.optimizer import Compression, DistributedOptimizer
from horovod_tpu.training import callbacks
from horovod_tpu.training.trainer import Trainer, TrainState
from horovod_tpu import checkpoint
from horovod_tpu import serving
from horovod_tpu.checkpoint import broadcast_parameters

__version__ = "0.2.0"  # keep in sync with pyproject.toml

__all__ = [
    "init",
    "shutdown",
    "is_initialized",
    "rank",
    "size",
    "local_rank",
    "local_size",
    "process_rank",
    "process_count",
    "is_primary",
    "MeshSpec",
    "build_mesh",
    "data_parallel_mesh",
    "scale_lr",
    "shard_steps",
    "shard_epochs",
    "allreduce",
    "allgather",
    "broadcast",
    "pmean_pytree",
    "broadcast_pytree",
    "broadcast_object",
    "allgather_object",
    "Compression",
    "DistributedOptimizer",
    "callbacks",
    "Trainer",
    "TrainState",
    "checkpoint",
    "broadcast_parameters",
    "runtime",
    "collectives",
    "mesh_lib",
]
