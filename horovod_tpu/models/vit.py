"""Vision Transformer for CIFAR/MNIST-scale images (Dosovitskiy et al.,
arXiv:2010.11929) — the TPU-first vision family.

The conv attribution (benchmarks/conv_profile.py, BASELINE.md) proved the
CIFAR-scale conv models are *shape-bound*: a 16-channel 3×3 conv fills
16/128 MXU lanes and no amount of batch fixes it (ResNet-20 plateaus at
MFU ≈ 0.20). The TPU-first answer is an architecture whose image compute
IS matmuls at MXU-friendly widths: patchify (one reshape + one Dense),
then d_model-wide transformer encoder blocks. Same Trainer / optimizer /
callback path as the CNNs (the capability the reference exercises,
tensorflow2_keras_mnist.py:43-52 — model architecture is a swappable leaf
of the framework, not part of it).

Design notes:
* patchify = reshape to [B, T, p·p·C] + Dense — no convs anywhere; the
  embedding, attention and MLP are all ≥ d_model-wide matmuls.
* bidirectional (non-causal) dense attention: at CIFAR scale T = (32/p)²
  is 64 patches — the [T, T] score matrix is tiny, so the dense path is
  the right kernel (the flash kernel exists for long sequences, not this).
* learned position embeddings (images are not translation-invariant at
  patch granularity), mean-pool head by default ('cls' token optional).
* bf16 compute / f32 params + logits, like every other model here.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from horovod_tpu.ops.attention import dense_attention


class EncoderBlock(nn.Module):
    d_model: int
    n_heads: int
    mlp_ratio: int = 4
    dropout: float = 0.0
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        head_dim = self.d_model // self.n_heads
        dense = lambda feat, name: nn.DenseGeneral(  # noqa: E731
            feat, dtype=self.compute_dtype, use_bias=True, name=name
        )
        h = nn.LayerNorm(dtype=self.compute_dtype)(x)
        qkv = dense((self.n_heads, 3 * head_dim), "qkv")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        att = dense_attention(q, k, v, causal=False)  # [B, T, H, hd]
        out = nn.DenseGeneral(
            self.d_model, axis=(-2, -1), dtype=self.compute_dtype,
            name="attn_out",
        )(att)
        out = nn.Dropout(self.dropout, deterministic=not train)(out)
        x = x + out
        h = nn.LayerNorm(dtype=self.compute_dtype)(x)
        h = dense(self.mlp_ratio * self.d_model, "mlp_up")(h)
        h = nn.gelu(h)
        h = dense(self.d_model, "mlp_down")(h)
        h = nn.Dropout(self.dropout, deterministic=not train)(h)
        return x + h


class ViT(nn.Module):
    """[B, H, W, C] images (float, or uint8 normalized on device) →
    [B, num_classes] float32 logits."""

    patch_size: int = 4
    d_model: int = 256
    n_heads: int = 8
    n_layers: int = 8
    mlp_ratio: int = 4
    num_classes: int = 10
    dropout: float = 0.0
    pool: str = "mean"  # 'mean' = GAP head (CIFAR-ResNet style), or 'cls'
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        if self.pool not in ("mean", "cls"):
            raise ValueError(f"pool must be 'mean' or 'cls', got {self.pool!r}")
        b, h, w, c = x.shape
        p = self.patch_size
        if h % p or w % p:
            raise ValueError(
                f"image {h}x{w} not divisible by patch_size {p}"
            )
        if jnp.issubdtype(x.dtype, jnp.integer):
            # Raw uint8 pixels → on-device /255 (see MnistCNN note: 4x less
            # host->device traffic, identical numerics to host normalize).
            x = x.astype(jnp.float32) / 255.0
        x = x.astype(self.compute_dtype)
        # Patchify as pure data movement + one matmul: [B, h/p, p, w/p, p, C]
        # → [B, T, p·p·C] → Dense(d_model).
        x = x.reshape(b, h // p, p, w // p, p, c)
        x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, (h // p) * (w // p), -1)
        x = nn.Dense(self.d_model, dtype=self.compute_dtype, name="embed")(x)
        t = x.shape[1]
        if self.pool == "cls":
            cls = self.param(
                "cls", nn.initializers.zeros, (1, 1, self.d_model), jnp.float32
            )
            x = jnp.concatenate(
                [jnp.broadcast_to(cls, (b, 1, self.d_model)).astype(x.dtype), x],
                axis=1,
            )
            t += 1
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02), (1, t, self.d_model),
            jnp.float32,
        )
        x = x + pos.astype(x.dtype)
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        for i in range(self.n_layers):
            x = EncoderBlock(
                self.d_model, self.n_heads, self.mlp_ratio, self.dropout,
                self.compute_dtype, name=f"Block_{i}",
            )(x, train=train)
        x = nn.LayerNorm(dtype=self.compute_dtype)(x)
        x = x[:, 0] if self.pool == "cls" else x.mean(axis=1)
        x = nn.Dense(self.num_classes, dtype=self.compute_dtype, name="head")(x)
        return x.astype(jnp.float32)
