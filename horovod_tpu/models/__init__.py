"""Model zoo: the reference CNN, ResNet-20 (CIFAR), and the transformer
flagship for long-context / tensor-parallel configurations — plus the
inference stack (KV-cache generation, beam search, speculative decoding,
weight-only int8)."""

from horovod_tpu.models.beam import make_beam_search_fn  # noqa: F401
from horovod_tpu.models.cnn import MnistCNN  # noqa: F401
from horovod_tpu.models.decoding import generate, make_generate_fn  # noqa: F401
from horovod_tpu.models.quant import (  # noqa: F401
    dequantize_params,
    quantize_params,
)
from horovod_tpu.models.resnet import ResNetCIFAR  # noqa: F401
from horovod_tpu.models.speculative import (  # noqa: F401
    make_speculative_fn,
    ngram_draft_fn,
)
from horovod_tpu.models.vit import ViT  # noqa: F401
from horovod_tpu.models.transformer import (  # noqa: F401
    ShardingConfig,
    TransformerLM,
    param_specs,
)
