"""Model zoo: the reference CNN, ResNet-20 (CIFAR), and the transformer
flagship for long-context / tensor-parallel configurations."""

from horovod_tpu.models.cnn import MnistCNN  # noqa: F401
from horovod_tpu.models.decoding import generate, make_generate_fn  # noqa: F401
from horovod_tpu.models.resnet import ResNetCIFAR  # noqa: F401
from horovod_tpu.models.transformer import (  # noqa: F401
    ShardingConfig,
    TransformerLM,
    param_specs,
)
