"""Speculative decoding: draft cheap token chunks, verify with ONE target
chunk-forward, accept the matching prefix — exact target-greedy output.

The reference has no inference stack at all (its serving story ends at a
SavedModel export, mnist_keras.py:126-140); `models/decoding.py` gives this
framework per-token KV-cache generation, and this module removes that
loop's fundamental limit: a decode step is a bandwidth-bound matvec, so
tokens/sec is capped by how fast weights stream — UNLESS several positions
are verified per weight pass. Speculative decoding (Leviathan et al.,
arXiv:2211.17192) does exactly that, and it is a natural fit for the
TPU/XLA model:

* **the whole loop is one jitted `lax.while_loop`** — draft, verify
  chunk-forward (the KV cache's chunk-extension path,
  transformer.Block._decode_attention), acceptance, cache-index rollback —
  with fully static shapes: one host dispatch per generation;
* **verification rides the MXU**: a γ-token chunk forward has the same
  weight traffic as ONE decode step but γ positions of compute — accepted
  tokens are bandwidth-free;
* **exactness by construction**: greedy acceptance keeps a drafted token
  only while it equals the target's own argmax, so the output is
  bit-identical to plain greedy decoding whatever the draft quality —
  drafts change the speed, never the result. Batch rows accept different
  prefix lengths and each advances by its OWN acceptance (per-row cache
  indices, transformer.Block's vector decode_index layout): a lucky row
  never waits for an unlucky one, so batched throughput keeps the batch-1
  acceptance rate instead of degrading toward the row-minimum.

The built-in draft is **prompt-lookup** (n-gram continuation: propose the
tokens that followed the most recent earlier occurrence of the current
n-gram suffix — "prompt lookup decoding", a draft-model-free scheme that
excels on self-repetitive text: code, summarization-with-quotes, copy
structure). Two generalizations, same exactness guarantee:

* a custom stateless ``draft_fn(buf [B, Tmax], cur_len [B], n_draft) ->
  [B, n_draft]`` (``cur_len`` arrives as a per-row vector; a scalar is
  also accepted for hand-driven use);
* a **draft model** (``draft_model=`` + ``draft_params=``: a smaller LM,
  the classic two-model scheme) — it keeps its own KV cache inside the
  loop. Static-shape subtlety: how far the draft cache trails the
  committed prefix varies by round (full acceptance consumes one token
  the draft never saw), so every round re-feeds the draft a fixed
  2-token window ending at the committed head — cache writes are
  idempotent for committed tokens, so the variable-length "catch-up" a
  Python implementation would branch on becomes a constant-shape
  overwrite — then scans γ-2 single-token draft steps.

**Sampling** (``temperature > 0``, with top-k/top-p): the rejection
scheme of arXiv:2211.17192 specialized to deterministic drafts — accept
draft token d with probability p(d) under the target's filtered
distribution, else resample from p restricted to the other tokens; the
committed law is exactly p per position, so sampled speculative output is
*distributionally* identical to `decoding.generate`'s sampled path
(bit-identity is impossible: the rng schedules differ). Randomness is
keyed by ``(absolute position, draft token, batch row)``, never by round:
with per-row advance each position is decided exactly once, and the
position/token keying additionally guarantees independence if a position
ever were revisited (the property the old lockstep scheme needed; kept
because it costs nothing and makes the draws schedule-invariant).

Restrictions: ``eos_id`` unsupported (use `decoding.generate` for
eos-terminated generation), and dense models only: MoE expert capacity is
enforced per call group, so a γ-token verify forward can route
differently than the single-token steps it replaces and the exactness
contract would silently break (`decoding.py`'s MoE caveat, made binding
here) — rejected loudly.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.models.decoding import (
    _NEG,
    check_sampling_params,
    filter_logits,
)


def ngram_draft_fn(*, ngram: int = 3) -> Callable:
    """Prompt-lookup draft: continue the most recent earlier occurrence of
    the current ``ngram``-token suffix.

    Returns ``draft_fn(buf [B, Tmax], cur_len [B] or scalar, gamma) ->
    [B, gamma]`` proposals. When no earlier occurrence exists a row falls
    back to repeating its last token — drafts are free to be wrong;
    verification discards mismatches.
    """

    def draft_fn(buf, cur_len, n_draft: int):
        b, tmax = buf.shape
        cur_len = jnp.asarray(cur_len, jnp.int32)
        if cur_len.ndim == 0:
            cur_len = jnp.broadcast_to(cur_len, (b,))
        # Suffix = each row's last `ngram` finalized tokens (indices clamp
        # at 0 when cur_len < ngram — the garbage suffix just drafts badly,
        # which verification absorbs).
        suf_idx = jnp.clip(
            cur_len[:, None] - ngram + jnp.arange(ngram, dtype=jnp.int32),
            0, tmax - 1,
        )
        suffix = jnp.take_along_axis(buf, suf_idx, axis=1)  # [B, ngram]
        n_windows = tmax - ngram
        win_idx = (
            jnp.arange(n_windows, dtype=jnp.int32)[:, None]
            + jnp.arange(ngram, dtype=jnp.int32)[None, :]
        )  # [S, ngram]
        windows = buf[:, win_idx]  # [B, S, ngram]
        starts = jnp.arange(n_windows, dtype=jnp.int32)
        # An *earlier* occurrence: the window must end before the suffix
        # starts (also excludes matching the suffix against itself).
        eq = jnp.all(windows == suffix[:, None, :], axis=-1) & (
            starts[None, :] < (cur_len - ngram)[:, None]
        )
        s_star = jnp.max(
            jnp.where(eq, starts[None, :], -1), axis=1
        )  # [B] latest match, -1 = none
        has = s_star >= 0
        follow = jnp.clip(
            s_star[:, None] + ngram + jnp.arange(n_draft, dtype=jnp.int32),
            0, tmax - 1,
        )
        draft = jnp.take_along_axis(buf, follow, axis=1)  # [B, n_draft]
        last = jnp.take_along_axis(buf, (cur_len - 1)[:, None], 1)
        return jnp.where(has[:, None], draft, last)

    return draft_fn


def make_speculative_fn(model, *, max_new_tokens: int, gamma: int = 4,
                        draft_fn: Callable | None = None,
                        draft_model=None, draft_params=None,
                        temperature: float = 0.0, top_k: int = 0,
                        top_p: float = 0.0,
                        include_prompt: bool = True,
                        return_stats: bool = False,
                        quantized: bool = False):
    """Build the compiled speculative generator.

    Greedy (``temperature=0``, default): ``(params, prompt) -> tokens``,
    bit-identical to `decoding.generate`'s greedy path. Sampled
    (``temperature > 0``, with top-k/top-p): ``(params, prompt, rng) ->
    tokens``, distributionally identical to the sampled `generate` (see
    module docstring — the rejection scheme commits exactly the target's
    filtered distribution per position).

    ``gamma`` = tokens verified per target pass (1 known-exact token + γ-1
    drafts): per round the target streams its weights once and each batch
    row commits between 1 and γ tokens — **per row**: acceptance is
    row-independent (per-row cache indices), so a batch keeps the batch-1
    acceptance rate instead of advancing in lockstep at the row-minimum.
    Drafts come from ``draft_fn`` (stateless), or
    ``draft_model``/``draft_params`` (a smaller LM with its own in-loop KV
    cache — see module docstring), or the default prompt-lookup n-gram.
    ``return_stats`` appends a dict with ``rounds`` (loop iterations until
    the slowest row finished) and ``tokens`` (total committed across rows;
    mean accepted-per-round = tokens / (rounds · B)).

    **Ragged prompts** — ``fn(params, prompt, rng_or_None, lengths)`` with
    ``lengths`` a ``[B]`` int array: same contract as
    `decoding.make_generate_fn`'s ragged mode (right-padded prompts, each
    row exact at its own length), built on the same per-row cache-index
    layout — so a serving batch mixes prompt lengths AND decodes
    speculatively. Not supported with ``draft_model`` (its prefill
    consumes the padded prompt).

    ``quantized=True``: ``params`` is a `models/quant.quantize_params`
    tree; every target pass dequantizes inside the loop body so the
    weight stream stays int8 (decoding.make_generate_fn's contract).
    The greedy exactness guarantee is UNCHANGED — it compares the
    target's argmax against itself, and both the speculative verify and
    the plain quantized decode consult the same quantized weights, so
    speculative output is bit-identical to
    ``make_generate_fn(quantized=True)``'s greedy path. (A quantized
    draft_model is not supported — drafts take plain params.)
    """
    if gamma < 2:
        raise ValueError("gamma must be >= 2 (1 exact token + >=1 draft)")
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    check_sampling_params(temperature, top_p)
    sampled = temperature != 0.0
    if draft_fn is not None and draft_model is not None:
        raise ValueError("pass draft_fn OR draft_model, not both")
    if draft_model is not None and draft_params is None:
        raise ValueError("draft_model needs draft_params")
    for m, role in ((model, "target"), (draft_model, "draft")):
        if m is not None and getattr(m, "moe_every", 0):
            raise ValueError(
                f"speculative decoding requires a dense model ({role}): MoE "
                "expert capacity binds per call group, so a chunked verify "
                "forward can legitimately route (and decode) differently "
                "than the per-token steps it replaces — the exact-output "
                "contract cannot hold; use decoding.generate for MoE models"
            )
    draft = draft_fn or (None if draft_model is not None else ngram_draft_fn())

    def run(params, prompt, rng=None, lengths=None):
        prompt = prompt.astype(jnp.int32)
        b, t0 = prompt.shape
        tmax = t0 + max_new_tokens + gamma  # chunk-overhang headroom
        if sampled and rng is None:
            raise ValueError(
                "sampled speculative decoding (temperature > 0) needs an "
                "rng: call fn(params, prompt, rng)"
            )
        if lengths is not None and draft_model is not None:
            raise ValueError(
                "ragged prompts (lengths=...) are not supported with a "
                "draft_model — its prefill consumes the padded prompt; "
                "use the n-gram/custom draft, or decoding.make_generate_fn"
            )
        from horovod_tpu.models.quant import make_unpack

        unpack = make_unpack(quantized)
        qparams = params
        dmodel = model.clone(
            decode=True, max_decode_len=tmax, dropout=0.0, remat=False,
        )
        logits, vars_ = dmodel.apply(
            {"params": unpack(qparams)}, prompt, mutable=["cache"]
        )
        if lengths is not None:
            # Ragged batch (the serving contract, decoding.py's per-row
            # layout): row i's prompt is its first lengths[i] tokens; its
            # first verified token reads the logits at lengths[i]-1, its
            # committed stream starts at position lengths[i], and every
            # per-row structure below (cur_len, cache index, buf writes)
            # starts from the vector. Pad garbage beyond a row's length is
            # progressively overwritten by committed tokens before any
            # query can attend to it — same argument as make_generate_fn's
            # ragged mode; the n-gram draft may read pads and propose
            # nonsense, which verification absorbs.
            lengths = jnp.asarray(lengths, jnp.int32)
            logits = jnp.take_along_axis(
                logits,
                jnp.minimum(lengths - 1, t0 - 1)[:, None, None],
                axis=1,
            )

        def _pkey(pos, tag, row):
            """Draw key for (absolute position, tag, batch row) — round-
            independent so lockstep re-derivation reuses the SAME draw for
            the same decision and a FRESH one when the draft token at a
            position changes between rounds (tag encodes it)."""
            k = jax.random.fold_in(rng, pos)
            k = jax.random.fold_in(k, tag)
            return jax.random.fold_in(k, row)

        rows = jnp.arange(b, dtype=jnp.int32)

        start = (
            jnp.full((b,), t0, jnp.int32) if lengths is None else lengths
        )
        if sampled:
            # "No draft at this position" draws (prefill token, bonus) use
            # tag 2*vocab — disjoint from the accept (tok) and resample
            # (vocab+tok) tag ranges. Position-keyed per row (= t0 for
            # full prompts, lengths[i] ragged).
            flt0 = filter_logits(logits[:, -1], temperature, top_k, top_p)
            next_tok = jax.vmap(
                lambda f, r, p_: jax.random.categorical(
                    _pkey(p_, 2 * flt0.shape[-1], r), f
                ).astype(jnp.int32)
            )(flt0, rows, start)
        else:
            next_tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        buf = jnp.zeros((b, tmax), jnp.int32)
        buf = lax.dynamic_update_slice(buf, prompt, (0, 0))

        ddraft = None
        dcache0 = None
        if draft_model is not None:
            if t0 < 2:
                raise ValueError(
                    "draft_model mode needs a prompt of >= 2 tokens (the "
                    "catch-up window spans the last two committed tokens)"
                )
            ddraft = draft_model.clone(
                decode=True, max_decode_len=tmax, dropout=0.0, remat=False,
            )
            # Prefill the draft on everything EXCEPT the prompt's last
            # token: each round's 2-token catch-up window re-feeds
            # [buf[cur_len-1], buf[cur_len]], so position t0-1 is covered
            # by round 1's window (and double-writes are idempotent).
            _, dvars = ddraft.apply(
                {"params": draft_params}, prompt[:, :-1], mutable=["cache"]
            )
            dcache0 = dict(dvars["cache"])
            # Per-row index layout from the start (the while_loop carry
            # must keep one pytree structure; _model_draft overwrites it
            # with cur_len - 1 anyway).
            dcache0["index"] = jnp.full((b,), t0 - 1, jnp.int32)

        def _model_draft(dcache, buf, cur_len):
            """γ-1 greedy proposals from the draft LM, cache maintained.

            ``buf[i, cur_len[i]]`` is row i's committed head (next_tok).
            The catch-up window [cur_len-1, cur_len] re-feeds whatever the
            draft cache might be missing — its (per-row) index is forced
            to cur_len-1 first, so committed tokens are (re)written at
            their true positions.
            """
            dcache = dict(dcache)
            dcache["index"] = cur_len - 1
            window = jnp.take_along_axis(
                buf,
                (cur_len - 1)[:, None] + jnp.arange(2, dtype=jnp.int32)[None, :],
                axis=1,
            )
            dlogits, dvars = ddraft.apply(
                {"params": draft_params, "cache": dcache}, window,
                mutable=["cache"],
            )
            tok = jnp.argmax(dlogits[:, -1], axis=-1).astype(jnp.int32)

            def step(carry, _):
                dcache, tok = carry
                slog, svars = ddraft.apply(
                    {"params": draft_params, "cache": dcache}, tok[:, None],
                    mutable=["cache"],
                )
                nxt = jnp.argmax(slog[:, -1], axis=-1).astype(jnp.int32)
                return (dict(svars["cache"]), nxt), tok

            (dcache, last), toks = lax.scan(
                step, (dict(dvars["cache"]), tok), None, length=gamma - 2
            )
            # ys = the tokens each step CONSUMED (tok_1..tok_{γ-2}); the
            # final carry is tok_{γ-1}, proposed but never consumed — its
            # missing draft-cache entry is exactly what the next round's
            # catch-up window re-feeds if it gets accepted.
            proposals = jnp.concatenate(
                [jnp.moveaxis(toks, 0, 1), last[:, None]], axis=1
            ) if gamma > 2 else tok[:, None]
            return proposals, dcache

        def cond(carry):
            # Until the SLOWEST row has its max_new_tokens; fast rows
            # freeze (m_row = 0) once done.
            return jnp.min(carry[2]) < max_new_tokens

        def body(carry):
            buf, cur_len, n_gen, cache, dcache, next_tok, rounds = carry
            active = n_gen < max_new_tokens  # [B]
            # next_tok is already the target's exact output — commit it,
            # then draft continuations for verification. (Frozen rows
            # rewrite their frozen token at their frozen position — a
            # deterministic no-op outside the output window.)
            buf = buf.at[rows, cur_len].set(next_tok)
            if ddraft is not None:
                proposals, dcache = _model_draft(dcache, buf, cur_len)
            else:
                proposals = draft(buf, cur_len + 1, gamma - 1)
            chunk = jnp.concatenate([next_tok[:, None], proposals], axis=1)
            # Quantized mode: dequantize per round, inside the loop body —
            # the weight stream of each verify pass stays int8 in HBM.
            # The cache index is the per-row committed prefix, so each
            # row's verify forward lands at its own positions.
            logits_c, new_vars = dmodel.apply(
                {"params": unpack(qparams), "cache": cache}, chunk,
                mutable=["cache"],
            )
            if sampled:
                flt = filter_logits(logits_c, temperature, top_k, top_p)
                probs = jax.nn.softmax(flt, axis=-1)  # [B, γ, V]
                vocab = flt.shape[-1]
                d = chunk[:, 1:]  # drafts at positions cur_len+1..+γ-1
                pos_mat = (
                    cur_len[:, None] + 1
                    + jnp.arange(gamma - 1, dtype=jnp.int32)[None, :]
                )  # [B, γ-1] absolute positions, per row
                us = jax.vmap(  # [B, γ-1] position/token/row-keyed uniforms
                    lambda drow, r, prow: jax.vmap(
                        lambda p_, t_: jax.random.uniform(_pkey(p_, t_, r))
                    )(prow, drow)
                )(d, rows, pos_mat)
                # Deterministic-draft rejection: accept d w.p. p(d) under
                # the target's filtered distribution.
                p_d = jnp.take_along_axis(probs[:, :-1], d[..., None], -1)
                acc = (us < p_d[..., 0]).astype(jnp.int32)
            else:
                a = jnp.argmax(logits_c, axis=-1).astype(jnp.int32)
                # chunk[:, j] (j >= 1) is correct iff it equals the
                # target's argmax after chunk[:, :j].
                acc = (chunk[:, 1:] == a[:, :-1]).astype(jnp.int32)
            m_row = 1 + jnp.sum(jnp.cumprod(acc, axis=1), axis=1)  # [B]
            # Per-row advance, clamped to the row's remaining budget (so
            # n_gen lands exactly on max_new_tokens and buf never outgrows
            # its γ-token headroom); frozen rows advance 0.
            m_row = jnp.where(
                active, jnp.minimum(m_row, max_new_tokens - n_gen), 0
            )
            # Commit accepted drafts (row i: positions cur_len[i]+1 ..
            # cur_len[i]+m_row[i]-1): write the whole tail, then let
            # positions >= cur_len+m_row be overwritten by later rounds —
            # simpler than a dynamic-length write, and that region is dead
            # until then.
            tail_pos = (
                cur_len[:, None] + 1
                + jnp.arange(gamma - 1, dtype=jnp.int32)[None, :]
            )
            buf = buf.at[rows[:, None], tail_pos].set(chunk[:, 1:])
            # The token at each row's position cur_len + m_row (its next
            # committed head). A row that rejected its draft there (or has
            # none at m_row == γ) resamples from the residual (target dist
            # minus the rejected token — exactly p overall); a row whose
            # clamped m_row kept an accepted draft carries it forward.
            if sampled:
                gather_m = jnp.clip(m_row - 1, 0, gamma - 1)[:, None]
                flt_m = jnp.take_along_axis(
                    flt, gather_m[..., None], axis=1
                )[:, 0]  # [B, V]
                has_draft = m_row < gamma  # [B]
                idx_d = jnp.clip(m_row, 1, gamma - 1)[:, None]
                d_m = jnp.take_along_axis(chunk, idx_d, 1)[:, 0]
                idx_a = jnp.clip(m_row - 1, 0, gamma - 2)[:, None]
                acc_m = jnp.take_along_axis(acc, idx_a, 1)[:, 0].astype(bool)
                masked = jnp.where(
                    has_draft[:, None] & jax.nn.one_hot(d_m, vocab, dtype=bool),
                    _NEG, flt_m,
                )
                pos_m = cur_len + m_row  # [B]

                def res_one(f_row, tok, r, p_, hd):
                    tag = jnp.where(hd, vocab + tok, 2 * vocab)
                    return jax.random.categorical(
                        _pkey(p_, tag, r), f_row
                    ).astype(jnp.int32)

                resampled = jax.vmap(res_one)(
                    masked, d_m, rows, pos_m, has_draft
                )
                new_next = jnp.where(has_draft & acc_m, d_m, resampled)
            else:
                new_next = jnp.take_along_axis(
                    a, jnp.clip(m_row - 1, 0, gamma - 1)[:, None], 1
                )[:, 0]
            next_tok = jnp.where(active, new_next, next_tok)
            # Roll the cache back to each row's committed prefix: stale K/V
            # above it are masked out by the attention's per-row index test
            # and overwritten by the next chunk write at exactly this index.
            cache = dict(new_vars["cache"])
            cache["index"] = cur_len + m_row
            return (
                buf, cur_len + m_row, n_gen + m_row, cache, dcache, next_tok,
                rounds + 1,
            )

        cache0 = dict(vars_["cache"])
        # Per-row cache indices from the start (prefill leaves a scalar);
        # ragged rows start at their own lengths.
        cache0["index"] = start
        carry = (
            buf, start, jnp.zeros((b,), jnp.int32),
            cache0,
            dcache0 if dcache0 is not None else jnp.int32(0),
            next_tok, jnp.int32(0),
        )
        buf, cur_len, n_gen, _, _, _, rounds = lax.while_loop(
            cond, body, carry
        )
        if lengths is not None:
            # Ragged extraction: row i's generated tokens live at
            # [lengths[i], lengths[i] + max_new_tokens).
            gen = jnp.take_along_axis(
                buf,
                lengths[:, None]
                + jnp.arange(max_new_tokens, dtype=jnp.int32)[None, :],
                axis=1,
            )
            out = (
                jnp.concatenate([prompt, gen], axis=1) if include_prompt
                else gen
            )
        else:
            out = lax.dynamic_slice(
                buf, (0, 0 if include_prompt else t0),
                (b, (t0 if include_prompt else 0) + max_new_tokens),
            )
        if return_stats:
            return out, {"rounds": rounds, "tokens": jnp.sum(n_gen)}
        return out

    return jax.jit(run)
