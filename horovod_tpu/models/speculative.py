"""Speculative decoding: draft cheap token chunks, verify with ONE target
chunk-forward, accept the matching prefix — exact target-greedy output.

The reference has no inference stack at all (its serving story ends at a
SavedModel export, mnist_keras.py:126-140); `models/decoding.py` gives this
framework per-token KV-cache generation, and this module removes that
loop's fundamental limit: a decode step is a bandwidth-bound matvec, so
tokens/sec is capped by how fast weights stream — UNLESS several positions
are verified per weight pass. Speculative decoding (Leviathan et al.,
arXiv:2211.17192) does exactly that, and it is a natural fit for the
TPU/XLA model:

* **the whole loop is one jitted `lax.while_loop`** — draft, verify
  chunk-forward (the KV cache's chunk-extension path,
  transformer.Block._decode_attention), acceptance, cache-index rollback —
  with fully static shapes: one host dispatch per generation;
* **verification rides the MXU**: a γ-token chunk forward has the same
  weight traffic as ONE decode step but γ positions of compute — accepted
  tokens are bandwidth-free;
* **exactness by construction**: greedy acceptance keeps a drafted token
  only while it equals the target's own argmax, so the output is
  bit-identical to plain greedy decoding whatever the draft quality —
  drafts change the speed, never the result. (Batch rows accept different
  prefix lengths; the shared cache index advances by the row-minimum, so
  extra row matches are simply re-derived next round — still exact.)

The built-in draft is **prompt-lookup** (n-gram continuation: propose the
tokens that followed the most recent earlier occurrence of the current
n-gram suffix — "prompt lookup decoding", a draft-model-free scheme that
excels on self-repetitive text: code, summarization-with-quotes, copy
structure). A custom ``draft_fn(buf [B, Tmax], cur_len, n_draft) ->
[B, n_draft]`` can be supplied — e.g. a small trained LM — with the same
exactness guarantee.

Restrictions: greedy only (``eos_id`` unsupported — use
`decoding.generate` for sampled or eos-terminated generation), and dense
models only: MoE expert capacity is enforced per call group, so a
γ-token verify forward can route differently than the single-token steps
it replaces and the exactness contract would silently break
(`decoding.py`'s MoE caveat, made binding here) — rejected loudly.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def ngram_draft_fn(*, ngram: int = 3) -> Callable:
    """Prompt-lookup draft: continue the most recent earlier occurrence of
    the current ``ngram``-token suffix.

    Returns ``draft_fn(buf [B, Tmax], cur_len, gamma) -> [B, gamma]``
    proposals. When no earlier occurrence exists a row falls back to
    repeating its last token — drafts are free to be wrong; verification
    discards mismatches.
    """

    def draft_fn(buf, cur_len, n_draft: int):
        b, tmax = buf.shape
        # Suffix = the last `ngram` finalized tokens (dynamic_slice clamps
        # the start when cur_len < ngram — the garbage suffix just drafts
        # badly, which verification absorbs).
        suffix = lax.dynamic_slice(
            buf, (jnp.int32(0), cur_len - ngram), (b, ngram)
        )  # [B, ngram]
        n_windows = tmax - ngram
        win_idx = (
            jnp.arange(n_windows, dtype=jnp.int32)[:, None]
            + jnp.arange(ngram, dtype=jnp.int32)[None, :]
        )  # [S, ngram]
        windows = buf[:, win_idx]  # [B, S, ngram]
        starts = jnp.arange(n_windows, dtype=jnp.int32)
        # An *earlier* occurrence: the window must end before the suffix
        # starts (also excludes matching the suffix against itself).
        eq = jnp.all(windows == suffix[:, None, :], axis=-1) & (
            starts[None, :] < cur_len - ngram
        )
        s_star = jnp.max(
            jnp.where(eq, starts[None, :], -1), axis=1
        )  # [B] latest match, -1 = none
        has = s_star >= 0
        follow = jnp.clip(
            s_star[:, None] + ngram + jnp.arange(n_draft, dtype=jnp.int32),
            0, tmax - 1,
        )
        draft = jnp.take_along_axis(buf, follow, axis=1)  # [B, n_draft]
        last = jnp.take_along_axis(buf, (cur_len - 1)[None, None].repeat(b, 0), 1)
        return jnp.where(has[:, None], draft, last)

    return draft_fn


def make_speculative_fn(model, *, max_new_tokens: int, gamma: int = 4,
                        draft_fn: Callable | None = None,
                        include_prompt: bool = True,
                        return_stats: bool = False):
    """Build the compiled speculative generator: ``(params, prompt) ->
    tokens`` (greedy; bit-identical to `decoding.generate`'s greedy path).

    ``gamma`` = tokens verified per target pass (1 known-exact token + γ-1
    drafts): per round the target streams its weights once and commits
    between 1 and γ tokens. ``return_stats`` appends a dict with
    ``rounds`` and ``tokens`` (accepted-per-round = tokens/rounds; plain
    decoding would use ``tokens`` rounds).
    """
    if gamma < 2:
        raise ValueError("gamma must be >= 2 (1 exact token + >=1 draft)")
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    if getattr(model, "moe_every", 0):
        raise ValueError(
            "speculative decoding requires a dense model: MoE expert "
            "capacity binds per call group, so a chunked verify forward "
            "can legitimately route (and decode) differently than the "
            "per-token steps it replaces — the exact-output contract "
            "cannot hold; use decoding.generate for MoE models"
        )
    draft = draft_fn or ngram_draft_fn()

    def run(params, prompt):
        prompt = prompt.astype(jnp.int32)
        b, t0 = prompt.shape
        tmax = t0 + max_new_tokens + gamma  # chunk-overhang headroom
        dmodel = model.clone(
            decode=True, max_decode_len=tmax, dropout=0.0, remat=False,
        )
        logits, vars_ = dmodel.apply(
            {"params": params}, prompt, mutable=["cache"]
        )
        next_tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        buf = jnp.zeros((b, tmax), jnp.int32)
        buf = lax.dynamic_update_slice(buf, prompt, (0, 0))

        def cond(carry):
            _, _, n_gen, _, _, _ = carry
            return n_gen < max_new_tokens

        def body(carry):
            buf, cur_len, n_gen, cache, next_tok, rounds = carry
            # next_tok is already the target's exact output — commit it,
            # then draft continuations for verification.
            buf = lax.dynamic_update_slice(
                buf, next_tok[:, None], (0, cur_len)
            )
            proposals = draft(buf, cur_len + 1, gamma - 1)
            chunk = jnp.concatenate([next_tok[:, None], proposals], axis=1)
            logits_c, new_vars = dmodel.apply(
                {"params": params, "cache": cache}, chunk, mutable=["cache"]
            )
            a = jnp.argmax(logits_c, axis=-1).astype(jnp.int32)  # [B, gamma]
            # chunk[:, j] (j >= 1) is correct iff it equals the target's
            # argmax after chunk[:, :j]; accept the matching prefix.
            match = (chunk[:, 1:] == a[:, :-1]).astype(jnp.int32)
            m_row = 1 + jnp.sum(jnp.cumprod(match, axis=1), axis=1)
            m = jnp.min(m_row)  # shared cache index ⇒ lockstep advance
            # Commit accepted drafts (positions cur_len+1 .. cur_len+m-1):
            # write the whole tail, then let positions >= cur_len+m be
            # overwritten by later rounds — simpler than a dynamic-length
            # write, and the [cur_len+m, ...) region is dead until then.
            buf = lax.dynamic_update_slice(
                buf, chunk[:, 1:], (0, cur_len + 1)
            )
            next_tok = jnp.take_along_axis(a, (m - 1)[None, None].repeat(b, 0), 1)[:, 0]
            # Roll the cache back to the committed prefix: stale K/V above
            # it are masked out by the attention's index test and will be
            # overwritten by the next chunk write at exactly this index.
            cache = dict(new_vars["cache"])
            cache["index"] = cur_len + m
            return (buf, cur_len + m, n_gen + m, cache, next_tok, rounds + 1)

        carry = (
            buf, jnp.int32(t0), jnp.int32(0), dict(vars_["cache"]),
            next_tok, jnp.int32(0),
        )
        buf, cur_len, n_gen, _, _, rounds = lax.while_loop(cond, body, carry)
        out = lax.dynamic_slice(
            buf, (0, 0 if include_prompt else t0),
            (b, (t0 if include_prompt else 0) + max_new_tokens),
        )
        if return_stats:
            return out, {"rounds": rounds, "tokens": n_gen}
        return out

    return jax.jit(run)
