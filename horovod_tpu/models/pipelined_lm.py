"""Decoder-only LM partitioned into pipeline stages over the ``pipe`` axis.

Companion to `parallel/pipeline.py` (see its module docstring for the
design): this model keeps every transformer-block parameter as a
``[n_layers, ...]`` stack. Sharding dim 0 over ``pipe`` gives each pipe
device a contiguous block of layers — its stage — and the GPipe schedule
runs as one `shard_map`'d scan with `ppermute` handoffs. Embedding, final
LayerNorm and the LM head stay replicated over ``pipe`` (they run on the
broadcast pipeline output).

The block math matches `transformer.Block` (pre-LN, RoPE, GELU MLP at 4x)
but is written functionally over explicit parameter stacks: flax modules
trace parameter creation structurally, which fights the stage-sliced manual
region; plain `self.param` stacks are transparent to shard_map, to the
optimizer, and to checkpointing.

Composes with data parallelism (batch axes sharded by GSPMD outside the
manual pipe region). TP/SP inside a stage is out of scope for this model —
use `TransformerLM` when you want model/seq axes instead of pipe.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.models.transformer import _rope
from horovod_tpu.parallel.mesh import DATA_AXIS, FSDP_AXIS, PIPE_AXIS
from horovod_tpu.parallel.pipeline import (
    spmd_pipeline,
    spmd_pipeline_1f1b,
    stage_slice_size,
)

BATCH_AXES = (DATA_AXIS, FSDP_AXIS)


def _layernorm(x, scale, eps=1e-6):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    return ((x32 - mu) * lax.rsqrt(var + eps) * scale).astype(x.dtype)


class PipelinedLM(nn.Module):
    """Causal LM ``[B, T] -> [B, T, vocab]`` with pipeline-parallel blocks.

    ``n_micro`` microbatches per step (bubble fraction shrinks as it grows);
    the global batch must be divisible by ``n_micro × dp``.
    """

    vocab_size: int = 256
    d_model: int = 256
    n_heads: int = 8
    n_layers: int = 4
    n_micro: int = 4
    compute_dtype: jnp.dtype = jnp.float32
    mesh: Mesh | None = None
    # 'gpipe' = AD-derived backward (parallel/pipeline.spmd_pipeline);
    # '1f1b' = hand-scheduled staggered backward with per-microbatch
    # rematerialization — the 1F1B activation-memory discipline
    # (spmd_pipeline_1f1b). Identical math; parity-tested gradients.
    schedule: str = "gpipe"

    @nn.compact
    def __call__(self, tokens, *, train: bool = False):
        d, h = self.d_model, self.n_heads
        hd = d // h
        L = self.n_layers
        lecun = nn.initializers.lecun_normal()
        ones = nn.initializers.ones

        blocks = {
            "ln1": self.param("ln1", ones, (L, d)),
            "qkv": self.param("qkv", lecun, (L, d, 3 * d)),
            "attn_out": self.param("attn_out", lecun, (L, d, d)),
            "ln2": self.param("ln2", ones, (L, d)),
            "mlp_up": self.param("mlp_up", lecun, (L, d, 4 * d)),
            "mlp_down": self.param("mlp_down", lecun, (L, 4 * d, d)),
        }
        embed = self.param(
            "embed", nn.initializers.normal(1.0), (self.vocab_size, d)
        )
        ln_f = self.param("ln_f", ones, (d,))
        lm_head = self.param("lm_head", lecun, (d, self.vocab_size))

        b, t = tokens.shape
        cd = self.compute_dtype
        x = embed[tokens].astype(cd)  # [B, T, d]

        # Validate unconditionally: a typo'd schedule on a pipe-less mesh
        # would otherwise train silently via the sequential path and only
        # error when the config moves to a real pipeline mesh.
        if self.schedule not in ("gpipe", "1f1b"):
            raise ValueError(
                f"schedule must be 'gpipe' or '1f1b', got {self.schedule!r}"
            )

        if self.mesh is None or self.mesh.shape.get(PIPE_AXIS, 1) == 1:
            # No pipe axis: run the stack sequentially (the n_stages=1
            # degenerate schedule) — same math, no manual region needed.
            def body(xc, p):
                return self._block(xc, p), None

            x, _ = lax.scan(body, x, blocks)
        else:
            for ax in ("seq", "model", "expert"):
                if self.mesh.shape.get(ax, 1) != 1:
                    raise ValueError(
                        f"PipelinedLM composes with data/pipe axes only; "
                        f"mesh has {ax}={self.mesh.shape[ax]}"
                    )
            n_stages = self.mesh.shape[PIPE_AXIS]
            stage_slice_size(L, n_stages)  # validates divisibility
            # Tiny batches (e.g. the Trainer's dp-sized init probe) can't
            # fill the microbatch queue; degrade the schedule, not the user.
            # Each microbatch must still cover the data axes (its batch dim
            # is sharded over them inside the manual region).
            dp = self.mesh.shape[DATA_AXIS] * self.mesh.shape[FSDP_AXIS]
            n_micro = max(1, min(self.n_micro, b // dp))
            if b % (n_micro * dp) != 0:
                raise ValueError(
                    f"batch ({b}) must divide into n_micro ({n_micro}) x "
                    f"data axes ({dp})"
                )
            mb = b // n_micro
            x_micro = x.reshape(n_micro, mb, t, d)

            act_spec = P(None, BATCH_AXES, None, None)
            param_specs = jax.tree.map(
                lambda l: P(PIPE_AXIS, *([None] * (l.ndim - 1))), blocks
            )

            def run(stage_params, xm):
                def stage(params, act):
                    def body(a, p):
                        return self._block(a, p), None

                    a, _ = lax.scan(body, act, params)
                    return a

                if self.schedule == "1f1b":
                    return spmd_pipeline_1f1b(stage, stage_params, xm)
                return spmd_pipeline(
                    lambda act: stage(stage_params, act), xm
                )

            x_micro = jax.shard_map(
                run,
                mesh=self.mesh,
                in_specs=(param_specs, act_spec),
                out_specs=act_spec,
                check_vma=False,
            )(blocks, x_micro)
            x = x_micro.reshape(b, t, d)

        x = _layernorm(x, ln_f)
        logits = x.astype(jnp.float32) @ lm_head.astype(jnp.float32)
        return logits

    def _block(self, x, p):
        """One pre-LN transformer block over a single layer's params."""
        mb, t, d = x.shape
        h_heads, hd = self.n_heads, d // self.n_heads
        cd = self.compute_dtype

        hidden = _layernorm(x, p["ln1"])
        qkv = hidden @ p["qkv"].astype(cd)  # [mb, T, 3d]
        qkv = qkv.reshape(mb, t, h_heads, 3 * hd)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (mb, t))
        q, k = _rope(q, positions), _rope(k, positions)
        # Flash kernel (O(T) memory): without it a pipeline stage would
        # materialize [T, T] scores per microbatch and PP could not compose
        # with the long contexts it exists to serve; dense fallback applies
        # automatically when the kernel's tiling doesn't hold (tiny tests).
        from horovod_tpu.ops.flash_attention import flash_attention

        att = flash_attention(q, k, v, causal=True)  # [mb, T, H, hd]
        out = att.reshape(mb, t, d) @ p["attn_out"].astype(cd)
        x = x + out

        hidden = _layernorm(x, p["ln2"])
        hidden = nn.gelu(hidden @ p["mlp_up"].astype(cd))
        return x + hidden @ p["mlp_down"].astype(cd)


def param_specs(params, mesh: Mesh) -> dict:
    """PartitionSpec tree for the pipelined layout: per-layer stacks sharded
    over ``pipe`` on dim 0, everything else replicated."""
    stacked = {"ln1", "qkv", "attn_out", "ln2", "mlp_up", "mlp_down"}

    def rule(path, leaf):
        names = [p.key for p in path if isinstance(p, jax.tree_util.DictKey)]
        if any(n in stacked for n in names):
            return P(PIPE_AXIS, *([None] * (leaf.ndim - 1)))
        return P()

    return jax.tree_util.tree_map_with_path(rule, params)
