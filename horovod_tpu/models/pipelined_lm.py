"""Decoder-only LM partitioned into pipeline stages over the ``pipe`` axis.

Companion to `parallel/pipeline.py` (see its module docstring for the
design): this model keeps every transformer-block parameter as a
``[n_layers, ...]`` stack. Sharding dim 0 over ``pipe`` gives each pipe
device a contiguous block of layers — its stage — and the GPipe schedule
runs as one `shard_map`'d scan with `ppermute` handoffs. Embedding, final
LayerNorm and the LM head stay replicated over ``pipe`` (they run on the
broadcast pipeline output).

The block math matches `transformer.Block` (pre-LN, RoPE, GELU MLP at 4x)
but is written functionally over explicit parameter stacks: flax modules
trace parameter creation structurally, which fights the stage-sliced manual
region; plain `self.param` stacks are transparent to shard_map, to the
optimizer, and to checkpointing.

Composes with data parallelism (batch axes sharded by GSPMD outside the
manual pipe region) and, since round 3, with Megatron tensor parallelism
INSIDE each stage (qkv/mlp_up column-parallel, attn_out/mlp_down
row-parallel over ``model``, one psum per residual join) AND with
sequence/context parallelism: activations shard their token dim over
``seq`` and every stage's attention runs as ring-flash collectives around
the seq ring — dp x pp x tp x sp on ONE mesh, so a pipelined model serves
the same long contexts the flat `TransformerLM` does.

``mlp='moe'`` swaps every block's dense MLP for a GShard dense-dispatch
MoE (the `models/moe.py` formulation, Mixtral-style every-layer routing)
written functionally over ``[n_layers, E, ...]`` expert stacks: E shards
over the ``expert`` mesh axis INSIDE the manual pipeline region (each
expert-rank routes identically in f32, slices its experts' columns of the
dispatch/combine one-hots, runs its expert FFNs — hidden dim additionally
Megatron-sharded over ``model`` when TP is live — and ONE
psum(expert×model) per block restores the residual), so dp x pp x ep (x
tp x sp) compose on ONE mesh. The router's load-balance aux loss and
drop-rate counters ride the schedules' differentiable ``with_aux``
channel out of the manual region (`parallel/pipeline.py`) and surface
through the standard sown 'losses'/'metrics' collections.
"""

from __future__ import annotations

import flax.linen as nn
import jax

from horovod_tpu import compat
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.models.transformer import _rope, packed_positions
from horovod_tpu.parallel.mesh import (
    DATA_AXIS,
    EXPERT_AXIS,
    FSDP_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
    SEQ_AXIS,
)
from horovod_tpu.parallel.pipeline import (
    interleaved_layer_order,
    spmd_pipeline,
    spmd_pipeline_1f1b,
    spmd_pipeline_interleaved,
    stage_slice_size,
)

BATCH_AXES = (DATA_AXIS, FSDP_AXIS)


def _layernorm(x, scale, eps=1e-6):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    return ((x32 - mu) * lax.rsqrt(var + eps) * scale).astype(x.dtype)


class PipelinedLM(nn.Module):
    """Causal LM ``[B, T] -> [B, T, vocab]`` with pipeline-parallel blocks.

    ``n_micro`` microbatches per step (bubble fraction shrinks as it grows);
    the global batch must be divisible by ``n_micro × dp``.
    """

    vocab_size: int = 256
    d_model: int = 256
    n_heads: int = 8
    n_layers: int = 4
    n_micro: int = 4
    # Sliding-window (local) attention inside every stage — same band
    # semantics as TransformerLM.window (global positions; exact through
    # the stage-internal ring when sp > 1). None = full causal.
    window: int | None = None
    compute_dtype: jnp.dtype = jnp.float32
    mesh: Mesh | None = None
    # 'gpipe' = AD-derived backward (parallel/pipeline.spmd_pipeline);
    # '1f1b' = hand-scheduled staggered backward with per-microbatch
    # rematerialization — the 1F1B activation-memory discipline
    # (spmd_pipeline_1f1b). Identical math; parity-tested gradients.
    # 'interleaved' = virtual-stage schedule (spmd_pipeline_interleaved):
    # each pipe device hosts `n_virtual` non-adjacent chunks, cutting the
    # fill bubble to (S-1)/(v*T + S-1). NOTE: on a live pipe mesh the layer
    # stacks are stored in PLACEMENT order (device-major) — convert with
    # to_logical_order/to_interleaved_order when moving checkpoints between
    # schedules.
    schedule: str = "gpipe"
    n_virtual: int = 2
    # 'dense' = reference-style GELU MLP at 4x; 'moe' = every block's MLP
    # routed through n_experts expert FFNs (GShard top-k dense dispatch,
    # experts sharded over the `expert` mesh axis — see module docstring).
    # All-blocks routing (not moe_every) because the schedule scans ONE
    # homogeneous parameter stack per stage; alternate dense/MoE layers
    # would make the stack heterogeneous. Use TransformerLM for moe_every.
    mlp: str = "dense"
    n_experts: int = 8
    moe_k: int = 2
    capacity_factor: float = 1.25
    moe_aux_coef: float = 1e-2
    # Dispatch group size: routing one-hots are [groups, S, E, C] with
    # C ∝ S, so grouping keeps dispatch cost linear in token count (same
    # contract as models/moe.py). Groups are contiguous chunks of this
    # shard's token stream — for bit-parity between pipelined and
    # sequential runs pick a size dividing every shard's tokens-per-
    # microbatch the same way.
    moe_group_size: int = 1024

    @nn.compact
    def __call__(self, tokens, *, train: bool = False, segment_ids=None):
        d, h = self.d_model, self.n_heads
        hd = d // h
        L = self.n_layers
        lecun = nn.initializers.lecun_normal()
        ones = nn.initializers.ones

        if self.mlp not in ("dense", "moe"):
            raise ValueError(f"mlp must be 'dense' or 'moe', got {self.mlp!r}")
        moe = self.mlp == "moe"
        blocks = {
            "ln1": self.param("ln1", ones, (L, d)),
            "qkv": self.param("qkv", lecun, (L, d, 3 * d)),
            "attn_out": self.param("attn_out", lecun, (L, d, d)),
            "ln2": self.param("ln2", ones, (L, d)),
        }
        if moe:
            e = self.n_experts
            blocks["router"] = self.param(
                "router",
                nn.initializers.lecun_normal(batch_axis=(0,)),
                (L, d, e),
            )
            blocks["moe_up"] = self.param(
                "moe_up",
                nn.initializers.lecun_normal(batch_axis=(0, 1)),
                (L, e, d, 4 * d),
            )
            blocks["moe_down"] = self.param(
                "moe_down",
                nn.initializers.lecun_normal(batch_axis=(0, 1)),
                (L, e, 4 * d, d),
            )
        else:
            blocks["mlp_up"] = self.param("mlp_up", lecun, (L, d, 4 * d))
            blocks["mlp_down"] = self.param(
                "mlp_down", lecun, (L, 4 * d, d)
            )
        embed = self.param(
            "embed", nn.initializers.normal(1.0), (self.vocab_size, d)
        )
        ln_f = self.param("ln_f", ones, (d,))
        lm_head = self.param("lm_head", lecun, (d, self.vocab_size))

        b, t = tokens.shape
        cd = self.compute_dtype
        x = embed[tokens].astype(cd)  # [B, T, d]
        # Packed sequences: per-document RoPE restart + segment-masked
        # attention inside every stage (the ids are per-microbatch CONSTANTS
        # — they never ride the stage ring; see spmd_pipeline extras).
        positions = (
            packed_positions(segment_ids) if segment_ids is not None else None
        )

        # Validate unconditionally: a typo'd schedule on a pipe-less mesh
        # would otherwise train silently via the sequential path and only
        # error when the config moves to a real pipeline mesh.
        if self.schedule not in ("gpipe", "1f1b", "interleaved"):
            raise ValueError(
                f"schedule must be 'gpipe', '1f1b' or 'interleaved', "
                f"got {self.schedule!r}"
            )

        # Validate expert-axis compatibility unconditionally (like the
        # schedule check above): a config must fail the same way whether it
        # lands on a pipe mesh or the sequential path.
        if self.mesh is not None:
            mesh_ep = self.mesh.shape.get(EXPERT_AXIS, 1)
            if mesh_ep > 1 and not moe:
                raise ValueError(
                    f"mesh has expert={mesh_ep} but mlp={self.mlp!r}; the "
                    f"expert axis needs mlp='moe'"
                )
            if moe and self.n_experts % mesh_ep != 0:
                raise ValueError(
                    f"n_experts ({self.n_experts}) must divide over the "
                    f"expert axis ({mesh_ep})"
                )

        aux_loss = fill = None
        if self.mesh is None or self.mesh.shape.get(PIPE_AXIS, 1) == 1:
            # No pipe axis: run the stack sequentially (the n_stages=1
            # degenerate schedule) — same math, no manual region needed.
            # With MoE, expert stacks may still be GSPMD-sharded over
            # `expert` via param_specs; the dispatch einsums partition
            # automatically (ep=1 math, compiler-inserted collectives).
            def body(xc, p):
                res = self._block(
                    xc, p, seg=segment_ids, positions=positions
                )
                return (res[0], res[1]) if moe else (res, None)

            x, auxs = lax.scan(body, x, blocks)
            if moe:
                aux_loss = auxs["aux"].sum()      # per-layer sow semantics
                fill = auxs["fill"].mean()
        else:
            ep = self.mesh.shape.get(EXPERT_AXIS, 1)
            sp = self.mesh.shape.get(SEQ_AXIS, 1)
            if t % sp != 0:
                raise ValueError(
                    f"seq length ({t}) must divide over the seq axis ({sp})"
                )
            tp = self.mesh.shape.get(MODEL_AXIS, 1)
            if tp > 1 and (h % tp or (4 * d) % tp):
                raise ValueError(
                    f"n_heads ({h}) and 4*d_model ({4 * d}) must divide "
                    f"over the model axis ({tp}) for in-stage TP"
                )
            n_stages = self.mesh.shape[PIPE_AXIS]
            stage_slice_size(L, n_stages)  # validates divisibility
            # Tiny batches (e.g. the Trainer's dp-sized init probe) can't
            # fill the microbatch queue; degrade the schedule, not the user.
            # Each microbatch must still cover the data axes (its batch dim
            # is sharded over them inside the manual region).
            dp = self.mesh.shape[DATA_AXIS] * self.mesh.shape[FSDP_AXIS]
            n_micro = max(1, min(self.n_micro, b // dp))
            if b % (n_micro * dp) != 0:
                raise ValueError(
                    f"batch ({b}) must divide into n_micro ({n_micro}) x "
                    f"data axes ({dp})"
                )
            mb = b // n_micro
            x_micro = x.reshape(n_micro, mb, t, d)
            extras = None
            if segment_ids is not None:
                extras = (
                    segment_ids.reshape(n_micro, mb, t),
                    positions.reshape(n_micro, mb, t),
                )

            # Activations shard their token dim over `seq` inside the manual
            # region; each stage's attention is then a ring-flash collective
            # around the seq ring (_block), the pp handoffs ppermute only
            # over `pipe` — same (pipe, seq) grid position, next stage.
            act_spec = P(None, BATCH_AXES, SEQ_AXIS, None)
            # Stage stacks over `pipe` on dim 0 + Megatron column/row TP
            # over `model` inside each stage (_TP_DIM; activations stay
            # replicated across model, each rank computing its head/feature
            # slice with one psum per residual join in _block) + expert
            # stacks over `expert` on their E dim.
            specs = _stack_specs(tp > 1)
            stack_param_specs = {
                k: P(PIPE_AXIS, *specs[k]) for k in blocks
            }

            # Interleaved: L must split into S*v chunks, and the wrap
            # register-file timing needs n_micro >= n_stages. Degrading v
            # to 1 would apply the PLACEMENT-ordered stacks contiguously —
            # a permuted layer composition, a different function — so it is
            # allowed only during flax's shape-only init probe (values are
            # discarded there); a real forward with too few microbatches
            # fails loudly instead.
            v_eff = 1
            if self.schedule == "interleaved":
                if L % (n_stages * self.n_virtual) != 0:
                    raise ValueError(
                        f"n_layers ({L}) must divide into pipe "
                        f"({n_stages}) x n_virtual ({self.n_virtual}) chunks"
                    )
                if n_micro >= n_stages:
                    v_eff = self.n_virtual
                elif not self.is_initializing():
                    raise ValueError(
                        f"interleaved schedule needs n_micro ({n_micro}, "
                        f"after batch clamping) >= pipe ({n_stages}); "
                        f"raise the batch or n_micro"
                    )

            def run(stage_params, xm, ex=None):
                def stage(params, act, extra=None):
                    seg, pos = extra if extra is not None else (None, None)

                    def body(a, p):
                        res = self._block(
                            a, p, tp=tp, sp=sp, ep=ep, seg=seg, positions=pos
                        )
                        return (res[0], res[1]) if moe else (res, None)

                    a, auxs = lax.scan(body, act, params)
                    if moe:
                        # This stage's layers, summed (per-layer sow adds).
                        return a, jax.tree.map(lambda v: v.sum(0), auxs)
                    return a

                # Uniform branch: `schedule` is module CONFIG, identical
                # on every rank — the pipeline variants legitimately
                # issue different collective counts.
                if self.schedule == "interleaved":  # hvt: noqa[HVT007]
                    chunked = jax.tree.map(
                        lambda p: p.reshape(
                            (v_eff, p.shape[0] // v_eff) + p.shape[1:]
                        ),
                        stage_params,
                    )
                    res = spmd_pipeline_interleaved(
                        stage, chunked, xm, n_virtual=v_eff, extras=ex,
                        with_aux=moe,
                    )
                elif self.schedule == "1f1b":
                    res = spmd_pipeline_1f1b(
                        stage, stage_params, xm, extras=ex, with_aux=moe
                    )
                elif ex is None:
                    res = spmd_pipeline(
                        lambda act: stage(stage_params, act), xm,
                        with_aux=moe,
                    )
                else:
                    res = spmd_pipeline(
                        lambda act, e: stage(stage_params, act, e), xm,
                        extras=ex, with_aux=moe,
                    )
                if not moe:
                    return res
                xm_out, aux = res
                # Stages hold disjoint layers: SUM over pipe. Shards hold
                # disjoint token groups: MEAN over data/fsdp/seq. Expert and
                # model ranks computed routing identically (pre-slice), so
                # the result is replicated over every mesh axis.
                aux = jax.tree.map(
                    lambda v: lax.pmean(
                        lax.psum(v, PIPE_AXIS),
                        (DATA_AXIS, FSDP_AXIS, SEQ_AXIS),
                    ),
                    aux,
                )
                return xm_out, aux

            extra_spec = P(None, BATCH_AXES, SEQ_AXIS)
            args = (blocks, x_micro)
            in_specs = (stack_param_specs, act_spec)
            if extras is not None:
                args += (extras,)
                in_specs += ((extra_spec, extra_spec),)
            out = compat.shard_map(
                run,
                mesh=self.mesh,
                in_specs=in_specs,
                out_specs=(act_spec, P()) if moe else act_spec,
                check_vma=False,
            )(*args)
            if moe:
                x_micro, aux_tree = out
                aux_loss = aux_tree["aux"] / n_micro
                fill = aux_tree["fill"] / (L * n_micro)
            else:
                x_micro = out
            x = x_micro.reshape(b, t, d)

        if moe:
            if train:
                self.sow(
                    "losses", "moe_load_balance",
                    self.moe_aux_coef * aux_loss,
                )
            self.sow("metrics", "moe_drop_rate", 1.0 - fill)

        x = _layernorm(x, ln_f)
        logits = x.astype(jnp.float32) @ lm_head.astype(jnp.float32)
        return logits

    def _block(self, x, p, tp: int = 1, sp: int = 1, ep: int = 1,
               seg=None, positions=None):
        """One pre-LN transformer block over a single layer's params.

        ``tp > 1`` = Megatron TP inside the (fully-manual) pipeline region:
        this model-rank's param slices are column-parallel for qkv/mlp_up
        (each rank owns ``h/tp`` heads / ``4d/tp`` features) and
        row-parallel for attn_out/mlp_down, with ONE `psum` over ``model``
        per residual join restoring the replicated activation.

        ``sp > 1`` = sequence parallelism inside the stage: ``x`` is this
        device's ``[mb, T/sp, d]`` token shard, RoPE positions carry the
        shard's global offset, and attention runs as `ring_flash_attention`
        around the ``seq`` ring (packed ``seg`` ids ride the ring with
        their K/V blocks)."""
        mb, t, d = x.shape
        h_local = self.n_heads // tp
        hd = d // self.n_heads
        cd = self.compute_dtype

        hidden = _layernorm(x, p["ln1"])
        qkv = hidden @ p["qkv"].astype(cd)  # [mb, T, 3d/tp]
        qkv = qkv.reshape(mb, t, h_local, 3 * hd)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        if positions is None:
            base = lax.axis_index(SEQ_AXIS) * t if sp > 1 else 0
            positions = base + jnp.broadcast_to(
                jnp.arange(t, dtype=jnp.int32), (mb, t)
            )
        q, k = _rope(q, positions), _rope(k, positions)
        # Flash kernel (O(T) memory): without it a pipeline stage would
        # materialize [T, T] scores per microbatch and PP could not compose
        # with the long contexts it exists to serve; dense fallback applies
        # automatically when the kernel's tiling doesn't hold (tiny tests).
        # With a live seq axis the same kernel runs per-hop inside the ring
        # (the within-chip and cross-chip halves of one online softmax).
        from horovod_tpu.ops import attention as attention_ops
        from horovod_tpu.ops.flash_attention import flash_attention

        if sp > 1:
            att = attention_ops.ring_flash_attention(
                q, k, v, axis_name=SEQ_AXIS, causal=True, segment_ids=seg,
                window=self.window,
            )
        else:
            att = flash_attention(
                q, k, v, causal=True,
                q_segment_ids=seg, kv_segment_ids=seg, window=self.window,
            )  # [mb, T, H/tp, hd]
        out = att.reshape(mb, t, h_local * hd) @ p["attn_out"].astype(cd)
        if tp > 1:
            out = lax.psum(out, MODEL_AXIS)
        x = x + out

        hidden = _layernorm(x, p["ln2"])
        if "moe_up" in p:
            mixed, aux = self._moe_mlp(hidden, p, ep=ep, tp=tp)
            return x + mixed, aux
        hidden = nn.gelu(hidden @ p["mlp_up"].astype(cd))
        down = hidden @ p["mlp_down"].astype(cd)
        if tp > 1:
            down = lax.psum(down, MODEL_AXIS)
        return x + down

    def _moe_mlp(self, x, p, ep: int, tp: int):
        """GShard dense-dispatch MoE over one layer's expert stacks.

        Functional mirror of `models/moe.py` (same routing, capacity and
        aux-loss math — see its docstring for the design rationale), written
        for the pipeline's manual region: ``p['moe_up']/['moe_down']`` are
        this expert-rank's ``[E/ep, d, 4d/tp-or-4d]`` slices (sharded by the
        shard_map in_specs), routing runs identically on every rank from the
        replicated f32 router, and each rank contracts only its experts'
        columns of the dispatch/combine one-hots — the cross-rank combine is
        ONE psum over (expert, model) per block. Returns ``(mixed [mb,T,d],
        {'aux': load-balance loss (group mean), 'fill': kept-slot
        fraction})``.
        """
        mb, t, d = x.shape
        e, k = self.n_experts, self.moe_k
        g = mb * t
        n = self._n_groups(g)
        s = g // n
        capacity = max(1, int(k * s / e * self.capacity_factor))
        cd = self.compute_dtype
        tokens = x.reshape(n, s, d)

        # --- routing (float32, replicated across expert/model ranks) ------
        logits = tokens.astype(jnp.float32) @ p["router"].astype(jnp.float32)
        probs = jax.nn.softmax(logits, axis=-1)  # [n, S, E]
        top_probs, top_idx = lax.top_k(probs, k)
        if k > 1:
            # GShard renormalization over the chosen experts; NOT for k=1 —
            # Switch gating uses the raw prob so the router stays coupled
            # to the task loss.
            top_probs = top_probs / (top_probs.sum(-1, keepdims=True) + 1e-9)

        assign1 = jax.nn.one_hot(top_idx[..., 0], e)
        frac = assign1.mean(1)
        aux = (e * jnp.sum(frac * probs.mean(1), axis=-1)).mean()

        # --- dispatch plan (cumsum slotting; overflow past capacity drops) -
        choice = jnp.moveaxis(jax.nn.one_hot(top_idx, e), -2, 1)  # [n,k,S,E]
        flat_choice = choice.reshape(n, k * s, e)
        pos = jnp.cumsum(flat_choice, axis=1) * flat_choice - 1.0
        pos = pos.reshape(n, k, s, e)
        in_cap = (pos >= 0) & (pos < capacity)
        slot = jnp.clip(pos, 0, capacity - 1).astype(jnp.int32)
        slot_oh = jax.nn.one_hot(slot, capacity) * in_cap[..., None]
        fill = jnp.sum(slot_oh.astype(jnp.float32)) / float(n * k * s)
        combine = jnp.einsum(
            "nksec,nsk->nsec", slot_oh, top_probs.astype(jnp.float32)
        )
        dispatch = slot_oh.sum(1)  # [n, S, E, C]

        # --- this rank's experts only ---------------------------------------
        if ep > 1:
            e_loc = e // ep
            off = lax.axis_index(EXPERT_AXIS) * e_loc
            dispatch = lax.dynamic_slice_in_dim(dispatch, off, e_loc, axis=2)
            combine = lax.dynamic_slice_in_dim(combine, off, e_loc, axis=2)
        expert_in = jnp.einsum(
            "nsec,nsd->necd", dispatch.astype(cd), tokens.astype(cd)
        )
        h = nn.gelu(
            jnp.einsum("necd,edh->nech", expert_in, p["moe_up"].astype(cd))
        )
        out = jnp.einsum("nech,ehd->necd", h, p["moe_down"].astype(cd))
        mixed = jnp.einsum("nsec,necd->nsd", combine.astype(cd), out)
        if ep > 1 or tp > 1:
            axes = tuple(
                ax for ax, live in
                ((EXPERT_AXIS, ep > 1), (MODEL_AXIS, tp > 1)) if live
            )
            mixed = lax.psum(mixed, axes)
        return (
            mixed.reshape(mb, t, d).astype(x.dtype),
            {"aux": aux, "fill": fill},
        )

    def _n_groups(self, g: int) -> int:
        from horovod_tpu.models.moe import dispatch_group_count

        return dispatch_group_count(g, self.moe_group_size)


# Per-stack TP layout (dims AFTER the leading [n_layers] stack dim):
# column-parallel kernels shard their OUTPUT dim over `model`, row-parallel
# their INPUT dim; LayerNorm scales replicate. Expert stacks [E, ...] shard
# E over `expert` (their hidden dim over `model` when TP is live); the tiny
# router replicates.
_TP_DIM = {"qkv": 1, "mlp_up": 1, "attn_out": 0, "mlp_down": 0}
_STACKED = (
    "ln1", "qkv", "attn_out", "ln2", "mlp_up", "mlp_down",
    "router", "moe_up", "moe_down",
)


def _stack_specs(tp: bool) -> dict:
    """{name: trailing-dims spec tuple} for every possible per-layer stack
    (dense and MoE alike — callers index by the stacks they created)."""
    out = {}
    for name in ("ln1", "qkv", "attn_out", "ln2", "mlp_up", "mlp_down"):
        ndim = 1 if name.startswith("ln") else 2
        spec = [None] * ndim
        if tp and name in _TP_DIM:
            spec[_TP_DIM[name]] = MODEL_AXIS
        out[name] = tuple(spec)
    out["router"] = (None, None)
    out["moe_up"] = (EXPERT_AXIS, None, MODEL_AXIS if tp else None)
    out["moe_down"] = (EXPERT_AXIS, MODEL_AXIS if tp else None, None)
    return out


def _reorder_stacks(params, order):
    """Apply a row permutation to every per-layer stack leaf."""
    import numpy as np

    idx = jnp.asarray(np.asarray(order, dtype=np.int32))

    def rule(path, leaf):
        names = [p.key for p in path if isinstance(p, jax.tree_util.DictKey)]
        if any(n in _STACKED for n in names):
            return jnp.take(leaf, idx, axis=0)
        return leaf

    return jax.tree_util.tree_map_with_path(rule, params)


def to_interleaved_order(params, n_layers: int, n_stages: int,
                         n_virtual: int):
    """Logical-order stacks → the placement order an interleaved pipe mesh
    stores (physical row p = logical layer `interleaved_layer_order(...)[p]`).
    Use when loading a sequential/gpipe checkpoint into an interleaved
    config."""
    return _reorder_stacks(
        params, interleaved_layer_order(n_layers, n_stages, n_virtual)
    )


def to_logical_order(params, n_layers: int, n_stages: int, n_virtual: int):
    """Inverse of `to_interleaved_order` — recover logical layer order from
    an interleaved checkpoint (e.g. to resume it on a different mesh or
    schedule)."""
    import numpy as np

    order = interleaved_layer_order(n_layers, n_stages, n_virtual)
    return _reorder_stacks(params, np.argsort(order))


def param_specs(params, mesh: Mesh) -> dict:
    """PartitionSpec tree for the pipelined layout: per-layer stacks sharded
    over ``pipe`` on dim 0 (+ Megatron column/row over ``model`` when that
    axis is live), everything else replicated."""
    tp = mesh.shape.get(MODEL_AXIS, 1) > 1
    stack_specs = _stack_specs(tp)

    def rule(path, leaf):
        names = [p.key for p in path if isinstance(p, jax.tree_util.DictKey)]
        name = next((n for n in names if n in stack_specs), None)
        if name is not None:
            return P(PIPE_AXIS, *stack_specs[name])
        return P()

    return jax.tree_util.tree_map_with_path(rule, params)
