"""ResNet for CIFAR-10 — the heavier-gradients benchmark model family.

BASELINE.json config 4 calls for "TF2 Keras CIFAR-10 ResNet-20 data-parallel
(heavier grads, same DistributedOptimizer path)": a model whose gradient
pytree stresses the allreduce path far more than the MNIST CNN. This is the
classic CIFAR ResNet of He et al. (arXiv:1512.03385 §4.2): depth 6n+2, three
stages of n basic blocks at 16/32/64 channels, global average pool.

TPU-first notes:
* BatchNorm statistics are computed inside the SPMD-jitted step, i.e. over
  the **global** batch — sync-BN semantics by construction (GPU DP stacks
  need an extra SyncBatchNorm op; here it is the default and XLA inserts the
  cross-chip reduction).
* Compute dtype configurable (bfloat16 on TPU) with float32 params and
  float32 BN statistics — the standard mixed-precision recipe the MXU wants.
* Identity shortcuts use 1x1 projection when shape changes (option B), which
  keeps every residual add an MXU-friendly matmul/conv rather than a pad.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class BasicBlock(nn.Module):
    filters: int
    strides: int = 1
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        conv = lambda f, s: nn.Conv(  # noqa: E731
            f, (3, 3), strides=(s, s), padding="SAME", use_bias=False,
            dtype=self.compute_dtype,
        )
        bn = lambda: nn.BatchNorm(  # noqa: E731
            use_running_average=not train, momentum=0.9, epsilon=1e-5,
            dtype=self.compute_dtype,
        )
        shortcut = x
        y = conv(self.filters, self.strides)(x)
        y = bn()(y)
        y = nn.relu(y)
        y = conv(self.filters, 1)(y)
        y = bn()(y)
        if shortcut.shape[-1] != self.filters or self.strides != 1:
            shortcut = nn.Conv(
                self.filters, (1, 1), strides=(self.strides, self.strides),
                use_bias=False, dtype=self.compute_dtype,
            )(shortcut)
            shortcut = bn()(shortcut)
        return nn.relu(y + shortcut)


class ResNetCIFAR(nn.Module):
    """CIFAR ResNet, depth = 6n+2 (20 → n=3). Returns float32 logits."""

    depth: int = 20
    num_classes: int = 10
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        if (self.depth - 2) % 6 != 0:
            raise ValueError(f"depth must be 6n+2, got {self.depth}")
        n = (self.depth - 2) // 6
        if jnp.issubdtype(x.dtype, jnp.integer):
            # Raw uint8 pixels → on-device /255 (see MnistCNN note: 4x less
            # host->device traffic, identical numerics to host normalize).
            x = x.astype(jnp.float32) / 255.0
        x = x.astype(self.compute_dtype)
        x = nn.Conv(16, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.compute_dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-5, dtype=self.compute_dtype)(x)
        x = nn.relu(x)
        for stage, (filters, stride) in enumerate([(16, 1), (32, 2), (64, 2)]):
            for block in range(n):
                x = BasicBlock(
                    filters,
                    strides=stride if block == 0 else 1,
                    compute_dtype=self.compute_dtype,
                )(x, train=train)
        x = x.mean(axis=(1, 2))  # global average pool
        x = nn.Dense(self.num_classes, dtype=self.compute_dtype)(x)
        return x.astype(jnp.float32)
