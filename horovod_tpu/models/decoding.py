"""Autoregressive inference: KV-cache prefill + `lax.scan` decode loop.

The reference stops at a serving *export* (mnist_keras.py:126-140 — a
SavedModel with a predict signature); for an LM-flagship framework the
serving-side capability is token generation, so this module makes inference
first-class the TPU way:

* **one compiled program** — prompt prefill (flash-kernel causal attention,
  K/V written into per-block caches) and the whole decode loop (a
  `lax.scan` of single-token steps against the cache) live inside a single
  `jit`, so the host dispatches once per generation, not once per token —
  on a tunneled runtime a per-token dispatch would cost more than the
  matvecs themselves;
* **training shardings reused** — the cache carries the same Megatron
  layout as training ([B, L, H, D] with heads over ``model``), so a
  TP-sharded checkpoint decodes without resharding;
* **static shapes** — the cache is sized `prompt_len + max_new_tokens` up
  front; early stop on ``eos_id`` is a masked fill, not a dynamic shape.

Sampling: greedy (``temperature=0``), temperature, top-k and top-p
(nucleus) — all inside the scan via `jax.random.categorical` with a
split-per-step key.

MoE caveat: expert capacity is enforced per *call* group, so a decode step
routes only that step's tokens while a teacher-forced forward routes every
position of the sequence at once. When capacity never binds (ample
``capacity_factor``) the two are bit-identical; when it binds they drop
*different* tokens, and decoded logits can legitimately diverge from a full
recompute — same semantics Switch/GShard serving has.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

_NEG = -1e30


def check_sampling_params(temperature: float, top_p: float) -> None:
    """The one place the sampling-knob ranges are enforced.

    top_p < 0 would make the nucleus empty and the clamped kth index wrap
    to the minimum logit (silently UNfiltered sampling); temperature < 0
    would invert the distribution (anti-nucleus) — both must raise, not
    silently misbehave.
    """
    if not 0.0 <= top_p <= 1.0:
        raise ValueError(f"top_p must be in [0, 1], got {top_p}")
    if temperature < 0.0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")


def filter_logits(logits, temperature: float, top_k: int, top_p: float):
    """Temperature/top-k/top-p filtering on [..., vocab] logits (f32 math).

    Returns the filtered logits whose softmax is the sampling distribution
    (`_NEG` on masked tokens). Shared by `_sample` and the speculative
    decoder's rejection scheme, which needs the distribution itself, not a
    draw. ``temperature`` must be > 0 here (greedy is its callers' fast
    path).
    """
    check_sampling_params(temperature, top_p)
    if temperature == 0.0:
        raise ValueError("filter_logits needs temperature > 0 (greedy is "
                         "the callers' argmax fast path)")
    logits = logits.astype(jnp.float32) / temperature
    if top_k:
        kth = lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, _NEG, logits)
    if top_p:
        # Nucleus: keep the smallest prefix of descending-prob tokens whose
        # EXCLUSIVE cumulative mass is < top_p (so the top token always
        # survives), then sample the renormalized rest via categorical.
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        exclusive = jnp.cumsum(probs, axis=-1) - probs
        n_keep = jnp.sum(exclusive < top_p, axis=-1, keepdims=True)
        kth = jnp.take_along_axis(sorted_logits, n_keep - 1, axis=-1)
        logits = jnp.where(logits < kth, _NEG, logits)
    return logits


def _sample(logits, rng, temperature: float, top_k: int, top_p: float = 0.0):
    """One next-token draw from [B, vocab] logits (f32 math)."""
    check_sampling_params(temperature, top_p)
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(
        rng, filter_logits(logits, temperature, top_k, top_p)
    ).astype(jnp.int32)


def make_generate_fn(model, *, max_new_tokens: int, temperature: float = 0.0,
                     top_k: int = 0, top_p: float = 0.0,
                     eos_id: int | None = None,
                     include_prompt: bool = True,
                     quantized: bool = False,
                     int8_compute: bool = False,
                     quantized_cache: bool = False):
    """Build the compiled generator: ``(params, prompt, rng) -> tokens``.

    ``model`` is the *training* `TransformerLM`; it is cloned into decode
    mode (``decode=True``, dropout off) with the cache sized to
    ``prompt.shape[1] + max_new_tokens``. The returned function is jitted
    and reusable across calls of the same prompt shape — the handle to hold
    when generating in a loop (a bare `generate` call per prompt re-traces).

    ``quantized=True``: ``params`` is a `models/quant.quantize_params`
    tree (int8 weights + scales); each decode step dequantizes inside the
    scan body so the per-token weight stream stays int8 in HBM — the
    bandwidth-bound step reads half the bytes (quant.py; approximate:
    outputs can differ from bf16 decoding near ties).

    ``int8_compute=True``: the PREFILL forward runs its matmuls on the
    int8 MXU (`quant.int8_dot_general`) — the compute-bound phase where
    the 2× int8 rate pays (1.2–1.44× measured, BASELINE.md); decode scan
    steps stay bf16, where per-step dynamic weight requantization was
    measured slower. Orthogonal to ``quantized`` (storage).

    ``quantized_cache=True``: K/V cache stored int8 with per-(position,
    head) scales (TransformerLM.quantized_cache) — the cache stream and
    cache HBM halve; the decode einsums read int8 directly (scales factor
    out of the head-dim contraction). Stacks with ``quantized`` weights
    and GQA; approximate, same quality gates.

    **Ragged prompts** — ``fn(params, prompt, rng, lengths)`` with
    ``lengths`` a ``[B]`` int array: each row's true prompt is its first
    ``lengths[i]`` tokens; the rest of the row is right-padding (any token
    id). The prefill writes pad K/V into the cache, but each row's first
    sampled token reads the logits at its own ``lengths[i]-1`` and decode
    steps write at per-row cache positions — generated K/V overwrite the
    pad entries before any query can attend to them (causal masking covers
    the not-yet-overwritten tail), so every row generates exactly as if it
    were alone in the batch at its own length. This is the serving path:
    one compiled program, mixed prompt lengths per batch.
    """
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")

    def run(params, prompt, rng, lengths=None):
        prompt = prompt.astype(jnp.int32)
        b, t0 = prompt.shape
        from horovod_tpu.models.quant import make_unpack

        unpack = make_unpack(quantized)
        qparams = params
        params = unpack(qparams)
        dmodel = model.clone(
            decode=True, max_decode_len=t0 + max_new_tokens, dropout=0.0,
            remat=False,
            **({"quantized_cache": True} if quantized_cache else {}),
        )
        # int8_compute applies to the PREFILL apply only — the measured
        # split (BASELINE.md int8 row): prefill is compute-bound and gains
        # 1.2-1.44x from the int8 MXU, while a decode step is bandwidth-
        # bound and per-step dynamic weight requantization makes it
        # SLOWER (0.87-1.0x) — so the scan body stays bf16. (For a full
        # int8 forward, use TransformerLM(int8_compute=True) directly.)
        pmodel = dmodel.clone(int8_compute=True) if int8_compute else dmodel
        # Prefill: one causal forward over the prompt; the mutable 'cache'
        # collection is created here ([B, L, H, D] per block + the position
        # index) and threaded through the scan as plain pytree state.
        logits, vars_ = pmodel.apply({"params": params}, prompt, mutable=["cache"])
        cache0 = vars_["cache"]
        if lengths is None:
            last_logits = logits[:, -1]
        else:
            # Ragged batch: row i's next-token logits live at its own last
            # REAL position, and its decode writes start at lengths[i] —
            # the per-row cache index layout (transformer.Block).
            lengths = jnp.asarray(lengths, jnp.int32)
            last_logits = jnp.take_along_axis(
                logits, (lengths - 1)[:, None, None], axis=1
            )[:, 0]
            cache0 = {**cache0, "index": lengths}
        rng, sub = jax.random.split(rng)
        tok = _sample(last_logits, sub, temperature, top_k, top_p)
        done = (
            jnp.zeros((b,), bool) if eos_id is None else tok == eos_id
        )
        fill = jnp.int32(0 if eos_id is None else eos_id)

        def body(carry, _):
            cache, tok, rng, done = carry
            # Quantized mode: dequantize HERE, inside the scan body — the
            # convert+scale fuses into this step's matmul reads, so the
            # HBM weight stream stays int8 (quant.py docstring).
            step_logits, step_vars = dmodel.apply(
                {"params": unpack(qparams), "cache": cache}, tok[:, None],
                mutable=["cache"],
            )
            rng, sub = jax.random.split(rng)
            nxt = _sample(step_logits[:, -1], sub, temperature, top_k, top_p)
            nxt = jnp.where(done, fill, nxt)
            new_done = done if eos_id is None else done | (nxt == eos_id)
            return (step_vars["cache"], nxt, rng, new_done), nxt

        (_, _, _, _), rest = lax.scan(
            body, (cache0, tok, rng, done), None,
            length=max_new_tokens - 1,
        )
        gen = jnp.concatenate([tok[:, None], jnp.moveaxis(rest, 0, 1)], axis=1)
        return jnp.concatenate([prompt, gen], axis=1) if include_prompt else gen

    return jax.jit(run)


def make_chunked_generate_fns(model, *, max_new_tokens: int, chunk: int,
                              temperature: float = 0.0, top_k: int = 0,
                              top_p: float = 0.0, eos_id: int | None = None,
                              quantized_cache: bool = False):
    """Chunked generation for STREAMING serving: two compiled programs that
    emit ``chunk`` tokens per dispatch with the KV cache carried between
    calls as ordinary arrays (device-resident between dispatches).

    Returns ``(start_fn, continue_fn)``:

    * ``start_fn(params, prompt [B, T0], rng, lengths [B]) ->
      (tokens [B, chunk], state)`` — prefill + the first ``chunk`` tokens
      (ragged per-row lengths, decoding.make_generate_fn's contract);
    * ``continue_fn(params, state) -> (tokens [B, chunk], state)`` — the
      next ``chunk`` tokens against the carried cache.

    ``state`` is a pytree ``(cache, last_tok, rng, done)``; its ``done``
    leaf ([B] bool) lets a server stop early once every row emitted
    ``eos_id``. The cache is sized ``prompt_len + max_new_tokens`` at the
    first call, so at most ``ceil(max_new_tokens / chunk)`` chunks are
    valid — the caller enforces the budget. Token streams are IDENTICAL
    to `make_generate_fn`'s for the same knobs (one compiled scan cut at
    chunk boundaries; greedy/sampling/eos semantics unchanged — parity
    tested).

    CONTRACT (load-bearing for `horovod_tpu/serving/decoder.py`): every
    ``state`` leaf except ``rng`` carries a leading batch axis and each
    row's trajectory depends only on its own row (ragged lengths make a
    row generate exactly as if alone) — that per-row independence is
    what lets the continuous-batching engine admit sequences mid-flight
    by splicing rows of a fresh ``start`` state into a live state. The
    ``rng`` leaf (shape [2]) is shared by the whole batch and is NOT
    spliceable; the engine keeps the live rng and folds an admission
    counter into each prefill's seed instead. Reordering this tuple or
    giving rng a batch axis changes that downstream contract.
    """
    if chunk < 1:
        raise ValueError("chunk must be >= 1")
    if max_new_tokens % chunk != 0:
        # The cache is sized t0 + max_new_tokens exactly; a partial final
        # chunk would scan past it. Divisibility keeps every chunk valid.
        raise ValueError(
            f"chunk ({chunk}) must divide max_new_tokens "
            f"({max_new_tokens})"
        )

    fill = jnp.int32(0 if eos_id is None else eos_id)

    def make_body(dmodel, params):
        def body(carry, _):
            cache, tok, rng, done = carry
            step_logits, step_vars = dmodel.apply(
                {"params": params, "cache": cache},
                tok[:, None], mutable=["cache"],
            )
            rng, sub = jax.random.split(rng)
            nxt = _sample(step_logits[:, -1], sub, temperature, top_k, top_p)
            nxt = jnp.where(done, fill, nxt)
            new_done = done if eos_id is None else done | (nxt == eos_id)
            return (step_vars["cache"], nxt, rng, new_done), nxt

        return body

    def dmodel_for(t0):
        kw = {"quantized_cache": True} if quantized_cache else {}
        return model.clone(
            decode=True, max_decode_len=t0 + max_new_tokens, dropout=0.0,
            remat=False, **kw,
        )

    def start(params, prompt, rng, lengths):
        prompt = prompt.astype(jnp.int32)
        b, t0 = prompt.shape
        dmodel = dmodel_for(t0)
        logits, vars_ = dmodel.apply(
            {"params": params}, prompt, mutable=["cache"]
        )
        lengths = jnp.asarray(lengths, jnp.int32)
        last_logits = jnp.take_along_axis(
            logits, (lengths - 1)[:, None, None], axis=1
        )[:, 0]
        rng, sub = jax.random.split(rng)
        tok = _sample(last_logits, sub, temperature, top_k, top_p)
        done = jnp.zeros((b,), bool) if eos_id is None else tok == eos_id
        cache0 = {**vars_["cache"], "index": lengths}
        (cache, tok_l, rng, done), rest = lax.scan(
            make_body(dmodel, params), (cache0, tok, rng, done), None,
            length=chunk - 1,
        )
        tokens = jnp.concatenate(
            [tok[:, None], jnp.moveaxis(rest, 0, 1)], axis=1
        )
        return tokens, (cache, tok_l, rng, done)

    def cont(params, state):
        cache, tok, rng, done = state
        # The cache length encodes t0 + max_new_tokens; reconstruct the
        # model at the same static size from the carried cache leaves.
        any_k = next(
            v["k"] for v in cache.values() if isinstance(v, dict) and "k" in v
        )
        dmodel = dmodel_for(any_k.shape[1] - max_new_tokens)
        (cache, tok_l, rng, done), toks = lax.scan(
            make_body(dmodel, params), (cache, tok, rng, done), None,
            length=chunk,
        )
        return jnp.moveaxis(toks, 0, 1), (cache, tok_l, rng, done)

    return jax.jit(start), jax.jit(cont)


def generate(model, params, prompt, max_new_tokens: int, *, rng=None,
             temperature: float = 0.0, top_k: int = 0, top_p: float = 0.0,
             eos_id: int | None = None, include_prompt: bool = True,
             quantized: bool = False, int8_compute: bool = False,
             quantized_cache: bool = False):
    """Generate ``max_new_tokens`` continuations of ``prompt`` ([B, T0] ints).

    Convenience wrapper over `make_generate_fn` (which see, for the handle
    to keep when calling repeatedly). ``temperature=0`` = greedy; after a
    row emits ``eos_id`` its remaining positions are filled with it.
    """
    fn = make_generate_fn(
        model, max_new_tokens=max_new_tokens, temperature=temperature,
        top_k=top_k, top_p=top_p, eos_id=eos_id,
        include_prompt=include_prompt, quantized=quantized,
        int8_compute=int8_compute, quantized_cache=quantized_cache,
    )
    if rng is None:
        rng = jax.random.PRNGKey(0)
    return fn(params, jnp.asarray(prompt), rng)
