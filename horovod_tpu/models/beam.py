"""Beam search over the KV-cache decode loop — one compiled program.

Completes the decode-mode family (greedy / temperature / top-k / top-p /
speculative): width-W maximum-likelihood search, TPU-shaped —

* **beams are batch rows.** Hypotheses live as a [B·W] batch through the
  same cached decode step the other modes use; one forward per step
  scores every beam of every row.
* **reordering is a gather.** When beam w extends from parent p, its KV
  cache rows are `leaf[B, W, ...][batch, parent]` — a batch-dim gather
  XLA turns into one dynamic-gather per cache leaf, inside the scan. No
  host, no dynamic shapes.
* **the whole search is one `lax.scan`** (prefill + W-way seeding + the
  step loop under a single jit): one dispatch per search, like
  `decoding.make_generate_fn`.

Scores are accumulated log-probabilities (f32, log_softmax of the step
logits); finished rows (``eos_id``) freeze their score and expand only to
eos. Final selection applies the GNMT length penalty
``((5 + len) / 6) ** length_penalty`` when requested.

Reference role: the reference has no inference stack at all
(SURVEY.md §5.4 — its serving story ends at a SavedModel export);
beam search is framework completeness beyond parity.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.models.decoding import _NEG


def make_beam_search_fn(model, *, max_new_tokens: int, beam_size: int,
                        length_penalty: float = 0.0,
                        eos_id: int | None = None,
                        include_prompt: bool = True,
                        return_scores: bool = False,
                        quantized: bool = False):
    """Build the compiled beam searcher: ``(params, prompt) -> tokens``.

    Returns the best beam per batch row (``[B, T]`` int32); with
    ``return_scores`` a ``(tokens, scores)`` pair where ``scores`` is the
    best beam's accumulated log-probability (length-penalized when
    ``length_penalty > 0``). ``quantized`` follows
    `decoding.make_generate_fn`'s contract (int8 param tree, per-step
    in-loop dequantization).
    """
    if beam_size < 1:
        raise ValueError("beam_size must be >= 1")
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    w = beam_size

    def run(params, prompt):
        prompt = prompt.astype(jnp.int32)
        b, t0 = prompt.shape
        from horovod_tpu.models.quant import make_unpack

        unpack = make_unpack(quantized)
        qparams = params
        dmodel = model.clone(
            decode=True, max_decode_len=t0 + max_new_tokens, dropout=0.0,
            remat=False,
        )
        logits, vars_ = dmodel.apply(
            {"params": unpack(qparams)}, prompt, mutable=["cache"]
        )
        logp0 = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32))  # [B, V]
        vocab = logp0.shape[-1]

        # Seed: the top-W first tokens per row ARE the initial beams.
        scores, tok0 = lax.top_k(logp0, w)  # [B, W]
        tok0 = tok0.astype(jnp.int32)
        finished = (
            jnp.zeros((b, w), bool) if eos_id is None else tok0 == eos_id
        )

        # Tile the prompt cache to [B*W] rows (beam-major within a row).
        def tile(leaf):
            if leaf.ndim == 0:  # the shared decode index
                return leaf
            return jnp.repeat(leaf, w, axis=0)

        cache = jax.tree.map(tile, dict(vars_["cache"]))
        gen0 = jnp.full((b, w, max_new_tokens), jnp.int32(0))
        gen0 = gen0.at[:, :, 0].set(tok0)

        def step(carry, i):
            cache, gen, scores, last, finished = carry
            step_logits, new_vars = dmodel.apply(
                {"params": unpack(qparams), "cache": cache},
                last.reshape(b * w, 1), mutable=["cache"],
            )
            logp = jax.nn.log_softmax(
                step_logits[:, -1].astype(jnp.float32)
            ).reshape(b, w, vocab)
            if eos_id is not None:
                # Finished beams expand only to eos, at no score cost —
                # they compete in the pool with a frozen score.
                frozen = jnp.full((vocab,), _NEG).at[eos_id].set(0.0)
                logp = jnp.where(finished[:, :, None], frozen, logp)
            total = scores[:, :, None] + logp  # [B, W, V]
            new_scores, flat_idx = lax.top_k(total.reshape(b, w * vocab), w)
            parent = flat_idx // vocab  # [B, W]
            token = (flat_idx % vocab).astype(jnp.int32)

            # Reorder histories and caches under the surviving beams.
            gen = jnp.take_along_axis(gen, parent[:, :, None], axis=1)
            gen = gen.at[:, :, i].set(token)  # i = position in gen buffer

            def reorder(leaf):
                if leaf.ndim == 0:
                    return leaf
                shaped = leaf.reshape((b, w) + leaf.shape[1:])
                idx = parent.reshape(
                    (b, w) + (1,) * (leaf.ndim - 1)
                )
                return jnp.take_along_axis(shaped, idx, axis=1).reshape(
                    leaf.shape
                )

            cache = jax.tree.map(reorder, dict(new_vars["cache"]))
            if eos_id is None:
                new_finished = finished
            else:
                new_finished = (
                    jnp.take_along_axis(finished, parent, axis=1)
                    | (token == eos_id)
                )
            return (cache, gen, new_scores, token, new_finished), None

        (cache, gen, scores, _, finished), _ = lax.scan(
            step, (cache, gen0, scores, tok0, finished),
            jnp.arange(1, max_new_tokens, dtype=jnp.int32),
        )

        # Length-penalized final selection (GNMT): len = tokens before the
        # first eos (inclusive), or the full budget.
        if eos_id is not None:
            is_eos = gen == eos_id
            any_eos = is_eos.any(axis=-1)
            first = jnp.argmax(is_eos, axis=-1) + 1
            lengths = jnp.where(any_eos, first, max_new_tokens)
        else:
            lengths = jnp.full((b, w), max_new_tokens)
        if length_penalty > 0.0:
            norm = ((5.0 + lengths.astype(jnp.float32)) / 6.0) ** length_penalty
            final = scores / norm
        else:
            final = scores
        best = jnp.argmax(final, axis=1)  # [B]
        tokens = jnp.take_along_axis(gen, best[:, None, None], axis=1)[:, 0]
        best_score = jnp.take_along_axis(final, best[:, None], axis=1)[:, 0]
        if eos_id is not None:
            # Pad everything after the first eos with eos (generate()'s
            # fill convention).
            pos = jnp.arange(max_new_tokens)
            blen = jnp.take_along_axis(lengths, best[:, None], axis=1)
            tokens = jnp.where(pos[None, :] < blen, tokens, jnp.int32(eos_id))
        if include_prompt:
            tokens = jnp.concatenate([prompt, tokens], axis=1)
        if return_scores:
            return tokens, best_score
        return tokens

    return jax.jit(run)
