"""Mixture-of-Experts MLP with expert parallelism over the ``expert`` axis.

The reference has no MoE (SURVEY.md §2.2: dense MLP head only,
tensorflow2_keras_mnist.py:49-51); this fills the framework's reserved
``expert`` mesh axis (parallel/mesh.py) with a first-class layer so EP is a
capability, not a name.

TPU-first design — the GShard/Switch dense-dispatch formulation
(arXiv:2006.16668, 2101.03961; PAPERS.md), which is the shape XLA partitions
well:

* **Static capacity.** Each expert processes a fixed ``capacity`` of tokens
  per batch; routing builds a one-hot dispatch tensor ``[G, E, C]`` and the
  data movement is two einsums. No dynamic shapes, no host round trips —
  everything stays inside the jitted step, scan/vmap-friendly.
* **Sharding, not message passing.** Expert weights are ``[E, ...]`` with E
  sharded over the ``expert`` axis; constraining the dispatched activations
  to ``P('expert', ...)`` makes GSPMD insert the all-to-all over ICI.
* **Router in float32** (bf16 softmax routing is unstable), top-k gating
  with renormalization, Switch-style load-balancing auxiliary loss published
  via ``self.sow('losses', ...)`` — the Trainer adds any sown 'losses'
  collection entries to the objective.
* **Overflow drops are safe by construction**: the transformer block adds
  the MoE output to the residual stream, so a token past capacity
  contributes zero instead of garbage.
* **Two routers.** ``router='top_k'`` (default): tokens pick experts —
  GShard/Switch semantics, capacity overflow possible (observable via
  ``moe_drop_rate``). ``router='expert_choice'`` (Zhou et al.,
  arXiv:2202.09368): each expert picks its top-``capacity`` tokens —
  perfectly load-balanced and drop-free BY CONSTRUCTION (no aux loss
  needed; the observability metric becomes ``moe_uncovered_rate``, the
  fraction of tokens no expert chose). Training-only for causal LMs:
  expert choice ranks tokens across the whole group, so selection of an
  early token depends on later tokens — the known train/inference
  asymmetry of EC routing; the decode path refuses it loudly.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from horovod_tpu.parallel.mesh import EXPERT_AXIS


def dispatch_group_count(g: int, group_size: int) -> int:
    """Smallest divisor of ``g`` whose groups stay within ``group_size`` —
    the shared dispatch-grouping contract (used here and by
    `models/pipelined_lm.PipelinedLM`'s in-pipeline MoE, which must group
    identically for pipelined-vs-sequential parity)."""
    for n in range(1, g + 1):
        if g % n == 0 and g // n <= group_size:
            return n
    return g


class MoEMlp(nn.Module):
    """Routed MLP: ``[B, T, d] -> [B, T, d]`` through E expert FFNs.

    Args:
      d_model: model width.
      n_experts: number of experts E (shardable over the ``expert`` axis).
      mlp_ratio: expert hidden width multiplier (reference-style 4x).
      k: experts per token (top-k routing; 1 = Switch, 2 = GShard default).
      capacity_factor: per-expert slots = ``k * G / E * capacity_factor``.
      aux_loss_coef: weight of the load-balancing loss sown into 'losses'.
      sharding: the model's ShardingConfig (constrains via its mesh if set).
    """

    d_model: int
    n_experts: int = 8
    mlp_ratio: int = 4
    k: int = 2
    capacity_factor: float = 1.25
    aux_loss_coef: float = 1e-2
    # 'top_k' (tokens pick experts, GShard/Switch) or 'expert_choice'
    # (experts pick tokens — drop-free, aux-free; see module docstring).
    router: str = "top_k"
    compute_dtype: jnp.dtype = jnp.float32
    sharding: object = None

    # Dispatch group size (GShard's group axis): routing/dispatch one-hots
    # are [S, E, C] with C ∝ S, so grouping keeps dispatch cost LINEAR in
    # token count — one flat group would make it quadratic (C would grow with
    # the whole batch).
    group_size: int = 1024

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        b, t, d = x.shape
        e = self.n_experts
        mesh = getattr(self.sharding, "mesh", None) if self.sharding else None
        if mesh is not None:
            ep = mesh.shape.get(EXPERT_AXIS, 1)
            if e % ep != 0:
                raise ValueError(
                    f"n_experts ({e}) must be divisible by the expert mesh "
                    f"axis ({ep})"
                )
        g = b * t
        n_groups = self._n_groups(g)
        s = g // n_groups  # tokens per dispatch group
        tokens = x.reshape(n_groups, s, d)
        capacity = max(1, int(self.k * s / e * self.capacity_factor))

        # --- routing (float32) ---------------------------------------------
        if self.router not in ("top_k", "expert_choice"):
            raise ValueError(
                f"router must be 'top_k' or 'expert_choice', got "
                f"{self.router!r}"
            )
        router = nn.Dense(
            e, use_bias=False, dtype=jnp.float32, name="router"
        )(tokens.astype(jnp.float32))
        probs = jax.nn.softmax(router, axis=-1)  # [n, S, E]

        if self.router == "expert_choice":
            return self._expert_choice(
                x, tokens, probs, capacity, n_groups, s
            )

        top_probs, top_idx = jax.lax.top_k(probs, self.k)  # [n, S, k]
        if self.k > 1:
            # GShard-style renormalization over the chosen experts. NOT for
            # k=1: p/p == 1 would make the gate constant and cut the router
            # off from the task loss — Switch gating uses the raw prob.
            top_probs = top_probs / (top_probs.sum(-1, keepdims=True) + 1e-9)

        # Switch load-balancing loss: E * sum_e fraction_routed_e * mean_prob_e
        # (top-1 assignment fraction, the standard formulation), meaned over
        # dispatch groups.
        assign1 = jax.nn.one_hot(top_idx[..., 0], e)  # [n, S, E]
        frac = assign1.mean(1)
        aux = (e * jnp.sum(frac * probs.mean(1), axis=-1)).mean()
        if train:
            self.sow("losses", "moe_load_balance", self.aux_loss_coef * aux)

        # --- dispatch plan: position of each (token, choice) in its expert --
        # Per group: one-hot choices [k, S, E] flattened to [k*S, E]; cumsum
        # down the token axis gives each routed token its slot in the
        # expert's capacity buffer; slots >= capacity overflow and drop.
        choice = jnp.moveaxis(
            jax.nn.one_hot(top_idx, e), -2, 1
        )  # [n, k, S, E]
        flat_choice = choice.reshape(n_groups, self.k * s, e)
        pos = jnp.cumsum(flat_choice, axis=1) * flat_choice - 1.0
        pos = pos.reshape(n_groups, self.k, s, e)
        in_cap = (pos >= 0) & (pos < capacity)
        slot = jnp.clip(pos, 0, capacity - 1).astype(jnp.int32)

        # combine[n, S, E, C]: gate mass of each token at its expert slot;
        # dispatch is its 0/1 skeleton.
        slot_oh = jax.nn.one_hot(slot, capacity) * in_cap[..., None]  # [n,k,S,E,C]
        # Router drop-rate observability: overflow drops are SAFE (residual
        # stream, zero contribution) but must never be silent — an EP config
        # can be dropping a third of its routed tokens and still "train".
        # Sown into the 'metrics' collection; Trainer averages any sown
        # metrics into the step/epoch logs (train_step requests the
        # collection as mutable; elsewhere the sow is a no-op).
        routed = float(n_groups * self.k * s)
        self.sow(
            "metrics", "moe_drop_rate",
            1.0 - jnp.sum(slot_oh.astype(jnp.float32)) / routed,
        )
        combine = jnp.einsum(
            "nksec,nsk->nsec", slot_oh, top_probs.astype(jnp.float32)
        )
        dispatch = slot_oh.sum(1)  # [n, S, E, C] (choices are disjoint experts)

        # --- expert computation, E sharded over the expert axis -------------
        cd = self.compute_dtype
        expert_in = jnp.einsum(
            "nsec,nsd->necd", dispatch.astype(cd), tokens.astype(cd)
        )  # [n, E, C, d]
        expert_in = self._constrain(expert_in, P(None, EXPERT_AXIS, None, None))
        out = self._experts(expert_in, d)

        # --- combine back to token order -----------------------------------
        mixed = jnp.einsum("nsec,necd->nsd", combine.astype(cd), out)
        return mixed.reshape(b, t, d).astype(x.dtype)

    def _expert_choice(self, x, tokens, probs, capacity, n_groups, s):
        """Expert-choice dispatch: each expert takes its top-``capacity``
        tokens of the group (scores = router softmax over experts, read
        column-wise). Every expert is exactly full — balanced and drop-free
        by construction, so there is no load-balancing aux loss; the
        observability dual of drop-rate is the fraction of tokens NO expert
        chose (they pass through on the residual stream only)."""
        b, t, d = x.shape
        e = self.n_experts
        cd = self.compute_dtype
        capacity = min(capacity, s)  # an expert cannot take a token twice
        # [n, E, S] scores; per-expert top-C over the token axis.
        g_val, g_idx = jax.lax.top_k(
            jnp.moveaxis(probs, -1, 1), capacity
        )  # both [n, E, C]
        dispatch = jax.nn.one_hot(g_idx, s)  # [n, E, C, S]
        # Coverage observability (see docstring).
        chosen = jnp.clip(dispatch.sum((1, 2)), 0.0, 1.0)  # [n, S]
        self.sow(
            "metrics", "moe_uncovered_rate",
            1.0 - jnp.sum(chosen) / float(n_groups * s),
        )
        expert_in = jnp.einsum(
            "necs,nsd->necd", dispatch.astype(cd), tokens.astype(cd)
        )
        expert_in = self._constrain(expert_in, P(None, EXPERT_AXIS, None, None))
        out = self._experts(expert_in, d)
        combine = dispatch * g_val[..., None]  # [n, E, C, S] gated
        mixed = jnp.einsum("necs,necd->nsd", combine.astype(cd), out)
        return mixed.reshape(b, t, d).astype(x.dtype)

    def _experts(self, expert_in, d):
        """The E parallel FFNs over [n, E, C, d] dispatched activations —
        shared by both routers (identical params/layout either way)."""
        cd = self.compute_dtype
        e = self.n_experts
        hidden = self.mlp_ratio * d
        w_up = self.param(
            "moe_up",
            nn.initializers.lecun_normal(batch_axis=(0,)),
            (e, d, hidden),
        )
        w_down = self.param(
            "moe_down",
            nn.initializers.lecun_normal(batch_axis=(0,)),
            (e, hidden, d),
        )
        h = jnp.einsum("necd,edh->nech", expert_in, w_up.astype(cd))
        h = nn.gelu(h)
        out = jnp.einsum("nech,ehd->necd", h, w_down.astype(cd))
        return self._constrain(out, P(None, EXPERT_AXIS, None, None))

    def _n_groups(self, g: int) -> int:
        return dispatch_group_count(g, self.group_size)

    def _constrain(self, v, spec):
        cfg = self.sharding
        if cfg is None or getattr(cfg, "mesh", None) is None:
            return v
        return jax.lax.with_sharding_constraint(
            v, jax.sharding.NamedSharding(cfg.mesh, spec)
        )
