"""int8 quantization: weight-only storage for bandwidth-bound decode, and
an int8 COMPUTE path for compute-bound prefill / large-batch decode.

**Weight-only storage** (`quantize_params` + ``quantized=True`` in the
decode family): autoregressive decode streams every weight once per
generated token (BASELINE.md decode rows: the step is HBM-bound), so
halving weight bytes is a direct tokens/sec lever. Kernels are stored as
int8 with per-output-channel f32 scales; the decode loop dequantizes
INSIDE each scan step, which XLA fuses into the matmul reads — the HBM
stream stays int8.

**int8 compute** (`int8_dot_general` + ``TransformerLM(int8_compute=
True)``): the v5e MXU runs int8×int8→int32 at twice its bf16 rate, which
is the lever for the COMPUTE-bound phase — prompt prefill (1.2–1.44×
measured at d1024–d2048, BASELINE.md). Every Dense matmul quantizes its
activations dynamically (symmetric per-row scales over the contracted
axes, recomputed per call — no calibration data) and its weights
per-output-channel, accumulates in int32 on the MXU, and rescales the
int32 result by the outer product of the two scale vectors. Decode scan
steps are bandwidth-bound and per-step weight requantization measured
SLOWER there, so `make_generate_fn(int8_compute=True)` applies it to
prefill only. Composes with weight-only storage: dequantize → requantize
round-trips onto the same int8 lattice (`_quantize_sym` is the single
lattice definition), so stacking adds no extra quality loss.

Both paths are approximate — outputs can differ from bf16 near argmax
ties — so they are serving knobs, not defaults; tests gate on top-1
agreement with the bf16 path on a trained model. Inference-only: round()
kills gradients, so the model forbids ``int8_compute`` under training.

Usage:
    qparams = quant.quantize_params(trainer.state.params)
    fn = make_generate_fn(model, max_new_tokens=..., quantized=True)
    tokens = fn(qparams, prompt, rng)

    # compute path (prefill / large-batch decode):
    fn = make_generate_fn(model, max_new_tokens=..., int8_compute=True)
    tokens = fn(params, prompt, rng)          # plain bf16/f32 params
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

_Q = "int8_q"


def _is_qleaf(x) -> bool:
    return isinstance(x, dict) and _Q in x


def _quantize_sym(x, axis):
    """THE int8 lattice, in one place: symmetric round-to-nearest with
    amax/127 scales reduced over ``axis`` (keepdims). Shared by the
    storage format (`quantize_params`) and the compute path
    (`int8_dot_general`) — one definition is what makes 'requantization
    round-trips the lattice' a guarantee rather than a coincidence.
    Returns ``(int8 values, f32 scale with keepdims)``."""
    x32 = x.astype(jnp.float32)
    scale = jnp.max(jnp.abs(x32), axis=axis, keepdims=True) / 127.0
    scale = jnp.maximum(scale, 1e-12)
    q = jnp.clip(jnp.round(x32 / scale), -127, 127).astype(jnp.int8)
    return q, scale


def quantize_params(params, *, min_size: int = 4096):
    """Quantize every >=2-D kernel with at least ``min_size`` elements to
    ``{'int8_q': int8, 'scale': f32}`` (symmetric, per-output-channel —
    the last axis); smaller leaves (LayerNorm scales, biases) pass through
    unchanged. The result has the same tree structure with quantized
    leaves replaced by those dicts; `dequantize_params` inverts.
    """

    def q(p):
        if p.ndim < 2 or p.size < min_size:
            return p
        # Reduce over axis 0 only: dequantization is elementwise, so any
        # broadcastable scale shape is valid — finer granularity is
        # strictly lower error. Reducing all leading axes would collapse
        # e.g. a [d, H, hd] qkv kernel's heads into one shared scale per
        # hd channel, starving small-magnitude heads of int8 levels.
        values, scale = _quantize_sym(p, axis=0)
        return {_Q: values, "scale": scale}

    return jax.tree.map(q, params)


def dequantize_params(qparams, dtype=jnp.bfloat16):
    """Reconstruct a plain param tree (``dtype`` compute copies).

    Called INSIDE the decode scan body so the convert+scale fuses into the
    step's matmul reads and the weights live in HBM as int8 — calling it
    outside the loop would materialize full-width weights once and forfeit
    the bandwidth saving.
    """

    def d(x):
        if _is_qleaf(x):
            return x[_Q].astype(dtype) * x["scale"].astype(dtype)
        return x

    return jax.tree.map(d, qparams, is_leaf=_is_qleaf)


def make_unpack(quantized: bool):
    """The decode-family dequant hook: identity for plain param trees,
    `dequantize_params` for quantized ones. Shared by
    decoding/speculative/beam so the dequant contract lives in ONE place —
    each caller invokes it INSIDE its step/loop body (see
    `dequantize_params` on why placement matters)."""
    if quantized:
        return dequantize_params
    return lambda q: q


def int8_dot_general(lhs, rhs, dimension_numbers, precision=None,
                     preferred_element_type=None):
    """Drop-in ``lax.dot_general`` running the contraction on the int8 MXU.

    Dynamic symmetric quantization on both operands: ``lhs`` (activations)
    gets one scale per row — per every non-contracted index, amax over the
    contracted axes, recomputed each call; ``rhs`` (weights) one scale per
    output channel. The int32 MXU accumulation is exact; the only error is
    the two roundings, bounded by each operand's per-row/channel amax/127.
    The result is rescaled by the outer product of the scale vectors in
    f32 and cast back.

    Covers the contraction patterns flax's Dense/DenseGeneral emit (no
    batch dimensions); inject via ``nn.DenseGeneral(dot_general=...)`` —
    how `TransformerLM(int8_compute=True)` wires it.
    """
    (lc, rc), (lb, rb) = dimension_numbers
    if lb or rb:
        raise NotImplementedError(
            "int8_dot_general covers Dense-style contractions (no batch "
            "dims); got batch dimension_numbers "
            f"{dimension_numbers}"
        )
    lc, rc = tuple(lc), tuple(rc)
    out_dtype = preferred_element_type or jnp.result_type(lhs, rhs)

    def q(x, contract_dims):
        xq, s = _quantize_sym(x, axis=contract_dims)
        return xq, jnp.squeeze(s, axis=contract_dims)

    lq, s_l = q(lhs, lc)  # s_l: lhs free dims
    rq, s_r = q(rhs, rc)  # s_r: rhs free dims
    out = lax.dot_general(
        lq, rq, dimension_numbers, preferred_element_type=jnp.int32
    )
    # Output layout (no batch dims): lhs free dims then rhs free dims.
    scale = (
        s_l.reshape(s_l.shape + (1,) * s_r.ndim)
        * s_r.reshape((1,) * s_l.ndim + s_r.shape)
    )
    return (out.astype(jnp.float32) * scale).astype(out_dtype)


def quantized_bytes(qparams) -> int:
    """Total parameter bytes as stored (int8 + scales + passthrough)."""
    total = 0
    for leaf in jax.tree.leaves(qparams):
        total += leaf.size * leaf.dtype.itemsize
    return total
