"""Weight-only int8 quantization for bandwidth-bound decoding.

Autoregressive decode streams every weight once per generated token
(BASELINE.md decode rows: the step is HBM-bound), so halving weight bytes
is a direct tokens/sec lever. This module stores matmul kernels as int8
with per-output-channel f32 scales; the decode loop dequantizes INSIDE
each scan step, which XLA fuses into the matmul reads — the HBM stream
stays int8 (measured on-chip: a 4096² matvec scan runs 1.28× faster with
int8-stored weights; see BASELINE.md for the end-to-end decode row).

Scope: post-training, weight-only (activations stay bf16 — no activation
quantization, no calibration data needed), symmetric with per-channel
scales over every axis but the kernel's first (axis-0 groups).
Quantized generation is approximate — outputs can differ from bf16
decoding near argmax ties — so this is a serving knob, not a default;
tests gate on top-1 agreement with the bf16 path on a trained model.

Usage:
    qparams = quant.quantize_params(trainer.state.params)
    fn = make_generate_fn(model, max_new_tokens=..., quantized=True)
    tokens = fn(qparams, prompt, rng)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_Q = "int8_q"


def _is_qleaf(x) -> bool:
    return isinstance(x, dict) and _Q in x


def quantize_params(params, *, min_size: int = 4096):
    """Quantize every >=2-D kernel with at least ``min_size`` elements to
    ``{'int8_q': int8, 'scale': f32}`` (symmetric, per-output-channel —
    the last axis); smaller leaves (LayerNorm scales, biases) pass through
    unchanged. The result has the same tree structure with quantized
    leaves replaced by those dicts; `dequantize_params` inverts.
    """

    def q(p):
        if p.ndim < 2 or p.size < min_size:
            return p
        p32 = p.astype(jnp.float32)
        # Reduce over axis 0 only: dequantization is elementwise, so any
        # broadcastable scale shape is valid — finer granularity is
        # strictly lower error. Reducing all leading axes would collapse
        # e.g. a [d, H, hd] qkv kernel's heads into one shared scale per
        # hd channel, starving small-magnitude heads of int8 levels.
        scale = jnp.max(jnp.abs(p32), axis=0, keepdims=True) / 127.0
        scale = jnp.maximum(scale, 1e-12)
        return {
            _Q: jnp.clip(jnp.round(p32 / scale), -127, 127).astype(jnp.int8),
            "scale": scale.astype(jnp.float32),
        }

    return jax.tree.map(q, params)


def dequantize_params(qparams, dtype=jnp.bfloat16):
    """Reconstruct a plain param tree (``dtype`` compute copies).

    Called INSIDE the decode scan body so the convert+scale fuses into the
    step's matmul reads and the weights live in HBM as int8 — calling it
    outside the loop would materialize full-width weights once and forfeit
    the bandwidth saving.
    """

    def d(x):
        if _is_qleaf(x):
            return x[_Q].astype(dtype) * x["scale"].astype(dtype)
        return x

    return jax.tree.map(d, qparams, is_leaf=_is_qleaf)


def make_unpack(quantized: bool):
    """The decode-family dequant hook: identity for plain param trees,
    `dequantize_params` for quantized ones. Shared by
    decoding/speculative/beam so the dequant contract lives in ONE place —
    each caller invokes it INSIDE its step/loop body (see
    `dequantize_params` on why placement matters)."""
    if quantized:
        return dequantize_params
    return lambda q: q


def quantized_bytes(qparams) -> int:
    """Total parameter bytes as stored (int8 + scales + passthrough)."""
    total = 0
    for leaf in jax.tree.leaves(qparams):
        total += leaf.size * leaf.dtype.itemsize
    return total
