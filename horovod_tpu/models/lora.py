"""LoRA — low-rank adapter fine-tuning (Hu et al., arXiv:2106.09685).

Fine-tuning a pretrained model updates weights by a low-rank delta most of
the time; LoRA makes that structural: every target kernel ``W [m, n]``
gains adapters ``A [m, r]`` (gaussian) and ``B [r, n]`` (zeros) and the
model runs with ``W + (alpha/r)·A@B``. Only A/B train — optimizer state
shrinks from O(params) to O(r·(m+n)) per kernel, and a fine-tune "run"
is a few-MB adapter file against a frozen base checkpoint.

TPU-native design: no module surgery. `LoRAModel` wraps any flax module;
its param tree is ``{'base': <inner params>, 'lora': <adapters>}`` and the
merge ``W + scale·A@B`` happens **inside the jitted step**, where XLA fuses
it into the consumer matmul's prologue — the base stays untouched in HBM,
and the backward computes adapter gradients from the same dW the full
backward already produces (no extra backward matmuls beyond the rank-r
contractions). Freezing is an optax partition (`freeze_base`): base updates
are `set_to_zero`, so `DistributedOptimizer`/`Trainer`/checkpointing all
see one ordinary param tree — every subsystem (broadcast, EMA, sharded
checkpoints, ZeRO-1) composes untouched.

Capability context: the reference has no fine-tuning story (its scripts
train from scratch, `/root/reference/tensorflow2_keras_mnist.py:96`); this
is a beyond-parity capability every framework at this scale is expected to
ship.

Usage:
    model = LoRAModel(inner=TransformerLM(...), rank=8, alpha=16.0)
    trainer = hvt.Trainer(
        model,
        hvt.DistributedOptimizer(lora.freeze_base(optax.adamw(1e-4))),
        loss="sparse_categorical_crossentropy",
    )
    state = trainer.build(x)
    state = state.replace(params={**state.params, "base": pretrained})
    ... fit ...
    merged = lora.merge_params(state.params)   # plain inner params:
    # serve/decode/export with the ORIGINAL module, adapters folded in —
    # e.g. serving.export_generate(dir, inner, merged, ...) ships the
    # fine-tuned model as an ordinary generation bundle.
"""

from __future__ import annotations

import math
from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

# Default target selection: 2-D+ kernels named like projection/matmul
# weights. Embeddings and norms stay frozen-only (the LoRA paper's recipe).
DEFAULT_TARGETS = (
    "qkv", "q_proj", "kv_proj", "attn_out", "mlp_up", "mlp_down", "lm_head",
)


def _match_fn(targets) -> Callable[[tuple, Any], bool]:
    if callable(targets):
        return targets

    def match(path, leaf) -> bool:
        names = {p.key for p in path if isinstance(p, jax.tree_util.DictKey)}
        return leaf.ndim >= 2 and bool(names & set(targets))

    return match


def init_adapters(rng, params, rank: int, targets=DEFAULT_TARGETS):
    """Adapter tree mirroring ``params``: matched kernels ``[m, ..., n]``
    (flattened to ``[m, prod(rest)]`` for the delta) get
    ``{'a': [m, r] ~ N(0, 1/r), 'b': [r, prod(rest)] = 0}``; everything
    else maps to an empty tuple (no adapter, nothing to train)."""
    match = _match_fn(targets)
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    keys = jax.random.split(rng, max(1, len(flat)))

    def one(key, path, leaf):
        if not match(path, leaf):
            return ()
        m, n = leaf.shape[0], math.prod(leaf.shape[1:])
        a = jax.random.normal(key, (m, rank), jnp.float32) / jnp.sqrt(rank)
        return {"a": a, "b": jnp.zeros((rank, n), jnp.float32)}

    leaves = [one(k, p, l) for k, (p, l) in zip(keys, flat)]
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(params), leaves
    )


def _is_adapter_node(x) -> bool:
    """Stops tree traversal at adapter positions: ``()`` (no adapter) or
    an ``{'a', 'b'}`` pair."""
    if isinstance(x, tuple) and x == ():
        return True
    return isinstance(x, dict) and set(x) == {"a", "b"}


def merge_delta(base, adapters, scale: float):
    """``W + scale · A@B`` per adapted leaf (delta computed in f32, cast to
    the leaf dtype); non-adapted leaves pass through."""

    def one(ab, w):
        if not isinstance(ab, dict):
            return w
        delta = (ab["a"] @ ab["b"]).reshape(w.shape) * scale
        return (w.astype(jnp.float32) + delta).astype(w.dtype)

    return jax.tree.map(one, adapters, base, is_leaf=_is_adapter_node)


def merge_params(params, *, rank: int | None = None, alpha: float = 16.0,
                 scale: float | None = None):
    """Fold a LoRAModel param tree ``{'base', 'lora'}`` into plain inner
    params (for decode/export/serving with the original module). ``scale``
    defaults to ``alpha / rank``; rank is read off the adapters when not
    given."""
    base, adapters = params["base"], params["lora"]
    if scale is None:
        if rank is None:
            rank = next(
                ab["a"].shape[1]
                for ab in jax.tree.leaves(adapters, is_leaf=_is_adapter_node)
                if isinstance(ab, dict)
            )
        scale = alpha / rank
    return merge_delta(base, adapters, scale)


def freeze_base(tx: optax.GradientTransformation) -> optax.GradientTransformation:
    """``tx`` on the ``lora`` subtree, ``set_to_zero`` on ``base`` — the
    optimizer carries state only for the adapters. Wrap the RESULT in
    `DistributedOptimizer` (gradient averaging is orthogonal)."""
    return optax.multi_transform(
        {"train": tx, "freeze": optax.set_to_zero()},
        param_labels=lambda params: {
            k: jax.tree.map(lambda _: "train" if k == "lora" else "freeze", v)
            for k, v in params.items()
        },
    )


class LoRAModel(nn.Module):
    """Any flax module with low-rank adapters on its matmul kernels.

    Param tree: ``{'base': inner params (frozen), 'lora': adapters}``.
    Forward merges ``W + (alpha/rank)·A@B`` in-step and delegates to the
    inner module — `train`/`labels`/`segment_ids` kwargs, dropout rngs, and
    sown 'losses'/'metrics' collections all pass through. Any OTHER mutable
    inner collection (batch-stats-style state, decode caches) rides as one
    wrapper variable holding the whole inner collection dict — collection
    ``inner_state`` — seeded from ``inner.init`` and written back after
    every apply, so the Trainer's ``model_state`` path works through the
    wrap unchanged."""

    inner: nn.Module
    rank: int = 8
    alpha: float = 16.0
    targets: Any = DEFAULT_TARGETS

    @nn.compact
    def __call__(self, *args, **kwargs):
        init_cache = {}

        def _inner_init(rng):
            init_cache["vars"] = self.inner.init(
                {"params": rng, "dropout": rng}, *args, **kwargs
            )
            return init_cache["vars"]["params"]

        base = self.param("base", _inner_init)
        adapters = self.param(
            "lora",
            lambda rng: init_adapters(rng, base, self.rank, self.targets),
        )
        if self.is_initializing():
            # 'intermediates' (and the other sown per-apply channels) must
            # not seed the carry: flax gives them append semantics, so a
            # carried tuple would grow on every mutable apply and change the
            # model_state pytree structure mid-scan.
            extra = {
                k: v
                for k, v in init_cache.get("vars", {}).items()
                if k not in ("params", "losses", "metrics", "intermediates")
            }
            carry = (
                self.variable("inner_state", "collections", lambda: extra)
                if extra
                else None
            )
        else:
            carry = (
                self.variable("inner_state", "collections", dict)
                if self.has_variable("inner_state", "collections")
                else None
            )
        seed = dict(carry.value) if carry is not None else {}
        merged = merge_delta(base, adapters, self.alpha / self.rank)
        rngs = {}
        if self.has_rng("dropout"):
            rngs["dropout"] = self.make_rng("dropout")
        # Inner state is writable only when the outer apply made
        # 'inner_state' mutable: a read-only eval must be read-only for the
        # inner module too (its is_mutable_collection update gates see the
        # truth), and the outer-init forward must NOT advance the freshly
        # seeded inner.init state (the carry keeps inner.init's values).
        state_writable = (
            not self.is_initializing()
            and self.is_mutable_collection("inner_state")
        )
        out, updated = self.inner.apply(
            {"params": merged, **seed}, *args, **kwargs, rngs=rngs,
            mutable=(list(seed) if state_writable else [])
            + ["losses", "metrics"],
        )
        new_state = {k: updated[k] for k in updated if k in seed}
        if carry is not None and new_state and state_writable:
            carry.value = {**seed, **new_state}
        # Re-sow the inner module's auxiliary channels so the Trainer's
        # objective/observability contracts survive the wrap. The sow NAME
        # must be the inner path's final dict key (e.g. 'moe_drop_rate'):
        # the Trainer's metric aggregator groups on it, and same-named sows
        # from different layers append — exactly the inner behavior.
        for col in ("losses", "metrics"):
            for path, leaf in jax.tree_util.tree_flatten_with_path(
                updated.get(col, {})
            )[0]:
                names = [
                    p.key for p in path
                    if isinstance(p, jax.tree_util.DictKey)
                ]
                if names:
                    self.sow(col, names[-1], leaf)
        return out
