"""Encoder-decoder (seq2seq) transformer — the cross-attention model family.

The reference repo has no sequence model at all (SURVEY.md §5.7: fixed
28×28 images, "no sequence dimension"); this framework's model zoo treats
sequence transduction as a first-class family alongside the decoder-only
LM. The architecture is the standard pre-LN encoder-decoder (Vaswani et
al.; T5-style layout with RoPE instead of learned/relative positions):

* **Encoder** — bidirectional (non-causal) self-attention over the source,
  padding masked via the flash kernel's segment ids (pad tokens get id 0,
  real tokens id 1 — segment-disjoint tiles are block-skipped, so a mostly
  padded batch also *costs* less, not just masks more);
* **Decoder** — causal self-attention over the target plus
  **cross-attention** into the encoder memory. Cross-attention is where
  this family earns its place in the test matrix: it exercises the flash
  kernel's Tk ≠ Tq grids (`ops/flash_attention.py` cross-attention
  support) with ``causal=False`` — the path no decoder-only model ever
  takes — including the padding mask riding the same segment-id operands.
  No RoPE on cross q/k: source and target positions are different spaces,
  so cross-attention is position-agnostic (the T5 convention).

Parallelism: data/FSDP batch sharding, Megatron tensor parallelism via
`param_specs` (the same name-keyed column/row rules as the decoder-only
LM, extended with the cross-attention projections), AND sequence/context
parallelism: with a live ``seq`` mesh axis all three attention families
run as ring collectives — the encoder's bidirectional segmented
self-attention and the decoder's causal self-attention through
`ring_flash_attention`, cross-attention through `ring_cross_attention`
(queries and memory sharded over DIFFERENT logical sequences; the memory
blocks and their padding ids rotate around the ring). Decode mode is the
one seq-parallel refusal: a single-token step has no sequence to shard.

Inference (`make_seq2seq_generate_fn`): encode once, then the whole
autoregressive decode — BOS prefill + `lax.scan` of single-token steps —
runs as ONE compiled program, mirroring `models/decoding.py`. The decoder
keeps two caches per block: the usual growing self-attention K/V cache,
and a **static cross K/V cache** computed from the memory once at prefill
(the per-layer cross projections of a fixed memory are loop-invariant; a
naive per-step recompute would stream the memory through two matmuls for
every generated token).
"""

from __future__ import annotations

import functools

import flax.linen as nn
import jax

from horovod_tpu import compat
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from horovod_tpu.models.transformer import (
    BATCH_AXES,
    ShardingConfig,
    _rope,
)
from horovod_tpu.ops import attention as attention_ops
from horovod_tpu.parallel.mesh import MODEL_AXIS, SEQ_AXIS

_NEG = -1e30


def _attention(cfg: ShardingConfig, q, k, v, *, causal: bool,
               q_ids=None, kv_ids=None, cross: bool = False):
    """One attention dispatch for all three seq2seq call sites.

    Without a live ``seq`` axis: the flash kernel locally, shard_mapped
    over the mesh exactly like `transformer.Block` (GSPMD cannot
    auto-partition a Mosaic custom call; attention mixes neither batch nor
    heads, so manual batch/head sharding is free). With sequence
    parallelism: the ring collectives — `ring_flash_attention` for the
    encoder's non-causal segmented self-attention and the decoder's causal
    self-attention, `ring_cross_attention` for cross-attention (queries
    and memory sharded over DIFFERENT logical sequences; kv ids rotate
    with their blocks, q ids stay local)."""
    from horovod_tpu.ops.flash_attention import flash_attention

    if cfg.seq_parallel:
        if cfg.attn != "ring":
            raise ValueError(
                "sequence-parallel Seq2SeqTransformer supports attn='ring' "
                f"only (got {cfg.attn!r}) — the dense/Ulysses paths are "
                "decoder-only territory"
            )
        qspec = P(BATCH_AXES, SEQ_AXIS, MODEL_AXIS, None)
        ids_spec = P(BATCH_AXES, SEQ_AXIS)
        if cross:
            fn = lambda q, k, v, qi, ki: attention_ops.ring_cross_attention(  # noqa: E731
                q, k, v, axis_name=SEQ_AXIS,
                q_segment_ids=qi, kv_segment_ids=ki,
            )
            return compat.shard_map(
                fn, mesh=cfg.mesh,
                in_specs=(qspec, qspec, qspec, ids_spec, ids_spec),
                out_specs=qspec, check_vma=False,
            )(q, k, v, q_ids, kv_ids)
        if q_ids is not None:
            # Encoder self-attention: q and kv ids are the SAME shard —
            # ring_flash_attention takes one segment_ids for both sides, so
            # a future asymmetric-mask caller must not silently lose kv_ids
            # here (every other path honors the two independently).
            if q_ids is not kv_ids:
                raise ValueError(
                    "sequence-parallel self-attention needs q_ids and "
                    "kv_ids to be the same array (asymmetric masks are "
                    "cross=True territory)"
                )
            fn = lambda q, k, v, ids: attention_ops.ring_flash_attention(  # noqa: E731
                q, k, v, axis_name=SEQ_AXIS, causal=causal, segment_ids=ids
            )
            return compat.shard_map(
                fn, mesh=cfg.mesh,
                in_specs=(qspec, qspec, qspec, ids_spec),
                out_specs=qspec, check_vma=False,
            )(q, k, v, q_ids)
        fn = lambda q, k, v: attention_ops.ring_flash_attention(  # noqa: E731
            q, k, v, axis_name=SEQ_AXIS, causal=causal
        )
        return compat.shard_map(
            fn, mesh=cfg.mesh, in_specs=(qspec, qspec, qspec),
            out_specs=qspec, check_vma=False,
        )(q, k, v)

    if cfg.attn == "dense":
        return attention_ops.dense_attention(
            q, k, v, causal=causal, q_segment_ids=q_ids, kv_segment_ids=kv_ids
        )

    def local(q, k, v, q_ids=None, kv_ids=None):
        return flash_attention(
            q, k, v, causal=causal, q_segment_ids=q_ids, kv_segment_ids=kv_ids
        )

    args = (q, k, v)
    if q_ids is not None:
        args += (q_ids, kv_ids)
    if cfg.mesh is not None and cfg.mesh.size > 1:
        spec = P(BATCH_AXES, None, MODEL_AXIS, None)
        in_specs = (spec, spec, spec)
        if q_ids is not None:
            in_specs += (P(BATCH_AXES, None), P(BATCH_AXES, None))
        local = compat.shard_map(
            local, mesh=cfg.mesh, in_specs=in_specs, out_specs=spec,
            check_vma=False,
        )
    return local(*args)


class EncoderBlock(nn.Module):
    d_model: int
    n_heads: int
    dropout: float
    compute_dtype: jnp.dtype
    sharding: ShardingConfig

    @nn.compact
    def __call__(self, x, positions, src_valid, train: bool = False):
        cfg = self.sharding
        head_dim = self.d_model // self.n_heads
        dense = functools.partial(
            nn.DenseGeneral, dtype=self.compute_dtype, use_bias=False
        )

        h = nn.LayerNorm(dtype=self.compute_dtype, use_bias=False)(x)
        qkv = dense(features=(self.n_heads, 3 * head_dim), name="qkv")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q, k = _rope(q, positions), _rope(k, positions)
        # Bidirectional self-attention; pad positions (id 0) are disjoint
        # from REAL tokens (id 1), so no real position ever sees a pad.
        # Pad queries still see each other (segment masking is equality-
        # based), so pad rows of the memory are garbage — harmless only
        # because the cross-attention mask drops them downstream; any new
        # consumer of the memory (e.g. mean-pooling) must mask too.
        out = _attention(
            cfg, q, k, v, causal=False, q_ids=src_valid, kv_ids=src_valid
        )
        out = dense(features=self.d_model, axis=(-2, -1), name="attn_out")(out)
        out = nn.Dropout(self.dropout, deterministic=not train)(out)
        x = x + out
        x = cfg.constrain(x, P(BATCH_AXES, SEQ_AXIS, None))

        h = nn.LayerNorm(dtype=self.compute_dtype, use_bias=False)(x)
        h = dense(features=4 * self.d_model, name="mlp_up")(h)
        h = nn.gelu(h)
        h = dense(features=self.d_model, name="mlp_down")(h)
        h = nn.Dropout(self.dropout, deterministic=not train)(h)
        return cfg.constrain(x + h, P(BATCH_AXES, SEQ_AXIS, None))


class DecoderBlock(nn.Module):
    d_model: int
    n_heads: int
    dropout: float
    compute_dtype: jnp.dtype
    sharding: ShardingConfig
    # Autoregressive inference: self-attention K/V live in a growing
    # [B, max_decode_len, H, D] cache; cross K/V in a static [B, S, H, D]
    # cache written once at prefill (see module docstring).
    decode: bool = False
    max_decode_len: int = 0

    @nn.compact
    def __call__(self, x, positions, memory, mem_valid, train: bool = False,
                 decode_index=None):
        cfg = self.sharding
        head_dim = self.d_model // self.n_heads
        dense = functools.partial(
            nn.DenseGeneral, dtype=self.compute_dtype, use_bias=False
        )

        # --- causal self-attention ----------------------------------------
        h = nn.LayerNorm(dtype=self.compute_dtype, use_bias=False)(x)
        qkv = dense(features=(self.n_heads, 3 * head_dim), name="qkv")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q, k = _rope(q, positions), _rope(k, positions)
        if self.decode:
            out = self._cached_self_attention(q, k, v, decode_index)
        else:
            out = _attention(cfg, q, k, v, causal=True)
        out = dense(features=self.d_model, axis=(-2, -1), name="attn_out")(out)
        out = nn.Dropout(self.dropout, deterministic=not train)(out)
        x = x + out
        x = cfg.constrain(x, P(BATCH_AXES, SEQ_AXIS, None))

        # --- cross-attention into the encoder memory ----------------------
        h = nn.LayerNorm(dtype=self.compute_dtype, use_bias=False)(x)
        q = dense(features=(self.n_heads, head_dim), name="cross_q")(h)
        if self.decode:
            out = self._cached_cross_attention(q, memory, mem_valid, dense)
        else:
            kv = dense(features=(self.n_heads, 2 * head_dim), name="cross_kv")(
                memory
            )
            ck, cv = jnp.split(kv, 2, axis=-1)
            # Tq = target length, Tk = source length — the kernel's
            # cross-attention grids. Non-causal: every target position sees
            # the whole (unpadded) source. Query ids are the constant 1, so
            # the mask reduces to the source-side padding mask.
            q_ids = jnp.ones(q.shape[:2], jnp.int32)
            out = _attention(
                cfg, q, ck, cv, causal=False, q_ids=q_ids, kv_ids=mem_valid,
                cross=True,
            )
        out = dense(features=self.d_model, axis=(-2, -1), name="cross_out")(out)
        out = nn.Dropout(self.dropout, deterministic=not train)(out)
        x = x + out
        x = cfg.constrain(x, P(BATCH_AXES, SEQ_AXIS, None))

        # --- MLP -----------------------------------------------------------
        h = nn.LayerNorm(dtype=self.compute_dtype, use_bias=False)(x)
        h = dense(features=4 * self.d_model, name="mlp_up")(h)
        h = nn.gelu(h)
        h = dense(features=self.d_model, name="mlp_down")(h)
        h = nn.Dropout(self.dropout, deterministic=not train)(h)
        return cfg.constrain(x + h, P(BATCH_AXES, SEQ_AXIS, None))

    def _cached_self_attention(self, q, k, v, decode_index):
        """Growing-cache causal self-attention (the full-history layout of
        `transformer.Block._decode_attention`, MHA-only): prefill writes
        [0:T] and attends causally over the fresh K/V; a decode step writes
        at ``decode_index`` and attends densely over the valid prefix."""
        cfg = self.sharding
        b, t, h, d = q.shape
        if self.max_decode_len < t:
            raise ValueError(
                f"max_decode_len ({self.max_decode_len}) < input length ({t})"
            )
        cache_spec = P(BATCH_AXES, None, MODEL_AXIS, None)
        first_call = not self.has_variable("cache", "k")
        zeros = lambda: jnp.zeros(  # noqa: E731
            (b, self.max_decode_len, h, d), self.compute_dtype
        )
        ck = self.variable("cache", "k", zeros)
        cv = self.variable("cache", "v", zeros)
        idx = jnp.asarray(decode_index, jnp.int32)
        ck.value = cfg.constrain(
            lax.dynamic_update_slice(
                ck.value, k.astype(ck.value.dtype), (0, idx, 0, 0)
            ),
            cache_spec,
        )
        cv.value = cfg.constrain(
            lax.dynamic_update_slice(
                cv.value, v.astype(cv.value.dtype), (0, idx, 0, 0)
            ),
            cache_spec,
        )
        if t > 1 and first_call:
            return _attention(cfg, q, k, v, causal=True)
        scale = d ** -0.5
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", q, ck.value,
            preferred_element_type=jnp.float32,
        ) * scale
        qpos = idx + jnp.arange(t, dtype=jnp.int32)
        kpos = jnp.arange(self.max_decode_len, dtype=jnp.int32)
        valid = kpos[None, :] <= qpos[:, None]
        s = jnp.where(valid[None, None], s, _NEG)
        p = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum(
            "bhqk,bkhd->bqhd", p.astype(cv.value.dtype), cv.value,
            preferred_element_type=jnp.float32,
        )
        return out.astype(q.dtype)

    def _cached_cross_attention(self, q, memory, mem_valid, dense):
        """Cross-attention against the static per-layer cross K/V cache.

        The cross projections of a fixed memory are loop-invariant, so they
        are computed ONCE — on the first (prefill) call, when the cache
        variables don't exist yet — and every decode step reads the cached
        [B, S, H, D] arrays instead of re-streaming the memory through two
        matmuls per token."""
        cfg = self.sharding
        head_dim = self.d_model // self.n_heads
        first_call = not self.has_variable("cache", "cross_k")

        if first_call:
            kv = dense(features=(self.n_heads, 2 * head_dim), name="cross_kv")(
                memory
            )
            k_new, v_new = jnp.split(kv, 2, axis=-1)
        else:
            # Decode steps never touch the cross_kv weights (that is the
            # point of the static cache); apply() reads params lazily, so
            # the unused entries in the provided tree are harmless.
            k_new = v_new = None
        ck = self.variable("cache", "cross_k", lambda: k_new)
        cv = self.variable("cache", "cross_v", lambda: v_new)
        k, v = ck.value, cv.value

        scale = head_dim ** -0.5
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
        ) * scale
        s = jnp.where(mem_valid.astype(bool)[:, None, None, :], s, _NEG)
        p = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum(
            "bhqk,bkhd->bqhd", p.astype(v.dtype), v,
            preferred_element_type=jnp.float32,
        )
        return out.astype(q.dtype)


class Encoder(nn.Module):
    vocab_size: int
    d_model: int
    n_heads: int
    n_layers: int
    dropout: float
    compute_dtype: jnp.dtype
    sharding: ShardingConfig
    pad_id: int

    @nn.compact
    def __call__(self, src, train: bool = False):
        cfg = self.sharding
        b, s = src.shape
        src_valid = (src != self.pad_id).astype(jnp.int32)
        positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
        x = nn.Embed(
            self.vocab_size, self.d_model, dtype=self.compute_dtype,
            name="embed",
        )(src)
        x = cfg.constrain(x, P(BATCH_AXES, SEQ_AXIS, None))
        for i in range(self.n_layers):
            x = EncoderBlock(
                self.d_model, self.n_heads, self.dropout, self.compute_dtype,
                cfg, name=f"Block_{i}",
            )(x, positions, src_valid, train)
        x = nn.LayerNorm(dtype=self.compute_dtype, use_bias=False)(x)
        return x, src_valid


class Decoder(nn.Module):
    vocab_size: int
    d_model: int
    n_heads: int
    n_layers: int
    dropout: float
    compute_dtype: jnp.dtype
    sharding: ShardingConfig
    logits_dtype: jnp.dtype
    decode: bool = False
    max_decode_len: int = 0

    @nn.compact
    def __call__(self, tgt, memory, mem_valid, train: bool = False):
        cfg = self.sharding
        b, t = tgt.shape
        decode_index = None
        if self.decode:
            idx_var = self.variable(
                "cache", "index", lambda: jnp.zeros((), jnp.int32)
            )
            decode_index = idx_var.value
            positions = decode_index + jnp.broadcast_to(
                jnp.arange(t, dtype=jnp.int32), (b, t)
            )
            idx_var.value = decode_index + t
        else:
            positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
        x = nn.Embed(
            self.vocab_size, self.d_model, dtype=self.compute_dtype,
            name="embed",
        )(tgt)
        x = cfg.constrain(x, P(BATCH_AXES, SEQ_AXIS, None))
        for i in range(self.n_layers):
            x = DecoderBlock(
                self.d_model, self.n_heads, self.dropout, self.compute_dtype,
                cfg, decode=self.decode, max_decode_len=self.max_decode_len,
                name=f"Block_{i}",
            )(x, positions, memory, mem_valid, train, decode_index)
        x = nn.LayerNorm(dtype=self.compute_dtype, use_bias=False)(x)
        logits = nn.DenseGeneral(
            features=self.vocab_size, dtype=self.compute_dtype,
            use_bias=False, name="lm_head",
        )(x)
        return logits.astype(self.logits_dtype)


class Seq2SeqTransformer(nn.Module):
    """Sequence-to-sequence transduction: ``{'src': [B,S], 'tgt': [B,T]} ->
    [B, T, vocab]`` teacher-forced logits.

    The training batch is a dict so the model plugs into `Trainer`
    unchanged (`shard_batch` tree-maps over pytree inputs): ``tgt`` is the
    decoder INPUT (BOS-prefixed, one position ahead of the labels); the
    caller supplies the shifted labels as ``y``. Source and target share
    one vocabulary id space but have separate embedding tables (the src/tgt
    distributional asymmetry of translation-style tasks).
    """

    vocab_size: int = 256
    d_model: int = 256
    n_heads: int = 8
    n_enc_layers: int = 4
    n_dec_layers: int = 4
    dropout: float = 0.1
    compute_dtype: jnp.dtype = jnp.float32
    sharding: ShardingConfig = ShardingConfig()
    logits_dtype: jnp.dtype = jnp.float32
    pad_id: int = 0
    decode: bool = False
    max_decode_len: int = 0

    def setup(self):
        cfg = self.sharding
        if cfg.seq_parallel and self.decode:
            # Training/eval run sequence-parallel (ring attention across
            # all three call sites); autoregressive DECODE does not — a
            # single-token step has no sequence to shard. Refuse loudly
            # rather than silently replicate (the house convention).
            raise ValueError(
                "seq2seq decode mode does not compose with a live 'seq' "
                "axis — generate on a mesh without sequence parallelism"
            )
        self.encoder = Encoder(
            self.vocab_size, self.d_model, self.n_heads, self.n_enc_layers,
            self.dropout, self.compute_dtype, self.sharding, self.pad_id,
        )
        self.decoder = Decoder(
            self.vocab_size, self.d_model, self.n_heads, self.n_dec_layers,
            self.dropout, self.compute_dtype, self.sharding,
            self.logits_dtype, decode=self.decode,
            max_decode_len=self.max_decode_len,
        )

    def __call__(self, batch, train: bool = False):
        memory, src_valid = self.encoder(batch["src"], train)
        return self.decoder(batch["tgt"], memory, src_valid, train)

    def encode(self, src, train: bool = False):
        return self.encoder(src, train)

    def decode_tokens(self, tgt, memory, src_valid, train: bool = False):
        return self.decoder(tgt, memory, src_valid, train)


def param_specs(params, mesh):
    """Megatron TP (+FSDP) PartitionSpecs for the seq2seq layout — the
    decoder-only LM's name-keyed rules plus the cross-attention
    projections (column-parallel q/kv, row-parallel output)."""
    from horovod_tpu.models import transformer as tlib

    return tlib.param_specs(
        params, mesh,
        extra_tp_dim={
            "cross_q": 1,    # [dm, H, hd]    — heads (column-parallel)
            "cross_kv": 1,   # [dm, H, 2·hd]  — heads (column-parallel)
            "cross_out": 0,  # [H, hd, dm]    — heads (row-parallel)
        },
    )


def make_seq2seq_generate_fn(model: Seq2SeqTransformer, *,
                             max_new_tokens: int, bos_id: int,
                             temperature: float = 0.0, top_k: int = 0,
                             top_p: float = 0.0, eos_id: int | None = None):
    """Build the compiled seq2seq generator: ``(params, src, rng) ->
    tokens [B, max_new_tokens]``.

    Encode + BOS prefill + the whole decode `lax.scan` in ONE jitted
    program (the `models/decoding.py` single-dispatch discipline). After a
    row emits ``eos_id`` its remaining positions fill with it.
    """
    from horovod_tpu.models.decoding import _sample

    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")

    def run(params, src, rng):
        src = src.astype(jnp.int32)
        b = src.shape[0]
        dmodel = model.clone(
            decode=True, max_decode_len=max_new_tokens, dropout=0.0
        )
        memory, src_valid = dmodel.apply(
            {"params": params}, src, method=Seq2SeqTransformer.encode
        )
        bos = jnp.full((b, 1), bos_id, jnp.int32)
        logits, vars_ = dmodel.apply(
            {"params": params}, bos, memory, src_valid,
            method=Seq2SeqTransformer.decode_tokens, mutable=["cache"],
        )
        rng, sub = jax.random.split(rng)
        tok = _sample(logits[:, -1], sub, temperature, top_k, top_p)
        done = jnp.zeros((b,), bool) if eos_id is None else tok == eos_id
        fill = jnp.int32(0 if eos_id is None else eos_id)

        def body(carry, _):
            cache, tok, rng, done = carry
            step_logits, step_vars = dmodel.apply(
                {"params": params, "cache": cache}, tok[:, None], memory,
                src_valid, method=Seq2SeqTransformer.decode_tokens,
                mutable=["cache"],
            )
            rng, sub = jax.random.split(rng)
            nxt = _sample(step_logits[:, -1], sub, temperature, top_k, top_p)
            nxt = jnp.where(done, fill, nxt)
            new_done = done if eos_id is None else done | (nxt == eos_id)
            return (step_vars["cache"], nxt, rng, new_done), nxt

        (_, _, _, _), rest = lax.scan(
            body, (vars_["cache"], tok, rng, done), None,
            length=max_new_tokens - 1,
        )
        return jnp.concatenate([tok[:, None], jnp.moveaxis(rest, 0, 1)], axis=1)

    return jax.jit(run)
