"""The reference MNIST CNN, rebuilt in flax.

Architecture parity (tensorflow2_keras_mnist.py:43-52 == mnist_keras.py:71-81):
Conv2D(32,3x3,relu) → Conv2D(64,3x3,relu) → MaxPool(2x2) → Dropout(.25)
→ Flatten → Dense(128,relu) → Dropout(.5) → Dense(10).

TPU-first deviations (numerics-preserving):
* Outputs **logits**, not softmax probabilities — losses use the fused
  logsumexp path (stabler and fuses into one XLA kernel); softmax is applied
  at predict/export time so the serving signature still maps input→prob
  (mnist_keras.py:133-134).
* Compute dtype is configurable (bfloat16 by default on TPU) with float32
  params — MXU-friendly without changing the training math materially.
* VALID padding, NHWC, exactly as Keras defaults gave the reference.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class MnistCNN(nn.Module):
    num_classes: int = 10
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        if jnp.issubdtype(x.dtype, jnp.integer):
            # Raw uint8 pixels: normalize on device. Feeding bytes instead of
            # host-normalized float32 quarters the host->device traffic and
            # the divide fuses into the first conv; numerics match the
            # reference's host-side /255 (both float32 before the cast).
            x = x.astype(jnp.float32) / 255.0
        x = x.astype(self.compute_dtype)
        x = nn.Conv(32, (3, 3), padding="VALID", dtype=self.compute_dtype)(x)
        x = nn.relu(x)
        x = nn.Conv(64, (3, 3), padding="VALID", dtype=self.compute_dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Dropout(0.25, deterministic=not train)(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128, dtype=self.compute_dtype)(x)
        x = nn.relu(x)
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.compute_dtype)(x)
        return x.astype(jnp.float32)
