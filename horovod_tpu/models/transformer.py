"""Decoder-only transformer LM — the long-context / model-parallel flagship.

The reference never goes past a 2-conv MNIST CNN (SURVEY.md §5.7: no
sequence axis anywhere), but this framework treats long-context and
multi-axis parallelism as first-class. This model composes every mesh axis:

* ``data``/``fsdp`` — batch sharding (+ optional parameter sharding);
* ``seq``  — sequence/context parallelism: activations sharded along the
  token axis; attention runs as ring or Ulysses collectives (ops/attention)
  inside a *partially-manual* `jax.shard_map` over only the ``seq`` axis,
  leaving batch/TP sharding to the compiler;
* ``model`` — tensor parallelism: QKV/MLP-up kernels column-sharded,
  proj/MLP-down row-sharded (Megatron layout) via sharding constraints the
  compiler turns into a single allreduce per residual join.

Architecture: pre-LN blocks, RoPE positions (sequence-length extensible —
what a long-context model wants), GELU MLP at 4×, tied-free LM head, logits
in float32 by default (``logits_dtype=bfloat16`` halves long-sequence HBM;
the named Trainer losses upcast to f32 on the fly — a custom callable loss
must do its own upcasting).

`param_specs(params, mesh)` gives the explicit PartitionSpec tree for the
TP/FSDP layout — path-based rules, no boxed-metadata machinery, so any
optimizer/checkpoint code sees plain arrays.
"""

from __future__ import annotations

import dataclasses
import functools

import flax.linen as nn
import jax

from horovod_tpu import compat
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.ops import attention as attention_ops, fused_ce
from horovod_tpu.parallel.mesh import (
    DATA_AXIS,
    EXPERT_AXIS,
    FSDP_AXIS,
    MODEL_AXIS,
    SEQ_AXIS,
)

BATCH_AXES = (DATA_AXIS, FSDP_AXIS)


def _rope(x, positions, *, base: float = 10000.0):
    """Rotary position embedding on [B, T, H, D] with global positions."""
    d = x.shape[-1]
    half = d // 2
    freqs = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[:, :, None, None].astype(jnp.float32) * freqs  # [B,T,1,half]
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    )
    return rotated.astype(x.dtype)


def packed_positions(segment_ids):
    """[B, T] within-document positions for contiguous-run packing: token i's
    position is its offset from the start of its run, so RoPE treats each
    packed document as starting at 0 (matching how the documents would embed
    unpacked)."""
    b, t = segment_ids.shape
    ar = jnp.arange(t, dtype=jnp.int32)
    changed = jnp.concatenate(
        [
            jnp.ones((b, 1), bool),
            segment_ids[:, 1:] != segment_ids[:, :-1],
        ],
        axis=1,
    )
    starts = jax.lax.cummax(jnp.where(changed, ar[None, :], 0), axis=1)
    return ar[None, :] - starts


@dataclasses.dataclass(frozen=True)
class ShardingConfig:
    """How the model meets the mesh.

    ``attn``: ``'ring'`` (sequence-parallel ring attention, flash-kernel
    block compute), ``'ring_dense'`` (ring with dense per-hop scores — the
    numerics ground truth), ``'ulysses'`` (all-to-all head swap), or
    ``'dense'`` (materialized-score attention, the numerics reference —
    NOT flash; on a mesh without a live ``seq`` axis the 'ring'/'ulysses'
    settings take the local flash-kernel path instead)."""

    mesh: Mesh | None = None
    attn: str = "ring"

    @property
    def seq_parallel(self) -> bool:
        return self.mesh is not None and self.mesh.shape.get(SEQ_AXIS, 1) > 1

    def constrain(self, x, spec: P):
        if self.mesh is None:
            return x
        return jax.lax.with_sharding_constraint(
            x, jax.sharding.NamedSharding(self.mesh, spec)
        )


class Block(nn.Module):
    d_model: int
    n_heads: int
    dropout: float
    compute_dtype: jnp.dtype
    sharding: ShardingConfig
    # Grouped-query attention (GQA, arXiv:2305.13245): n_kv_heads < n_heads
    # shares each K/V head across n_heads/n_kv_heads query heads. Training
    # repeats K/V up to H after projection (the FLOPs are identical; the
    # win is the decode cache at [B, L, H_kv, D] — 1/group of the MHA
    # bytes streamed per generated token, which is what bandwidth-bound
    # decode pays for). None = MHA (the fused qkv projection, param-layout
    # compatible with existing checkpoints).
    n_kv_heads: int | None = None
    # Sliding-window attention (Mistral-style local attention,
    # arXiv:2310.06825): each query sees only its `window` most recent
    # keys. The flash kernel block-skips tiles outside the band (FLOPs
    # scale with T·window, not T²/2), the ring variant skips whole
    # out-of-band hops, and the decode path masks the stale cache prefix.
    # Window counts ROW positions (token distance within a packed row),
    # composing with segment masking by intersection. None = full causal.
    window: int | None = None
    # MoE (expert-parallel) MLP instead of the dense one: the EP capability,
    # routed over the mesh's `expert` axis (models/moe.py).
    use_moe: bool = False
    n_experts: int = 8
    moe_k: int = 2
    capacity_factor: float = 1.25
    moe_aux_coef: float = 1e-2
    # 'top_k' or 'expert_choice' (drop-free, training-only — see
    # models/moe.py module docstring).
    moe_router: str = "top_k"
    # Autoregressive inference (models/decoding.py): K/V for past tokens live
    # in a ``cache`` variable collection sized [B, max_decode_len, H_kv, D]
    # (H_kv == n_kv_heads, == H for MHA).
    decode: bool = False
    max_decode_len: int = 0
    # Streaming decode (requires ``window``): the cache is a [B, window,
    # H_kv, D] RING BUFFER (slot = position mod window) instead of the full
    # [B, max_decode_len, ...] history — O(window) memory and O(window)
    # cache reads per generated token however long the generation runs.
    # Exact: a windowed query never needs anything the ring has evicted.
    sliding_cache: bool = False
    # int8 MXU compute for Dense matmuls (inference-only; see
    # models/quant.int8_dot_general — dynamic activation scales,
    # per-channel weight scales, int32 accumulation).
    int8_compute: bool = False
    # int8 KV cache (decode): K/V stored as int8 with per-(position, head)
    # f32 scales — the cache stream halves (it was ~a third of decode HBM
    # traffic at MHA shapes) and so does cache HBM, doubling the context
    # envelope per chip. Scales factor OUT of the head-dim contraction, so
    # the decode einsums read int8 directly and apply scales to the
    # [.., L]-shaped scores/probs — no dequantized [B, L, H, D] copy
    # exists even transiently. Approximate (two 127-level roundings);
    # quality-gated like the weight paths (models/quant.py).
    quantized_cache: bool = False
    # Attention sinks (StreamingLLM, arXiv:2309.17453 / Longformer-style
    # global+local): the first `attention_sinks` positions stay visible —
    # and, with sliding_cache, pinned in the cache — in addition to the
    # window band. A first-class mask, consistent across training, eval,
    # prefill, chunk extension and decode (sinks+band everywhere), so a
    # model can be TRAINED global+local and streamed exactly; cloning a
    # densely-trained model with (window, attention_sinks, sliding_cache)
    # for generation is the approximate StreamingLLM recipe. Sink-masked
    # forwards run the flash kernel (a pinned sink tile per q block —
    # O(T·(window+sinks)); dense fallback when the tiling doesn't hold)
    # and compose with sequence parallelism: the flash ring adds a dense
    # sink contribution on the hop holding global block 0, Ulysses passes
    # them to its local kernel (the dense-block ring refuses).
    attention_sinks: int = 0

    @nn.compact
    def __call__(self, x, positions, train: bool = False, segment_ids=None,
                 decode_index=None):
        cfg = self.sharding
        head_dim = self.d_model // self.n_heads
        dense_kw = {}
        if self.int8_compute:
            from horovod_tpu.models.quant import int8_dot_general

            dense_kw["dot_general"] = int8_dot_general
        dense = functools.partial(
            nn.DenseGeneral, dtype=self.compute_dtype, use_bias=False,
            **dense_kw,
        )

        # --- attention -----------------------------------------------------
        h = nn.LayerNorm(dtype=self.compute_dtype, use_bias=False)(x)
        h_kv = self.n_kv_heads or self.n_heads
        if self.n_heads % h_kv != 0:
            raise ValueError(
                f"n_heads ({self.n_heads}) must be a multiple of "
                f"n_kv_heads ({h_kv})"
            )
        rep = self.n_heads // h_kv
        # Explicit names: param_specs keys its TP rules on them, so layer
        # additions/reorderings can't silently re-shard the wrong kernel.
        if rep == 1:
            qkv_shape = (self.n_heads, 3 * head_dim)
            qkv = dense(features=qkv_shape, name="qkv")(h)  # [B,T,H,3D] — column-parallel
            q, k, v = jnp.split(qkv, 3, axis=-1)
        else:
            q = dense(features=(self.n_heads, head_dim), name="q_proj")(h)
            kv = dense(features=(h_kv, 2 * head_dim), name="kv_proj")(h)
            k, v = jnp.split(kv, 2, axis=-1)  # [B, T, H_kv, D]
        q, k = _rope(q, positions), _rope(k, positions)

        if cfg.mesh is not None:
            model_par = cfg.mesh.shape.get(MODEL_AXIS, 1)
            if self.n_heads % model_par != 0:
                raise ValueError(
                    f"n_heads ({self.n_heads}) must divide over the model "
                    f"axis ({model_par}) for sharded attention"
                )
            if h_kv % model_par != 0:
                raise ValueError(
                    f"n_kv_heads ({h_kv}) must divide over the model axis "
                    f"({model_par}) — the kv projection and decode cache "
                    f"shard their head dim"
                )

        if self.decode:
            out = self._decode_attention(q, k, v, decode_index)
            out = dense(features=self.d_model, axis=(-2, -1), name="attn_out")(out)
            x = x + out
            h = nn.LayerNorm(dtype=self.compute_dtype, use_bias=False)(x)
            h = self._mlp(h, dense, train=False)
            return x + h

        if rep > 1:
            # Training/prefill attention runs at full H: repeating K/V heads
            # keeps q-head i paired with kv-head i // rep under any TP
            # sharding (contiguous H/tp slices of the repeated layout align
            # with the q slices). The repeat is XLA-fused into the attention
            # consumers; the cache (decode path above) never stores it.
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)

        if self.attention_sinks:
            if self.window is None:
                raise ValueError(
                    "attention_sinks is the global+local mask's global "
                    "part — it needs window set (full causal attention "
                    "already sees every sink)"
                )
            if cfg.seq_parallel and cfg.attn == "ring_dense":
                raise ValueError(
                    "sinks need attn='ring' or 'ulysses' — the dense-block "
                    "ring is sink-unaware"
                )
        if cfg.seq_parallel:
            impls = {
                "ring": attention_ops.ring_flash_attention,
                "ring_dense": attention_ops.ring_attention,
                "ulysses": attention_ops.ulysses_attention,
            }
            if cfg.attn not in impls:
                raise ValueError(
                    f"sequence-parallel attention needs attn in {sorted(impls)}, "
                    f"got {cfg.attn!r}"
                )
            if segment_ids is not None and cfg.attn == "ring_dense":
                raise ValueError(
                    "packed sequences (segment_ids) need attn='ring' or "
                    "'ulysses' — the dense-block ring is segment-unaware"
                )
            # Fully-manual region: batch stays split over data/fsdp, heads
            # over model (attention never mixes batch or heads, so manual
            # sharding there is free); the seq axis is the collective one.
            # The segment ids (when packing) shard with the tokens; ring
            # rotates the kv ids, Ulysses all-gathers them (ops/attention).
            spec = P(BATCH_AXES, SEQ_AXIS, MODEL_AXIS, None)
            impl_kw = dict(
                axis_name=SEQ_AXIS, causal=True, window=self.window
            )
            if self.attention_sinks:
                impl_kw["sinks"] = self.attention_sinks
            impl = functools.partial(impls[cfg.attn], **impl_kw)
            if segment_ids is None:
                fn, args, in_specs = impl, (q, k, v), (spec, spec, spec)
            else:
                fn = lambda q, k, v, ids: impl(q, k, v, segment_ids=ids)  # noqa: E731
                args = (q, k, v, segment_ids)
                in_specs = (spec, spec, spec, P(BATCH_AXES, SEQ_AXIS))
            out = compat.shard_map(
                fn, mesh=cfg.mesh, in_specs=in_specs, out_specs=spec,
                check_vma=False,
            )(*args)
        elif cfg.attn == "dense":
            out = attention_ops.dense_attention(
                q, k, v, causal=True, window=self.window,
                sinks=self.attention_sinks,
                q_segment_ids=segment_ids, kv_segment_ids=segment_ids,
            )
        else:
            # Local path: the pallas flash kernel (O(T) memory, ~2-3x over
            # XLA's materialized attention on v5e; falls back to dense when
            # its tiling doesn't hold, interprets off-TPU). GSPMD cannot
            # auto-partition a Mosaic custom call, so on a multi-device mesh
            # it runs in a fully-manual shard_map (batch over data/fsdp,
            # heads over model — attention mixes neither).
            from horovod_tpu.ops.flash_attention import flash_attention

            # sinks ride the kernel's pinned sink tile (a no-op at 0;
            # dense fallback automatic) — one code path for plain, windowed
            # and global+local local attention.
            def local(q, k, v, ids=None):
                return flash_attention(
                    q, k, v, causal=True, window=self.window,
                    sinks=self.attention_sinks,
                    q_segment_ids=ids, kv_segment_ids=ids,
                )

            args = (q, k, v) if segment_ids is None else (q, k, v, segment_ids)
            if cfg.mesh is not None and cfg.mesh.size > 1:
                spec = P(BATCH_AXES, None, MODEL_AXIS, None)
                in_specs = (spec, spec, spec)
                if segment_ids is not None:
                    in_specs += (P(BATCH_AXES, None),)
                local = compat.shard_map(
                    local, mesh=cfg.mesh, in_specs=in_specs, out_specs=spec,
                    check_vma=False,
                )
            out = local(*args)

        out = dense(features=self.d_model, axis=(-2, -1), name="attn_out")(out)  # row-parallel
        out = nn.Dropout(self.dropout, deterministic=not train)(out)
        x = x + out
        x = cfg.constrain(x, P(BATCH_AXES, SEQ_AXIS, None))

        # --- MLP (dense, or expert-parallel MoE) ---------------------------
        h = nn.LayerNorm(dtype=self.compute_dtype, use_bias=False)(x)
        h = self._mlp(h, dense, train=train)
        h = nn.Dropout(self.dropout, deterministic=not train)(h)
        x = x + h
        return cfg.constrain(x, P(BATCH_AXES, SEQ_AXIS, None))

    def _mlp(self, h, dense, *, train: bool):
        if self.use_moe:
            from horovod_tpu.models.moe import MoEMlp

            if self.moe_router == "expert_choice" and self.decode:
                raise ValueError(
                    "expert_choice routing is training-only: expert "
                    "selection ranks tokens across the whole group, which "
                    "a per-token decode step cannot reproduce (the known "
                    "EC train/inference asymmetry) — decode with "
                    "moe_router='top_k'"
                )
            return MoEMlp(
                self.d_model,
                n_experts=self.n_experts,
                k=self.moe_k,
                capacity_factor=self.capacity_factor,
                aux_loss_coef=self.moe_aux_coef,
                router=self.moe_router,
                compute_dtype=self.compute_dtype,
                sharding=self.sharding,
                name="moe",
            )(h, train=train)
        h = dense(features=4 * self.d_model, name="mlp_up")(h)  # column-parallel
        h = nn.gelu(h)
        return dense(features=self.d_model, name="mlp_down")(h)  # row-parallel

    def _decode_attention(self, q, k, v, decode_index):
        """KV-cache attention for autoregressive inference.

        The cache holds every past token's K/V ([B, max_decode_len, H_kv,
        D] — n_kv_heads, not H: under GQA it stores only the projected kv
        heads — sharded over ``model`` on a TP mesh, the same Megatron
        split as training, so decode reuses the training shardings
        untouched).
        Two static shapes arrive here:

        * **prefill** (T > 1 on a fresh cache): the prompt's K/V are
          written at [0:T] and attention runs causally over the prompt alone
          — exactly the training forward, so the flash kernel applies and no
          [T, max_decode_len] scores are built;
        * **decode step** (T == 1): the new token's K/V land at
          ``decode_index`` and its query attends densely over the valid
          cache prefix — a matvec per head, bandwidth-bound by design;
        * **chunk extension** (T > 1 on a warm cache): T fresh tokens land
          at ``decode_index`` and attend over the prefix plus themselves
          (causal within the chunk) — chunked long-prompt prefill with
          [T, L]-bounded scores, and the verify pass of speculative
          decoding (models/speculative.py).
        """
        cfg = self.sharding
        b, t, h, d = q.shape
        h_kv = k.shape[2]  # < h under GQA: the cache stays at H_kv heads
        rep = h // h_kv
        if self.max_decode_len < t:
            raise ValueError(
                f"max_decode_len ({self.max_decode_len}) < input length ({t})"
            )
        if self.sliding_cache and self.window is None:
            raise ValueError(
                "sliding_cache is the ring buffer for sliding-window "
                "attention — set window too"
            )
        if self.attention_sinks < 0:
            raise ValueError("attention_sinks must be >= 0")
        if self.attention_sinks and self.window is None:
            raise ValueError(
                "attention_sinks is the global+local mask's global part — "
                "it needs window set (full causal attention already sees "
                "every sink)"
            )
        sinks = self.attention_sinks
        cache_spec = P(BATCH_AXES, None, MODEL_AXIS, None)
        first_call = not self.has_variable("cache", "k")
        cache_len = (
            sinks + min(self.window, self.max_decode_len)
            if self.sliding_cache else self.max_decode_len
        )
        qc = self.quantized_cache
        if qc and self.sliding_cache:
            raise ValueError(
                "quantized_cache does not compose with sliding_cache "
                "(the ring path keeps full-width slots) — pick one"
            )
        cache_dtype = jnp.int8 if qc else self.compute_dtype
        zeros = lambda: jnp.zeros(  # noqa: E731
            (b, cache_len, h_kv, d), cache_dtype
        )
        ck = self.variable("cache", "k", zeros)
        cv = self.variable("cache", "v", zeros)
        if qc:
            # Per-(position, head) symmetric scales — they factor out of
            # the head-dim contraction, so reads stay int8 end to end.
            # The fresh full-precision k/v stay untouched (the prefill
            # flash attention below uses THEM, so prefill logits are
            # exact); only the cache writes carry the quantized copies.
            szeros = lambda: jnp.zeros(  # noqa: E731
                (b, cache_len, h_kv), jnp.float32
            )
            ksc = self.variable("cache", "k_scale", szeros)
            vsc = self.variable("cache", "v_scale", szeros)
            from horovod_tpu.models.quant import _quantize_sym

            wk, k_s = _quantize_sym(k, axis=-1)  # int8, [B, T, H_kv, 1]
            wv, v_s = _quantize_sym(v, axis=-1)
            k_s, v_s = k_s[..., 0], v_s[..., 0]  # [B, T, H_kv]
        else:
            wk, wv = k, v
        idx = jnp.asarray(decode_index, jnp.int32)
        if idx.ndim == 1 and self.sliding_cache:
            raise ValueError(
                "per-row decode indices are not supported with "
                "sliding_cache — the ring buffer's slot math is lockstep"
            )
        if self.sliding_cache:
            if t > 1 and not first_call:
                raise ValueError(
                    "sliding_cache supports prefill + single-token decode "
                    "steps; chunk extension (speculative decoding's verify "
                    "pass) needs the full-history cache — evicted rows "
                    "could be needed by the chunk's early tokens"
                )
            # Per-slot absolute positions ([B, W] so batch-reordering
            # consumers like beam search gather it like the K/V arrays);
            # -1 = never written.
            cpos = self.variable(
                "cache", "pos",
                lambda: jnp.full((b, cache_len), -1, jnp.int32),
            )
            # Slot layout: positions < sinks pin to slots [0, sinks); the
            # rest ring over [sinks, sinks + window). A fresh token is kept
            # iff it is a sink or among the last `window` ring-eligible
            # tokens of this write (earlier ones would be evicted within
            # the same chunk); dropped tokens scatter to an out-of-bounds
            # slot under mode='drop'. Kept slots are unique: sink slots by
            # position, ring slots because the last `window` ring positions
            # are distinct mod window.
            win = cache_len - sinks
            new_pos = idx + jnp.arange(t, dtype=jnp.int32)
            ring_slot = sinks + (new_pos - sinks) % win
            slot = jnp.where(new_pos < sinks, new_pos, ring_slot)
            keep = (new_pos < sinks) | (new_pos >= idx + t - win)
            slot = jnp.where(keep, slot, cache_len)  # OOB → dropped
            ck.value = cfg.constrain(
                ck.value.at[:, slot].set(
                    k.astype(ck.value.dtype), mode="drop"
                ),
                cache_spec,
            )
            cv.value = cfg.constrain(
                cv.value.at[:, slot].set(
                    v.astype(cv.value.dtype), mode="drop"
                ),
                cache_spec,
            )
            cpos.value = cpos.value.at[:, slot].set(
                jnp.broadcast_to(new_pos, (b, t)), mode="drop"
            )
        elif idx.ndim == 0:
            ck.value = cfg.constrain(
                jax.lax.dynamic_update_slice(
                    ck.value, wk.astype(ck.value.dtype), (0, idx, 0, 0)
                ),
                cache_spec,
            )
            cv.value = cfg.constrain(
                jax.lax.dynamic_update_slice(
                    cv.value, wv.astype(cv.value.dtype), (0, idx, 0, 0)
                ),
                cache_spec,
            )
            if qc:
                # Same layout pinning as the value writes: heads over
                # `model`, so the persistent scale state never picks up a
                # GSPMD-chosen resharding inside the decode scan.
                scale_spec = P(BATCH_AXES, None, MODEL_AXIS)
                ksc.value = cfg.constrain(
                    jax.lax.dynamic_update_slice(
                        ksc.value, k_s, (0, idx, 0)
                    ),
                    scale_spec,
                )
                vsc.value = cfg.constrain(
                    jax.lax.dynamic_update_slice(
                        vsc.value, v_s, (0, idx, 0)
                    ),
                    scale_spec,
                )
        else:
            # Per-row indices ([B]): each row writes its fresh K/V at its
            # own positions — the ragged-prompt / per-row-speculative
            # layout. mode='drop' guards rows whose positions run past the
            # cache (they are masked out of the attention below anyway).
            rows = jnp.arange(b, dtype=jnp.int32)[:, None]
            pos = idx[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]
            ck.value = cfg.constrain(
                ck.value.at[rows, pos].set(
                    wk.astype(ck.value.dtype), mode="drop"
                ),
                cache_spec,
            )
            cv.value = cfg.constrain(
                cv.value.at[rows, pos].set(
                    wv.astype(cv.value.dtype), mode="drop"
                ),
                cache_spec,
            )
            if qc:
                scale_spec = P(BATCH_AXES, None, MODEL_AXIS)
                ksc.value = cfg.constrain(
                    ksc.value.at[rows, pos].set(k_s, mode="drop"),
                    scale_spec,
                )
                vsc.value = cfg.constrain(
                    vsc.value.at[rows, pos].set(v_s, mode="drop"),
                    scale_spec,
                )
        if t > 1 and first_call:
            # Prefill: the cache was empty below `idx` (generate() starts at
            # 0), so causal attention over the fresh K/V is the full answer —
            # the training forward's local flash path (O(T) memory), with the
            # same manual-sharding treatment on a live mesh (GSPMD cannot
            # auto-partition the Mosaic custom call).
            from horovod_tpu.ops.flash_attention import flash_attention

            if rep > 1:  # prefill attends at full H, like training
                k = jnp.repeat(k, rep, axis=2)
                v = jnp.repeat(v, rep, axis=2)
            # Same global+local mask as training/decode, computed from the
            # fresh K/V (the ring cache may already have evicted mid-prompt
            # keys an early query needs); sinks ride the kernel's pinned
            # tile, dense fallback automatic.
            local = functools.partial(
                flash_attention, causal=True, window=self.window,
                sinks=sinks,
            )
            if cfg.mesh is not None and cfg.mesh.size > 1:
                spec = P(BATCH_AXES, None, MODEL_AXIS, None)
                local = compat.shard_map(
                    local, mesh=cfg.mesh, in_specs=(spec, spec, spec),
                    out_specs=spec, check_vma=False,
                )
            return local(q, k, v)
        # Decode step (t == 1) or chunk extension (t > 1 on a warm cache —
        # chunked long-prompt prefill, and speculative decoding's verify
        # pass): the t fresh queries attend over the cache prefix
        # [0 .. idx + row], causal within the chunk. Scores are [t, L] per
        # head — chunking is exactly what bounds that memory for long
        # prompts. Grouped einsum (g query heads share each cached kv head)
        # so the cache streams ONCE per kv head — never materializing a
        # repeated [B, L, H, D] copy, which would forfeit GQA's bandwidth
        # saving.
        scale = d ** -0.5
        q5 = q.reshape(b, t, h_kv, rep, d)
        s = jnp.einsum(
            "bqhgd,bkhd->bhgqk", q5, ck.value,
            preferred_element_type=jnp.float32,
        ) * scale
        if qc:
            # The per-(position, head) scale factors out of the head-dim
            # contraction: score = (q · k_int8) · k_scale. The einsum above
            # read int8 directly (the convert rides the dot); only the
            # [.., L]-shaped scores pay the scale multiply.
            s = s * jnp.transpose(ksc.value, (0, 2, 1))[:, :, None, None, :]
        if self.sliding_cache:
            # Ring slots carry their absolute positions: valid = written,
            # causal, and inside the band OR a pinned sink (eviction
            # already guarantees the band bound for fully-warm caches; the
            # explicit check keeps partially-warm ones exact too).
            # (Scalar idx only — per-row rejects above.)
            qpos = idx + jnp.arange(t, dtype=jnp.int32)
            kpos = cpos.value[:, None, :]  # [B, 1, W]
            qp = qpos[None, :, None]  # [1, t, 1]
            band = (kpos > qp - self.window) | (kpos < sinks)
            valid = (kpos >= 0) & (kpos <= qp) & band
            valid = valid[:, None, None, :, :]  # [B, 1, 1, t, W]
        else:
            # qpos is [Bq, t] with Bq ∈ {1, B}: a scalar index broadcasts
            # one mask over the batch, per-row indices ([B]) carry a mask
            # per row.
            qpos = (
                idx.reshape(1, 1) if idx.ndim == 0 else idx[:, None]
            ) + jnp.arange(t, dtype=jnp.int32)[None, :]
            kpos = jnp.arange(self.max_decode_len, dtype=jnp.int32)
            valid = kpos[None, None, :] <= qpos[:, :, None]  # [Bq, t, L]
            if self.window is not None:
                # Sliding window over the cache: a query at qpos sees cache
                # rows in (qpos − window, qpos] — plus the first `sinks`
                # positions when streaming a densely-trained model
                # (StreamingLLM; the full-history twin of the ring path,
                # which the ring's exactness tests compare against).
                keep = kpos[None, None, :] > qpos[:, :, None] - self.window
                if sinks:
                    keep |= (kpos < sinks)[None, None, :]
                valid &= keep
            valid = valid[:, None, None, :, :]  # [Bq, 1, 1, t, L]
        s = jnp.where(valid, s, attention_ops._BIG_NEG)
        p = jax.nn.softmax(s, axis=-1)
        if qc:
            # Same factoring on the value side: fold v_scale into the
            # probabilities (shaped [.., L]) and contract against int8 v.
            p_eff = p * jnp.transpose(vsc.value, (0, 2, 1))[:, :, None, None, :]
            out = jnp.einsum(
                "bhgqk,bkhd->bqhgd", p_eff, cv.value,
                preferred_element_type=jnp.float32,
            )
        else:
            out = jnp.einsum(
                "bhgqk,bkhd->bqhgd", p.astype(cv.value.dtype), cv.value,
                preferred_element_type=jnp.float32,
            )
        return out.reshape(b, t, h, d).astype(q.dtype)


class LMHead(nn.Module):
    """The LM head as an explicit ``[d_model, vocab]`` kernel (param path
    ``lm_head/kernel``, identical to the former DenseGeneral's) so the fused
    chunked-CE path (ops/fused_ce.py) can reach the kernel without
    materializing full logits."""

    d_model: int
    vocab_size: int
    compute_dtype: jnp.dtype = jnp.float32
    logits_dtype: jnp.dtype = jnp.float32
    int8_compute: bool = False

    def setup(self):
        self.kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (self.d_model, self.vocab_size),
        )

    def __call__(self, x):
        if self.int8_compute:
            from horovod_tpu.models.quant import int8_dot_general

            logits = int8_dot_general(
                x.astype(self.compute_dtype),
                self.kernel.astype(self.compute_dtype),
                (((x.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=self.logits_dtype,
            )
            return logits
        logits = jnp.dot(
            x.astype(self.compute_dtype), self.kernel.astype(self.compute_dtype)
        )
        return logits.astype(self.logits_dtype)

    def fused_loss(self, x, labels, n_chunks: int):
        """(per-token loss, per-token correct) without full logits."""
        return fused_ce.fused_linear_cross_entropy(
            x.astype(self.compute_dtype), self.kernel, labels,
            max(1, n_chunks),
        )


class TransformerLM(nn.Module):
    """Causal LM over integer tokens: ``[B, T] -> [B, T, vocab]`` logits.

    With ``labels=...`` passed to ``__call__`` the model instead returns
    ``(per_token_loss, per_token_correct)`` computed by the fused chunked-CE
    head (``fused_head_chunks`` row-chunks; see ops/fused_ce.py) — the
    ``Trainer(loss='module')`` contract. Without labels the full-logits path
    is unchanged (predict/decode/export)."""

    vocab_size: int = 256
    d_model: int = 256
    n_heads: int = 8
    # Grouped-query attention: K/V projected to n_kv_heads < n_heads (each
    # shared by n_heads/n_kv_heads query heads). Shrinks the decode cache —
    # and the bytes streamed per generated token — by that group factor;
    # training FLOPs are unchanged. None = MHA (fused qkv projection).
    n_kv_heads: int | None = None
    # Sliding-window (local) attention: each query attends to its `window`
    # most recent tokens only (see Block.window). None = full causal.
    window: int | None = None
    n_layers: int = 4
    dropout: float = 0.1
    compute_dtype: jnp.dtype = jnp.float32
    sharding: ShardingConfig = ShardingConfig()
    # Memory knobs for long context (HBM is the binding constraint on one
    # chip — BASELINE.md context-envelope rows):
    # * remat: rematerialize each block in the backward pass
    #   (jax.checkpoint) — activations per layer drop to the block inputs;
    # * logits_dtype: bf16 halves the [B, T, vocab] logits + cotangent that
    #   dominate long-sequence HBM; the loss upcasts to f32 on the fly
    #   (fused by XLA, never materialized), so logsumexp stays accurate.
    remat: bool = False
    logits_dtype: jnp.dtype = jnp.float32
    # int8 MXU compute for every Dense matmul + the LM head (inference
    # only — prefill and large-batch decode are compute-bound, where the
    # v5e's 2x int8 MXU rate pays; models/quant.int8_dot_general).
    int8_compute: bool = False
    # moe_every=k replaces every k-th block's MLP with an expert-parallel
    # MoE (0 = dense everywhere, the default).
    moe_every: int = 0
    n_experts: int = 8
    moe_k: int = 2
    capacity_factor: float = 1.25
    moe_aux_coef: float = 1e-2
    moe_router: str = "top_k"  # or 'expert_choice' (see models/moe.py)
    # Autoregressive inference (models/decoding.py `generate`): per-block K/V
    # caches sized [B, max_decode_len, H_kv, D] in the ``cache`` collection; the
    # top-level ``cache/index`` counts consumed positions. T>1 = prefill,
    # T==1 = one decode step.
    decode: bool = False
    max_decode_len: int = 0
    # Ring-buffer cache for windowed models: O(window) decode memory and
    # cache traffic regardless of generation length (see Block).
    sliding_cache: bool = False
    # int8 K/V cache with per-(position, head) scales (see Block) — the
    # decode cache stream and cache HBM halve; approximate, quality-gated.
    quantized_cache: bool = False
    # StreamingLLM attention sinks (decode-time; see Block.attention_sinks).
    attention_sinks: int = 0
    # Row-chunk count for the fused linear-CE head when ``labels`` are fed
    # through ``__call__`` (loss='module'): peak head memory is
    # ceil(B·T/chunks)·vocab floats instead of the full [B, T, vocab] logits
    # + cotangent. 0 → a single chunk (dense-equivalent memory, same math).
    fused_head_chunks: int = 0

    @nn.compact
    def __call__(
        self, tokens, *, train: bool = False, segment_ids=None, labels=None
    ):
        cfg = self.sharding
        b, t = tokens.shape
        if self.int8_compute and train:
            raise ValueError(
                "int8_compute is inference-only: round() kills gradients "
                "(quantization-aware training would need a straight-"
                "through estimator) — clone the model with "
                "int8_compute=False for training"
            )
        if self.int8_compute and self.moe_every:
            raise ValueError(
                "int8_compute does not cover MoE expert matmuls (the "
                "routed einsums bypass the Dense dot_general injection) — "
                "an MoE model would silently keep its dominant FLOPs in "
                "bf16; use a dense model or int8_compute=False"
            )
        decode_index = None
        if self.decode:
            if self.remat or train or segment_ids is not None:
                raise ValueError(
                    "decode mode is inference-only: remat/train/segment_ids "
                    "do not apply"
                )
            idx_var = self.variable(
                "cache", "index", lambda: jnp.zeros((), jnp.int32)
            )
            # The cache index is a scalar (lockstep decode) or a [B] vector
            # (per-row positions: ragged-prompt generation, per-row
            # speculative acceptance). Callers switch layouts by writing the
            # threaded cache['index'] entry between applies.
            decode_index = idx_var.value
            offs = jnp.arange(t, dtype=jnp.int32)
            if decode_index.ndim == 0:
                positions = decode_index + jnp.broadcast_to(offs, (b, t))
            else:
                positions = decode_index[:, None] + offs[None, :]
            idx_var.value = decode_index + t
        elif segment_ids is None:
            positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
        else:
            # Packed sequences: RoPE positions restart at each document
            # boundary, and attention is restricted to within-document pairs
            # (the flash kernel's segment masking, with block-level
            # early-out on disjoint tiles).
            positions = packed_positions(segment_ids)
        x = nn.Embed(self.vocab_size, self.d_model, dtype=self.compute_dtype)(tokens)
        x = cfg.constrain(x, P(BATCH_AXES, SEQ_AXIS, None))
        # `train` is argnum 3 of Block.__call__ (self, x, positions, train)
        # and must stay a static python bool through the remat boundary.
        block_cls = (
            nn.remat(Block, static_argnums=(3,)) if self.remat else Block
        )
        for i in range(self.n_layers):
            x = block_cls(
                self.d_model, self.n_heads, self.dropout,
                self.compute_dtype, cfg,
                n_kv_heads=self.n_kv_heads,
                window=self.window,
                use_moe=self.moe_every > 0 and (i + 1) % self.moe_every == 0,
                n_experts=self.n_experts,
                moe_k=self.moe_k,
                capacity_factor=self.capacity_factor,
                moe_aux_coef=self.moe_aux_coef,
                moe_router=self.moe_router,
                decode=self.decode,
                max_decode_len=self.max_decode_len,
                sliding_cache=self.sliding_cache,
                quantized_cache=self.quantized_cache,
                attention_sinks=self.attention_sinks,
                int8_compute=self.int8_compute,
                # Explicit name = flax's auto-name, so the param tree is
                # identical with and without remat (the remat wrapper would
                # otherwise scope as CheckpointBlock_i).
                name=f"Block_{i}",
            )(x, positions, train, segment_ids, decode_index)
        x = nn.LayerNorm(dtype=self.compute_dtype, use_bias=False)(x)
        head = LMHead(
            self.d_model, self.vocab_size,
            compute_dtype=self.compute_dtype,
            logits_dtype=self.logits_dtype,
            int8_compute=self.int8_compute,
            name="lm_head",
        )
        if labels is not None:
            return head.fused_loss(x, labels, self.fused_head_chunks)
        return head(x)


def param_specs(params, mesh: Mesh, extra_tp_dim: dict | None = None) -> dict:
    """PartitionSpec tree for the Megatron TP (+FSDP) layout.

    Path-based rules over the plain param pytree:

    * QKV kernel   [d_model, H, 3·head] → heads on ``model`` (column);
    * attn proj    [H, head, d_model]   → heads on ``model`` (row);
    * MLP up       [d_model, 4·d]       → features on ``model`` (column);
    * MLP down     [4·d, d_model]       → inputs on ``model`` (row);
    * LM head      [d_model, vocab]     → vocab on ``model``;
    * embedding / LayerNorm             → replicated on ``model``.

    With an ``fsdp`` axis > 1, each kernel's first divisible non-model dim is
    additionally sharded over ``fsdp`` (weight-gathered FSDP: XLA inserts the
    gathers where the weights are consumed).

    ``extra_tp_dim`` extends the name→column/row rule table — how sibling
    model families (e.g. `models/seq2seq.py` with its cross-attention
    projections) reuse these rules without duplicating them.
    """
    fsdp = mesh.shape.get(FSDP_AXIS, 1) > 1

    # Rules keyed on the explicit layer names the model declares — immune to
    # flax auto-numbering shifts when layers are added or reordered.
    tp_dim = {
        "qkv": 1,        # [dm, H, 3·hd] — heads (column-parallel)
        "q_proj": 1,     # [dm, H, hd]   — heads (column-parallel, GQA)
        "kv_proj": 1,    # [dm, H_kv, 2·hd] — kv heads (column-parallel, GQA)
        "attn_out": 0,   # [H, hd, dm]  — heads (row-parallel)
        "mlp_up": 1,     # [dm, 4·dm]   — features (column-parallel)
        "mlp_down": 0,   # [4·dm, dm]   — inputs (row-parallel)
        "lm_head": 1,    # [dm, vocab]  — vocab (column-parallel)
    }
    if extra_tp_dim:
        tp_dim = {**tp_dim, **extra_tp_dim}
    # Expert weights: experts over the `expert` axis, hidden over `model`
    # (column for up, row for down) — EP × TP composition.
    moe_dims = {
        "moe_up": {0: EXPERT_AXIS, 2: MODEL_AXIS},    # [E, dm, hidden]
        "moe_down": {0: EXPERT_AXIS, 1: MODEL_AXIS},  # [E, hidden, dm]
    }

    def rule(path, leaf):
        names = [
            p.key for p in path if isinstance(p, jax.tree_util.DictKey)
        ]
        spec: list = [None] * leaf.ndim
        # LoRA adapter leaves (…/lora/…/{a,b}) live under the SAME layer
        # names as the kernels they adapt, but their shapes carry the rank
        # dimension — TP/EP-sharding them is degenerate for small ranks and
        # a divisibility (or rank) failure otherwise. Adapters skip both
        # rule tables; the fsdp rule below still applies, with its own
        # divisibility check.
        # Match the LoRAModel adapter layout precisely (a 'lora' subtree
        # whose leaves are named 'a'/'b' — models/lora.py `init_adapters`),
        # so a user model that merely CONTAINS a submodule named 'lora'
        # still gets its kernels TP/EP-sharded, while a LoRAModel nested
        # under any wrapper keeps the exemption.
        is_lora = "lora" in names and names[-1:] in (["a"], ["b"])
        moe = next((n for n in names if n in moe_dims), None) if not is_lora else None
        if moe is not None:
            for dim, axis in moe_dims[moe].items():
                if leaf.shape[dim] % mesh.shape[axis] != 0:
                    # Silent replication would quietly discard the memory
                    # scaling EP exists for — fail like MeshSpec.resolve.
                    raise ValueError(
                        f"{moe} dim {dim} ({leaf.shape[dim]}) is not "
                        f"divisible by mesh axis {axis!r} "
                        f"({mesh.shape[axis]})"
                    )
                spec[dim] = axis
        else:
            layer = next((n for n in names if n in tp_dim), None)
            if layer is not None and leaf.ndim >= 2 and not is_lora:
                spec[tp_dim[layer]] = MODEL_AXIS
        if fsdp and leaf.ndim >= 2:
            for dim in range(leaf.ndim):
                if spec[dim] is None and leaf.shape[dim] % mesh.shape[FSDP_AXIS] == 0:
                    spec[dim] = FSDP_AXIS
                    break
        return P(*spec)

    return jax.tree_util.tree_map_with_path(rule, params)
