"""Elastic data-parallel training — continue-through-failure instead of
restart-on-failure (Horovod Elastic's role, TPU/JAX-native).

Three pieces, one subsystem:

* `coordinator` — the rendezvous/heartbeat control plane: a TCP server
  (supervisor-owned) tracking membership, versioning the world by
  **generation**, assigning ranks, electing the state-broadcast root, and
  carrying heartbeats so pod-mode hang detection needs no shared
  filesystem.
* `state` — the ``commit()/restore()`` state contract plus the trainer
  callback that commits on cadence and runs the epoch-end membership
  agreement (the synchronized teardown boundary).
* `rescale` — `ensure_world` (tear down + re-init the jax runtime at a
  settled world's size) and `run` (the per-generation driver loop).

Worker-side idiom::

    from horovod_tpu import elastic

    def train(state, world):
        trainer = make_trainer()           # reacts to the new world size
        trainer.build(x0, y0)
        if state.state is not None:        # rescale / rejoin: adopt commit
            trainer.install_state(state.state)
        else:                              # fresh process: checkpoint fallback
            trainer.state, done = checkpoint.restore_latest_and_broadcast(...)
            state.epoch = max(state.epoch, done)
        cb = elastic.ElasticStateCallback(state, state.client)
        trainer.fit(..., initial_epoch=state.epoch,
                    initial_step=state.step,   # mid-epoch commits resume
                    callbacks=[..., cb])       # at the committed step

    elastic.run(train)   # reads HVT_ELASTIC_COORDINATOR/_MEMBER

Launcher-side: ``hvt-launch run --elastic --min-ranks 2 -- ...`` (or the
job-spec ``elastic:`` block) starts the coordinator and supervises
members individually — a clean leave shrinks the fleet in place, a
replacement grows it back, and only hard crashes escalate to per-rank
restarts (README "Elastic training").
"""

from horovod_tpu.elastic.coordinator import (  # noqa: F401
    Coordinator,
    ElasticClient,
    ElasticError,
    WorldInfo,
)
from horovod_tpu.elastic.rescale import (  # noqa: F401
    ensure_world,
    member_id_from_env,
    run,
)
from horovod_tpu.elastic.state import (  # noqa: F401
    ElasticState,
    ElasticStateCallback,
    HostsUpdatedInterrupt,
    LeaveInterrupt,
    ShardedLeaf,
    progress_marker,
    validate_committable,
)
