"""Rendezvous coordinator — the membership/generation control plane of
elastic training (SURVEY.md §5.3's "elastic / dynamic world size" gap;
Horovod Elastic's rendezvous server, TPU-native).

One small TCP server, owned by the supervisor process, speaking
line-delimited JSON (one request, one response, one connection — a member
death can never wedge the server). It is the single source of truth for:

* **Membership**: who is in the fleet (``sync`` auto-joins, ``leave``
  departs, the supervisor marks hard deaths via `Coordinator.mark_dead`).
* **Generations**: every membership event bumps an integer generation.
  Workers learn the current generation from beat responses and compare it
  to the generation they last rendezvoused at — a mismatch means the world
  changed and they must re-rendezvous at the next commit boundary.
* **Rank assignment**: a ``sync`` round blocks until every live member has
  asked, then assigns contiguous ranks 0..n-1 in join order (survivors
  keep their relative order, so rank 0 — the single writer — stays stable
  across shrinks that don't kill it), picks the jax.distributed
  coordinator port for the new world, and elects the **root**: the member
  with the most committed progress, from whom (re)joiners receive state
  (`ElasticState.sync`).
* **Heartbeats**: beats ride the control socket (``beat`` requests), so
  pod-mode hang detection needs NO shared filesystem — the
  ``HVT_HEARTBEAT_DIR`` requirement disappears under ``--elastic``.
  Members blocked in a ``sync`` call are exempt from staleness: a pending
  rendezvous is itself proof of liveness. Beats cut the other way too:
  with ``heartbeat_window`` set, a member whose beats are fresh is exempt
  from rendezvous-timeout expiry — it is mid-epoch and busy, not dead, and
  a joiner waiting out a long epoch must not get it declared dead.

The wire format is deliberately dumb (JSON lines over TCP, new connection
per call): the control plane moves a few hundred bytes per epoch per
member; all bulk state movement (params to joiners) happens over the
data plane (`collectives.broadcast_pytree` on the freshly built world).
"""

from __future__ import annotations

import dataclasses
import json
import os
import socket
import socketserver
import threading
import time


class ElasticError(RuntimeError):
    """A coordinator-reported protocol failure (world full, below
    min_ranks, malformed request)."""


# jax.distributed ports rotate within this window (``sync_port_base +
# generation % SYNC_PORT_WINDOW``): wide enough that an orphan holding a
# recent generation's port cannot wedge the next world, bounded so a
# long-lived churning fleet cannot drift the port into other services'
# ranges (or past 65535).
SYNC_PORT_WINDOW = 64

# Radix of the committed-progress ordering key (`state.progress_marker`:
# epoch·RADIX + min(step, RADIX-1)): epochs dominate, steps break ties.
# Wide enough that no practical epoch length overflows it (1e9 optimizer
# steps ≈ years of training); `progress_marker` clamps anyway, so even a
# beyond-radix epoch degrades to an in-epoch tie rather than letting a
# mid-epoch commit outrank the next epoch's start. Lives here (not
# state.py) because state imports coordinator, and the journal's
# epoch/step decompose below must use the same radix.
PROGRESS_STEP_RADIX = 1_000_000_000


@dataclasses.dataclass(frozen=True)
class WorldInfo:
    """One settled rendezvous round — everything a worker needs to (re)build
    its runtime for the new generation."""

    rank: int
    size: int
    generation: int
    jax_coordinator: str | None  # None ⇔ size == 1 (bare local mode)
    root_rank: int               # who broadcasts committed state
    max_progress: int            # the root's committed progress marker

    @classmethod
    def from_wire(cls, msg: dict) -> "WorldInfo":
        return cls(
            rank=int(msg["rank"]),
            size=int(msg["size"]),
            generation=int(msg["generation"]),
            jax_coordinator=msg.get("jax_coordinator") or None,
            root_rank=int(msg.get("root_rank", 0)),
            max_progress=int(msg.get("max_progress", -1)),
        )


@dataclasses.dataclass
class Member:
    """Coordinator-side record of one fleet member."""

    member_id: str
    host: str
    join_seq: int
    status: str = "live"        # live | left | dead
    reason: str = ""
    rank: int | None = None
    progress: int = -1          # last reported committed progress
    last_beat: float = 0.0      # coordinator-side monotonic clock
    joined_at: float = 0.0


class Coordinator:
    """The rendezvous/heartbeat server. Thread-safe; the supervisor calls
    the ``mark_dead``/``stale_members``/``snapshot`` methods in-process
    while workers speak the TCP protocol."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        min_ranks: int = 1,
        max_ranks: int | None = None,
        expected: int | None = None,
        rendezvous_timeout: float = 60.0,
        heartbeat_window: float | None = None,
        sync_port_base: int | None = None,
        journal=None,
    ):
        """``expected``: how many members the FIRST round should wait for
        (the supervisor's initial spawn count); later rounds settle on the
        current live membership. ``heartbeat_window``: members whose last
        TCP beat is fresher than this are exempt from rendezvous-timeout
        expiry (a fresh beat proves the process alive and busy — typically
        mid-epoch while a joiner waits for the next commit boundary); with
        ``None`` every absentee expires, so ``rendezvous_timeout`` must
        then exceed the worst-case epoch duration. ``sync_port_base``:
        fixed-base jax.distributed port rotation
        (``base + generation % SYNC_PORT_WINDOW``) for multi-host fleets
        where the coordinator cannot probe a free port on rank 0's host;
        None (single-host) probes a free local port per round.
        ``journal``: optional ``fn(name, value, **fields)`` — the
        supervisor's `RestartLog.write` — receiving generation-tagged
        membership/rescale events."""
        self._host = host
        self._requested_port = port
        self.min_ranks = int(min_ranks)
        self.max_ranks = int(max_ranks) if max_ranks is not None else None
        self.expected = int(expected) if expected is not None else None
        self.rendezvous_timeout = float(rendezvous_timeout)
        self.heartbeat_window = (
            float(heartbeat_window) if heartbeat_window is not None else None
        )
        self.sync_port_base = sync_port_base
        self._journal = journal

        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self.generation = 0
        self.members: dict[str, Member] = {}
        self._join_seq = 0
        self._settled = 0          # how many rounds have settled
        self._last_settle: dict | None = None
        # member_id -> {"progress": int, "since": monotonic, "world": dict|None}
        self._waiters: dict[str, dict] = {}
        # member_id -> its latest settled world, for retry re-delivery: a
        # round that settles while a member's socket is dead (client-side
        # sync timeout) must hand the SAME world to its retry, or that
        # member waits for a round its peers already left.
        self._answers: dict[str, dict] = {}
        self._server: socketserver.ThreadingTCPServer | None = None
        self._thread: threading.Thread | None = None

    # --- lifecycle ---------------------------------------------------------

    def start(self) -> "Coordinator":
        coord = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                try:
                    line = self.rfile.readline()
                    if not line:
                        return
                    reply = coord._dispatch(json.loads(line))
                except ElasticError as e:
                    reply = {"error": str(e)}
                except Exception as e:  # malformed request — never crash
                    reply = {"error": f"{type(e).__name__}: {e}"}
                try:
                    self.wfile.write(json.dumps(reply).encode() + b"\n")
                except OSError:
                    pass  # caller died mid-reply; membership catches it

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = Server((self._host, self._requested_port), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        with self._cond:
            # Unblock any waiter still parked in a sync round.
            for slot in self._waiters.values():
                slot.setdefault("error", "coordinator stopped")
            self._cond.notify_all()

    @property
    def address(self) -> str:
        assert self._server is not None, "call start() first"
        return f"{self._host}:{self._server.server_address[1]}"

    # --- protocol ----------------------------------------------------------

    def _dispatch(self, msg: dict) -> dict:
        cmd = msg.get("cmd")
        if cmd == "sync":
            return self._handle_sync(msg)
        if cmd == "beat":
            return self._handle_beat(msg)
        if cmd == "leave":
            return self._handle_leave(msg)
        if cmd == "state":
            return self.snapshot()
        raise ElasticError(f"unknown command {cmd!r}")

    def _bump(self, why: str, member_id: str, reason: str = "") -> None:
        """One membership event: new generation + a journal line. Caller
        holds the lock."""
        self.generation += 1
        self._write_journal(
            why, 1.0, member=member_id, generation=self.generation,
            reason=reason,
        )
        self._cond.notify_all()

    def _fail_waiter(self, member_id: str, message: str) -> None:
        """Release a parked sync handler for a member that was removed
        (died/left mid-rendezvous) — settle only answers LIVE members, so
        without this the handler thread would spin until its client's
        socket timeout, leaking a thread per hard death. Caller holds the
        lock."""
        slot = self._waiters.get(member_id)
        if slot is not None and slot.get("world") is None:
            slot["error"] = message
            self._cond.notify_all()

    def _write_journal(self, name: str, value: float, **fields) -> None:
        if self._journal is not None:
            try:
                self._journal(name, value, **fields)
            except Exception:
                pass  # observability must never take down the control plane

    def _handle_sync(self, msg: dict) -> dict:
        member_id = str(msg["member"])
        host = str(msg.get("host") or "127.0.0.1")
        progress = int(msg.get("progress", -1))
        deadline = time.monotonic() + self.rendezvous_timeout
        with self._cond:
            m = self.members.get(member_id)
            if m is None or m.status != "live":
                live = self._live()
                if self.max_ranks is not None and len(live) >= self.max_ranks:
                    raise ElasticError(
                        f"world is full ({len(live)}/{self.max_ranks} ranks)"
                    )
                self._join_seq += 1
                now = time.monotonic()
                m = Member(
                    member_id=member_id, host=host, join_seq=self._join_seq,
                    last_beat=now, joined_at=now,
                )
                self.members[member_id] = m
                self._bump("join", member_id)
            m.progress = progress
            m.last_beat = time.monotonic()
            if bool(msg.get("retry")):
                ans = self._answers.get(member_id)
                if ans is not None and ans["generation"] == self.generation:
                    # The round settled while this member's socket was
                    # dead (between its sync timeout and this retry):
                    # re-deliver the same world instead of parking it for
                    # a round its peers already left.
                    return dict(ans)
            else:
                # A fresh (non-retry) sync proves the previous answer was
                # received; drop it so a LATER retry can never be fed a
                # stale world from a still-current generation.
                self._answers.pop(member_id, None)
            slot = {"progress": progress, "since": time.monotonic(),
                    "world": None}
            self._waiters[member_id] = slot
            self._cond.notify_all()
            while slot.get("world") is None and "error" not in slot:
                if self._waiters.get(member_id) is not slot:
                    # The member reconnected (client-side socket timeout →
                    # re-sync) and a newer waiter slot took over; settle
                    # only answers the CURRENT slot, so without this the
                    # stale handler thread would spin forever.
                    slot["error"] = "superseded by a newer sync"
                    break
                self._maybe_settle()
                if slot.get("world") is not None or "error" in slot:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    # Past the deadline the round can stay open for a
                    # whole epoch (beat-fresh absentees); poll expiry at
                    # the normal wait cadence, not a tight spin.
                    self._expire_laggards()
                    remaining = 0.25
                self._cond.wait(timeout=min(remaining, 0.25))
            if self._waiters.get(member_id) is slot:
                self._waiters.pop(member_id)
            if "error" in slot:
                raise ElasticError(slot["error"])
            return slot["world"]

    def _handle_beat(self, msg: dict) -> dict:
        member_id = str(msg["member"])
        with self._cond:
            m = self.members.get(member_id)
            if m is not None:
                m.last_beat = time.monotonic()
                if "progress" in msg:
                    m.progress = int(msg["progress"])
            # ``pending``: the membership changed since the world this
            # member last received (its `_answers` entry) — piggybacked
            # on the heartbeat so workers' STEADY-STATE sub-epoch rescale
            # rounds stay one cheap boolean agreement instead of a full
            # vote (`ElasticStateCallback.rescale_every_steps`).
            ans = self._answers.get(member_id)
            pending = bool(
                m is not None and m.status == "live"
                and (ans is None or ans.get("generation") != self.generation)
            )
            return {"generation": self.generation,
                    "pending": pending,
                    "known": m is not None and m.status == "live"}

    def _handle_leave(self, msg: dict) -> dict:
        member_id = str(msg["member"])
        reason = str(msg.get("reason", "leave"))
        with self._cond:
            m = self.members.get(member_id)
            if m is not None and m.status == "live":
                m.status = "left"
                m.reason = reason
                self._bump("leave", member_id, reason=reason)
                self._fail_waiter(member_id, f"member left ({reason})")
                self._maybe_settle()
            return {"ok": 1, "generation": self.generation}

    # --- settle ------------------------------------------------------------

    def _live(self) -> list[Member]:
        return sorted(
            (m for m in self.members.values() if m.status == "live"),
            key=lambda m: m.join_seq,
        )

    def _maybe_settle(self) -> None:
        """Settle the pending rendezvous round when every live member is
        waiting (and the first round has gathered its expected quorum).
        Caller holds the lock."""
        live = self._live()
        waiting = [m for m in live if m.member_id in self._waiters
                   and self._waiters[m.member_id].get("world") is None]
        if not waiting or len(waiting) < len(live):
            return
        if len(live) < self.min_ranks:
            return
        if (
            self._settled == 0
            and self.expected is not None
            and len(live) < min(
                self.expected,
                self.max_ranks if self.max_ranks is not None else self.expected,
            )
            # the expected quorum is waived once the oldest waiter has
            # out-waited the rendezvous window (a member died pre-join)
            and not self._quorum_expired()
        ):
            return
        self._settle(live)

    def _quorum_expired(self) -> bool:
        oldest = min(
            (w["since"] for w in self._waiters.values()), default=None
        )
        return (
            oldest is not None
            and time.monotonic() - oldest > self.rendezvous_timeout
        )

    def _expire_laggards(self) -> None:
        """A waiter out-waited the rendezvous window: live members that never
        showed up AND whose beats have gone silent for ``heartbeat_window``
        are presumed dead (crashed without the supervisor noticing yet),
        dropped, and the round re-evaluated. A beat-fresh absentee is busy
        training toward its commit boundary, not dead — the waiters keep
        waiting for it instead of settling without it. Caller holds the
        lock."""
        now = time.monotonic()
        live = self._live()
        absent = [m for m in live if m.member_id not in self._waiters]
        laggards = [
            m for m in absent
            if self.heartbeat_window is None
            or now - m.last_beat > self.heartbeat_window
        ]
        if not laggards:
            if absent:
                # Every absentee is provably alive (fresh beats): nothing
                # to expire, the round simply hasn't gathered yet.
                return
            if len(live) >= self.min_ranks:
                # Everyone alive IS waiting — only the first round's
                # expected quorum held the settle back, and expiry waives
                # it (_quorum_expired is now true).
                self._maybe_settle()
                return
            # Below min_ranks with nobody left to expire: fail loudly.
            for slot in self._waiters.values():
                if slot.get("world") is None:
                    slot["error"] = (
                        f"rendezvous timed out below min_ranks "
                        f"({len(live)} < {self.min_ranks})"
                    )
            self._cond.notify_all()
            return
        for m in laggards:
            m.status = "dead"
            m.reason = "rendezvous-timeout"
            self._bump("dead", m.member_id, reason="rendezvous-timeout")
        self._maybe_settle()

    def _pick_sync_port(self) -> int:
        if self.sync_port_base is not None:
            # Rotation keeps an orphan holding the old port from wedging
            # the new world (the supervise_hosts trick, per generation);
            # the bounded window keeps a churning fleet's port from
            # drifting upward forever.
            return int(self.sync_port_base) + self.generation % SYNC_PORT_WINDOW
        with socket.socket() as s:
            s.bind(("", 0))
            return s.getsockname()[1]

    def _settle(self, live: list[Member]) -> None:
        size = len(live)
        prev = self._last_settle
        for rank, m in enumerate(live):
            m.rank = rank
        root = max(live, key=lambda m: (m.progress, -m.rank))
        if size > 1:
            port = self._pick_sync_port()
            jax_coordinator = f"{live[0].host}:{port}"
        else:
            jax_coordinator = None  # bare local mode — no control plane
        self._settled += 1
        kind = (
            "start" if prev is None
            else "shrink" if size < prev["size"]
            else "grow" if size > prev["size"]
            else "steady"
        )
        self._last_settle = {
            "generation": self.generation, "size": size,
            "members": [m.member_id for m in live],
            "jax_coordinator": jax_coordinator,
            "kind": kind, "wall_time": time.time(),
        }
        # Progress decomposed from the root's committed marker
        # (state.progress_marker: epoch·PROGRESS_STEP_RADIX + step):
        # settle records say WHERE in training the membership change
        # landed, and shrink/grow get a dedicated step-valued record so
        # job specs can gate "the rescale really happened MID-epoch"
        # (`shrink_step=1..N`).
        step = max(0, root.progress) % PROGRESS_STEP_RADIX
        epoch = max(0, root.progress) // PROGRESS_STEP_RADIX
        self._write_journal(
            kind, float(size), generation=self.generation, size=size,
            members=",".join(m.member_id for m in live),
            root=root.member_id, progress=root.progress,
            epoch=epoch, step=step,
        )
        if kind in ("shrink", "grow"):
            self._write_journal(
                f"{kind}_step", float(step), generation=self.generation,
                epoch=epoch,
            )
        for m in live:
            world = {
                "rank": m.rank, "size": size,
                "generation": self.generation,
                "jax_coordinator": jax_coordinator,
                "root_rank": root.rank, "max_progress": root.progress,
            }
            self._waiters[m.member_id]["world"] = world
            self._answers[m.member_id] = world
        self._cond.notify_all()

    # --- supervisor-side API ------------------------------------------------

    def mark_dead(self, member_id: str, reason: str = "crash") -> bool:
        """Remove a member the supervisor observed dying (process exit, TCP
        beat gone stale). Bumps the generation so survivors re-rendezvous."""
        with self._cond:
            m = self.members.get(member_id)
            if m is None or m.status != "live":
                return False
            m.status = "dead"
            m.reason = reason
            self._bump("dead", member_id, reason=reason)
            self._fail_waiter(member_id, f"member removed ({reason})")
            self._maybe_settle()
            return True

    def stale_members(self, timeout: float, *, now: float | None = None
                      ) -> list[str]:
        """Live members whose last TCP beat is older than ``timeout``.
        Members parked in a sync round are exempt — a pending rendezvous
        is proof the process is alive and connected."""
        now = time.monotonic() if now is None else now
        with self._lock:
            return [
                m.member_id for m in self.members.values()
                if m.status == "live"
                and m.member_id not in self._waiters
                and now - m.last_beat > timeout
            ]

    def member_status(self, member_id: str) -> tuple[str, str]:
        """(status, reason) for a member; ("unknown", "") if never joined."""
        with self._lock:
            m = self.members.get(member_id)
            return (m.status, m.reason) if m is not None else ("unknown", "")

    def live_count(self) -> int:
        with self._lock:
            return sum(
                1 for m in self.members.values() if m.status == "live"
            )

    def snapshot(self) -> dict:
        """JSON-safe control-plane state (the ``state`` protocol command and
        the supervisor's journal/teardown view)."""
        now = time.monotonic()
        with self._lock:
            return {
                "generation": self.generation,
                "min_ranks": self.min_ranks,
                "max_ranks": self.max_ranks,
                "settled_rounds": self._settled,
                "last_settle": dict(self._last_settle)
                if self._last_settle else None,
                "members": {
                    m.member_id: {
                        "host": m.host, "status": m.status,
                        "reason": m.reason, "rank": m.rank,
                        "progress": m.progress,
                        # Seconds since the last TCP beat (coordinator
                        # clock) — the /metrics heartbeat-age gauge; live
                        # members only (a left/dead member's age is
                        # meaningless and would only grow forever).
                        "beat_age_s": round(now - m.last_beat, 3)
                        if m.status == "live" else None,
                    }
                    for m in self.members.values()
                },
            }


class ElasticClient:
    """Worker-side handle on the coordinator. One connection per call —
    stateless on the wire, so a mid-call death on either side surfaces as
    a socket error, never a wedged server thread."""

    def __init__(
        self,
        address: str | None = None,
        member_id: str | None = None,
        *,
        host: str | None = None,
        timeout: float = 300.0,
    ):
        from horovod_tpu import runtime
        from horovod_tpu.analysis import registry

        address = address or registry.get_str(runtime.ENV_ELASTIC_COORDINATOR)
        if not address:
            raise ValueError(
                "no coordinator address — pass address= or export "
                f"{runtime.ENV_ELASTIC_COORDINATOR}"
            )
        self.coord_host, port_s = address.rsplit(":", 1)
        self.coord_port = int(port_s)
        self.member_id = (
            member_id
            or registry.get_str(runtime.ENV_ELASTIC_MEMBER)
            or f"{socket.gethostname()}-{os.getpid()}"
        )
        # The address peers use to dial THIS member's jax coordinator when
        # it lands rank 0. Single-host fleets loop back; multi-host members
        # advertise their hostname.
        self.host = host or (
            "127.0.0.1" if self.coord_host in ("127.0.0.1", "localhost")
            else socket.gethostname()
        )
        self.timeout = timeout
        self.synced_generation = -1
        # Set from each beat reply: the coordinator observed a membership
        # change this member has not rendezvoused over yet (the cheap
        # steady-state signal for sub-epoch rescale rounds).
        self.last_beat_pending = False

    def _call(self, timeout: float | None = None, **msg) -> dict:
        with socket.create_connection(
            (self.coord_host, self.coord_port),
            timeout=timeout or self.timeout,
        ) as s:
            s.sendall(json.dumps(msg).encode() + b"\n")
            buf = b""
            while not buf.endswith(b"\n"):
                chunk = s.recv(65536)
                if not chunk:
                    raise ElasticError("coordinator closed the connection")
                buf += chunk
        reply = json.loads(buf)
        if "error" in reply:
            raise ElasticError(reply["error"])
        return reply

    def sync(self, progress: int = -1,
             timeout: float | None = None) -> WorldInfo:
        """Block until the next rendezvous round settles; returns this
        member's place in the new world. Auto-joins on first call.

        The wait is UNBOUNDED by design — the server holds the round open
        as long as absent members are provably alive (fresh beats), which
        can be a whole epoch. With ``timeout=None`` each attempt waits
        ``self.timeout`` on the socket and then simply re-enters the
        rendezvous (the server supersedes the stale waiter slot), so a
        slow epoch elsewhere cannot crash a joiner while a half-open
        connection still cannot wedge it. Pass an explicit ``timeout`` to
        bound the total wait instead.

        **Warm-standby parking** (``HVT_ELASTIC_SPARE``, set on members
        by `supervise_elastic(spares=K)`): a sync the coordinator
        rejects because the world is already full parks — sleep, knock
        again — instead of failing. The rejection happens BEFORE
        membership, so a parked spare never appears on the coordinator;
        the moment an eviction or death frees a slot, the next knock
        joins the rendezvous and the spare is promoted into the new
        generation. With an explicit ``timeout`` the parking is bounded
        by the same deadline."""
        from horovod_tpu.analysis import registry

        park = registry.get_flag("HVT_ELASTIC_SPARE")
        deadline = (
            time.monotonic() + timeout if timeout is not None else None
        )
        retry = False
        while True:
            try:
                world = WorldInfo.from_wire(self._call(
                    cmd="sync", member=self.member_id, host=self.host,
                    progress=progress, retry=retry, timeout=timeout,
                ))
                break
            except TimeoutError:
                if timeout is not None:
                    raise
                retry = True
            except ElasticError as e:
                if not park or "world is full" not in str(e):
                    raise
                if deadline is not None and time.monotonic() > deadline:
                    raise
                time.sleep(0.5)
        self.synced_generation = world.generation
        return world

    def beat(self, progress: int | None = None) -> int:
        """One TCP heartbeat; returns the coordinator's CURRENT generation
        (compare with `synced_generation` to detect membership changes).
        Also records the reply's ``pending`` membership flag on
        ``self.last_beat_pending`` — the piggybacked signal sub-epoch
        rescale rounds consult before escalating to a full vote."""
        msg = {"cmd": "beat", "member": self.member_id}
        if progress is not None:
            msg["progress"] = progress
        reply = self._call(timeout=10.0, **msg)
        self.last_beat_pending = bool(reply.get("pending", False))
        return int(reply["generation"])

    def leave(self, reason: str = "leave") -> None:
        """Planned departure — the clean-shrink signal."""
        self._call(cmd="leave", member=self.member_id, reason=reason,
                   timeout=10.0)

    def state(self) -> dict:
        return self._call(cmd="state", timeout=10.0)
